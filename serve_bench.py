"""Serving load driver: concurrent clients, mixed buckets, chaos kill.

The evidence round for the online matching service
(``dgmc_tpu/serve/``), recorded the way training rounds record
``BENCH_*``/``SCALE_*``::

    python serve_bench.py --out benchmarks/SERVE_r01.json

Protocol (one supervised service, measured end to end):

1. **Cold start** — spawn ``python -m dgmc_tpu.serve --supervise`` on a
   synthetic corpus with an empty checkpoint dir (``--init-missing``)
   and measure spawn → first successful ``/match`` answer (imports,
   checkpoint init, corpus ψ₁ build + cache write, AOT bucket warmup —
   the whole cold path).
2. **Load phase 1** — N concurrent clients × Q queries each, mixed
   bucket sizes, client-observed latency per query; the compile-event
   counter is read before and after through ``/status`` — the
   zero-per-query-compiles cross-check (the RCP202 telemetry account:
   compiles after warmup must be 0).
3. **Chaos** — SIGKILL the serving WORKER mid-run (pid from
   ``/healthz``). The supervisor restarts it; the restarted worker must
   come back **warm** from the on-disk embedding cache (cache-hit gauge
   asserted) and on a possibly NEW port (clients re-discover through
   ``heartbeat.json``, the same discovery the supervisor uses).
   Measured: kill → first successful answer (warm restart-to-first-
   answer), which must beat the cold startup.
4. **Load phase 2** — remaining queries against the restarted worker,
   compile delta asserted zero again.
5. **Teardown** — SIGTERM the worker (graceful exit 0 → the supervisor
   records ``outcome: completed``, ``restarts: 1``).

The record carries server-side latency p50/p95 (the worker's own
per-query histogrammed account), client-observed p50/p95, sustained
QPS, the cold/warm restart split, the compile account and Hits@1
against the sampled queries' known ground truth.

Since r02 every load query carries a client-minted W3C ``traceparent``
and the record additionally carries the ``qtrace`` attribution block
(``obs.qtrace``): per-stage p50/p95, the p95−p50 tail gap attributed
to a named stage, the client-vs-server latency skew (``client_ms``
minus the server's ``trace_ms`` — the wire + HTTP + JSON overhead the
server-side span tree cannot see), and the measured tracing overhead
(alternating traced / ``x-qtrace: off`` probes; the driver gates the
traced p50 penalty at <5%). The per-stage sums must cover the traced
end-to-end total within tolerance — a span tree that loses the query's
time is a failed round, not a cosmetic gap.

Since r03 the record also carries the ``quality`` account: Hits@1
against the sampled ground truth, the distribution of the per-query
confidence proxies every answer returns beside ``stages_ms``
(``entropy``, ``margin``, ``correction``, ``saturation``), the
shortlist-saturation fraction, and the shadow-audit block scraped from
the drained worker's ``quality.json`` (sampled queries re-scored
through the exhaustive scan off the hot lock). The driver gates on the
audit: recall against the exhaustive reference must be exactly 1.0 —
the engine's shortlist tiers are bit-exact, so anything less is a
correctness bug, not noise — and the saturation fraction must be
MEASURED (``None`` means the confidence plane never reported).

Since r04 the record carries the capacity/goodput plane: a
QPS-vs-concurrency ramp (1→2→4→8 fresh clients against the quiet
restarted worker) with its measured knee (``obs.capacity.knee_of``),
the queueing model scraped from the worker's ``/status`` capacity
section (arrival rate, mean service time, Little's-law utilization ρ,
saturation QPS, the engine-lock wait/hold split reconciled against
qtrace's ``admission_queue_wait`` span), the per-bucket padding-waste
and goodput account (``obs.goodput``) for both the served queries and
a host-side collation ``pairs_sweep`` (B ∈ {1,2,4,8}), and the
batching-headroom projection seeded from the committed bench
``pairs_sweep``'s ``step_ms_per_pair`` (falling back to the serve
path's own measured service time as a labeled single-point estimate).
The driver gates on all of it being MEASURED: a missing ramp, ratio,
reconciliation block, or ``/metrics`` capacity family fails the round.

Since r05 the record carries the SLO/error-budget plane: the bench
writes the default serve SLO spec (``obs.slo.DEFAULT_SERVE_SPEC``)
into the workdir and passes it to the worker via ``--slo``, then
scrapes the drained worker's ``slo.json`` (per-objective error-budget
consumption, fast/slow multi-window burn rates, breach counters) and
``anomalies.json`` (the streaming EWMA/CUSUM watch: per-signal
spike/shift counters and the bounded event ring). The driver gates on
the SLO account being MEASURED (an availability objective with no
budget number is a failed round) and on the anomaly ring being
BOUNDED (events ≤ capacity, truncation accounted).
"""

import argparse
import hashlib
import json
import os
import shutil
import signal
import subprocess
import sys
import threading
import time

from dgmc_tpu.obs.observe import percentile
from dgmc_tpu.obs.qtrace import format_traceparent
from dgmc_tpu.obs.slo import DEFAULT_SERVE_SPEC
from dgmc_tpu.serve.client import (confidence_of, discover_endpoint,
                                   get_json, post_match, query_payload,
                                   sample_query)
from dgmc_tpu.serve.corpus import synthetic_corpus


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split('\n')[0])
    p.add_argument('--out', type=str, default=None,
                   help='write the round record here (e.g. '
                        'benchmarks/SERVE_r01.json); default: stdout '
                        'only')
    p.add_argument('--round', type=int, default=1)
    p.add_argument('--workdir', type=str, default='/tmp/serve_bench')
    p.add_argument('--clients', type=int, default=4)
    p.add_argument('--queries-per-client', dest='queries_per_client',
                   type=int, default=12)
    p.add_argument('--corpus-nodes', dest='corpus_nodes', type=int,
                   default=4096)
    p.add_argument('--corpus-edges', dest='corpus_edges', type=int,
                   default=16384)
    p.add_argument('--corpus-dim', dest='corpus_dim', type=int,
                   default=64)
    p.add_argument('--buckets', type=str, default='16x48,32x96')
    p.add_argument('--dim', type=int, default=64)
    p.add_argument('--rnd_dim', type=int, default=16)
    p.add_argument('--num_layers', type=int, default=2)
    p.add_argument('--num_steps', type=int, default=4)
    p.add_argument('--k', type=int, default=10)
    p.add_argument('--offload-corpus', dest='offload_corpus',
                   action='store_true',
                   help='run the service in the host-RAM corpus tier')
    p.add_argument('--startup-timeout', dest='startup_timeout',
                   type=float, default=300.0)
    p.add_argument('--audit-sample', dest='audit_sample', type=float,
                   default=1.0,
                   help='shadow-audit sample rate passed to the '
                        'service (1.0: every query is re-scored '
                        'through the exhaustive scan, so the recall '
                        'gate is deterministic; 0 disables)')
    p.add_argument('--min-margin', dest='min_margin', type=float,
                   default=0.0,
                   help='low-confidence margin threshold passed to '
                        'the service (0 disables the breach hook)')
    p.add_argument('--seed', type=int, default=0)
    return p.parse_args(argv)


class Endpoint:
    """Shared, re-discoverable service endpoint (the worker's port can
    MOVE across the chaos restart — discovery follows heartbeat.json)."""

    def __init__(self, obs_root):
        self.obs_root = obs_root
        self._lock = threading.Lock()
        self.port = None
        self.pid = None

    def refresh(self, timeout_s=0.0):
        found = discover_endpoint(self.obs_root, timeout_s=timeout_s)
        if found is not None:
            with self._lock:
                self.port = found[1]
                self.pid = found[2]
        return found


def wait_first_answer(endpoint, payload, deadline_s, exclude_pid=None):
    """Poll /match until a 200 (optionally from a pid other than
    ``exclude_pid`` — the restarted worker, not a zombie of the old
    one). Returns (elapsed_s, pid)."""
    t0 = time.perf_counter()
    deadline = t0 + deadline_s
    while time.perf_counter() < deadline:
        endpoint.refresh()
        if endpoint.port is not None:
            health = get_json(endpoint.port, '/healthz', timeout_s=2.0)
            pid = (health[1].get('pid')
                   if health and isinstance(health[1], dict) else None)
            if pid is not None and pid != exclude_pid:
                r = post_match(endpoint.port, payload, timeout_s=30.0)
                if r is not None and r[0] == 200:
                    return time.perf_counter() - t0, pid
        time.sleep(0.2)
    raise RuntimeError(f'no /match answer within {deadline_s}s '
                       f'(obs root {endpoint.obs_root})')


def compile_events(port):
    st = get_json(port, '/status', timeout_s=10.0)
    if not st or not isinstance(st[1], dict):
        return None
    return (st[1].get('compile') or {}).get('events')


def mint_traceparent(tag):
    """A deterministic client-side W3C trace context for one bench
    query: the bench OWNS the trace ids, and the server must adopt and
    echo them (asserted as the trace-adoption gate)."""
    trace_id = hashlib.sha256(f'{tag}:trace'.encode()).hexdigest()[:32]
    span_id = hashlib.sha256(f'{tag}:span'.encode()).hexdigest()[:16]
    return trace_id, format_traceparent(trace_id, span_id)


def run_clients(jobs_per_client, endpoint, deadline_s=600.0,
                progress=None, pace_s=0.0, trace_tag=''):
    """N threads, each draining its job list; latencies + hits come
    back per client. A failed POST (the mid-run kill window) refreshes
    the endpoint and retries the SAME query until the deadline.
    ``progress`` (a mutable ``{'done': n}``) lets the driver time the
    chaos kill against real completions; ``pace_s`` spaces a client's
    queries so a load phase stays open long enough to be killed into.
    Each query carries a bench-minted ``traceparent`` and the result
    rows collect the server's span-tree account (``stages_ms``,
    ``trace_ms``) beside the client clock (``client_ms``)."""
    results = [[] for _ in jobs_per_client]

    def client(tid):
        for qi, (payload, gt) in enumerate(jobs_per_client[tid]):
            if pace_s:
                time.sleep(pace_s)
            want_id, tp = mint_traceparent(f'{trace_tag}:{tid}:{qi}')
            t_end = time.time() + deadline_s
            while True:
                port = endpoint.port
                t0 = time.perf_counter()
                r = (post_match(port, payload, timeout_s=60.0,
                                traceparent=tp)
                     if port else None)
                if r is not None and r[0] == 200:
                    lat = time.perf_counter() - t0
                    hits = sum(
                        1 for m, t in zip(r[1]['matches'], gt)
                        if m['target'] == int(t))
                    results[tid].append(
                        {'latency_s': lat, 'hits': hits, 'n': len(gt),
                         'server_ms': r[1].get('latency_ms'),
                         'stages_ms': r[1].get('stages_ms'),
                         'trace_ms': r[1].get('trace_ms'),
                         'client_ms': r[1].get('client_ms'),
                         'quality': confidence_of(r[1]),
                         'trace_adopted':
                             r[1].get('trace_id') == want_id})
                    if progress is not None:
                        progress['done'] = progress.get('done', 0) + 1
                    break
                if time.time() > t_end:
                    results[tid].append({'failed': True})
                    break
                endpoint.refresh()
                time.sleep(0.2)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(jobs_per_client))]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results, time.perf_counter() - t0


def measure_overhead(endpoint, payload, samples_per_arm=24):
    """Tracing overhead on the sampled-off path: one sequential client
    alternating traced queries against ``x-qtrace: off`` ones (same
    payload, same bucket, interleaved so drift hits both arms equally).
    Returns ``{'traced_p50_ms', 'untraced_p50_ms', 'overhead_frac',
    'samples_per_arm'}`` — ``overhead_frac`` is the traced-p50 penalty
    the driver gates at <5%."""
    traced, untraced = [], []
    for i in range(2 * samples_per_arm):
        is_traced = (i % 2 == 0)
        t0 = time.perf_counter()
        r = post_match(endpoint.port, payload, timeout_s=60.0,
                       qtrace=None if is_traced else False)
        dt = (time.perf_counter() - t0) * 1e3
        if r is not None and r[0] == 200:
            (traced if is_traced else untraced).append(dt)
    if not traced or not untraced:
        return {'traced_p50_ms': None, 'untraced_p50_ms': None,
                'overhead_frac': None,
                'samples_per_arm': samples_per_arm}
    p_t = percentile(sorted(traced), 0.5)
    p_u = percentile(sorted(untraced), 0.5)
    return {'traced_p50_ms': round(p_t, 3),
            'untraced_p50_ms': round(p_u, 3),
            'overhead_frac': round((p_t - p_u) / p_u, 4),
            'samples_per_arm': samples_per_arm}


def concurrency_ramp(endpoint, corpus_x, shapes, args,
                     levels=(1, 2, 4, 8), queries_per_client=6):
    """The QPS-vs-concurrency ramp (r04+): fresh client cohorts of
    1→2→4→8 against the quiet restarted worker, each level its own
    measured leg, and the curve's measured knee
    (:func:`dgmc_tpu.obs.capacity.knee_of`) — the last concurrency
    whose marginal QPS gain still cleared the floor. The serialized
    executor makes the shape predictable (QPS should flatten once the
    lock saturates); the ramp MEASURES where instead of assuming it."""
    from dgmc_tpu.obs.capacity import knee_of
    rows = []
    for li, level in enumerate(levels):
        jobs = [[] for _ in range(level)]
        for c in range(level):
            for q in range(queries_per_client):
                n, e = shapes[(c + q) % len(shapes)]
                shrink = (c + q) % 3
                g, gt = sample_query(
                    corpus_x, n - shrink, e - 2 * shrink,
                    seed=args.seed + 90000 + 1000 * (li * 16 + c) + q)
                jobs[c].append((query_payload(g), gt))
        res, wall = run_clients(jobs, endpoint,
                                trace_tag=f'ramp{level}')
        flat_ok = [r for cr in res for r in cr if not r.get('failed')]
        lats = sorted(r['latency_s'] for r in flat_ok)
        rows.append({
            'clients': level,
            'queries': len(flat_ok),
            'failed': sum(1 for cr in res for r in cr
                          if r.get('failed')),
            'qps': round(len(flat_ok) / max(wall, 1e-9), 2),
            'p50_ms': (round(percentile(lats, 0.5) * 1e3, 3)
                       if lats else None),
            'p95_ms': (round(percentile(lats, 0.95) * 1e3, 3)
                       if lats else None),
        })
        print(f'# ramp {level} client(s): {rows[-1]}', file=sys.stderr,
              flush=True)
    return {'levels': rows, 'knee': knee_of(rows)}


def collation_goodput(shapes, dim, seed=0, batches=(1, 2, 4, 8)):
    """Per-B goodput of the collation path alone: query-shaped graphs
    (the same size distribution :func:`sample_query` draws) padded into
    the largest serve bucket via ``pad_pair_batch`` on the host — no
    device work, just the padding-waste account the batcher would
    execute at each batch size (``obs.goodput``)."""
    import numpy as np

    from dgmc_tpu.obs import goodput as goodput_mod
    from dgmc_tpu.utils.data import Graph, GraphPair, pad_pair_batch

    rng = np.random.RandomState(seed)
    n_max = max(s[0] for s in shapes)
    e_max = max(s[1] for s in shapes)

    def graph(n, e):
        return Graph(
            edge_index=rng.randint(0, n, (2, e)).astype(np.int32),
            x=rng.randn(n, dim).astype(np.float32))

    out = {}
    for b in batches:
        pairs = []
        for i in range(b):
            n, e = shapes[i % len(shapes)]
            pairs.append(GraphPair(s=graph(n, e),
                                   t=graph(n_max, e_max)))
        batch = pad_pair_batch(pairs, num_nodes_s=n_max,
                               num_edges_s=e_max)
        gr = goodput_mod.goodput_ratio(goodput_mod.pair_fills(
            goodput_mod.mask_fills(batch.s.node_mask, batch.s.edge_mask),
            goodput_mod.mask_fills(batch.t.node_mask,
                                   batch.t.edge_mask)))
        out[str(b)] = round(gr, 4) if gr is not None else None
    return out


def batching_headroom_block(target_qps=None, mean_service_ms=None,
                            bench_path='benchmarks/BENCH_r06.json'):
    """The batching-headroom estimate, seeded from the committed bench
    ``pairs_sweep``'s measured ``step_ms_per_pair`` when the round
    carries one; falls back to the serve path's own measured mean
    service time as the B=1 point (an honest single-point projection,
    labeled as such) — never fabricates per-B numbers."""
    from dgmc_tpu.obs.capacity import batching_headroom
    sweep = {}
    source = None
    try:
        with open(bench_path) as f:
            d = json.load(f)
        raw = (((d.get('result') or {}).get('sparse_dbp15k') or {})
               .get('pairs_sweep')) or {}
        sweep = {b: leg.get('step_ms_per_pair') for b, leg in raw.items()
                 if isinstance(leg, dict)
                 and leg.get('step_ms_per_pair')}
        if sweep:
            source = os.path.basename(bench_path)
    except (OSError, ValueError):
        pass
    if not sweep and mean_service_ms:
        sweep = {'1': mean_service_ms}
        source = 'serve mean_service_ms (no committed pairs_sweep)'
    hr = batching_headroom(sweep, target_qps=target_qps)
    if hr is not None:
        hr['seeded_from'] = source
    return hr


def qtrace_attribution(ok_rows):
    """The ``qtrace`` block from the clients' collected span accounts:
    end-to-end trace percentiles, per-stage p50/p95, the p95−p50 tail
    gap attributed to its dominant stage, span-tree coverage of the
    total, and the client-vs-server skew. ``None`` when no query
    carried a span tree (the unmeasured-account gate)."""
    traced = [r for r in ok_rows
              if r.get('stages_ms') and r.get('trace_ms') is not None]
    if not traced:
        return None
    totals = sorted(r['trace_ms'] for r in traced)
    stage_samples = {}
    for r in traced:
        for name, ms in r['stages_ms'].items():
            stage_samples.setdefault(name, []).append(ms)
    stage_p50, stage_p95, gap_by_stage = {}, {}, {}
    for name, vals in sorted(stage_samples.items()):
        vals.sort()
        stage_p50[name] = round(percentile(vals, 0.5), 3)
        stage_p95[name] = round(percentile(vals, 0.95), 3)
        gap_by_stage[name] = round(stage_p95[name] - stage_p50[name], 3)
    dominant = max(gap_by_stage, key=lambda s: gap_by_stage[s])
    coverage = sorted(sum(r['stages_ms'].values()) / r['trace_ms']
                      for r in traced if r['trace_ms'] > 0)
    skews = sorted(r['client_ms'] - r['trace_ms'] for r in traced
                   if r.get('client_ms') is not None)
    return {
        'traced_queries': len(traced),
        'trace_adopted': sum(1 for r in traced
                             if r.get('trace_adopted')),
        'p50_ms': round(percentile(totals, 0.5), 3),
        'p95_ms': round(percentile(totals, 0.95), 3),
        'p99_ms': round(percentile(totals, 0.99), 3),
        'stage_p50_ms': stage_p50,
        'stage_p95_ms': stage_p95,
        'gap_ms': round(percentile(totals, 0.95)
                        - percentile(totals, 0.5), 3),
        'gap_attribution_ms': gap_by_stage,
        'dominant_stage': dominant,
        'stage_sum_coverage_p50': (round(percentile(coverage, 0.5), 4)
                                   if coverage else None),
        'client_server_skew_p50_ms': (
            round(percentile(skews, 0.5), 3) if skews else None),
        'client_server_skew_p95_ms': (
            round(percentile(skews, 0.95), 3) if skews else None),
    }


def quality_account(ok_rows, serve_quality):
    """The round's ``quality`` block: per-query confidence
    distributions collected client-side (every 200 answer carries the
    engine's proxies beside ``stages_ms``) plus the worker's own
    serve-side account — ``low_confidence`` breaches and the
    shadow-audit evidence. The caller stamps ``hits1`` in afterwards
    (it owns the ground truth)."""
    samples = {}
    sat = []
    for r in ok_rows:
        q = r.get('quality') or {}
        for sig in ('entropy', 'margin', 'correction', 'saturation'):
            if q.get(sig) is not None:
                samples.setdefault(sig, []).append(float(q[sig]))
        if q.get('saturated_frac') is not None:
            sat.append(float(q['saturated_frac']))
    signals = {}
    for sig, vals in sorted(samples.items()):
        vals.sort()
        signals[sig] = {'mean': round(sum(vals) / len(vals), 6),
                        'p50': round(percentile(vals, 0.5), 6),
                        'p95': round(percentile(vals, 0.95), 6)}
    return {
        'signals': signals,
        'saturated_frac': (round(sum(sat) / len(sat), 6)
                           if sat else None),
        'low_confidence': serve_quality.get('low_confidence'),
        'audit': serve_quality.get('audit'),
    }


def read_worker_artifact(obs_root, name):
    """The worker's freshest on-disk copy of artifact ``name``
    (freshest attempt wins — the post-chaos worker's account). Reading
    from disk AFTER teardown means the graceful close's final flush
    has landed, so the numbers are complete, unlike a live ``/status``
    scrape racing the flush thread. Returns the parsed dict or
    ``None``."""
    dirs = [obs_root]
    try:
        dirs += [os.path.join(obs_root, d)
                 for d in sorted(os.listdir(obs_root))
                 if d.startswith('attempt_')]
    except OSError:
        pass
    best = None
    for d in dirs:
        path = os.path.join(d, name)
        try:
            mtime = os.path.getmtime(path)
            with open(path) as f:
                payload = json.load(f)
        except (OSError, ValueError):
            continue
        if best is None or mtime > best[0]:
            best = (mtime, payload)
    if best is None or not isinstance(best[1], dict):
        return None
    return best[1]


def read_worker_quality(obs_root):
    """The worker's drained ``quality.json`` ``serve`` block. See
    :func:`read_worker_artifact`: the graceful close drains the
    shadow-audit queue before the final flush, so the on-disk audit
    numbers are complete."""
    payload = read_worker_artifact(obs_root, 'quality.json')
    if payload is None:
        return {}
    return payload.get('serve') or {}


def slo_account(slo_payload):
    """The round's ``slo`` block from the worker's drained
    ``slo.json``: per-objective budget consumption, the worst
    fast-window burn rate, which burn pairs were alerting, and the
    breach counters. ``None`` when the worker never wrote the account
    (the unmeasured-SLO gate)."""
    if not slo_payload:
        return None
    objectives = {}
    worst_fast_burn = None
    alerting = []
    for name, obj in sorted((slo_payload.get('objectives')
                             or {}).items()):
        if not isinstance(obj, dict):
            continue
        burn = {}
        for wname, b in sorted((obj.get('burn') or {}).items()):
            if not isinstance(b, dict):
                continue
            burn[wname] = {'long': b.get('long'),
                           'short': b.get('short'),
                           'threshold': b.get('threshold'),
                           'alerting': bool(b.get('alerting'))}
            if b.get('alerting'):
                alerting.append(f'{name}:{wname}')
            if wname == 'fast' and b.get('long') is not None:
                worst_fast_burn = max(worst_fast_burn or 0.0,
                                      b['long'])
        objectives[name] = {
            'objective': obj.get('objective'),
            'bad_fraction': obj.get('window_bad_fraction'),
            'budget_consumed': obj.get('budget_consumed'),
            'events': obj.get('events'),
            'burn': burn,
        }
    breaches = slo_payload.get('breaches') or {}
    return {
        'spec': slo_payload.get('slo'),
        'objectives': objectives,
        'worst_fast_burn': worst_fast_burn,
        'alerting': sorted(alerting),
        'breach_counts': breaches.get('counts') or {},
        'floors': slo_payload.get('floors'),
    }


def anomaly_account(anomaly_payload):
    """The round's ``anomaly`` block from the worker's drained
    ``anomalies.json``: per-signal sample/spike/shift counters and the
    boundedness evidence (events vs capacity, truncation counter).
    ``None`` when the worker never wrote the account."""
    if not anomaly_payload:
        return None
    return {
        'capacity': anomaly_payload.get('capacity'),
        'events': len(anomaly_payload.get('events') or []),
        'truncated': anomaly_payload.get('truncated'),
        'signals': anomaly_payload.get('signals') or {},
    }


def main(argv=None):
    args = parse_args(argv)
    work = os.path.abspath(args.workdir)
    shutil.rmtree(work, ignore_errors=True)
    os.makedirs(work, exist_ok=True)
    obs_root = os.path.join(work, 'obs')
    ckpt_dir = os.path.join(work, 'ckpt')

    # The SLO spec the worker runs under (r05+): the bench pins the
    # default serve spec to disk so the round record's account is
    # reproducible from the committed defaults, and the worker tracks
    # budget/burn against exactly this file.
    slo_spec_path = os.path.join(work, 'slo_spec.json')
    with open(slo_spec_path, 'w') as f:
        json.dump(DEFAULT_SERVE_SPEC, f, indent=1)

    serve_cmd = [
        sys.executable, '-m', 'dgmc_tpu.serve', '--supervise',
        '--max-restarts', '3', '--restart-backoff', '0.2',
        '--ckpt_dir', ckpt_dir, '--init-missing',
        '--corpus-nodes', str(args.corpus_nodes),
        '--corpus-edges', str(args.corpus_edges),
        '--corpus-dim', str(args.corpus_dim),
        '--buckets', args.buckets,
        '--dim', str(args.dim), '--rnd_dim', str(args.rnd_dim),
        '--num_layers', str(args.num_layers),
        '--num_steps', str(args.num_steps), '--k', str(args.k),
        '--obs-dir', obs_root, '--obs-port', '0',
        '--slo', slo_spec_path,
        '--watchdog-deadline', '120',
        '--audit-sample', str(args.audit_sample),
        '--min-margin', str(args.min_margin),
        '--seed', str(args.seed),
    ] + (['--offload-corpus'] if args.offload_corpus else [])

    # Query pool: mixed bucket sizes, deterministic, ground truth known.
    corpus_x = synthetic_corpus(args.corpus_nodes, args.corpus_edges,
                                args.corpus_dim,
                                seed=args.seed).x
    shapes = []
    for part in args.buckets.split(','):
        n, e = part.split('x')
        shapes.append((int(n), int(e)))
    jobs = [[] for _ in range(args.clients)]
    for c in range(args.clients):
        for q in range(args.queries_per_client):
            n, e = shapes[(c + q) % len(shapes)]
            # Under-fill two of every three queries by a few nodes and
            # edges: the router still lands them in the same bucket
            # (smallest bucket ≥ shape), so the latency protocol is
            # unchanged, but the padding-waste plane gets real nonzero
            # pad fractions to account instead of the degenerate
            # exact-fill case.
            shrink = (c + q) % 3
            g, gt = sample_query(corpus_x, n - shrink, e - 2 * shrink,
                                 seed=args.seed + 1000 * c + q)
            jobs[c].append((query_payload(g), gt))
    probe_payload = jobs[0][0][0]

    print(f'# spawning: {" ".join(serve_cmd)}', file=sys.stderr,
          flush=True)
    t_spawn = time.perf_counter()
    sup = subprocess.Popen(serve_cmd)
    endpoint = Endpoint(obs_root)
    try:
        cold_s, pid_1 = wait_first_answer(endpoint, probe_payload,
                                          args.startup_timeout)
        cold_s = round(time.perf_counter() - t_spawn, 3)
        print(f'# cold startup -> first answer: {cold_s}s (worker pid '
              f'{pid_1})', file=sys.stderr, flush=True)
        health = get_json(endpoint.port, '/healthz')[1]
        gauges_cold = health.get('gauges') or {}

        c_warm = compile_events(endpoint.port)
        half = [j[:len(j) // 2] for j in jobs]
        rest = [j[len(j) // 2:] for j in jobs]
        res1, wall1 = run_clients(half, endpoint, trace_tag='p1')
        c_after_1 = compile_events(endpoint.port)

        # Chaos: SIGKILL the WORKER (not the supervisor) while phase-2
        # clients are actively issuing queries — the in-flight and
        # following queries retry through the restart window and must
        # land on the restarted worker (re-discovering its port).
        holder = {}
        progress = {'done': 0}
        n_phase2 = sum(len(j) for j in rest)
        # Pace phase-2 clients so the phase is still open when the kill
        # lands: every query before the kill answers normally, every one
        # after rides the retry loop through the restart.
        pace = max(0.05, 2.0 * (wall1 / max(sum(len(j) for j in half),
                                            1)))

        def phase2():
            holder['res'], holder['wall'] = run_clients(
                rest, endpoint, progress=progress, pace_s=pace,
                trace_tag='p2')

        th = threading.Thread(target=phase2)
        th.start()
        # Kill once a quarter of phase 2 has genuinely completed —
        # synchronized to real progress, not a sleep race.
        kill_after = max(1, n_phase2 // 4)
        t_wait = time.time() + 120
        while progress['done'] < kill_after and time.time() < t_wait \
                and th.is_alive():
            time.sleep(0.02)
        t_kill = time.perf_counter()
        os.kill(pid_1, signal.SIGKILL)
        print(f'# SIGKILL worker {pid_1} (mid-load)', file=sys.stderr,
              flush=True)
        warm_s, pid_2 = wait_first_answer(
            endpoint, probe_payload, args.startup_timeout,
            exclude_pid=pid_1)
        warm_s = round(time.perf_counter() - t_kill, 3)
        print(f'# warm restart -> first answer: {warm_s}s (worker pid '
              f'{pid_2})', file=sys.stderr, flush=True)
        health2 = get_json(endpoint.port, '/healthz')[1]
        gauges_warm = health2.get('gauges') or {}

        c_warm2 = compile_events(endpoint.port)
        th.join()
        res2, wall2 = holder['res'], holder['wall']
        c_after_2 = compile_events(endpoint.port)

        # Tracing-overhead phase: sequential alternating traced /
        # x-qtrace:off probes against the restarted (quiet) worker —
        # the traced-p50 penalty must stay under 5%.
        overhead = measure_overhead(endpoint, probe_payload)
        print(f'# tracing overhead: {overhead}', file=sys.stderr,
              flush=True)

        # Concurrency ramp (r04+): runs BEFORE the final /status scrape
        # so the capacity section's arrival/service account includes
        # the ramp's queries.
        ramp = concurrency_ramp(endpoint, corpus_x, shapes, args)

        status = get_json(endpoint.port, '/status')[1]
        health_code, health_final = get_json(endpoint.port, '/healthz')
        metrics_text = get_json(endpoint.port, '/metrics')[1]
        # Scrape evidence for out-of-band verification (the CI smoke
        # strict-parses the exposition and asserts the health verdict
        # without having to race the live process).
        with open(os.path.join(work, 'metrics.prom'), 'w') as f:
            f.write(metrics_text if isinstance(metrics_text, str)
                    else json.dumps(metrics_text))
        with open(os.path.join(work, 'healthz.json'), 'w') as f:
            json.dump({'code': health_code, 'payload': health_final}, f,
                      indent=1)

        # Graceful teardown: TERM the worker -> rc 0 -> the supervisor
        # records 'completed' and exits 0 itself.
        os.kill(pid_2, signal.SIGTERM)
        rc = sup.wait(timeout=60)
    finally:
        if sup.poll() is None:
            sup.terminate()
            try:
                sup.wait(timeout=20)
            except subprocess.TimeoutExpired:
                sup.kill()

    with open(os.path.join(obs_root, 'recovery.json')) as f:
        recovery = json.load(f)

    flat = [r for res in (res1, res2) for c in res for r in c]
    ok = [r for r in flat if not r.get('failed')]
    qtrace_block = qtrace_attribution(ok)
    if qtrace_block is not None:
        qtrace_block['overhead'] = overhead
    quality_block = quality_account(ok, read_worker_quality(obs_root))
    slo_block = slo_account(read_worker_artifact(obs_root, 'slo.json'))
    anomaly_block = anomaly_account(
        read_worker_artifact(obs_root, 'anomalies.json'))
    lats = sorted(r['latency_s'] for r in ok)
    server_ms = sorted(r['server_ms'] for r in ok
                       if r.get('server_ms') is not None)
    hits = sum(r['hits'] for r in ok)
    total_gt = sum(r['n'] for r in ok)
    quality_block['hits1'] = (round(hits / total_gt, 4)
                              if total_gt else None)
    steps = (status.get('steps') or {})
    cap_live = (status.get('capacity') or {}) if isinstance(status, dict) \
        else {}
    knee = ramp.get('knee') or {}
    headroom = batching_headroom_block(
        target_qps=knee.get('qps'),
        mean_service_ms=cap_live.get('mean_service_ms'))
    compiles_load = ((c_after_1 - c_warm)
                     if None not in (c_after_1, c_warm) else None)
    compiles_load_2 = ((c_after_2 - c_warm2)
                       if None not in (c_after_2, c_warm2) else None)

    record = {
        'family': 'SERVE',
        'round': args.round,
        'tool': 'serve_bench.py',
        'time_unix': round(time.time(), 1),
        'cmd': serve_cmd,
        'config': {
            'corpus_nodes': args.corpus_nodes,
            'corpus_edges': args.corpus_edges,
            'corpus_dim': args.corpus_dim,
            'buckets': args.buckets,
            'dim': args.dim, 'rnd_dim': args.rnd_dim,
            'num_layers': args.num_layers,
            'num_steps': args.num_steps, 'k': args.k,
            'offload_corpus': bool(args.offload_corpus),
        },
        'clients': args.clients,
        'queries': len(ok),
        'queries_failed': len(flat) - len(ok),
        # Headline QPS is the UNINTERRUPTED phase (phase 2 deliberately
        # absorbs a worker kill + restart and is paced; its effective
        # rate is reported separately as the availability figure).
        'qps': round(sum(len(c) for c in res1)
                     / max(wall1, 1e-9), 2),
        'qps_through_restart': round(
            sum(len(c) for c in res2) / max(wall2, 1e-9), 2),
        'load_wall_s': round(wall1 + wall2, 3),
        'latency': {
            'server_p50_ms': (round(percentile(server_ms, 0.5), 3)
                              if server_ms else None),
            'server_p95_ms': (round(percentile(server_ms, 0.95), 3)
                              if server_ms else None),
            'client_p50_ms': (round(percentile(lats, 0.5) * 1e3, 3)
                              if lats else None),
            'client_p95_ms': (round(percentile(lats, 0.95) * 1e3, 3)
                              if lats else None),
            'observer_step_p50_ms': (
                round(steps['p50_s'] * 1e3, 3)
                if steps.get('p50_s') else None),
            'observer_step_p95_ms': (
                round(steps['p95_s'] * 1e3, 3)
                if steps.get('p95_s') else None),
        },
        'hits_at_1': round(hits / total_gt, 4) if total_gt else None,
        'quality': quality_block,
        'qtrace': qtrace_block,
        # The capacity/goodput plane (r04+): the measured
        # QPS-vs-concurrency ramp with its knee, the queueing model
        # scraped from the drained worker's /status capacity section,
        # and the padding-waste account for both the served queries and
        # the host-side collation sweep.
        'ramp': ramp,
        'capacity': {
            'arrival_qps': cap_live.get('arrival_qps'),
            'mean_service_ms': cap_live.get('mean_service_ms'),
            'saturation_qps': cap_live.get('saturation_qps'),
            'utilization': cap_live.get('utilization'),
            'projected_wait_ms': cap_live.get('projected_wait_ms'),
            'lock_wait_ms': cap_live.get('lock_wait_ms'),
            'lock_hold_ms': cap_live.get('lock_hold_ms'),
            'admission_reconciliation': cap_live.get(
                'admission_reconciliation'),
            'knee': ramp.get('knee'),
            'batching_headroom': headroom,
        },
        'goodput': {
            'serve': {
                'goodput_ratio': cap_live.get('goodput_ratio'),
                'pad_fraction': cap_live.get('pad_fraction'),
                'buckets': cap_live.get('buckets'),
            },
            'pairs_sweep': collation_goodput(shapes, args.corpus_dim,
                                             seed=args.seed),
        },
        # The SLO/error-budget and anomaly planes (r05+): scraped from
        # the drained worker's slo.json / anomalies.json — the
        # error-budget account the worker kept live against the spec
        # the bench pinned, and the streaming watch's spike/shift
        # counters with the bounded-ring evidence.
        'slo': slo_block,
        'anomaly': anomaly_block,
        'restart': {
            'cold_first_answer_s': cold_s,
            'warm_first_answer_s': warm_s,
            'warm_beats_cold': warm_s < cold_s,
            'cold_cache_hit': int(gauges_cold.get('corpus_cache_hit',
                                                  -1)),
            'warm_cache_hit': int(gauges_warm.get('corpus_cache_hit',
                                                  -1)),
            'killed_pid': pid_1,
            'restarted_pid': pid_2,
        },
        'compiles': {
            'warmup': c_warm,
            'during_load_phase1': compiles_load,
            'warmup_after_restart': c_warm2,
            'during_load_phase2': compiles_load_2,
            'per_query': (None if None in (compiles_load,
                                           compiles_load_2)
                          else (compiles_load + compiles_load_2)
                          / max(len(ok), 1)),
        },
        'supervision': {
            'outcome': recovery.get('outcome'),
            'restarts': recovery.get('restarts'),
            'supervisor_rc': rc,
        },
        'metrics_endpoint_bytes': (len(metrics_text)
                                   if isinstance(metrics_text, str)
                                   else None),
        'healthz_code': health_code,
    }

    problems = []
    if record['supervision']['outcome'] != 'completed':
        problems.append(f"outcome {record['supervision']['outcome']}")
    if record['supervision']['restarts'] != 1:
        problems.append(f"restarts {record['supervision']['restarts']}")
    if record['restart']['warm_cache_hit'] != 1:
        problems.append('warm restart did not hit the corpus cache')
    if record['restart']['cold_cache_hit'] != 0:
        problems.append('cold start unexpectedly hit a cache')
    if not record['restart']['warm_beats_cold']:
        problems.append(f'warm {warm_s}s did not beat cold {cold_s}s')
    if compiles_load is None or compiles_load_2 is None:
        # A failed /status scrape means the compile account was never
        # MEASURED — that must read as a failed gate, not as zero.
        problems.append(f'compile account unmeasured (phase1 '
                        f'{compiles_load}, phase2 {compiles_load_2}: '
                        f'a compile-counter scrape failed)')
    elif compiles_load or compiles_load_2:
        problems.append(f'per-query compiles: {compiles_load} + '
                        f'{compiles_load_2} after warmup')
    if record['queries_failed']:
        problems.append(f"{record['queries_failed']} queries failed")
    if qtrace_block is None:
        problems.append('qtrace account unmeasured (no query returned '
                        'a span tree)')
    else:
        # The span tree must COVER the traced end-to-end total: the
        # untimed remainder (HTTP body parse, dispatch glue) is bounded
        # by tolerance; a sum past the total is a broken clock.
        cov = qtrace_block['stage_sum_coverage_p50']
        if cov is None or not (0.70 <= cov <= 1.02):
            problems.append(f'stage sums do not cover the traced '
                            f'total (p50 coverage {cov})')
        if qtrace_block['trace_adopted'] \
                < qtrace_block['traced_queries']:
            problems.append(
                f"server adopted only "
                f"{qtrace_block['trace_adopted']}/"
                f"{qtrace_block['traced_queries']} client trace ids")
        frac = (qtrace_block.get('overhead') or {}).get('overhead_frac')
        if frac is None:
            problems.append('tracing overhead unmeasured')
        elif frac >= 0.05:
            problems.append(f'tracing overhead {frac:.1%} >= 5% '
                            f'on p50')
    if not ramp.get('levels') or ramp.get('knee') is None:
        problems.append('concurrency ramp unmeasured (no QPS-vs-'
                        'concurrency curve)')
    elif any(r.get('failed') for r in ramp['levels']):
        problems.append('ramp queries failed')
    if record['goodput']['serve'].get('goodput_ratio') is None:
        problems.append('serve goodput unmeasured (the capacity plane '
                        'never reported a ratio)')
    if any(v is None
           for v in record['goodput']['pairs_sweep'].values()):
        problems.append('collation pairs_sweep goodput unmeasured')
    if record['capacity'].get('admission_reconciliation') is None:
        problems.append('lock-wait vs qtrace admission_queue_wait '
                        'reconciliation unmeasured')
    if slo_block is None or not slo_block.get('objectives'):
        problems.append('slo account unmeasured (the worker wrote no '
                        'slo.json despite --slo)')
    else:
        avail = slo_block['objectives'].get('availability') or {}
        if avail.get('budget_consumed') is None:
            problems.append('slo availability budget never measured '
                            '(no events reached the tracker)')
        if not avail.get('events'):
            problems.append('slo availability objective saw zero '
                            'events during the load phases')
    if anomaly_block is None:
        problems.append('anomaly account unmeasured (the worker wrote '
                        'no anomalies.json)')
    else:
        cap = anomaly_block.get('capacity') or 0
        if anomaly_block['events'] > cap:
            problems.append(f"anomaly ring unbounded: "
                            f"{anomaly_block['events']} events > "
                            f"capacity {cap}")
        if anomaly_block.get('truncated') is None:
            problems.append('anomaly ring truncation counter missing')
        watched = (anomaly_block.get('signals') or {})
        if not watched.get('query_latency_s', {}).get('samples'):
            problems.append('anomaly watch never saw query_latency_s '
                            '(the per-query feed is dead)')
    for fam in ('dgmc_inflight', 'dgmc_pad_fraction',
                'dgmc_goodput_ratio', 'dgmc_lock_wait_seconds',
                'dgmc_lock_hold_seconds',
                'dgmc_slo_error_budget_consumed', 'dgmc_slo_burn_rate',
                'dgmc_anomaly_spikes_total'):
        if not isinstance(metrics_text, str) \
                or f'# TYPE {fam} ' not in metrics_text:
            problems.append(f'metric family {fam} missing from '
                            f'/metrics')
    if quality_block['saturated_frac'] is None:
        problems.append('confidence plane unmeasured (no answer '
                        'carried a quality block)')
    audit = quality_block.get('audit') or {}
    if args.audit_sample > 0:
        if not audit.get('audited'):
            problems.append('shadow audit unmeasured (audit enabled '
                            'but no query was re-scored)')
        elif audit.get('recall_min') != 1.0:
            # Both shortlist tiers are bit-exact against the exhaustive
            # scan, so any recall below 1.0 is a correctness bug.
            problems.append(f"shadow-audit recall_min "
                            f"{audit.get('recall_min')} != 1.0 against "
                            f"the exhaustive reference")
    record['outcome'] = ('completed' if not problems
                         else f'failed ({"; ".join(problems)})')

    out = json.dumps(record, indent=1)
    print(out)
    if args.out:
        with open(args.out, 'w') as f:
            f.write(out + '\n')
        print(f'# wrote {args.out}', file=sys.stderr)
    return 0 if not problems else 1


if __name__ == '__main__':
    sys.exit(main())
