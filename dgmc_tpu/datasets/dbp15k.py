"""DBP15K cross-lingual knowledge-graph alignment dataset.

Capability parity with PyG's ``DBP15K`` as consumed by the reference
(reference ``examples/dbp15k.py:5,27``): per language pair
(``zh_en``/``ja_en``/``fr_en``) two KGs of ~15-20k entities each, per-entity
word-embedding features, directed relation edges, and train/test alignment
pairs. The reference's ``SumEmbedding`` transform sums each entity's word
vectors (reference ``examples/dbp15k.py:19-22``).

This loader parses the standard raw layout (JAPE/DBP15K release):

    <root>/<pair>/triples_1, triples_2        head rel tail (tab-separated)
    <root>/<pair>/ent_ids_1, ent_ids_2        global-id <tab> uri
    <root>/<pair>/sup_pairs | sup_ent_ids     train alignments (id1 id2)
    <root>/<pair>/ref_pairs | ref_ent_ids     test alignments
    <root>/<pair>/<lang>_vectorList.json      per-entity feature vectors
                                              (list indexed by global id),
    or precomputed ``x1.npy`` / ``x2.npy`` caches in the same directory.

No network access is assumed: if the raw files are missing the loader
raises with instructions rather than downloading.
"""

import json
import os

import numpy as np

from dgmc_tpu.utils.data import Graph

PAIRS = ('zh_en', 'ja_en', 'fr_en')


def _read_pairs(path):
    out = []
    with open(path) as f:
        for line in f:
            a, b = line.split()[:2]
            out.append((int(a), int(b)))
    return out


def _read_triples(path):
    out = []
    with open(path) as f:
        for line in f:
            h, r, t = line.split()[:3]
            out.append((int(h), int(r), int(t)))
    return out


def _read_ids(path):
    ids = []
    with open(path) as f:
        for line in f:
            ids.append(int(line.split()[0]))
    return ids


class DBP15K:
    """One language pair of DBP15K.

    Attributes after construction:
        x1, x2: ``[N, W, D]`` float32 per-entity word vectors (W >= 1).
        edge_index1, edge_index2: ``[2, E]`` int64 directed edges.
        rel1, rel2: ``[E]`` int64 relation types.
        train_y, test_y: ``[2, M]`` int64 alignment pairs in *local* indices.
    """

    def __init__(self, root, pair, download=False):
        if pair not in PAIRS:
            raise ValueError(f'pair must be one of {PAIRS}, got {pair!r}')
        self.root = os.path.expanduser(root)
        self.pair = pair
        d = os.path.join(self.root, pair)
        if not os.path.isdir(d):
            if download:
                from dgmc_tpu.datasets.download import download_and_extract
                download_and_extract('dbp15k', self.root)
                for sub in ('DBP15K', 'DBP15k'):  # flatten release nesting
                    nested = os.path.join(self.root, sub, pair)
                    if not os.path.isdir(d) and os.path.isdir(nested):
                        d = nested
            if not os.path.isdir(d):
                raise FileNotFoundError(
                    f'DBP15K raw data not found at {d}. Download the '
                    f'DBP15K (JAPE) release and extract it so that '
                    f'{d}/triples_1 exists, or pass download=True on a '
                    f'networked machine.')
        self._load(d)

    def _load(self, d):
        triples1 = _read_triples(os.path.join(d, 'triples_1'))
        triples2 = _read_triples(os.path.join(d, 'triples_2'))
        ids1 = _read_ids(os.path.join(d, 'ent_ids_1'))
        ids2 = _read_ids(os.path.join(d, 'ent_ids_2'))

        self.g2l_1 = {g: i for i, g in enumerate(ids1)}
        self.g2l_2 = {g: i for i, g in enumerate(ids2)}

        def localize(triples, g2l):
            e = np.array([(g2l[h], g2l[t]) for h, _, t in triples
                          if h in g2l and t in g2l], np.int64).T
            r = np.array([r for h, r, t in triples
                          if h in g2l and t in g2l], np.int64)
            if e.size == 0:
                e = np.zeros((2, 0), np.int64)
            return e, r

        self.edge_index1, self.rel1 = localize(triples1, self.g2l_1)
        self.edge_index2, self.rel2 = localize(triples2, self.g2l_2)

        def read_split(names):
            for n in names:
                p = os.path.join(d, n)
                if os.path.exists(p):
                    pairs = _read_pairs(p)
                    return np.array(
                        [(self.g2l_1[a], self.g2l_2[b]) for a, b in pairs
                         if a in self.g2l_1 and b in self.g2l_2],
                        np.int64).T
            raise FileNotFoundError(f'none of {names} found in {d}')

        self.train_y = read_split(['sup_pairs', 'sup_ent_ids'])
        self.test_y = read_split(['ref_pairs', 'ref_ent_ids'])

        self.x1 = self._features(d, self.pair.split('_')[0], ids1, 'x1')
        self.x2 = self._features(d, self.pair.split('_')[1], ids2, 'x2')

    def _features(self, d, lang, ids, cache_name):
        cache = os.path.join(d, f'{cache_name}.npy')
        if os.path.exists(cache):
            x = np.load(cache).astype(np.float32)
        else:
            vec_path = os.path.join(d, f'{lang}_vectorList.json')
            if not os.path.exists(vec_path):
                vec_path = os.path.join(d, 'vectorList.json')
            if not os.path.exists(vec_path):
                raise FileNotFoundError(
                    f'no entity features: expected {cache} or a '
                    f'vectorList.json in {d}')
            with open(vec_path) as f:
                vecs = np.asarray(json.load(f), np.float32)
            x = vecs[np.asarray(ids)]
        if x.ndim == 2:           # one vector per entity -> W = 1
            x = x[:, None, :]
        return x

    @property
    def num_nodes1(self):
        return self.x1.shape[0]

    @property
    def num_nodes2(self):
        return self.x2.shape[0]

    def graphs(self, sum_embedding=True):
        """The two KGs as host :class:`Graph` objects (features summed over
        the word axis when ``sum_embedding``, like the reference transform at
        ``examples/dbp15k.py:19-22``)."""
        def build(x, e):
            feats = x.sum(axis=1) if sum_embedding else x
            return Graph(edge_index=e, x=feats.astype(np.float32))
        return build(self.x1, self.edge_index1), \
            build(self.x2, self.edge_index2)

    def __repr__(self):
        return (f'DBP15K({self.pair}, N1={self.num_nodes1}, '
                f'N2={self.num_nodes2})')
