"""PascalVOC-with-Berkeley-keypoints dataset.

Capability parity with PyG's ``PascalVOCKeypoints`` as consumed by the
reference (reference ``examples/pascal.py:5,31-41``): 20 VOC categories;
each sample is one object instance with its Berkeley keypoint annotations,
cropped to the object bounding box; node features are VGG16 activations at
the keypoints (``dgmc_tpu/datasets/features.py``); ``y`` holds the keypoint
*class* index within the category's keypoint vocabulary (what
``ValidPairDataset`` matches on, reference ``dgmc/utils/data.py:82-117``).

Expected raw layout (no downloads attempted):

    <root>/annotations/<category>/*.xml    Berkeley keypoint annotations:
        <annotation><image>...</image>
          <visible_bounds xmin= xmax= ymin= ymax=/>
          <keypoints><keypoint name= x= y= visible=/>...</keypoints>
        </annotation>
    <root>/images/*.jpg                    VOC images (optional; zeros
                                           otherwise)
    <root>/ImageSets/Main/<category>_{train,val}.txt
                                           official VOC split lists
                                           (``image_id [label]`` lines;
                                           label -1 = excluded). A plain id
                                           list at <root>/splits/<category>_
                                           {train,val}.txt also works. When
                                           neither exists, a deterministic
                                           fraction split is used with a
                                           warning (not the official
                                           protocol).
"""

import glob
import os
import xml.etree.ElementTree as ET

import numpy as np

from dgmc_tpu.utils.data import Graph

CATEGORIES = ('aeroplane', 'bicycle', 'bird', 'boat', 'bottle', 'bus', 'car',
              'cat', 'chair', 'cow', 'diningtable', 'dog', 'horse',
              'motorbike', 'person', 'pottedplant', 'sheep', 'sofa', 'train',
              'tvmonitor')


def _parse_annotation(path):
    tree = ET.parse(path)
    root = tree.getroot()
    image = root.findtext('image', default='').strip()
    vb = root.find('visible_bounds')
    bounds = None
    if vb is not None:
        x0 = float(vb.get('xmin', 0))
        y0 = float(vb.get('ymin', 0))
        # Berkeley annotations carry width/height; tolerate xmax/ymax too.
        if vb.get('width') is not None:
            x1 = x0 + float(vb.get('width'))
            y1 = y0 + float(vb.get('height', 0))
        else:
            x1 = float(vb.get('xmax', x0))
            y1 = float(vb.get('ymax', y0))
        bounds = (x0, y0, x1, y1)
    kps = []
    kp_root = root.find('keypoints')
    if kp_root is not None:
        for kp in kp_root.findall('keypoint'):
            visible = kp.get('visible', '1')
            if visible in ('0', 'false', 'False'):
                continue
            kps.append((kp.get('name'),
                        float(kp.get('x')), float(kp.get('y'))))
    return image, bounds, kps


class PascalVOCKeypoints:
    """One category of PascalVOC keypoint instances."""

    def __init__(self, root, category, train=True, transform=None,
                 pre_filter=None, features=None, device_features=None,
                 train_fraction=0.8, download=False):
        if category not in CATEGORIES:
            raise ValueError(f'unknown category {category!r}')
        self.root = os.path.expanduser(root)
        self.category = category
        self.transform = transform
        if features is None:
            from dgmc_tpu.datasets.features import VGG16Features
            features = VGG16Features(weights=device_features or 'random')
        self.features = features

        ann_dir = os.path.join(self.root, 'annotations', category)
        if not os.path.isdir(ann_dir) and download:
            from dgmc_tpu.datasets.download import download_and_extract
            download_and_extract('voc_keypoints', self.root)
            download_and_extract('voc2011', self.root)
            self._normalize_download_layout()
        if not os.path.isdir(ann_dir):
            raise FileNotFoundError(
                f'Berkeley keypoint annotations not found at {ann_dir}; '
                f'place them there, or pass download=True on a networked '
                f'machine.')

        # The keypoint-name vocabulary of this category, fixed by sorted
        # first appearance across the split — the class index ValidPairDataset
        # matches on.
        paths = sorted(glob.glob(os.path.join(ann_dir, '*.xml')))
        names = set()
        parsed = []
        for p in paths:
            image, bounds, kps = _parse_annotation(p)
            parsed.append((p, image, bounds, kps))
            names.update(n for n, _, _ in kps)
        self.keypoint_names = sorted(names)
        name_to_class = {n: i for i, n in enumerate(self.keypoint_names)}

        # Split: prefer the official VOC image-id lists (what PyG's
        # PascalVOCKeypoints uses, so accuracies are comparable to the
        # reference, reference ``examples/pascal.py:31-38``); fall back to a
        # deterministic fraction split over instances only when no lists are
        # present — that fallback is NOT the official protocol and may put
        # instances of one image in both splits.
        split_ids = self._load_split_ids(train)
        if split_ids is not None:
            kept = [rec for rec in parsed if rec[1] in split_ids]
            if parsed and split_ids and not kept:
                raise ValueError(
                    f'split list for {category!r} matched 0 of '
                    f'{len(parsed)} annotated instances — the list ids do '
                    f'not correspond to the annotations\' <image> fields '
                    f'(wrong VOC year, or ids carry file suffixes?)')
            parsed = kept
        else:
            import warnings
            warnings.warn(
                f'No official split list found for {category!r} under '
                f'{self.root}/ImageSets/Main; using a {train_fraction:.0%} '
                f'fraction split — results are not comparable to the '
                f'reference protocol.', stacklevel=2)
            n_train = int(len(parsed) * train_fraction)
            parsed = parsed[:n_train] if train else parsed[n_train:]

        # VGG features are expensive (one forward per instance); cache them
        # on disk keyed by the weight source, like the reference's processed
        # files (PyG PascalVOCKeypoints caches its VGG features the same
        # way).
        cache = self._feature_cache(category)

        self._graphs = []
        dirty = False
        for p, image, bounds, kps in parsed:
            if not kps:
                continue
            pts = np.array([(x, y) for _, x, y in kps], np.float64)
            y = np.array([name_to_class[n] for n, _, _ in kps], np.int64)
            # Skip instances with duplicate keypoint classes (cannot define
            # a bijective ground truth).
            if len(np.unique(y)) != len(y):
                continue
            name = os.path.splitext(os.path.basename(p))[0]
            if bounds is not None:
                x0, y0, x1, y1 = bounds
            else:
                (x0, y0), (x1, y1) = pts.min(axis=0), pts.max(axis=0)
            local = pts - np.array([x0, y0])
            if name in cache:
                x = cache[name]
            else:
                # Crop the instance to its (slightly padded) bounding box so
                # keypoints are well separated on the conv feature maps —
                # the reference pipeline's crop-to-bbox preprocessing.
                img = self._image(image)
                h, w = img.shape[:2]
                pad = 0.05 * max(x1 - x0, y1 - y0)
                cx0 = int(max(0, np.floor(x0 - pad)))
                cy0 = int(max(0, np.floor(y0 - pad)))
                cx1 = int(min(w, np.ceil(x1 + pad))) or w
                cy1 = int(min(h, np.ceil(y1 + pad))) or h
                if cx1 > cx0 and cy1 > cy0:
                    crop = img[cy0:cy1, cx0:cx1]
                    crop_pts = pts - np.array([cx0, cy0])
                else:
                    crop, crop_pts = img, pts
                x = self.features(crop, crop_pts)
                cache[name] = x
                dirty = True
            g = Graph(edge_index=np.zeros((2, 0), np.int64), x=x,
                      pos=local.astype(np.float32), y=y, name=name)
            if pre_filter is not None and not pre_filter(g):
                continue
            self._graphs.append(g)
        if dirty:
            self._save_feature_cache(category, cache)

    def _load_split_ids(self, train):
        """Image ids from the official VOC split lists, if present.

        Looks for ``<root>/ImageSets/Main/<category>_{train,val}.txt`` (VOC
        layout: ``image_id [label]`` lines, label -1 meaning the category is
        absent) or a plain id list at ``<root>/splits/<category>_*.txt``.
        Returns None when neither exists.
        """
        name = 'train' if train else 'val'
        candidates = [
            os.path.join(self.root, 'ImageSets', 'Main',
                         f'{self.category}_{name}.txt'),
            os.path.join(self.root, 'splits', f'{self.category}_{name}.txt'),
        ]
        for path in candidates:
            if not os.path.exists(path):
                continue
            ids = set()
            with open(path) as f:
                for line in f:
                    parts = line.split()
                    if not parts or (len(parts) >= 2 and parts[1] == '-1'):
                        continue
                    ids.add(parts[0])
            return ids
        return None

    def _feature_cache(self, category):
        tag = getattr(self.features, 'tag', None)
        if not tag or tag == 'none':
            self._cache_path = None
            return {}
        d = os.path.join(self.root, 'processed')
        self._cache_path = os.path.join(d, f'{category}_{tag}.npz')
        if os.path.exists(self._cache_path):
            with np.load(self._cache_path) as z:
                return {k: z[k] for k in z.files}
        return {}

    def _save_feature_cache(self, category, cache):
        if self._cache_path is None:
            return
        os.makedirs(os.path.dirname(self._cache_path), exist_ok=True)
        np.savez(self._cache_path, **cache)

    def _normalize_download_layout(self):
        """Map freshly extracted archives onto the layout this loader
        reads: the VOC tar unpacks as ``TrainVal/VOCdevkit/VOC2011/...``
        and the Berkeley tgz may nest its ``annotations`` dir — locate
        ``JPEGImages`` / ``ImageSets/Main`` / ``annotations`` wherever
        they landed and symlink them to ``<root>/{images,ImageSets,
        annotations}``."""
        wanted = {'images': 'JPEGImages', 'ImageSets': 'ImageSets',
                  'annotations': 'annotations'}
        for link_name, dir_name in wanted.items():
            link = os.path.join(self.root, link_name)
            if os.path.exists(link):
                continue
            for cur, dirs, _ in os.walk(self.root):
                if os.path.basename(cur) == dir_name and cur != link:
                    os.symlink(os.path.abspath(cur), link)
                    break

    def _image(self, image_name):
        from PIL import Image
        for ext in ('.jpg', '.png'):
            p = os.path.join(self.root, 'images', image_name + ext)
            if os.path.exists(p):
                return np.asarray(Image.open(p).convert('RGB'))
        # Warn once — but only when visual features are actually being
        # extracted (weights='none' is deliberate structure-only mode).
        if (not getattr(self, '_warned_missing_images', False)
                and getattr(self.features, 'tag', None) != 'none'):
            self._warned_missing_images = True
            import warnings
            warnings.warn(
                f'no image found for {image_name!r} under '
                f'{os.path.join(self.root, "images")}; visual features '
                f'will be extracted from ZERO images (structure-only '
                f'training). Place the VOC JPEGImages there to fix.')
        return np.zeros((256, 256, 3), np.uint8)

    def __len__(self):
        return len(self._graphs)

    def __getitem__(self, idx):
        g = self._graphs[idx]
        return self.transform(g) if self.transform else g

    @property
    def num_node_features(self):
        return self._graphs[0].x.shape[1]

    def __repr__(self):
        return (f'PascalVOCKeypoints({self.category}, {len(self)}, '
                f'kps={len(self.keypoint_names)})')
