from dgmc_tpu.datasets.dbp15k import DBP15K
from dgmc_tpu.datasets.pascal_pf import PascalPF
from dgmc_tpu.datasets.willow import WILLOWObjectClass
from dgmc_tpu.datasets.pascal_voc import PascalVOCKeypoints
from dgmc_tpu.datasets.features import VGG16Features
from dgmc_tpu.datasets.convert_vgg import convert_checkpoint

__all__ = [
    'convert_checkpoint',
    'DBP15K',
    'PascalPF',
    'WILLOWObjectClass',
    'PascalVOCKeypoints',
    'VGG16Features',
]
