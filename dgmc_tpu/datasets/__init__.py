from dgmc_tpu.datasets.dbp15k import DBP15K
from dgmc_tpu.datasets.pascal_pf import PascalPF
from dgmc_tpu.datasets.willow import WILLOWObjectClass
from dgmc_tpu.datasets.pascal_voc import PascalVOCKeypoints
from dgmc_tpu.datasets.features import VGG16Features

__all__ = [
    'DBP15K',
    'PascalPF',
    'WILLOWObjectClass',
    'PascalVOCKeypoints',
    'VGG16Features',
]
