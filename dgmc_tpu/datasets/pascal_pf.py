"""PascalPF (Proposal Flow) keypoint-pair dataset.

Capability parity with PyG's ``PascalPF`` as consumed by the reference
(reference ``examples/pascal_pf.py:8,74``): per category, keypoint sets
read from the ``PF-dataset-PASCAL`` annotation ``.mat`` files, normalized
into ``[-1, 1]``, plus the official evaluation pair list from
``parsePascalVOC.mat``. Used zero-shot at test time, one pair at a time
(reference ``examples/pascal_pf.py:115-123``).

Expected raw layout (no downloads are attempted):

    <root>/PF-dataset-PASCAL/Annotations/<category>/*.mat   (kps [M, 2|3])
    <root>/PF-dataset-PASCAL/parsePascalVOC.mat             (pair list)
"""

import glob
import os

import numpy as np

from dgmc_tpu.utils.data import Graph

CATEGORIES = ('aeroplane', 'bicycle', 'bird', 'boat', 'bottle', 'bus', 'car',
              'cat', 'chair', 'cow', 'diningtable', 'dog', 'horse',
              'motorbike', 'person', 'pottedplant', 'sheep', 'sofa', 'train',
              'tvmonitor')


class PascalPF:
    """One category of PascalPF: normalized keypoint clouds + test pairs.

    ``self.items`` maps image name -> ``Graph`` (``pos`` only — graphs are
    built by a transform, e.g. KNN, exactly as the reference applies its
    transform pipeline at reference ``examples/pascal_pf.py:68-74``);
    ``self.pairs`` is a list of (name_s, name_t) evaluation pairs.
    """

    def __init__(self, root, category, transform=None, download=False):
        if category not in CATEGORIES:
            raise ValueError(f'unknown category {category!r}')
        self.root = os.path.expanduser(root)
        self.category = category
        self.transform = transform
        base = os.path.join(self.root, 'PF-dataset-PASCAL')
        if not os.path.isdir(base) and download:
            from dgmc_tpu.datasets.download import download_and_extract
            download_and_extract('pascal_pf', self.root)
        if not os.path.isdir(base):
            raise FileNotFoundError(
                f'PascalPF raw data not found at {base}; place the '
                f'PF-dataset-PASCAL release there, or pass download=True '
                f'on a networked machine.')
        self._load(base)

    def _load(self, base):
        from scipy.io import loadmat
        ann = os.path.join(base, 'Annotations', self.category)
        self.items = {}
        for path in sorted(glob.glob(os.path.join(ann, '*.mat'))):
            m = loadmat(path)
            kps = np.asarray(m['kps'], np.float32)[:, :2]
            keep = ~np.isnan(kps).any(axis=1)
            kps = kps[keep]
            if kps.shape[0] == 0:
                continue
            # Normalize into [-1, 1] per item, preserving aspect.
            center = (kps.max(0) + kps.min(0)) / 2
            scale = (kps.max(0) - kps.min(0)).max() / 2
            pos = (kps - center) / max(scale, 1e-6)
            name = os.path.splitext(os.path.basename(path))[0]
            # Keypoint identity index: row i in source matches row i in
            # target for same-category PF pairs (the reference evaluates
            # y = arange, reference examples/pascal_pf.py:121-122).
            self.items[name] = Graph(edge_index=np.zeros((2, 0), np.int64),
                                     pos=pos, y=np.arange(len(pos)),
                                     name=name)

        pairs_file = os.path.join(base, 'parsePascalVOC.mat')
        self.pairs = []
        if os.path.exists(pairs_file):
            m = loadmat(pairs_file, simplify_cells=True)
            entry = m['PascalVOC']
            cat_idx = list(entry['class']).index(self.category)
            pair_arr = np.asarray(entry['pair'][cat_idx], dtype=object)
            # simplify_cells squeezes aggressively: a single pair may come
            # back as a flat [2] array of name strings rather than a [1, 2]
            # row list — renormalize to rows of two names.
            if pair_arr.ndim == 1 and pair_arr.size == 2 and \
                    all(isinstance(v, str) for v in pair_arr):
                pair_arr = pair_arr[None, :]
            for row in np.atleast_1d(pair_arr):
                row = np.atleast_1d(np.asarray(row, dtype=object))
                if row.size < 2:
                    continue
                a, b = str(row[0]), str(row[1])
                if a in self.items and b in self.items:
                    self.pairs.append((a, b))
        if not self.pairs:
            # No pair list (or none resolvable): consecutive same-category
            # pairs.
            names = sorted(self.items)
            self.pairs = [(names[i], names[i + 1])
                          for i in range(len(names) - 1)]

    def get(self, name):
        g = self.items[name]
        return self.transform(g) if self.transform else g

    def pair_graphs(self):
        """Yield (graph_s, graph_t, y_col) for every evaluation pair; the
        ground truth matches keypoint i to keypoint i (both PF items of a
        category index the same keypoint set)."""
        for a, b in self.pairs:
            g_s, g_t = self.get(a), self.get(b)
            n = min(g_s.pos.shape[0], g_t.pos.shape[0])
            yield g_s, g_t, np.arange(n, dtype=np.int64)

    def __len__(self):
        return len(self.items)

    def __repr__(self):
        return (f'PascalPF({self.category}, items={len(self.items)}, '
                f'pairs={len(self.pairs)})')
