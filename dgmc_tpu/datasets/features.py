"""Keypoint visual-feature extraction (the WILLOW / PascalVOC node features).

The reference's keypoint datasets (PyG ``WILLOWObjectClass`` /
``PascalVOCKeypoints``, consumed at reference ``examples/willow.py:7-8``,
``examples/pascal.py:5``) attach, to every keypoint, VGG16 features — the
``relu4_2`` and ``relu5_1`` activation maps bilinearly sampled at the
keypoint location and concatenated (512 + 512 = 1024 dims). Here that
pipeline is TPU-native: a jit-compiled JAX VGG16 conv stack batched over
images, with three weight sources:

- ``weights=<path.npz>``: converted pretrained weights (keys
  ``features.<i>.weight`` / ``.bias`` as in torchvision's VGG16, or
  ``conv<b>_<j>/{w,b}``) — full parity with the reference pipeline.
- ``weights='random'``: deterministic He-initialized filters. Random
  convolutional features are a documented offline fallback — geometry still
  dominates matching quality on WILLOW-scale data; no network access needed.
- ``weights='none'``: skip images entirely; features are zeros (callers
  typically add positional signal via transforms instead).
"""

import os

import numpy as np

VGG_CFG = (64, 64, 'M', 128, 128, 'M', 256, 256, 256, 'M',
           512, 512, 512, 'M', 512, 512, 512, 'M')
# Indices (conv counter) of the two tapped activations. relu4_2 is the 9th
# conv (0-based 8), relu5_1 the 11th (0-based 10), counting convs only.
TAP_RELU4_2 = 8
TAP_RELU5_1 = 10
FEATURE_DIM = 1024
IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


def _he_weights(seed=0):
    rng = np.random.RandomState(seed)
    params = []
    c_in = 3
    for c in VGG_CFG:
        if c == 'M':
            continue
        fan_in = 3 * 3 * c_in
        w = rng.randn(3, 3, c_in, c).astype(np.float32)
        w *= np.sqrt(2.0 / fan_in)
        params.append((w, np.zeros(c, np.float32)))
        c_in = c
    return params


def _load_npz(path):
    raw = np.load(path)
    params = []
    if any(k.startswith('features.') for k in raw.files):
        idxs = sorted({int(k.split('.')[1]) for k in raw.files
                       if k.startswith('features.')})
        for i in idxs:
            w = raw[f'features.{i}.weight']
            b = raw[f'features.{i}.bias']
            # torch layout [out, in, kh, kw] -> HWIO.
            params.append((np.transpose(w, (2, 3, 1, 0)).astype(np.float32),
                           b.astype(np.float32)))
    else:
        block_sizes = (2, 2, 3, 3, 3)
        for bi, n in enumerate(block_sizes, start=1):
            for j in range(1, n + 1):
                w = raw[f'conv{bi}_{j}/w']
                b = raw[f'conv{bi}_{j}/b']
                if w.shape[0] == w.shape[1] == 3:
                    params.append((w.astype(np.float32),
                                   b.astype(np.float32)))
                else:
                    params.append(
                        (np.transpose(w, (2, 3, 1, 0)).astype(np.float32),
                         b.astype(np.float32)))
    return params


class VGG16Features:
    """Batched keypoint feature extractor on the accelerator.

    Call with a ``[H, W, 3]`` uint8/float image and ``[M, 2]`` pixel
    keypoint coordinates; returns ``[M, 1024]`` float32 features.
    """

    def __init__(self, weights='random', input_size=256):
        self.input_size = input_size
        if weights == 'none':
            self.params = None
            self.tag = 'none'
        elif weights == 'random' or weights is None:
            self.params = _he_weights()
            self.tag = 'random'
        elif isinstance(weights, str) and os.path.exists(weights):
            self.params = _load_npz(weights)
            self.tag = os.path.splitext(os.path.basename(weights))[0]
        else:
            raise FileNotFoundError(
                f'VGG16 weights not found at {weights!r}; pass '
                f"'random'/'none' or a converted .npz path")
        self._apply = None

    def _build(self):
        import jax
        import jax.numpy as jnp

        def forward(params, img):
            # img [H, W, 3] float32 in [0, 1].
            x = (img - IMAGENET_MEAN) / IMAGENET_STD
            x = x[None]
            taps = []
            ci = 0
            for c in VGG_CFG:
                if c == 'M':
                    x = jax.lax.reduce_window(
                        x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1),
                        'VALID')
                    continue
                w, b = params[ci]
                x = jax.lax.conv_general_dilated(
                    x, w, (1, 1), 'SAME',
                    dimension_numbers=('NHWC', 'HWIO', 'NHWC'))
                x = jax.nn.relu(x + b)
                if ci in (TAP_RELU4_2, TAP_RELU5_1):
                    taps.append(x[0])
                if ci == TAP_RELU5_1:
                    break
                ci += 1
            return taps

        def sample(fmap, coords_01):
            # Bilinear sample fmap [h, w, C] at coords in [0, 1] ([M, 2] xy).
            h, w = fmap.shape[0], fmap.shape[1]
            xf = coords_01[:, 0] * (w - 1)
            yf = coords_01[:, 1] * (h - 1)
            x0 = jnp.clip(jnp.floor(xf).astype(jnp.int32), 0, w - 2)
            y0 = jnp.clip(jnp.floor(yf).astype(jnp.int32), 0, h - 2)
            dx = (xf - x0)[:, None]
            dy = (yf - y0)[:, None]
            f00 = fmap[y0, x0]
            f01 = fmap[y0, x0 + 1]
            f10 = fmap[y0 + 1, x0]
            f11 = fmap[y0 + 1, x0 + 1]
            return ((1 - dy) * ((1 - dx) * f00 + dx * f01) +
                    dy * ((1 - dx) * f10 + dx * f11))

        def extract(params, img, coords_01):
            t4, t5 = forward(params, img)
            return jnp.concatenate(
                [sample(t4, coords_01), sample(t5, coords_01)], axis=-1)

        self._apply = jax.jit(extract)

    def __call__(self, image, keypoints_xy):
        """image: ``[H, W, 3]``; keypoints_xy: ``[M, 2]`` pixel coords."""
        M = keypoints_xy.shape[0]
        if self.params is None:
            return np.zeros((M, FEATURE_DIM), np.float32)
        if self._apply is None:
            self._build()
        from PIL import Image
        if not isinstance(image, np.ndarray):
            image = np.asarray(image)
        img = Image.fromarray(image.astype(np.uint8)).resize(
            (self.input_size, self.input_size))
        arr = np.asarray(img, np.float32) / 255.0
        if arr.ndim == 2:
            arr = np.stack([arr] * 3, axis=-1)
        h, w = image.shape[0], image.shape[1]
        coords = np.asarray(keypoints_xy, np.float32) / np.array(
            [max(w - 1, 1), max(h - 1, 1)], np.float32)
        coords = np.clip(coords, 0.0, 1.0)
        out = self._apply(self.params, arr, coords)
        return np.asarray(out, np.float32)
