"""Opt-in dataset acquisition (parity with PyG's auto-download).

The reference gets download/extract/cache for free from PyG datasets
(reference ``examples/dbp15k.py:5,27``); this module provides the same
for networked machines while keeping the offline default: every loader
raises with placement instructions unless ``download=True`` is passed.

URLs mirror the sources the PyG dataset classes use. This build
environment has no egress, so they are best-effort: verified structure,
unverifiable liveness — a failed fetch reports the URL and leaves the
offline instructions intact.
"""

import os
import shutil
import sys
import tarfile
import time
import urllib.error
import urllib.request
import zipfile

URLS = {
    'dbp15k': 'https://www.dropbox.com/s/rb9rwgqxilkqf8p/DBP15K.zip?dl=1',
    'voc2011': ('http://host.robots.ox.ac.uk/pascal/VOC/voc2011/'
                'VOCtrainval_25-May-2011.tar'),
    'voc_keypoints': ('https://www2.eecs.berkeley.edu/Research/Projects/'
                      'CS/vision/shape/poselets/'
                      'voc2011_keypoints_Feb2012.tgz'),
    'willow': ('http://www.di.ens.fr/willow/research/graphlearning/'
               'WILLOW-ObjectClass_dataset.zip'),
    'pascal_pf': ('http://www.di.ens.fr/willow/research/proposalflow/'
                  'dataset/PF-dataset-PASCAL.zip'),
}


def _permanent(e):
    """True for failures a retry cannot fix: client errors (4xx other
    than the rate/timeout pair) and local path problems. Everything else
    — connection resets, 5xx, DNS hiccups, timeouts — is transient."""
    if isinstance(e, urllib.error.HTTPError):
        return 400 <= e.code < 500 and e.code not in (408, 429)
    if isinstance(e, urllib.error.URLError):
        return isinstance(e.reason, (FileNotFoundError, IsADirectoryError,
                                     NotADirectoryError, PermissionError))
    return isinstance(e, (ValueError, FileNotFoundError))


def fetch(url, dest_path, progress=True, retries=4, backoff_s=1.0,
          backoff_max_s=30.0):
    """Stream ``url`` to ``dest_path`` (atomic via .part rename).

    Transient failures (resets, 5xx, timeouts) are retried up to
    ``retries`` times with exponential backoff plus jitter
    (``backoff_s * 2**attempt``, capped at ``backoff_max_s``, stretched
    up to 25% — the jitter keeps a fleet of workers from re-stampeding a
    recovering server in lockstep). Permanent failures (4xx, bad local
    paths) and an exhausted budget raise a terminal ``RuntimeError``
    with the manual-placement instructions. The deterministic
    ``download-fail`` fault (``dgmc_tpu/resilience/faults.py``)
    exercises the retry path in tests."""
    from dgmc_tpu.resilience import faults
    os.makedirs(os.path.dirname(os.path.abspath(dest_path)), exist_ok=True)
    part = dest_path + '.part'
    attempts = max(1, retries + 1)
    for attempt in range(attempts):
        try:
            if faults.consume_download_fault():
                raise ConnectionResetError(
                    'injected transient download failure '
                    '(dgmc_tpu.resilience.faults)')
            with urllib.request.urlopen(url, timeout=60) as r, \
                    open(part, 'wb') as f:
                shutil.copyfileobj(r, f)
        except Exception as e:
            if os.path.exists(part):
                os.remove(part)
            last_attempt = attempt == attempts - 1
            if last_attempt or _permanent(e):
                tried = attempt + 1
                raise RuntimeError(
                    f'download failed for {url} after {tried} '
                    f'attempt(s): {e}; fetch it manually and place '
                    f'it per the loader instructions') from e
            delay = faults.transient_jitter(
                min(backoff_max_s, backoff_s * (2 ** attempt)))
            print(f'download: transient failure for {url} '
                  f'(attempt {attempt + 1}/{attempts}: {e}); '
                  f'retrying in {delay:.1f}s', file=sys.stderr)
            time.sleep(delay)
            continue
        os.replace(part, dest_path)
        return dest_path


def _check_member_path(name, dest_dir):
    """Reject absolute paths and ``..`` traversal in archive members —
    several dataset archives arrive over plain HTTP, so a tampered archive
    must not be able to write outside ``dest_dir``."""
    target = os.path.realpath(os.path.join(dest_dir, name))
    base = os.path.realpath(dest_dir)
    if not (target == base or target.startswith(base + os.sep)):
        raise ValueError(f'archive member escapes extraction dir: {name!r}')


def extract(archive, dest_dir):
    """Extract a .zip/.tar/.tgz/.tar.gz archive into ``dest_dir``,
    refusing path-traversal members."""
    os.makedirs(dest_dir, exist_ok=True)
    if zipfile.is_zipfile(archive):
        with zipfile.ZipFile(archive) as z:
            for name in z.namelist():
                _check_member_path(name, dest_dir)
            z.extractall(dest_dir)
    elif tarfile.is_tarfile(archive):
        with tarfile.open(archive) as t:
            if hasattr(tarfile, 'data_filter'):
                # The stdlib filter also strips setuid bits / device nodes
                # and rejects traversal (default from Python 3.14; opt-in
                # since 3.12 security backports).
                t.extractall(dest_dir, filter='data')
            else:
                for m in t.getmembers():
                    _check_member_path(m.name, dest_dir)
                    if not (m.isreg() or m.isdir()):
                        raise ValueError(
                            f'refusing non-regular tar member: {m.name!r}')
                t.extractall(dest_dir)
    else:
        raise ValueError(f'unrecognized archive format: {archive}')
    return dest_dir


def download_and_extract(key, root, keep_archive=False):
    """Fetch the named dataset archive (see ``URLS``) into ``root`` and
    extract it there. Returns ``root``."""
    url = URLS[key]
    name = os.path.basename(url.split('?')[0])
    archive = os.path.join(root, name)
    if not os.path.exists(archive):
        fetch(url, archive)
    extract(archive, root)
    if not keep_archive:
        os.remove(archive)
    return root
