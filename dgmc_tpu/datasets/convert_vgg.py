"""Convert a torchvision VGG16 checkpoint to the ``.npz`` weight layout
consumed by :class:`dgmc_tpu.datasets.VGG16Features`.

The reference's keypoint workloads take node features from torchvision's
*pretrained* VGG16 (consumed via the PyG datasets at reference
``examples/pascal.py:5`` and ``examples/willow.py:7-8``). This sandbox has
no network access, so the pretrained weights cannot ship in-tree; this
converter is the documented parity pipeline: download
``vgg16-397923af.pth`` (the torchvision VGG16 checkpoint) on any machine,
run::

    dgmc-convert-vgg16 vgg16-397923af.pth vgg16.npz
    python examples/pascal.py --vgg_weights vgg16.npz

Only the 13 convolutional layers are kept (the classifier head is unused —
the extractor taps relu4_2/relu5_1, ``features.py``). Weights stay in the
torch ``[out, in, kh, kw]`` layout under the torchvision key names
(``features.<i>.weight`` / ``.bias``); ``VGG16Features`` transposes to the
HWIO layout XLA wants at load time.
"""

import argparse

import numpy as np

# torchvision VGG16 `features` indices of the 13 conv layers (the gaps are
# ReLU/MaxPool entries of the nn.Sequential).
CONV_INDICES = (0, 2, 5, 7, 10, 12, 14, 17, 19, 21, 24, 26, 28)
# Per-conv (out_channels, in_channels) for shape validation, derived from
# the VGG16 configuration (features.VGG_CFG).
CONV_SHAPES = (
    (64, 3), (64, 64), (128, 64), (128, 128), (256, 128), (256, 256),
    (256, 256), (512, 256), (512, 512), (512, 512), (512, 512), (512, 512),
    (512, 512),
)


def convert_state_dict(state_dict):
    """Torchvision VGG16 state dict (or any mapping of array-likes with
    ``features.<i>.weight/.bias`` keys) -> dict of float32 numpy arrays in
    the documented npz layout. Validates that all 13 conv layers are
    present with VGG16 shapes."""
    out = {}
    for idx, (c_out, c_in) in zip(CONV_INDICES, CONV_SHAPES):
        for suffix, want in ((f'features.{idx}.weight', (c_out, c_in, 3, 3)),
                             (f'features.{idx}.bias', (c_out,))):
            if suffix not in state_dict:
                raise KeyError(
                    f'missing {suffix!r}: not a torchvision VGG16 '
                    f'checkpoint (13 conv layers expected)')
            arr = np.asarray(state_dict[suffix], dtype=np.float32)
            if arr.shape != want:
                raise ValueError(
                    f'{suffix}: shape {arr.shape} != VGG16 {want}')
            out[suffix] = arr
    return out


def convert_checkpoint(src_path, out_path):
    """Load a ``.pth`` torchvision checkpoint (or an ``.npz`` mapping with
    the same keys) and write the converted ``.npz``. Returns the output
    path."""
    if src_path.endswith('.npz'):
        raw = dict(np.load(src_path))
    else:
        import torch
        obj = torch.load(src_path, map_location='cpu', weights_only=True)
        if hasattr(obj, 'state_dict'):
            obj = obj.state_dict()
        raw = {k: v.numpy() for k, v in obj.items()
               if hasattr(v, 'numpy')}
    np.savez(out_path, **convert_state_dict(raw))
    return out_path


def main(argv=None):
    parser = argparse.ArgumentParser(
        description='torchvision VGG16 checkpoint -> dgmc_tpu .npz weights')
    parser.add_argument('src', help='vgg16-*.pth (torchvision state dict)')
    parser.add_argument('out', help='output .npz path')
    args = parser.parse_args(argv)
    convert_checkpoint(args.src, args.out)
    print(f'wrote {args.out}')


if __name__ == '__main__':
    main()
