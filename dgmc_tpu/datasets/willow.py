"""WILLOW-ObjectClass keypoint dataset.

Capability parity with PyG's ``WILLOWObjectClass`` as consumed by the
reference (reference ``examples/willow.py:7,48``): 5 categories (face,
motorbike, car, duck, winebottle), each image annotated with exactly 10
keypoints; node features are VGG16 activations sampled at the keypoints
(see ``dgmc_tpu/datasets/features.py``), positions are the keypoint
coordinates, and the ground truth between any two same-category items is
the identity over the 10 keypoints (reference ``examples/willow.py:94-97``).

Expected raw layout (the official release; no downloads attempted):

    <root>/WILLOW-ObjectClass/<Category>/*.png
    <root>/WILLOW-ObjectClass/<Category>/*.mat   (pts_coord [2, 10])
"""

import glob
import os

import numpy as np

from dgmc_tpu.utils.data import Graph

CATEGORIES = ('face', 'motorbike', 'car', 'duck', 'winebottle')
_DIRNAMES = {'face': 'Face', 'motorbike': 'Motorbike', 'car': 'Car',
             'duck': 'Duck', 'winebottle': 'Winebottle'}
NUM_KEYPOINTS = 10


class WILLOWObjectClass:
    """One category of WILLOW-ObjectClass as a list-like of ``Graph`` s."""

    def __init__(self, root, category, transform=None, features=None,
                 device_features=None, download=False):
        if category not in CATEGORIES:
            raise ValueError(f'unknown category {category!r}')
        self.root = os.path.expanduser(root)
        self.category = category
        self.transform = transform
        if features is None:
            from dgmc_tpu.datasets.features import VGG16Features
            features = VGG16Features(weights=device_features or 'random')
        self.features = features
        base = os.path.join(self.root, 'WILLOW-ObjectClass',
                            _DIRNAMES[category])
        if not os.path.isdir(base) and download:
            from dgmc_tpu.datasets.download import download_and_extract
            download_and_extract('willow', self.root)
        if not os.path.isdir(base):
            base_alt = os.path.join(self.root, 'WILLOW-ObjectClass', category)
            if os.path.isdir(base_alt):
                base = base_alt
            else:
                raise FileNotFoundError(
                    f'WILLOW raw data not found at {base}; place the '
                    f'WILLOW-ObjectClass release under {self.root}, or '
                    f'pass download=True on a networked machine.')
        self._graphs = self._load(base)

    def _load(self, base):
        from PIL import Image
        from scipy.io import loadmat
        graphs = []
        for mat_path in sorted(glob.glob(os.path.join(base, '*.mat'))):
            m = loadmat(mat_path)
            pts = np.asarray(m['pts_coord'], np.float64)[:2].T  # [10, 2] xy
            name = os.path.splitext(os.path.basename(mat_path))[0]
            img_path = os.path.join(base, name + '.png')
            if os.path.exists(img_path):
                img = np.asarray(Image.open(img_path).convert('RGB'))
            else:
                img = np.zeros((256, 256, 3), np.uint8)
            x = self.features(img, pts)
            # Positions normalized like the PyG processing: centered on the
            # keypoint centroid (graph transforms rebuild edges from pos).
            pos = (pts - pts.mean(axis=0)).astype(np.float32)
            graphs.append(Graph(
                edge_index=np.zeros((2, 0), np.int64), x=x, pos=pos,
                y=np.arange(pts.shape[0], dtype=np.int64), name=name))
        if not graphs:
            raise FileNotFoundError(f'no .mat annotations under {base}')
        return graphs

    def __len__(self):
        return len(self._graphs)

    def __getitem__(self, idx):
        g = self._graphs[idx]
        return self.transform(g) if self.transform else g

    def shuffled_split(self, n_train, seed=0):
        """Random n_train / rest split (reference ``willow.py:144-146``)."""
        order = np.random.RandomState(seed).permutation(len(self))
        pick = lambda idxs: _Subset(self, idxs)  # noqa: E731
        return pick(order[:n_train]), pick(order[n_train:])

    @property
    def num_node_features(self):
        return self._graphs[0].x.shape[1]

    def __repr__(self):
        return f'WILLOWObjectClass({self.category}, {len(self)})'


class _Subset:
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __len__(self):
        return len(self.indices)

    def __getitem__(self, i):
        return self.dataset[self.indices[i]]
