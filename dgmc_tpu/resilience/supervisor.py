"""Fault-tolerant run supervisor: detect → kill → resume → degrade.

PR 5's watchdog can *describe* a wedged run (``hang_report.json``); this
module is the half that *survives* one. ``--supervise`` re-runs the same
CLI command in a child process and closes the detection→recovery loop:

- **Crash / preemption** (nonzero exit, death by signal — what a
  scheduler preemption or an injected ``sigkill@N`` looks like): restart
  from the latest checkpoint (the CLIs auto-resume via ``--ckpt_dir``)
  after a bounded exponential backoff.
- **Hang**: the child's watchdog heartbeat file
  (``<obs>/attempt_<k>/heartbeat.json``, written by the watchdog thread
  every poll) goes stale past the deadline, or a ``hang_report.json``
  appears — the supervisor SIGTERMs the child (letting the watchdog dump
  its report), escalates to SIGKILL after a grace period, and restarts.
  The layering matters: the in-process watchdog thread catches a main
  thread wedged in one XLA call; the out-of-process heartbeat watch
  catches a process too far gone to run even its watchdog thread.
- **Repeated failure at the same step**: a graceful-degradation ladder
  rewrites the child's command before the next restart —
  ``DGMC_TPU_DISABLE_FUSED=1`` (every Pallas gate picks its XLA
  fallback), then ``--f32`` (drop the bf16 policy), then halving
  ``--model_shards`` (shrink the mesh) — so a run that keeps dying in
  the same place trades speed for survival instead of burning its whole
  restart budget on one suspect kernel/policy/topology.
- **Budget**: ``--max-restarts`` bounds the loop; exhausting it records
  ``outcome: gave-up`` and exits nonzero with the last failure's
  evidence on disk.

Everything the supervisor does lands in ``<obs>/recovery.json`` (events,
attempts, degradations — atomically rewritten as the run progresses), and
each attempt keeps its own full telemetry under ``<obs>/attempt_<k>/``;
``python -m dgmc_tpu.obs.report <obs>`` renders the recovery timeline and
``obs.diff --max-restarts-regression`` gates on unexpected restarts.

This module deliberately imports **no jax of its own** and never touches
the backend: the monitor process must stay responsive while the child
wedges, and the child's devices are the child's problem. (Reaching it
through ``dgmc_tpu.resilience`` still runs the package root's imports;
the monitor just never initializes a backend.)
"""

import json
import os
import signal
import subprocess
import sys
import time

from dgmc_tpu.utils.io import write_json_atomic

__all__ = ['Supervisor', 'add_supervisor_args', 'strip_supervisor_args',
           'supervise_cli', 'DEFAULT_MAX_RESTARTS',
           'DEFAULT_HANG_DEADLINE_S']

DEFAULT_MAX_RESTARTS = 5
#: Watchdog deadline injected into supervised children that have an obs
#: dir but no explicit ``--watchdog-deadline`` of their own.
DEFAULT_HANG_DEADLINE_S = 600.0
RECOVERY_FILE = 'recovery.json'
#: The per-attempt obs subdirectory naming contract. The supervisor
#: writes these; ``faults.ledger_dir`` (fire-once ledger placement) and
#: ``obs.report`` (supervised-root loading) parse them — keep all three
#: on these helpers.
ATTEMPT_PREFIX = 'attempt_'


def attempt_dirname(k):
    return f'{ATTEMPT_PREFIX}{k}'


def is_attempt_dirname(name):
    return (name.startswith(ATTEMPT_PREFIX)
            and name[len(ATTEMPT_PREFIX):].isdigit())
#: "no failure yet" sentinel for same-step tracking — distinct from
#: None, which is a real observation ("died with no step evidence").
_NO_FAILURE = object()

#: Supervisor-only flags (name -> number of value tokens) stripped from
#: the child's argv: the child must run unsupervised or it would recurse.
_OWN_FLAGS = {
    '--supervise': 0,
    '--max-restarts': 1, '--max_restarts': 1,
    '--restart-backoff': 1, '--restart_backoff': 1,
}


def add_supervisor_args(parser):
    """Register ``--supervise`` / ``--max-restarts`` on an argparse
    parser (every experiment CLI + bench.py)."""
    parser.add_argument(
        '--supervise', action='store_true',
        help='run this command under the fault-tolerant supervisor: the '
             'run executes in a child process; on crash, preemption or '
             'hang (watchdog heartbeat stale / hang_report.json) the '
             'child is killed and restarted from the latest checkpoint '
             'with exponential backoff and a graceful-degradation '
             'ladder (disable fused Pallas kernels -> f32 policy -> '
             'shrink the mesh). Recovery timeline: '
             '<obs-dir>/recovery.json')
    parser.add_argument(
        '--max-restarts', '--max_restarts', dest='max_restarts', type=int,
        default=DEFAULT_MAX_RESTARTS, metavar='N',
        help='restart budget under --supervise (default %(default)s); '
             'exhausting it exits nonzero with outcome "gave-up"')
    parser.add_argument(
        '--restart-backoff', '--restart_backoff', dest='restart_backoff',
        type=float, default=1.0, metavar='SEC',
        help='base of the exponential restart backoff (default '
             '%(default)s s, doubling per restart, capped at 60 s)')
    return parser


def strip_supervisor_args(argv):
    """argv minus the supervisor's own flags (child command line)."""
    out, i = [], 0
    while i < len(argv):
        tok = argv[i]
        name = tok.split('=', 1)[0]
        if name in _OWN_FLAGS:
            i += 1 + (0 if '=' in tok else _OWN_FLAGS[name])
            continue
        out.append(tok)
        i += 1
    return out


def _replace_flag_value(argv, names, value):
    """Return argv with flag ``names``'s value replaced (appended when
    absent). Handles both ``--flag V`` and ``--flag=V``."""
    out, i, done = [], 0, False
    while i < len(argv):
        tok = argv[i]
        name = tok.split('=', 1)[0]
        if name in names:
            out.append(f'{name}={value}' if '=' in tok else name)
            if '=' not in tok:
                out.append(str(value))
                i += 1
            done = True
            i += 1
            continue
        out.append(tok)
        i += 1
    if not done:
        out.extend([names[0], str(value)])
    return out


def _flag_value(argv, names):
    for i, tok in enumerate(argv):
        name, _, inline = tok.partition('=')
        if name in names:
            if inline:
                return inline
            if i + 1 < len(argv):
                return argv[i + 1]
    return None


# -- degradation ladder ----------------------------------------------------

def _rung_disable_fused(argv, env):
    if env.get('DGMC_TPU_DISABLE_FUSED'):
        return argv, env, None
    env = dict(env, DGMC_TPU_DISABLE_FUSED='1')
    return argv, env, 'DGMC_TPU_DISABLE_FUSED=1 (all Pallas gates fall ' \
                      'back to XLA)'


def _rung_force_f32(argv, env):
    # Already-f32 runs (any spelling: --f32, --precision f32/=f32) get
    # no rung: a no-op rewrite would burn a ladder slot and record a
    # degradation that ruled nothing out.
    if '--f32' in argv or _flag_value(argv, ('--precision',)) == 'f32':
        return argv, env, None
    return argv + ['--f32'], env, '--f32 (bf16 policy off)'


def _rung_shrink_mesh(argv, env):
    cur = _flag_value(argv, ('--model_shards', '--model-shards'))
    if cur is None or int(cur) <= 1:
        return argv, env, None
    new = max(1, int(cur) // 2)
    argv = _replace_flag_value(argv, ('--model_shards', '--model-shards'),
                               new)
    return argv, env, f'--model_shards {cur} -> {new} (shrink the mesh)'


#: name -> rewrite(argv, env) -> (argv, env, description-or-None).
LADDER_RUNGS = {
    'disable-fused': _rung_disable_fused,
    'f32': _rung_force_f32,
    'shrink-mesh': _rung_shrink_mesh,
}
DEFAULT_LADDER = ('disable-fused', 'f32', 'shrink-mesh')


class Supervisor:
    """Run ``cmd + argv`` under crash/hang supervision.

    Args:
        cmd: interpreter prefix, e.g. ``[sys.executable, '-m',
            'dgmc_tpu.experiments.dbp15k']``.
        argv: the child's own arguments (already stripped of supervisor
            flags). Its ``--obs-dir`` is rewritten per attempt to
            ``<obs_dir>/attempt_<k>``.
        obs_dir: root obs directory (recovery.json + per-attempt
            telemetry); ``None`` disables hang detection and puts
            recovery.json next to ``ckpt_dir`` (or the cwd).
        ckpt_dir: the run's checkpoint dir (restart = resume); ``None``
            means restarts re-run from scratch.
        hang_deadline_s: child watchdog deadline; the supervisor treats a
            heartbeat older than ``2x`` this as a wedged child. ``None``
            disables the heartbeat watch (hang_report detection stays).
        first_heartbeat_s: how long after spawn a child may go without
            writing its FIRST heartbeat before it counts as wedged
            (default ``max(4x hang_deadline, 300)``). The heartbeat file
            is written by the child's watchdog thread, which only exists
            once RunObserver is up — a child stuck in imports or
            ``jax.distributed.initialize`` (one host of the mesh never
            joining) writes neither heartbeat nor hang_report, and
            without this bound the supervisor would wait on it forever.
            Only active when the heartbeat watch is (``hang_deadline_s``
            set and an obs dir present).
        ladder: rung names from :data:`LADDER_RUNGS`, applied one per
            escalation after ``same_step_threshold`` failures at the
            same step.
    """

    def __init__(self, cmd, argv, *, obs_dir=None, ckpt_dir=None,
                 max_restarts=DEFAULT_MAX_RESTARTS, backoff_s=1.0,
                 backoff_max_s=60.0, grace_s=10.0, hang_deadline_s=None,
                 first_heartbeat_s=None, ladder=DEFAULT_LADDER,
                 same_step_threshold=2, poll_s=0.5, env=None):
        self.cmd = list(cmd)
        self.argv = list(argv)
        self.obs_dir = obs_dir
        self.ckpt_dir = ckpt_dir
        self.max_restarts = int(max_restarts)
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self.grace_s = float(grace_s)
        self.hang_deadline_s = hang_deadline_s
        self.first_heartbeat_s = first_heartbeat_s
        self.ladder = [r for r in ladder if r in LADDER_RUNGS]
        self.same_step_threshold = int(same_step_threshold)
        self.poll_s = float(poll_s)
        self._base_env = dict(os.environ if env is None else env)
        self.recovery_path = os.path.join(
            obs_dir or ckpt_dir or '.', RECOVERY_FILE)
        # Children with neither --ckpt_dir nor --obs-dir still need a
        # home for the fire-once fault ledger (faults.LEDGER_ENV): the
        # recovery file's directory is always resolvable and survives
        # restarts.
        self._base_env.setdefault(
            'DGMC_TPU_FAULT_LEDGER_DIR',
            os.path.dirname(os.path.abspath(self.recovery_path)))
        self.events = []
        self.attempts = []
        self.degradations = []
        self.restarts = 0
        self.outcome = 'running'
        self._stop_signal = None

    # -- recording ---------------------------------------------------------

    def _event(self, event, **detail):
        rec = {'time': round(time.time(), 3), 'event': event,
               'attempt': len(self.attempts) - 1, **detail}
        self.events.append(rec)
        line = ' '.join(f'{k}={v}' for k, v in detail.items())
        print(f'[supervisor] {event} {line}'.rstrip(),
              file=sys.stderr, flush=True)
        self._write_recovery()

    def _write_recovery(self):
        payload = {
            'tool': 'dgmc_tpu.resilience.supervisor',
            'cmd': self.cmd,
            'argv': self.argv,
            'max_restarts': self.max_restarts,
            'hang_deadline_s': self.hang_deadline_s,
            'outcome': self.outcome,
            'restarts': self.restarts,
            'degradations': self.degradations,
            'attempts': self.attempts,
            'events': self.events,
        }
        # quiet: a supervisor must never die of its own telemetry.
        write_json_atomic(self.recovery_path, payload, indent=1,
                          quiet=True)

    # -- child plumbing ----------------------------------------------------

    def _attempt_dirs(self, k):
        if not self.obs_dir:
            return None, None, None
        adir = os.path.join(self.obs_dir, attempt_dirname(k))
        return (adir, os.path.join(adir, 'heartbeat.json'),
                os.path.join(adir, 'hang_report.json'))

    @staticmethod
    def _candidate_paths(path):
        """The watched file plus its multi-process homes: a multi-host
        child's RunObserver writes under ``<attempt>/host_<i>/``
        (parallel.host_obs_dir), so the heartbeat/hang_report of a
        sharded run never lands at the attempt root. Any host's file
        counts — the straggling host is exactly the evidence."""
        if not path:
            return []
        adir, name = os.path.split(path)
        out = [path]
        try:
            hosts = [d for d in os.listdir(adir)
                     if d.startswith('host_')
                     and os.path.isdir(os.path.join(adir, d))]
        except OSError:
            hosts = []
        out.extend(os.path.join(adir, d, name) for d in sorted(hosts))
        return out

    def _clear_stale_evidence(self, *paths):
        """Drop liveness evidence left in a reused attempt dir by a
        PREVIOUS supervisor session (same ``--obs-dir``; attempt
        numbering restarts at 0). ``_watch`` cannot tell an hours-old
        deadline ``hang_report.json`` or heartbeat from this child's, so
        without this a re-run kills its own healthy children on the
        first poll — long before they finish importing jax. The child
        rewrites all telemetry in its attempt dir anyway; only the
        liveness files need pre-clearing."""
        for path in paths:
            for p in self._candidate_paths(path):
                try:
                    os.remove(p)
                except OSError:
                    pass

    def _child_argv(self, attempt_dir):
        argv = list(self.argv)
        if attempt_dir:
            argv = _replace_flag_value(argv, ('--obs-dir', '--obs_dir'),
                                       attempt_dir)
        return argv

    def _read_heartbeat(self, path):
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _latest_ckpt_step(self):
        if self.ckpt_dir and os.path.isdir(self.ckpt_dir):
            steps = [int(d) for d in os.listdir(self.ckpt_dir)
                     if d.isdigit()
                     and os.path.isdir(os.path.join(self.ckpt_dir, d))]
            if steps:
                return max(steps)
        return None

    def _steps_completed(self, heartbeat_path, start_step=None):
        """Best evidence of where the attempt died, in GLOBAL schedule
        units: the heartbeat's step counter (any host's — the minimum,
        so a straggler counts) is per-PROCESS and resets on every
        restart, so it is offset by ``start_step`` (the checkpoint step
        the attempt resumed from) — otherwise a run preempted every K
        steps reports K forever and a healthy, progressing run reads as
        stuck at one step and gets wrongly degraded. Fallback: the
        newest committed checkpoint step."""
        steps = [hb['steps_completed']
                 for hb in map(self._read_heartbeat,
                               self._candidate_paths(heartbeat_path))
                 if hb and hb.get('steps_completed') is not None]
        if steps:
            return (start_step or 0) + min(steps)
        return self._latest_ckpt_step()

    def _kill(self, proc, reason):
        """SIGTERM (lets the child watchdog dump its report), grace,
        SIGKILL."""
        self._event('kill', reason=reason, pid=proc.pid)
        try:
            proc.terminate()
            try:
                proc.wait(timeout=self.grace_s)
                return
            except subprocess.TimeoutExpired:
                pass
            proc.kill()
            proc.wait(timeout=self.grace_s)
        except OSError:
            pass

    def _watch(self, proc, heartbeat_path, hang_report_path):
        """Wait for child exit; return a hang reason if WE killed it."""
        stale_after = (2.0 * self.hang_deadline_s
                       if self.hang_deadline_s else None)
        first_beat_by = None
        if stale_after and heartbeat_path:
            first_beat_by = time.time() + (
                self.first_heartbeat_s if self.first_heartbeat_s
                is not None else max(4.0 * self.hang_deadline_s, 300.0))
        while True:
            if self._stop_signal is not None:
                return f'preempted:{self._stop_signal}'
            try:
                proc.wait(timeout=self.poll_s)
                return None
            except subprocess.TimeoutExpired:
                pass
            for path in self._candidate_paths(hang_report_path):
                if not os.path.exists(path):
                    continue
                rep = self._read_heartbeat(path) or {}
                # The watchdog re-dumps on SIGTERM during shutdown too;
                # only a DEADLINE dump means "wedged, kill me".
                if str(rep.get('reason', '')).startswith('deadline'):
                    self._kill(proc, 'hang-report')
                    return 'hang-report'
            if stale_after and heartbeat_path:
                # Before the first heartbeat (imports, compiles) the
                # child is given the benefit of the doubt: the watchdog
                # thread writes one as soon as it is armed. Any host's
                # heartbeat going stale condemns the run — one wedged
                # host wedges the collective.
                beats = [hb for hb in map(
                    self._read_heartbeat,
                    self._candidate_paths(heartbeat_path)) if hb]
                if beats and any(
                        time.time() - hb.get('time', 0) > stale_after
                        for hb in beats):
                    self._kill(proc, 'heartbeat-stale')
                    return 'heartbeat-stale'
                # ...but the doubt is bounded: a child wedged BEFORE its
                # watchdog thread exists (imports, distributed init with
                # a host that never joins) writes neither heartbeat nor
                # hang_report, ever.
                if not beats and first_beat_by \
                        and time.time() > first_beat_by:
                    self._kill(proc, 'no-first-heartbeat')
                    return 'no-first-heartbeat'

    def _on_signal(self, signum, frame):
        self._stop_signal = signal.Signals(signum).name

    # -- the loop ----------------------------------------------------------

    def run(self):
        """Supervise until completion, preemption of the supervisor
        itself, or an exhausted restart budget. Returns the exit code."""
        prev_handlers = {}
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                prev_handlers[sig] = signal.signal(sig, self._on_signal)
            except ValueError:
                break
        try:
            return self._run()
        finally:
            for sig, prev in prev_handlers.items():
                signal.signal(sig, prev)
            self._write_recovery()

    def _run(self):
        argv, env = self.argv, dict(self._base_env)
        # The "no previous failure" sentinel is NOT None: an attempt
        # with no step evidence at all (died in setup/compile, no obs
        # dir) reports steps_completed=None, and repeated no-progress
        # deaths are precisely a "same step" pattern the ladder must
        # escalate on.
        rung_idx, same_step_fails, last_fail_step = 0, 0, _NO_FAILURE
        attempt = 0
        while True:
            attempt_dir, hb_path, hang_path = self._attempt_dirs(attempt)
            if attempt_dir:
                os.makedirs(attempt_dir, exist_ok=True)
                self._clear_stale_evidence(hb_path, hang_path)
            start_step = self._latest_ckpt_step()
            child_argv = self._child_argv(attempt_dir)
            rec = {'attempt': attempt,
                   'obs_dir': attempt_dir,
                   'argv': child_argv,
                   'env_overrides': {
                       k: v for k, v in env.items()
                       if self._base_env.get(k) != v},
                   'start_time': round(time.time(), 3)}
            self.attempts.append(rec)
            self._event('start', cmd=' '.join(self.cmd + child_argv))
            try:
                proc = subprocess.Popen(self.cmd + child_argv, env=env)
            except OSError as e:
                # A failed fork/exec (EAGAIN under memory pressure — the
                # very condition a leaking child produces) is transient
                # like any crash: it gets the backoff and the restart
                # budget, not an instant give-up.
                proc, hang_reason = None, None
                spawn_failure = f'spawn-failed:{type(e).__name__}: {e}'
            else:
                spawn_failure = None
                hang_reason = self._watch(proc, hb_path, hang_path)
                if hang_reason and hang_reason.startswith('preempted'):
                    # Reap the child BEFORE recording: the attempt's rc
                    # and final step evidence only exist once it is dead.
                    self._kill(proc, hang_reason)
            rec['end_time'] = round(time.time(), 3)
            rec['rc'] = proc.returncode if proc else None
            rec['steps_completed'] = self._steps_completed(hb_path,
                                                           start_step)

            if hang_reason and hang_reason.startswith('preempted'):
                rec['reason'] = hang_reason
                self.outcome = 'preempted'
                self._event('preempted', signal=self._stop_signal)
                return 128 + getattr(signal,
                                     self._stop_signal or 'SIGTERM',
                                     signal.SIGTERM)
            if proc and hang_reason is None and proc.returncode == 0:
                rec['reason'] = 'completed'
                self.outcome = 'completed'
                self._event('complete', restarts=self.restarts)
                return 0

            reason = spawn_failure or hang_reason or (
                f'signal:{signal.Signals(-proc.returncode).name}'
                if proc.returncode < 0 else f'exit:{proc.returncode}')
            rec['reason'] = reason
            self._event('failure', reason=reason,
                        steps_completed=rec['steps_completed'])

            self.restarts += 1
            if self.restarts > self.max_restarts:
                self.outcome = 'gave-up'
                self._event('give-up', restarts=self.restarts - 1,
                            max_restarts=self.max_restarts)
                return proc.returncode if proc and proc.returncode \
                    and proc.returncode > 0 else 1

            # Same-step escalation: repeated death at one step (or with
            # no progress evidence at all) means retrying harder won't
            # help — degrade instead.
            step = rec['steps_completed']
            if step == last_fail_step:
                same_step_fails += 1
            else:
                same_step_fails = 0
            last_fail_step = step
            if same_step_fails >= self.same_step_threshold - 1:
                while rung_idx < len(self.ladder):
                    rung = self.ladder[rung_idx]
                    rung_idx += 1
                    argv, env, desc = LADDER_RUNGS[rung](argv, env)
                    self.argv = argv
                    if desc:
                        self.degradations.append(
                            {'rung': rung, 'attempt': attempt,
                             'detail': desc})
                        self._event('degrade', rung=rung, detail=desc)
                        break
                same_step_fails = 0

            delay = min(self.backoff_max_s,
                        self.backoff_s * (2 ** (self.restarts - 1)))
            self._event('restart', number=self.restarts,
                        backoff_s=round(delay, 2),
                        resume_from=('checkpoint' if self.ckpt_dir
                                     else 'scratch'))
            end = time.time() + delay
            while time.time() < end:
                if self._stop_signal is not None:
                    self.outcome = 'preempted'
                    self._event('preempted', signal=self._stop_signal)
                    return 128 + getattr(signal,
                                         self._stop_signal or 'SIGTERM',
                                         signal.SIGTERM)
                time.sleep(min(self.poll_s, max(0.0, end - time.time())))
            attempt += 1


def supervise_cli(module, args, argv=None, *,
                  ladder=DEFAULT_LADDER, cmd=None):
    """``--supervise`` glue for a CLI ``main()``: re-run the same command
    (minus supervisor flags) in supervised children.

    Args:
        module: the child's ``python -m`` module path (ignored when
            ``cmd`` is given — bench.py passes its script path).
        args: the parsed namespace (reads obs_dir / ckpt_dir /
            watchdog_deadline / max_restarts / restart_backoff).
        argv: the original argv (defaults to ``sys.argv[1:]``).
        ladder: degradation rungs valid for this CLI's flag surface.

    Returns the supervisor's exit code (0 = run completed).
    """
    argv = list(sys.argv[1:] if argv is None else argv)
    child_argv = strip_supervisor_args(argv)
    obs_dir = getattr(args, 'obs_dir', None)
    ckpt_dir = getattr(args, 'ckpt_dir', None)
    deadline = getattr(args, 'watchdog_deadline', None)
    if obs_dir and deadline is None:
        # Hang detection needs an armed watchdog in the child; arm the
        # default deadline when the user did not pick one. An EXPLICIT
        # --watchdog-deadline 0 is the documented opt-out (a
        # legitimately slow job) and is honored, not overridden.
        deadline = DEFAULT_HANG_DEADLINE_S
        child_argv = child_argv + ['--watchdog-deadline', str(deadline)]
    elif not deadline:
        deadline = None
    if not obs_dir:
        print('[supervisor] no --obs-dir: hang detection disabled '
              '(crash/preemption recovery only)', file=sys.stderr)
        deadline = None
    if not ckpt_dir:
        print('[supervisor] no --ckpt_dir: restarts re-run from scratch',
              file=sys.stderr)
    sup = Supervisor(
        cmd or [sys.executable, '-m', module], child_argv,
        obs_dir=obs_dir, ckpt_dir=ckpt_dir,
        max_restarts=getattr(args, 'max_restarts', DEFAULT_MAX_RESTARTS),
        backoff_s=getattr(args, 'restart_backoff', 1.0),
        hang_deadline_s=deadline, ladder=ladder)
    return sup.run()
