"""Fault-tolerant run supervisor: detect → kill → resume → degrade.

PR 5's watchdog can *describe* a wedged run (``hang_report.json``); this
module is the half that *survives* one. ``--supervise`` re-runs the same
CLI command in a child process and closes the detection→recovery loop:

- **Crash / preemption** (nonzero exit, death by signal — what a
  scheduler preemption or an injected ``sigkill@N`` looks like): restart
  from the latest checkpoint (the CLIs auto-resume via ``--ckpt_dir``)
  after a bounded exponential backoff.
- **Hang**: the child's watchdog heartbeat file
  (``<obs>/attempt_<k>/heartbeat.json``, written by the watchdog thread
  every poll) goes stale past the deadline, or a ``hang_report.json``
  appears — the supervisor SIGTERMs the child (letting the watchdog dump
  its report), escalates to SIGKILL after a grace period, and restarts.
  The layering matters: the in-process watchdog thread catches a main
  thread wedged in one XLA call; the out-of-process heartbeat watch
  catches a process too far gone to run even its watchdog thread.
  A child that advertises a live-telemetry port (``--obs-port``; the
  port rides in ``heartbeat.json``) is additionally monitored through
  its ``/healthz`` endpoint — the SAME staleness verdict, evaluated
  in-process by the child's own plane — with the file heartbeats as
  the fallback whenever the scrape fails; a 503 kills the child as
  ``healthz-stale``.
- **Repeated failure at the same step**: a graceful-degradation ladder
  rewrites the child's command before the next restart —
  ``DGMC_TPU_DISABLE_FUSED=1`` (every Pallas gate picks its XLA
  fallback), then ``--f32`` (drop the bf16 policy), then halving
  ``--model_shards`` (shrink the mesh) — so a run that keeps dying in
  the same place trades speed for survival instead of burning its whole
  restart budget on one suspect kernel/policy/topology.
- **Distributed failure → elastic restart**: a dead peer host (stale
  control-plane heartbeat or a ``peer-death`` tombstone — see
  :mod:`~dgmc_tpu.resilience.distributed_guard`), a collective fence
  that exited ``FENCE_TIMEOUT_RC``, or a watchdog-caught hang means the
  MESH broke, not the program. Instead of retrying into the same wedged
  collective, the supervisor immediately halves the mesh flags
  (``--model_shards`` / ``--row_shards``), publishes attempt number and
  new mesh size to the host-0 recovery ledger so every host rejoins in
  agreement, and restarts — the checkpoint layer reshards the restored
  state onto the smaller mesh. ``--no-elastic`` opts out.
- **Budget**: ``--max-restarts`` bounds the loop; exhausting it records
  ``outcome: gave-up`` and exits nonzero with the last failure's
  evidence on disk.

Everything the supervisor does lands in ``<obs>/recovery.json`` (events,
attempts, degradations — atomically rewritten as the run progresses), and
each attempt keeps its own full telemetry under ``<obs>/attempt_<k>/``;
``python -m dgmc_tpu.obs.report <obs>`` renders the recovery timeline and
``obs.diff --max-restarts-regression`` gates on unexpected restarts.

This module deliberately imports **no jax of its own** and never touches
the backend: the monitor process must stay responsive while the child
wedges, and the child's devices are the child's problem. (Reaching it
through ``dgmc_tpu.resilience`` still runs the package root's imports;
the monitor just never initializes a backend.)
"""

import json
import os
import signal
import subprocess
import sys
import time

from dgmc_tpu.utils.io import write_json_atomic

__all__ = ['Supervisor', 'add_supervisor_args', 'strip_supervisor_args',
           'supervise_cli', 'DEFAULT_MAX_RESTARTS',
           'DEFAULT_HANG_DEADLINE_S', 'DEFAULT_PEER_STALE_S']

DEFAULT_MAX_RESTARTS = 5
#: Watchdog deadline injected into supervised children that have an obs
#: dir but no explicit ``--watchdog-deadline`` of their own.
DEFAULT_HANG_DEADLINE_S = 600.0
#: How stale a PEER host's control-plane heartbeat may go before the
#: supervisor declares that host dead and elastically restarts. The
#: heartbeat refresher writes every ~1 s while its process lives, so
#: this only needs to outlast filesystem jitter — it is NOT a progress
#: deadline (that is the watchdog's and the fence guard's job).
DEFAULT_PEER_STALE_S = 15.0
RECOVERY_FILE = 'recovery.json'
#: The per-attempt obs subdirectory naming contract. The supervisor
#: writes these; ``faults.ledger_dir`` (fire-once ledger placement) and
#: ``obs.report`` (supervised-root loading) parse them — keep all three
#: on these helpers.
ATTEMPT_PREFIX = 'attempt_'


def attempt_dirname(k):
    return f'{ATTEMPT_PREFIX}{k}'


def is_attempt_dirname(name):
    return (name.startswith(ATTEMPT_PREFIX)
            and name[len(ATTEMPT_PREFIX):].isdigit())
#: "no failure yet" sentinel for same-step tracking — distinct from
#: None, which is a real observation ("died with no step evidence").
_NO_FAILURE = object()

#: Supervisor-only flags (name -> number of value tokens) stripped from
#: the child's argv: the child must run unsupervised or it would recurse.
_OWN_FLAGS = {
    '--supervise': 0,
    '--max-restarts': 1, '--max_restarts': 1,
    '--restart-backoff': 1, '--restart_backoff': 1,
    '--no-elastic': 0, '--no_elastic': 0,
}


def add_supervisor_args(parser):
    """Register ``--supervise`` / ``--max-restarts`` on an argparse
    parser (every experiment CLI + bench.py)."""
    parser.add_argument(
        '--supervise', action='store_true',
        help='run this command under the fault-tolerant supervisor: the '
             'run executes in a child process; on crash, preemption or '
             'hang (watchdog heartbeat stale / hang_report.json) the '
             'child is killed and restarted from the latest checkpoint '
             'with exponential backoff and a graceful-degradation '
             'ladder (disable fused Pallas kernels -> f32 policy -> '
             'shrink the mesh). Recovery timeline: '
             '<obs-dir>/recovery.json')
    parser.add_argument(
        '--max-restarts', '--max_restarts', dest='max_restarts', type=int,
        default=DEFAULT_MAX_RESTARTS, metavar='N',
        help='restart budget under --supervise (default %(default)s); '
             'exhausting it exits nonzero with outcome "gave-up"')
    parser.add_argument(
        '--restart-backoff', '--restart_backoff', dest='restart_backoff',
        type=float, default=1.0, metavar='SEC',
        help='base of the exponential restart backoff (default '
             '%(default)s s, doubling per restart, capped at 60 s)')
    parser.add_argument(
        '--no-elastic', '--no_elastic', dest='elastic',
        action='store_false', default=True,
        help='disable elastic restarts under --supervise: by default a '
             'DISTRIBUTED failure (peer death, stale peer heartbeat, '
             'fence-deadline exit, watchdog hang) immediately shrinks '
             'the mesh (--model_shards / --row_shards halved), records '
             'the decision in the control-plane ledger, and resumes '
             'from the latest checkpoint resharded onto the smaller '
             'mesh')
    return parser


def strip_supervisor_args(argv):
    """argv minus the supervisor's own flags (child command line)."""
    out, i = [], 0
    while i < len(argv):
        tok = argv[i]
        name = tok.split('=', 1)[0]
        if name in _OWN_FLAGS:
            i += 1 + (0 if '=' in tok else _OWN_FLAGS[name])
            continue
        out.append(tok)
        i += 1
    return out


def _replace_flag_value(argv, names, value):
    """Return argv with flag ``names``'s value replaced (appended when
    absent). Handles both ``--flag V`` and ``--flag=V``."""
    out, i, done = [], 0, False
    while i < len(argv):
        tok = argv[i]
        name = tok.split('=', 1)[0]
        if name in names:
            out.append(f'{name}={value}' if '=' in tok else name)
            if '=' not in tok:
                out.append(str(value))
                i += 1
            done = True
            i += 1
            continue
        out.append(tok)
        i += 1
    if not done:
        out.extend([names[0], str(value)])
    return out


def _flag_value(argv, names):
    for i, tok in enumerate(argv):
        name, _, inline = tok.partition('=')
        if name in names:
            if inline:
                return inline
            if i + 1 < len(argv):
                return argv[i + 1]
    return None


# -- degradation ladder ----------------------------------------------------

def _rung_disable_fused(argv, env):
    if env.get('DGMC_TPU_DISABLE_FUSED'):
        return argv, env, None
    env = dict(env, DGMC_TPU_DISABLE_FUSED='1')
    return argv, env, 'DGMC_TPU_DISABLE_FUSED=1 (all Pallas gates fall ' \
                      'back to XLA)'


def _rung_force_f32(argv, env):
    # Already-f32 runs (any spelling: --f32, --precision f32/=f32) get
    # no rung: a no-op rewrite would burn a ladder slot and record a
    # degradation that ruled nothing out.
    if '--f32' in argv or _flag_value(argv, ('--precision',)) == 'f32':
        return argv, env, None
    return argv + ['--f32'], env, '--f32 (bf16 policy off)'


#: Mesh-size flag families the shrink rung (and the elastic restart)
#: knows how to halve — the legacy correspondence sharding and the
#: partition-rule streamed layout.
_MESH_FLAGS = (('--model_shards', '--model-shards'),
               ('--row_shards', '--row-shards'))


def _rung_shrink_mesh(argv, env):
    for names in _MESH_FLAGS:
        cur = _flag_value(argv, names)
        if cur is None or int(cur) <= 1:
            continue
        new = max(1, int(cur) // 2)
        argv = _replace_flag_value(argv, names, new)
        return argv, env, f'{names[0]} {cur} -> {new} (shrink the mesh)'
    return argv, env, None


def mesh_size(argv):
    """The current mesh-shard count named by ``argv`` (or ``None``)."""
    for names in _MESH_FLAGS:
        cur = _flag_value(argv, names)
        if cur is not None:
            return int(cur)
    return None


#: name -> rewrite(argv, env) -> (argv, env, description-or-None).
LADDER_RUNGS = {
    'disable-fused': _rung_disable_fused,
    'f32': _rung_force_f32,
    'shrink-mesh': _rung_shrink_mesh,
}
DEFAULT_LADDER = ('disable-fused', 'f32', 'shrink-mesh')


class Supervisor:
    """Run ``cmd + argv`` under crash/hang supervision.

    Args:
        cmd: interpreter prefix, e.g. ``[sys.executable, '-m',
            'dgmc_tpu.experiments.dbp15k']``.
        argv: the child's own arguments (already stripped of supervisor
            flags). Its ``--obs-dir`` is rewritten per attempt to
            ``<obs_dir>/attempt_<k>``.
        obs_dir: root obs directory (recovery.json + per-attempt
            telemetry); ``None`` disables hang detection and puts
            recovery.json next to ``ckpt_dir`` (or the cwd).
        ckpt_dir: the run's checkpoint dir (restart = resume); ``None``
            means restarts re-run from scratch.
        hang_deadline_s: child watchdog deadline; the supervisor treats a
            heartbeat older than ``2x`` this as a wedged child. ``None``
            disables the heartbeat watch (hang_report detection stays).
        first_heartbeat_s: how long after spawn a child may go without
            writing its FIRST heartbeat before it counts as wedged
            (default ``max(4x hang_deadline, 300)``). The heartbeat file
            is written by the child's watchdog thread, which only exists
            once RunObserver is up — a child stuck in imports or
            ``jax.distributed.initialize`` (one host of the mesh never
            joining) writes neither heartbeat nor hang_report, and
            without this bound the supervisor would wait on it forever.
            Only active when the heartbeat watch is (``hang_deadline_s``
            set and an obs dir present).
        ladder: rung names from :data:`LADDER_RUNGS`, applied one per
            escalation after ``same_step_threshold`` failures at the
            same step.
        elastic: perform an **elastic restart** on a *distributed*
            failure (peer death, stale peer heartbeat, fence-deadline
            exit, watchdog hang): immediately halve the mesh flags
            (``--model_shards`` / ``--row_shards``), publish the
            decision to the control-plane ledger (host-0 leadership —
            see :mod:`~dgmc_tpu.resilience.distributed_guard`), and
            resume from the latest checkpoint, which
            ``train/checkpoint.py`` reshards onto the smaller mesh.
        host_index: this supervisor's host index. Host 0 leads: it
            writes the recovery ledger; followers wait for its decision
            before restarting so every host rejoins with the same
            attempt number and mesh size.
        peer_stale_s: staleness bound on PEER control-plane heartbeats
            (``<attempt>/control/host_<i>.json``) before a peer counts
            as dead and the child (wedged in a collective with it) is
            killed.
    """

    def __init__(self, cmd, argv, *, obs_dir=None, ckpt_dir=None,
                 max_restarts=DEFAULT_MAX_RESTARTS, backoff_s=1.0,
                 backoff_max_s=60.0, grace_s=10.0, hang_deadline_s=None,
                 first_heartbeat_s=None, ladder=DEFAULT_LADDER,
                 same_step_threshold=2, poll_s=0.5, env=None,
                 elastic=True, host_index=0,
                 peer_stale_s=DEFAULT_PEER_STALE_S):
        self.cmd = list(cmd)
        self.argv = list(argv)
        self.obs_dir = obs_dir
        self.ckpt_dir = ckpt_dir
        self.max_restarts = int(max_restarts)
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self.grace_s = float(grace_s)
        self.hang_deadline_s = hang_deadline_s
        self.first_heartbeat_s = first_heartbeat_s
        self.ladder = [r for r in ladder if r in LADDER_RUNGS]
        self.same_step_threshold = int(same_step_threshold)
        self.poll_s = float(poll_s)
        self._base_env = dict(os.environ if env is None else env)
        self.recovery_path = os.path.join(
            obs_dir or ckpt_dir or '.', RECOVERY_FILE)
        # Children with neither --ckpt_dir nor --obs-dir still need a
        # home for the fire-once fault ledger (faults.LEDGER_ENV): the
        # recovery file's directory is always resolvable and survives
        # restarts.
        self._base_env.setdefault(
            'DGMC_TPU_FAULT_LEDGER_DIR',
            os.path.dirname(os.path.abspath(self.recovery_path)))
        self.elastic = bool(elastic)
        self.host_index = int(host_index)
        self.peer_stale_s = float(peer_stale_s)
        #: How long a FOLLOWER supervisor waits for the leader's ledger
        #: decision before restarting on its own terms (a follower that
        #: can't see the leader must still make progress eventually).
        self.ledger_wait_s = 30.0
        self._t_created = time.time()
        self._ledger = None
        if obs_dir:
            from dgmc_tpu.resilience.distributed_guard import (
                RecoveryLedger, control_dir)
            self._ledger = RecoveryLedger(control_dir(obs_dir),
                                          host_index=self.host_index)
        self.events = []
        self.attempts = []
        self.degradations = []
        self.elastic_events = []
        self.restarts = 0
        self.outcome = 'running'
        self._stop_signal = None
        #: port -> (scrape_time, verdict) for the /healthz watch.
        self._healthz_cache = {}

    # -- recording ---------------------------------------------------------

    def _event(self, event, **detail):
        rec = {'time': round(time.time(), 3), 'event': event,
               'attempt': len(self.attempts) - 1, **detail}
        self.events.append(rec)
        line = ' '.join(f'{k}={v}' for k, v in detail.items())
        print(f'[supervisor] {event} {line}'.rstrip(),
              file=sys.stderr, flush=True)
        self._write_recovery()

    def _write_recovery(self):
        payload = {
            'tool': 'dgmc_tpu.resilience.supervisor',
            'cmd': self.cmd,
            'argv': self.argv,
            'max_restarts': self.max_restarts,
            'hang_deadline_s': self.hang_deadline_s,
            'outcome': self.outcome,
            'restarts': self.restarts,
            'degradations': self.degradations,
            'elastic': self.elastic_events,
            'attempts': self.attempts,
            'events': self.events,
        }
        # quiet: a supervisor must never die of its own telemetry.
        write_json_atomic(self.recovery_path, payload, indent=1,
                          quiet=True)

    # -- child plumbing ----------------------------------------------------

    def _attempt_dirs(self, k):
        if not self.obs_dir:
            return None, None, None, None
        adir = os.path.join(self.obs_dir, attempt_dirname(k))
        from dgmc_tpu.resilience.distributed_guard import control_dir
        return (adir, os.path.join(adir, 'heartbeat.json'),
                os.path.join(adir, 'hang_report.json'),
                control_dir(adir))

    @staticmethod
    def _candidate_paths(path):
        """The watched file plus its multi-process homes: a multi-host
        child's RunObserver writes under ``<attempt>/host_<i>/``
        (parallel.host_obs_dir), so the heartbeat/hang_report of a
        sharded run never lands at the attempt root. Any host's file
        counts — the straggling host is exactly the evidence."""
        if not path:
            return []
        adir, name = os.path.split(path)
        out = [path]
        try:
            hosts = [d for d in os.listdir(adir)
                     if d.startswith('host_')
                     and os.path.isdir(os.path.join(adir, d))]
        except OSError:
            hosts = []
        out.extend(os.path.join(adir, d, name) for d in sorted(hosts))
        return out

    def _clear_stale_evidence(self, *paths):
        """Drop liveness evidence left in a reused attempt dir by a
        PREVIOUS supervisor session (same ``--obs-dir``; attempt
        numbering restarts at 0). ``_watch`` cannot tell an hours-old
        deadline ``hang_report.json`` or heartbeat from this child's, so
        without this a re-run kills its own healthy children on the
        first poll — long before they finish importing jax. The child
        rewrites all telemetry in its attempt dir anyway; only the
        liveness files need pre-clearing."""
        for path in paths:
            for p in self._candidate_paths(path):
                try:
                    os.remove(p)
                except OSError:
                    pass

    def _clear_control_dir(self, cdir):
        """Control-plane liveness (host heartbeats, tombstones) is
        per-attempt like the watchdog heartbeat: a PREVIOUS session's
        files in a reused attempt dir would read as instantly-dead
        peers and kill a healthy child on the first poll. Only files
        older than this supervisor session are cleared: on a shared
        obs filesystem a faster host's supervisor reaches the attempt
        first and its child may already have written THIS attempt's
        heartbeats or tombstones — wiping those would hide exactly the
        peer-death evidence this session must classify on."""
        if not cdir:
            return
        try:
            names = os.listdir(cdir)
        except OSError:
            return
        for name in names:
            p = os.path.join(cdir, name)
            try:
                # 2 s slack: sandboxed filesystems truncate mtimes, and
                # a file another host wrote a moment before this
                # supervisor started must survive; genuinely stale
                # evidence is minutes-to-hours old.
                if os.path.getmtime(p) < self._t_created - 2.0:
                    os.remove(p)
            except OSError:
                pass

    def _child_argv(self, attempt_dir):
        argv = list(self.argv)
        if attempt_dir:
            argv = _replace_flag_value(argv, ('--obs-dir', '--obs_dir'),
                                       attempt_dir)
        return argv

    def _read_heartbeat(self, path):
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _healthz_verdict(self, host, port, now):
        """Scrape a child's ``/healthz`` (at the host+port its
        heartbeat advertises): ``True`` = endpoint says healthy,
        ``False`` = endpoint EXPLICITLY says stale (a ``healthy:
        false`` body — the 503), ``None`` = the scrape failed
        (unreachable, garbage, or an errored handler answering 500
        with no verdict) — fall back to the heartbeat file; a failed
        scrape must never condemn the child on its own. Scrapes are
        throttled per endpoint so a tight poll loop does not hammer
        the child's plane; a cached verdict under 1 s old is reused."""
        key = (host, port)
        cached = self._healthz_cache.get(key)
        if cached is not None and now - cached[0] < 1.0:
            return cached[1]
        from dgmc_tpu.obs.live import probe_healthz
        res = probe_healthz(port, host=host, timeout_s=2.0)
        verdict = None
        if res is not None:
            code, payload = res
            if 'healthy' in payload:
                verdict = bool(payload['healthy'])
            elif code == 200:
                verdict = True
        self._healthz_cache[key] = (now, verdict)
        return verdict

    def _latest_ckpt_step(self):
        if self.ckpt_dir and os.path.isdir(self.ckpt_dir):
            steps = [int(d) for d in os.listdir(self.ckpt_dir)
                     if d.isdigit()
                     and os.path.isdir(os.path.join(self.ckpt_dir, d))]
            if steps:
                return max(steps)
        return None

    def _steps_completed(self, heartbeat_path, start_step=None):
        """Best evidence of where the attempt died, in GLOBAL schedule
        units: the heartbeat's step counter (any host's — the minimum,
        so a straggler counts) is per-PROCESS and resets on every
        restart, so it is offset by ``start_step`` (the checkpoint step
        the attempt resumed from) — otherwise a run preempted every K
        steps reports K forever and a healthy, progressing run reads as
        stuck at one step and gets wrongly degraded. Fallback: the
        newest committed checkpoint step."""
        steps = [hb['steps_completed']
                 for hb in map(self._read_heartbeat,
                               self._candidate_paths(heartbeat_path))
                 if hb and hb.get('steps_completed') is not None]
        ck = self._latest_ckpt_step()
        if steps:
            derived = (start_step or 0) + min(steps)
            # The heartbeat samples at the watchdog's poll cadence and
            # can lag fast steps; a COMMITTED checkpoint is proof of
            # progress at least that far, so it floors the estimate
            # (matters for the same-step ladder: a run that died right
            # after checkpointing step N must not read as stuck at the
            # stale heartbeat's step).
            return derived if ck is None else max(derived, ck)
        return ck

    def _kill(self, proc, reason):
        """SIGTERM (lets the child watchdog dump its report), grace,
        SIGKILL."""
        self._event('kill', reason=reason, pid=proc.pid)
        try:
            proc.terminate()
            try:
                proc.wait(timeout=self.grace_s)
                return
            except subprocess.TimeoutExpired:
                pass
            proc.kill()
            proc.wait(timeout=self.grace_s)
        except OSError:
            pass

    def _dead_peer(self, cdir):
        """A peer host the control plane says is dead: tombstoned, or
        its heartbeat refresher stopped (the process died with it).
        Returns ``'host_<i>'`` or ``None``. Hosts that never wrote a
        heartbeat are absent (still importing), not dead — and this
        host's OWN child is excluded from the staleness scan: its
        liveness is the watchdog heartbeat's job, and a delayed write
        from it must not read as a dead *peer* and shrink a healthy
        mesh (a tombstone for it still counts — tombstones are written
        deliberately)."""
        if not cdir:
            return None
        from dgmc_tpu.resilience.distributed_guard import (
            read_heartbeats, read_tombstones)
        tombs = read_tombstones(cdir)
        if tombs:
            return f'host_{min(tombs)}'
        beats = read_heartbeats(cdir)
        if len(beats) < 2:
            return None
        now = time.time()
        stale = [h for h, rec in beats.items()
                 if h != self.host_index
                 and now - rec.get('time', 0) > self.peer_stale_s]
        if stale and len(stale) < len(beats):
            return f'host_{min(stale)}'
        return None

    def _dead_peer_tombstone(self, cdir):
        """Post-mortem tombstone check (``'host_<i>'`` or ``None``)."""
        if not cdir:
            return None
        from dgmc_tpu.resilience.distributed_guard import read_tombstones
        tombs = read_tombstones(cdir)
        return f'host_{min(tombs)}' if tombs else None

    def _is_distributed_failure(self, reason):
        """Failures that mean the MESH broke, not the program: a dead
        or partitioned peer, a fence that missed its deadline, or a
        wedged collective the watchdog/heartbeat layer caught. These
        trigger the elastic restart; ordinary crashes just retry.
        ``no-first-heartbeat`` is deliberately NOT here: a slow first
        compile looks identical to a distributed-init wedge from this
        vantage point, and permanently halving a healthy mesh for slow
        compilation is the worse error — the init wedge gets its crisp
        signal from the fence-guarded ``initialize_distributed``
        (``exit:FENCE_TIMEOUT_RC``), and failing that, the same-step
        ladder still reaches the shrink rung."""
        from dgmc_tpu.resilience.distributed_guard import FENCE_TIMEOUT_RC
        return (reason.startswith(('peer-death', 'hang-report',
                                   'heartbeat-stale', 'healthz-stale'))
                or reason == f'exit:{FENCE_TIMEOUT_RC}')

    def _adopt_ledger_mesh(self, argv, attempt):
        """Follower path: block (bounded) for the leader's decision on
        ``attempt`` and rewrite this host's mesh flag to the decided
        size. Returns the (possibly rewritten) argv."""
        led = self._ledger.wait_for_attempt(
            attempt, timeout_s=self.ledger_wait_s,
            poll_s=min(self.poll_s, 0.2))
        if led is None:
            self._event('ledger-timeout', attempt=attempt,
                        waited_s=self.ledger_wait_s)
            return argv
        shards = (led.get('mesh') or {}).get('shards')
        cur = mesh_size(argv)
        if not shards or cur is None or shards == cur:
            return argv
        for names in _MESH_FLAGS:
            if _flag_value(argv, names) is not None:
                argv = _replace_flag_value(argv, names, shards)
                self._event('ledger-adopt', attempt=attempt,
                            detail=f'{names[0]} {cur} -> {shards} '
                                   f'(leader decision)')
                break
        return argv

    def _watch(self, proc, heartbeat_path, hang_report_path,
               control_dir=None):
        """Wait for child exit; return a hang reason if WE killed it."""
        # One health definition: the same factor the child's /healthz
        # endpoint applies (obs/live.py) — a 503 from the plane and a
        # heartbeat-file staleness kill are the same verdict.
        from dgmc_tpu.obs.live import STALE_AFTER_FACTOR
        stale_after = (STALE_AFTER_FACTOR * self.hang_deadline_s
                       if self.hang_deadline_s else None)
        first_beat_by = None
        if stale_after and heartbeat_path:
            first_beat_by = time.time() + (
                self.first_heartbeat_s if self.first_heartbeat_s
                is not None else max(4.0 * self.hang_deadline_s, 300.0))
        while True:
            if self._stop_signal is not None:
                return f'preempted:{self._stop_signal}'
            try:
                proc.wait(timeout=self.poll_s)
                return None
            except subprocess.TimeoutExpired:
                pass
            dead = self._dead_peer(control_dir)
            if dead is not None:
                # The surviving child is (or soon will be) wedged in a
                # collective its dead peer can never join: kill it now
                # and let the elastic restart shrink the mesh.
                self._kill(proc, f'peer-death:{dead}')
                return f'peer-death:{dead}'
            for path in self._candidate_paths(hang_report_path):
                if not os.path.exists(path):
                    continue
                rep = self._read_heartbeat(path) or {}
                # The watchdog re-dumps on SIGTERM during shutdown too;
                # only a DEADLINE dump (the watchdog's staleness dump or
                # a fence-deadline dump whose process somehow survived)
                # means "wedged, kill me".
                if str(rep.get('reason', '')).startswith(
                        ('deadline', 'fence-deadline')):
                    self._kill(proc, 'hang-report')
                    return 'hang-report'
            if stale_after and heartbeat_path:
                # Before the first heartbeat (imports, compiles) the
                # child is given the benefit of the doubt: the watchdog
                # thread writes one as soon as it is armed. Any host's
                # heartbeat going stale condemns the run — one wedged
                # host wedges the collective.
                beats = [hb for hb in map(
                    self._read_heartbeat,
                    self._candidate_paths(heartbeat_path)) if hb]
                now = time.time()
                for hb in beats:
                    # Endpoint-aware first: a heartbeat advertising a
                    # live port gets its verdict from /healthz — the
                    # child's own plane evaluating the SAME staleness
                    # definition live, immune to heartbeat-file write
                    # lag. The file age is the fallback whenever the
                    # scrape fails (no plane, port gone, timeout).
                    port = hb.get('port')
                    verdict = None
                    if port:
                        verdict = self._healthz_verdict(
                            hb.get('host') or '127.0.0.1', port, now)
                    if verdict is True:
                        continue
                    if verdict is False:
                        self._kill(proc, 'healthz-stale')
                        return 'healthz-stale'
                    if now - hb.get('time', 0) > stale_after:
                        self._kill(proc, 'heartbeat-stale')
                        return 'heartbeat-stale'
                # ...but the doubt is bounded: a child wedged BEFORE its
                # watchdog thread exists (imports, distributed init with
                # a host that never joins) writes neither heartbeat nor
                # hang_report, ever.
                if not beats and first_beat_by \
                        and time.time() > first_beat_by:
                    self._kill(proc, 'no-first-heartbeat')
                    return 'no-first-heartbeat'

    def _on_signal(self, signum, frame):
        self._stop_signal = signal.Signals(signum).name

    # -- the loop ----------------------------------------------------------

    def run(self):
        """Supervise until completion, preemption of the supervisor
        itself, or an exhausted restart budget. Returns the exit code."""
        prev_handlers = {}
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                prev_handlers[sig] = signal.signal(sig, self._on_signal)
            except ValueError:
                break
        try:
            return self._run()
        finally:
            for sig, prev in prev_handlers.items():
                signal.signal(sig, prev)
            self._write_recovery()

    def _run(self):
        argv, env = self.argv, dict(self._base_env)
        # The "no previous failure" sentinel is NOT None: an attempt
        # with no step evidence at all (died in setup/compile, no obs
        # dir) reports steps_completed=None, and repeated no-progress
        # deaths are precisely a "same step" pattern the ladder must
        # escalate on.
        rung_idx, same_step_fails, last_fail_step = 0, 0, _NO_FAILURE
        attempt = 0
        while True:
            attempt_dir, hb_path, hang_path, ctrl_dir = \
                self._attempt_dirs(attempt)
            if attempt_dir:
                os.makedirs(attempt_dir, exist_ok=True)
                self._clear_stale_evidence(hb_path, hang_path)
                self._clear_control_dir(ctrl_dir)
            start_step = self._latest_ckpt_step()
            child_argv = self._child_argv(attempt_dir)
            rec = {'attempt': attempt,
                   'obs_dir': attempt_dir,
                   'argv': child_argv,
                   'env_overrides': {
                       k: v for k, v in env.items()
                       if self._base_env.get(k) != v},
                   'start_time': round(time.time(), 3)}
            self.attempts.append(rec)
            self._event('start', cmd=' '.join(self.cmd + child_argv))
            try:
                proc = subprocess.Popen(self.cmd + child_argv, env=env)
            except OSError as e:
                # A failed fork/exec (EAGAIN under memory pressure — the
                # very condition a leaking child produces) is transient
                # like any crash: it gets the backoff and the restart
                # budget, not an instant give-up.
                proc, hang_reason = None, None
                spawn_failure = f'spawn-failed:{type(e).__name__}: {e}'
            else:
                spawn_failure = None
                hang_reason = self._watch(proc, hb_path, hang_path,
                                          ctrl_dir)
                if hang_reason and hang_reason.startswith('preempted'):
                    # Reap the child BEFORE recording: the attempt's rc
                    # and final step evidence only exist once it is dead.
                    self._kill(proc, hang_reason)
            rec['end_time'] = round(time.time(), 3)
            rec['rc'] = proc.returncode if proc else None
            rec['steps_completed'] = self._steps_completed(hb_path,
                                                           start_step)

            if hang_reason and hang_reason.startswith('preempted'):
                rec['reason'] = hang_reason
                self.outcome = 'preempted'
                self._event('preempted', signal=self._stop_signal)
                return 128 + getattr(signal,
                                     self._stop_signal or 'SIGTERM',
                                     signal.SIGTERM)
            if proc and hang_reason is None and proc.returncode == 0:
                rec['reason'] = 'completed'
                self.outcome = 'completed'
                self._event('complete', restarts=self.restarts)
                return 0

            reason = spawn_failure or hang_reason or (
                f'signal:{signal.Signals(-proc.returncode).name}'
                if proc.returncode < 0 else f'exit:{proc.returncode}')
            # A child that died by its own hand can still carry
            # distributed evidence the poll loop never saw: a
            # peer-death tombstone (the injected fault SIGKILLs
            # immediately after writing it) means a HOST died, not the
            # run — reclassify so the elastic path fires.
            if not reason.startswith('peer-death'):
                dead = self._dead_peer_tombstone(ctrl_dir)
                if dead is not None:
                    reason = f'peer-death:{dead} ({reason})'
            rec['reason'] = reason
            self._event('failure', reason=reason,
                        steps_completed=rec['steps_completed'])

            self.restarts += 1
            if self.restarts > self.max_restarts:
                self.outcome = 'gave-up'
                self._event('give-up', restarts=self.restarts - 1,
                            max_restarts=self.max_restarts)
                return proc.returncode if proc and proc.returncode \
                    and proc.returncode > 0 else 1

            # Elastic restart: a DISTRIBUTED failure (a peer died, a
            # fence timed out, a collective wedged) is not a bug to
            # retry harder against — the mesh itself must shrink. Fires
            # immediately, without waiting for the same-step ladder:
            # restarting on the same mesh would wedge the same
            # collective again.
            elastically_shrunk = False
            if self.elastic and self._is_distributed_failure(reason):
                new_argv, new_env, desc = _rung_shrink_mesh(argv, env)
                if desc:
                    argv, env = new_argv, new_env
                    self.argv = argv
                    self.elastic_events.append(
                        {'attempt': attempt, 'reason': reason,
                         'detail': desc, 'mesh_after': mesh_size(argv)})
                    self._event('elastic-shrink', reason=reason,
                                detail=desc)
                    elastically_shrunk = True
                    # The mesh changed; old same-step evidence is moot.
                    same_step_fails, last_fail_step = 0, _NO_FAILURE

            # Same-step escalation: repeated death at one step (or with
            # no progress evidence at all) means retrying harder won't
            # help — degrade instead.
            if not elastically_shrunk:
                step = rec['steps_completed']
                if step == last_fail_step:
                    same_step_fails += 1
                else:
                    same_step_fails = 0
                last_fail_step = step
                if same_step_fails >= self.same_step_threshold - 1:
                    while rung_idx < len(self.ladder):
                        rung = self.ladder[rung_idx]
                        rung_idx += 1
                        argv, env, desc = LADDER_RUNGS[rung](argv, env)
                        self.argv = argv
                        if desc:
                            self.degradations.append(
                                {'rung': rung, 'attempt': attempt,
                                 'detail': desc})
                            self._event('degrade', rung=rung, detail=desc)
                            break
                    same_step_fails = 0

            # Publish the next attempt's terms before any child can
            # start it: with host-0 leadership every host's supervisor
            # restarts onto the SAME attempt number and mesh size. A
            # FOLLOWER waits for the leader's decision and ADOPTS its
            # mesh size — two hosts restarting with different
            # --model_shards would wedge the very first collective
            # again. A follower that cannot see a decision within
            # ledger_wait_s proceeds on its own terms (progress beats
            # a monitor deadlocked on a dead leader).
            if self._ledger is not None:
                if self._ledger.is_leader:
                    try:
                        self._ledger.decide(
                            attempt + 1, reason,
                            mesh={'shards': mesh_size(argv)},
                            detail=(self.elastic_events[-1]['detail']
                                    if elastically_shrunk else None))
                    except OSError:
                        pass  # the ledger never takes the monitor down
                else:
                    argv = self._adopt_ledger_mesh(argv, attempt + 1)
                    self.argv = argv

            delay = min(self.backoff_max_s,
                        self.backoff_s * (2 ** (self.restarts - 1)))
            self._event('restart', number=self.restarts,
                        backoff_s=round(delay, 2),
                        resume_from=('checkpoint' if self.ckpt_dir
                                     else 'scratch'))
            end = time.time() + delay
            while time.time() < end:
                if self._stop_signal is not None:
                    self.outcome = 'preempted'
                    self._event('preempted', signal=self._stop_signal)
                    return 128 + getattr(signal,
                                         self._stop_signal or 'SIGTERM',
                                         signal.SIGTERM)
                time.sleep(min(self.poll_s, max(0.0, end - time.time())))
            attempt += 1


def supervise_cli(module, args, argv=None, *,
                  ladder=DEFAULT_LADDER, cmd=None):
    """``--supervise`` glue for a CLI ``main()``: re-run the same command
    (minus supervisor flags) in supervised children.

    Args:
        module: the child's ``python -m`` module path (ignored when
            ``cmd`` is given — bench.py passes its script path).
        args: the parsed namespace (reads obs_dir / ckpt_dir /
            watchdog_deadline / max_restarts / restart_backoff).
        argv: the original argv (defaults to ``sys.argv[1:]``).
        ladder: degradation rungs valid for this CLI's flag surface.

    Returns the supervisor's exit code (0 = run completed).
    """
    argv = list(sys.argv[1:] if argv is None else argv)
    child_argv = strip_supervisor_args(argv)
    obs_dir = getattr(args, 'obs_dir', None)
    ckpt_dir = getattr(args, 'ckpt_dir', None)
    deadline = getattr(args, 'watchdog_deadline', None)
    if obs_dir and deadline is None:
        # Hang detection needs an armed watchdog in the child; arm the
        # default deadline when the user did not pick one. An EXPLICIT
        # --watchdog-deadline 0 is the documented opt-out (a
        # legitimately slow job) and is honored, not overridden.
        deadline = DEFAULT_HANG_DEADLINE_S
        child_argv = child_argv + ['--watchdog-deadline', str(deadline)]
    elif not deadline:
        deadline = None
    if obs_dir and deadline \
            and getattr(args, 'fence_deadline', None) is None:
        # Arm the collective-fence deadline alongside the watchdog: a
        # fence that misses it exits FENCE_TIMEOUT_RC with a
        # hang_report.json naming the missing host/phase — prompt,
        # attributable evidence instead of waiting out the heartbeat
        # staleness. Same opt-out contract: --fence-deadline 0 is
        # honored.
        child_argv = child_argv + ['--fence-deadline', str(deadline)]
    if not obs_dir:
        print('[supervisor] no --obs-dir: hang detection disabled '
              '(crash/preemption recovery only)', file=sys.stderr)
        deadline = None
    if not ckpt_dir:
        print('[supervisor] no --ckpt_dir: restarts re-run from scratch',
              file=sys.stderr)
    sup = Supervisor(
        cmd or [sys.executable, '-m', module], child_argv,
        obs_dir=obs_dir, ckpt_dir=ckpt_dir,
        max_restarts=getattr(args, 'max_restarts', DEFAULT_MAX_RESTARTS),
        backoff_s=getattr(args, 'restart_backoff', 1.0),
        hang_deadline_s=deadline, ladder=ladder,
        elastic=getattr(args, 'elastic', True),
        # Multi-host launchers run one supervisor per host (same
        # command, shared obs filesystem); the env var names this
        # host's index so exactly one supervisor leads the ledger.
        host_index=int(os.environ.get('DGMC_TPU_HOST_INDEX', '0') or 0))
    return sup.run()
