"""Fault-tolerant run supervision: detection → recovery, closed-loop.

The watchdog (``dgmc_tpu/obs/watchdog.py``) turned silent rc:124 deaths
into evidence; this package acts on it, treating preemption, wedged
collectives, non-finite steps, and torn checkpoints as routine events to
recover from — the same stance the DGMC paper takes toward noisy initial
correspondences (detect, correct, keep iterating):

- :mod:`~dgmc_tpu.resilience.supervisor` — ``--supervise``: run the CLI
  in a child process; kill and resume from the latest checkpoint on
  crash/hang/preemption, with a bounded exponential-backoff restart
  budget and a graceful-degradation ladder (disable fused Pallas
  kernels → f32 policy → shrink the mesh). Timeline in
  ``<obs>/recovery.json``.
- :mod:`~dgmc_tpu.resilience.faults` — ``--inject-fault``:
  deterministic fault injection (crash/kill/stall at step N, NaN into
  grads, checkpoint truncation/corruption, transient download
  failures) so every recovery path is exercised by tests.
- :mod:`~dgmc_tpu.resilience.guard` — host-side rollback policy over
  the in-graph non-finite guard of ``make_train_step(guard=True)``.
- :mod:`~dgmc_tpu.resilience.distributed_guard` — the multi-host
  control plane: per-host heartbeat files, peer-death/straggler
  detection, the host-0 recovery ledger, and collective fences with
  deadlines (a wedged fence dumps ``hang_report.json`` naming the
  missing host/phase and exits ``FENCE_TIMEOUT_RC`` instead of hanging
  forever). The supervisor turns its evidence into **elastic
  restarts**: shrink the mesh, reshard the checkpoint, resume.

``faults``, ``supervisor`` and ``distributed_guard`` are jax-free
(importable anywhere, even while a backend is wedged); ``guard``
touches jax only when a rollback actually fires.
"""

from dgmc_tpu.resilience.distributed_guard import (FENCE_TIMEOUT_RC,
                                                   FenceGuard,
                                                   HostChannel,
                                                   RecoveryLedger)
from dgmc_tpu.resilience.faults import (FaultInjected, FaultPlan,
                                        FaultSpec, add_fault_args,
                                        arm_download_faults,
                                        consume_download_fault,
                                        corrupt_checkpoint, parse_spec)
from dgmc_tpu.resilience.guard import RollbackGuard
from dgmc_tpu.resilience.supervisor import (Supervisor,
                                            add_supervisor_args,
                                            strip_supervisor_args,
                                            supervise_cli)

__all__ = [
    'FENCE_TIMEOUT_RC',
    'FaultInjected',
    'FaultPlan',
    'FaultSpec',
    'FenceGuard',
    'HostChannel',
    'RecoveryLedger',
    'add_fault_args',
    'arm_download_faults',
    'consume_download_fault',
    'corrupt_checkpoint',
    'parse_spec',
    'RollbackGuard',
    'Supervisor',
    'add_supervisor_args',
    'strip_supervisor_args',
    'supervise_cli',
]
