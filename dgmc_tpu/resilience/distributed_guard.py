"""Distributed resilience control plane: heartbeats, ledger, fences.

Every multi-host failure mode this repo has actually hit
(``MULTICHIP_r01-r05``) looked the same from outside: one process wedged
in a collective, every peer blocked with it, and the external timeout
delivered rc:124 with nothing on disk. PR 7's supervisor closed the
single-process loop (crash → restart → degrade); this module closes the
*distributed* one with three jax-free pieces that work while a backend
is wedged — and that therefore must never import jax:

- :class:`HostChannel` — a per-host heartbeat side-channel under the obs
  directory (``<obs>/control/host_<i>.json``, atomic tmp+rename, a
  daemon refresher thread keeps it fresh while the host lives). Peers
  read each other's files: a stale file means the *process* died
  (peer-death — the refresher thread dies with it); a step counter that
  stops advancing while the file stays fresh means a straggler or a
  wedged collective. :meth:`HostChannel.dead_peers` /
  :meth:`HostChannel.stragglers` are the detection queries the
  supervisor and the fence guard share.
- :class:`RecoveryLedger` — one shared decision file
  (``<obs-root>/control/ledger.json``) with **host-0 leadership**: only
  the leader writes, every host reads, so all hosts agree on the attempt
  number and the (possibly shrunk) mesh size before rejoining. Followers
  :meth:`~RecoveryLedger.wait_for_attempt` instead of guessing.
- :class:`FenceGuard` — a deadline on one *blocking* section (an epoch
  device fence, ``jax.distributed.initialize``, a checkpoint barrier).
  A fence that misses its deadline dumps ``hang_report.json`` naming
  the fence's phase/step and the hosts that never reached it (from the
  channel's last-fence records), then — because a process wedged inside
  one XLA collective can never recover — exits with
  :data:`FENCE_TIMEOUT_RC` so the supervisor sees an *attributable
  death* instead of the rc:124 silence.

The supervisor (``resilience/supervisor.py``) consumes all three: stale
peer heartbeats and peer-death tombstones (``faults.py``'s
``peer-death@N``) classify a failure as *distributed*, and a distributed
failure triggers an **elastic restart** — shrink the mesh flags, record
the decision in the ledger, resume from the latest checkpoint resharded
onto the smaller mesh (``train/checkpoint.py``).
"""

import json
import os
import sys
import threading
import time

from dgmc_tpu.utils.io import write_json_atomic

__all__ = ['HostChannel', 'RecoveryLedger', 'FenceGuard',
           'control_dir', 'control_root', 'FENCE_TIMEOUT_RC',
           'CONTROL_DIRNAME', 'LEDGER_FILE']

#: Subdirectory of an obs dir holding the control-plane files. Heartbeats
#: and tombstones live under the *attempt* obs dir (liveness is
#: per-attempt); the ledger lives under the obs ROOT (decisions span
#: attempts) — see :func:`control_root`.
CONTROL_DIRNAME = 'control'
LEDGER_FILE = 'ledger.json'

#: Exit code of a process whose :class:`FenceGuard` deadline fired. Kept
#: far from the shell/timeout conventions (124/125/126/127) and from
#: 128+signal so the supervisor can classify it unambiguously as a
#: distributed failure (``exit:67`` → elastic restart, not plain retry).
FENCE_TIMEOUT_RC = 67

#: Default refresher cadence of the heartbeat daemon thread.
DEFAULT_BEAT_INTERVAL_S = 1.0

_HOST_FILE = 'host_{}.json'
_TOMBSTONE_FILE = 'host_{}.tombstone.json'


def control_dir(obs_dir):
    """The control-plane directory of one run/attempt's obs dir."""
    return os.path.join(obs_dir, CONTROL_DIRNAME)


def control_root(obs_dir):
    """The obs ROOT's control dir — where the ledger lives. A supervised
    child's ``--obs-dir`` is rewritten to ``<root>/attempt_<k>``; ledger
    decisions must span attempts, so the attempt suffix is stripped
    (mirrors ``faults.ledger_dir``)."""
    from dgmc_tpu.resilience.supervisor import is_attempt_dirname
    base = os.path.basename(os.path.normpath(obs_dir))
    if is_attempt_dirname(base):
        return control_dir(os.path.dirname(os.path.normpath(obs_dir)))
    return control_dir(obs_dir)


def host_heartbeat_path(cdir, host_index):
    return os.path.join(cdir, _HOST_FILE.format(int(host_index)))


def tombstone_path(cdir, host_index):
    return os.path.join(cdir, _TOMBSTONE_FILE.format(int(host_index)))


def write_tombstone(cdir, host_index, step=None, reason='peer-death'):
    """Declare host ``host_index`` dead (the ``peer-death@N`` fault and
    any orderly shutdown path use this): peers and the supervisor treat
    a tombstone as definitive, no staleness argument needed."""
    path = tombstone_path(cdir, host_index)
    write_json_atomic(path, {
        'host': int(host_index), 'pid': os.getpid(),
        'time': round(time.time(), 3), 'step': step, 'reason': reason,
    }, indent=1)
    return path


def read_tombstones(cdir):
    """``{host_index: record}`` for every tombstone in ``cdir``."""
    out = {}
    try:
        names = os.listdir(cdir)
    except OSError:
        return out
    for name in names:
        if not name.endswith('.tombstone.json'):
            continue
        try:
            with open(os.path.join(cdir, name)) as f:
                rec = json.load(f)
            out[int(rec['host'])] = rec
        except (OSError, ValueError, KeyError, TypeError):
            continue
    return out


def read_heartbeats(cdir):
    """``{host_index: record}`` for every host heartbeat in ``cdir``."""
    out = {}
    try:
        names = os.listdir(cdir)
    except OSError:
        return out
    for name in names:
        if not (name.startswith('host_') and name.endswith('.json')
                and not name.endswith('.tombstone.json')):
            continue
        stem = name[len('host_'):-len('.json')]
        if not stem.isdigit():
            continue
        try:
            with open(os.path.join(cdir, name)) as f:
                out[int(stem)] = json.load(f)
        except (OSError, ValueError):
            continue
    return out


class HostChannel:
    """This host's heartbeat writer + the peer-state reader.

    Args:
        obs_dir: the run's obs directory (the *attempt* dir under a
            supervisor); heartbeats land in ``<obs_dir>/control/``.
        host_index: this process's host/process index (0 = leader).
        num_hosts: expected mesh size (recorded for readers; a reader
            must not infer it from file count while hosts are still
            importing).
        fault_plan: optional
            :class:`~dgmc_tpu.resilience.faults.FaultPlan`; when its
            ``coord-partition`` fault has fired, every write is
            suppressed — the host *looks* dead to its peers while still
            running, which is exactly the partition being simulated.
        interval_s: refresher-thread cadence (:meth:`start`).
    """

    def __init__(self, obs_dir, host_index=0, num_hosts=1,
                 fault_plan=None, interval_s=DEFAULT_BEAT_INTERVAL_S):
        self.dir = control_dir(obs_dir)
        self.host_index = int(host_index)
        self.num_hosts = int(num_hosts)
        self.interval_s = float(interval_s)
        self._plan = fault_plan
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self._phase = 'startup'
        self._step = None
        self._last_fence = None
        os.makedirs(self.dir, exist_ok=True)

    @property
    def path(self):
        return host_heartbeat_path(self.dir, self.host_index)

    # -- writing -----------------------------------------------------------

    def _partitioned(self):
        return bool(getattr(self._plan, 'coord_partitioned', False))

    def _write(self):
        if self._partitioned():
            return False
        with self._lock:
            payload = {
                'host': self.host_index,
                'pid': os.getpid(),
                'time': round(time.time(), 3),
                'phase': self._phase,
                'step': self._step,
                'last_fence': self._last_fence,
                'mesh': {'hosts': self.num_hosts},
            }
        return write_json_atomic(self.path, payload, indent=1,
                                 quiet=True)

    def beat(self, phase, step=None):
        """Record this host's current activity and refresh the file."""
        with self._lock:
            self._phase = phase
            if step is not None:
                self._step = step
        self._write()

    def record_fence(self, phase, step):
        """Record a *completed* fence — the attribution a hang report
        needs: a peer whose ``last_fence`` is behind the fence that
        timed out is precisely the missing host."""
        with self._lock:
            self._last_fence = {'phase': phase, 'step': step,
                                'time': round(time.time(), 3)}
            if step is not None:
                self._step = step
        self._write()

    def start(self):
        """Write the first heartbeat and start the refresher thread.
        The thread only refreshes the timestamp — liveness means *the
        process is alive*, so peer-death detection keys on staleness
        (the thread dies with the process) while wedged-collective
        detection is the fence guard's job, not staleness."""
        self._write()
        self._thread = threading.Thread(
            target=self._refresh, name='dgmc-host-channel', daemon=True)
        self._thread.start()
        return self

    def _refresh(self):
        while not self._stop.wait(self.interval_s):
            self._write()

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s * 2 + 1.0)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    # -- reading -----------------------------------------------------------

    def peers(self):
        """``{host_index: heartbeat_record}`` including this host."""
        return read_heartbeats(self.dir)

    def tombstones(self):
        return read_tombstones(self.dir)

    def dead_peers(self, stale_s, now=None):
        """Hosts that must be presumed dead: tombstoned, or their
        heartbeat file went stale (the refresher thread died with the
        process). Hosts that never wrote a file are *absent*, not dead —
        they may still be importing; the fence guard's deadline bounds
        that doubt."""
        now = time.time() if now is None else now
        dead = dict(self.tombstones())
        for host, rec in self.peers().items():
            if host == self.host_index or host in dead:
                continue
            age = now - rec.get('time', 0)
            if age > stale_s:
                dead[host] = dict(rec, stale_s=round(age, 3))
        return dead

    def stragglers(self, behind_steps=1):
        """Hosts whose step counter lags the leader of the pack by more
        than ``behind_steps`` (fresh heartbeats only — a stale host is
        dead, not slow)."""
        peers = {h: r for h, r in self.peers().items()
                 if r.get('step') is not None}
        if len(peers) < 2:
            return {}
        ahead = max(r['step'] for r in peers.values())
        return {h: dict(r, behind=ahead - r['step'])
                for h, r in peers.items()
                if ahead - r['step'] > behind_steps}


class LedgerError(RuntimeError):
    """A non-leader tried to write the recovery ledger."""


class RecoveryLedger:
    """The shared recovery-decision file, host-0 leadership.

    Every host (and every host's supervisor) must agree on the attempt
    number and the mesh size before rejoining a shrunk run — two hosts
    restarting with different ``--model_shards`` would wedge the very
    first collective again. Only the **leader** (host 0's supervisor)
    writes; followers read, or block in :meth:`wait_for_attempt` until
    the leader has published the decision for their next attempt.
    """

    def __init__(self, root_dir, host_index=0):
        self.dir = root_dir
        self.host_index = int(host_index)
        self.path = os.path.join(root_dir, LEDGER_FILE)

    @property
    def is_leader(self):
        return self.host_index == 0

    def read(self):
        try:
            with open(self.path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {'attempt': None, 'mesh': None, 'decisions': []}

    def decide(self, attempt, reason, mesh=None, dead_hosts=(),
               detail=None):
        """Publish the decision for ``attempt`` (leader only): why the
        previous attempt ended, the mesh the next one runs on, and which
        hosts are excluded. Atomic rewrite — a follower sees the old
        complete decision or the new one, never a torn file."""
        if not self.is_leader:
            raise LedgerError(
                f'host {self.host_index} is not the ledger leader '
                f'(host 0 decides; followers wait_for_attempt)')
        ledger = self.read()
        decision = {
            'attempt': int(attempt),
            'time': round(time.time(), 3),
            'reason': reason,
            'mesh': mesh,
            'dead_hosts': sorted(int(h) for h in dead_hosts),
            'detail': detail,
        }
        ledger['attempt'] = int(attempt)
        ledger['mesh'] = mesh
        decisions = ledger.setdefault('decisions', [])
        decisions.append(decision)
        write_json_atomic(self.path, ledger, indent=1)
        return decision

    def wait_for_attempt(self, attempt, timeout_s, poll_s=0.2):
        """Follower path: block until the leader has published a
        decision for ``attempt`` (or newer). Returns the ledger dict, or
        ``None`` on timeout — a follower that cannot see a decision must
        not invent its own mesh size."""
        deadline = time.time() + timeout_s
        while True:
            ledger = self.read()
            if ledger.get('attempt') is not None \
                    and ledger['attempt'] >= attempt:
                return ledger
            if time.time() >= deadline:
                return None
            time.sleep(poll_s)


class FenceGuard:
    """Deadline on one blocking section; miss → report → exit.

    Usage::

        with FenceGuard(report_path, deadline_s=120.0,
                        phase='epoch-fence', step=epoch,
                        channel=host_channel):
            np.asarray(shard.data)   # the blocking device fetch

    If the block does not exit within ``deadline_s``, a timer thread
    writes ``hang_report.json`` — reason ``fence-deadline``, the fence's
    phase/step, every peer's last completed fence, and the hosts that
    never reached this fence — then calls ``os._exit(FENCE_TIMEOUT_RC)``
    (``on_timeout='exit'``). Exiting is deliberate: a process wedged in
    one XLA collective cannot be un-wedged from Python, and a prompt,
    attributable death is what the supervisor's elastic restart needs
    (rc:124 silence is the failure mode this exists to kill).

    ``on_timeout='report'`` only writes the report (tests, and callers
    that have their own kill path). The guard is reusable but not
    reentrant; entering arms a fresh timer, a clean exit cancels it.
    """

    def __init__(self, report_path, deadline_s, phase, step=None,
                 channel=None, on_timeout='exit', context_fn=None,
                 on_dump=None):
        if on_timeout not in ('exit', 'report'):
            raise ValueError(f'on_timeout must be "exit" or "report", '
                             f'got {on_timeout!r}')
        self.report_path = report_path
        self.deadline_s = float(deadline_s)
        self.phase = phase
        self.step = step
        self.channel = channel
        self.on_timeout = on_timeout
        self._context_fn = context_fn
        #: Anomaly fan-out (the flight recorder's fence-timeout
        #: trigger): called with the report's reason string after the
        #: report is written, BEFORE any os._exit — the last code this
        #: process runs, so it must never raise (and is wrapped anyway).
        self._on_dump = on_dump
        self._timer = None
        self._entered_at = None
        self._lock = threading.Lock()
        self._completed = False
        self.fired = False

    def _missing_hosts(self):
        """Peers that never completed this fence — the attribution."""
        if self.channel is None:
            return []
        out = []
        now = time.time()
        for host, rec in sorted(self.channel.peers().items()):
            if host == self.channel.host_index:
                continue
            fence = rec.get('last_fence') or {}
            reached = (fence.get('phase') == self.phase
                       and fence.get('step') is not None
                       and self.step is not None
                       and fence['step'] >= self.step)
            if not reached:
                out.append({
                    'host': host,
                    'phase': rec.get('phase'),
                    'step': rec.get('step'),
                    'last_fence': fence or None,
                    'heartbeat_age_s': round(now - rec.get('time', 0), 3),
                })
        for host, tomb in sorted(self.channel.tombstones().items()):
            out.append({'host': host, 'dead': True,
                        'tombstone': tomb})
        return out

    def _fire(self):
        # A fence that completed right AT the deadline races the timer
        # thread — Timer.cancel() is a no-op once the callback started.
        # The completed flag (set first thing in __exit__, same lock)
        # keeps a just-successful fence from being reported dead and
        # os._exit()ing a healthy run; only the microseconds between
        # the last shard arriving and __exit__ running remain exposed.
        with self._lock:
            if self._completed:
                return
            self.fired = True
        now = time.time()
        # Late import: thread_stacks lives in obs.watchdog (also
        # jax-free); importing it here avoids a module-level cycle with
        # obs.run's lazy import of this module.
        from dgmc_tpu.obs.watchdog import thread_stacks
        report = {
            'reason': f'fence-deadline: {self.phase} incomplete after '
                      f'{self.deadline_s}s',
            'time': now,
            'pid': os.getpid(),
            'argv': sys.argv,
            'deadline_s': self.deadline_s,
            'stalled_for_s': round(now - (self._entered_at or now), 3),
            'in_flight': {'phase': 'fence', 'name': self.phase,
                          'since_s': round(
                              now - (self._entered_at or now), 3)},
            'fence': {'phase': self.phase, 'step': self.step},
            'missing_hosts': self._missing_hosts(),
            'threads': thread_stacks(),
        }
        if self._context_fn is not None:
            try:
                report['context'] = self._context_fn()
            except Exception:
                pass
        write_json_atomic(self.report_path, report, indent=1, quiet=True)
        if self._on_dump is not None:
            try:
                self._on_dump(report['reason'])
            except Exception:
                pass
        if self.on_timeout == 'exit':
            os._exit(FENCE_TIMEOUT_RC)

    def __enter__(self):
        self._entered_at = time.time()
        with self._lock:
            self._completed = False
            self.fired = False
        self._timer = threading.Timer(self.deadline_s, self._fire)
        self._timer.daemon = True
        self._timer.start()
        return self

    def __exit__(self, *exc):
        with self._lock:
            self._completed = True
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        return False
