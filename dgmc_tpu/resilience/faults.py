"""Deterministic, flag-driven fault injection.

Every recovery path in this repo (supervisor restarts, watchdog hang
reports, non-finite guard skips/rollbacks, checkpoint-corruption
fallback, download retries) is exercised by *injected* faults rather
than by luck — the same way the DGMC paper treats noisy initial
correspondences as a routine input to recover from, not an anomaly.

Faults are armed from the CLI (``--inject-fault SPEC``, repeatable) and
fire at exact, reproducible points:

=====================  ==================================================
``raise@N``            raise :class:`FaultInjected` before step/epoch N
``sigterm@N``          ``SIGTERM`` to self before step N (preemption)
``sigkill@N``          ``SIGKILL`` to self before step N (hard crash)
``stall@N`` /          sleep ``S`` seconds (default 3600) before step N —
``stall@N:S``          a wedged-collective stand-in the watchdog must
                       catch and the supervisor must kill
``nan-grads@N``        NaN into every gradient leaf on optimizer step N
                       (in-graph; ``make_train_step(fault_nan_step=N)``)
``ckpt-truncate@N``    truncate the largest file of the step-N checkpoint
                       right after it is saved
``ckpt-corrupt@N``     flip bytes in the largest file of the step-N
                       checkpoint right after it is saved
``download-fail`` /    fail the next K download attempts with a transient
``download-fail:K``    error (``datasets/download.py`` must retry past
                       them); also armable via the
                       ``DGMC_TPU_FAULT_DOWNLOADS=K`` env var
``peer-death@N`` /     a peer host dies at step N: write a control-plane
``peer-death@N:H``     tombstone for host H (default: this host's index)
                       then ``SIGKILL`` self — the supervisor must
                       classify it as a *distributed* failure and
                       perform an elastic mesh-shrinking restart
``straggler@N:MS``     sleep MS milliseconds before every step >= N —
                       a persistently slow host the skew/straggler
                       detection must surface (a *condition*, so it
                       deliberately re-fires every step, unledgered)
``coord-partition@N``  from step N on, stop writing control-plane
                       heartbeats: the host looks dead to its peers
                       while still running (a coordination-service
                       partition); heals on restart (ledgered)
``collective-stall@N``/ sleep S seconds (default 3600) INSIDE the next
``collective-stall@N:S`` device fence at step N — the wedged-collective
                       stand-in the fence deadline must convert into a
                       ``hang_report.json`` + ``FENCE_TIMEOUT_RC`` exit
=====================  ==================================================

**Fire-once semantics across restarts.** A supervised run replays its
schedule after every restart; a ``sigkill@5`` that re-fired on the
replayed step 5 would crash-loop forever. Host-side faults therefore
record themselves in ``<state_dir>/faults_fired.json`` the moment they
fire (before delivering the kill), and a restarted process skips them.
``nan-grads`` deliberately does NOT use the ledger: it is part of the
deterministic step stream, and an interrupted-and-resumed run must
replay it to reproduce the uninterrupted run's trajectory exactly.

This module imports **no jax of its own** — faults must be armable in
any process, including the supervisor's backend-free monitor loop.
"""

import json
import os
import random
import signal
import sys
import time

__all__ = ['FaultInjected', 'FaultSpec', 'FaultPlan', 'add_fault_args',
           'parse_spec', 'corrupt_checkpoint', 'arm_download_faults',
           'consume_download_fault', 'download_faults_remaining',
           'ledger_dir']

FIRED_LEDGER = 'faults_fired.json'

#: Host-side fault kinds that fire in the training loop, once.
_STEP_KINDS = ('raise', 'sigterm', 'sigkill', 'stall', 'peer-death',
               'coord-partition')
_CKPT_KINDS = ('ckpt-truncate', 'ckpt-corrupt')
#: Fence-scoped kinds (fire inside the device-fence guard, once).
_FENCE_KINDS = ('collective-stall',)
#: Condition kinds: persistent states, not events — unledgered, re-fire
#: deliberately (a straggler is slow on EVERY step, including replays).
_CONDITION_KINDS = ('straggler',)
KINDS = _STEP_KINDS + _CKPT_KINDS + _FENCE_KINDS + _CONDITION_KINDS + \
    ('nan-grads', 'download-fail')


class FaultInjected(RuntimeError):
    """The ``raise@N`` fault."""


class FaultSpec:
    """One parsed ``kind[@step][:arg]`` spec."""

    def __init__(self, kind, step=None, arg=None):
        self.kind = kind
        self.step = step
        self.arg = arg

    @property
    def key(self):
        return f'{self.kind}@{self.step}' if self.step is not None \
            else self.kind

    def __repr__(self):
        return f'FaultSpec({self.key}' + \
            (f':{self.arg})' if self.arg is not None else ')')


def parse_spec(text):
    """``'sigkill@5'`` / ``'stall@3:20'`` / ``'download-fail:2'`` ->
    :class:`FaultSpec`. Raises ``ValueError`` with the grammar on junk."""
    body, arg = (text.split(':', 1) + [None])[:2]
    kind, step = (body.split('@', 1) + [None])[:2]
    kind = kind.strip()
    if kind not in KINDS:
        raise ValueError(
            f'unknown fault kind {kind!r} in spec {text!r}; known: '
            f'{", ".join(KINDS)} (grammar: kind@step[:arg])')
    if kind == 'download-fail':
        if step is not None:
            raise ValueError(
                f'{text!r}: download-fail takes a count (:K), not a step')
        return FaultSpec(kind, arg=int(arg) if arg else 1)
    if step is None:
        raise ValueError(f'{text!r}: {kind} needs a step (e.g. {kind}@3)')
    step = int(step)
    if arg is not None:
        # peer-death's arg is a host INDEX, not a duration.
        arg = int(arg) if kind == 'peer-death' else float(arg)
    elif kind in ('stall', 'collective-stall'):
        arg = 3600.0
    elif kind == 'straggler':
        arg = 1000.0   # milliseconds of injected per-step lag
    return FaultSpec(kind, step=step, arg=arg)


def add_fault_args(parser):
    """Register ``--inject-fault`` on an argparse parser."""
    parser.add_argument(
        '--inject-fault', '--inject_fault', dest='inject_fault',
        action='append', default=[], metavar='SPEC',
        help='deterministic fault injection (repeatable): raise@N, '
             'sigterm@N, sigkill@N, stall@N[:SEC], nan-grads@N, '
             'ckpt-truncate@N, ckpt-corrupt@N, download-fail[:K], '
             'peer-death@N[:HOST], straggler@N:MS, coord-partition@N, '
             'collective-stall@N[:SEC]. Process-killing faults fire '
             'ONCE across supervised restarts (ledger in the '
             'checkpoint/obs dir); nan-grads replays deterministically; '
             'straggler re-fires every step by design. See '
             'dgmc_tpu/resilience/faults.py.')
    return parser


LEDGER_ENV = 'DGMC_TPU_FAULT_LEDGER_DIR'


def ledger_dir(ckpt_dir, obs_dir):
    """Where the fire-once ledger should live: the checkpoint dir, else
    the obs ROOT — a supervised child's ``--obs-dir`` is rewritten to
    ``<root>/attempt_<k>`` per attempt, and a ledger inside one attempt
    would be invisible to the next (faults would re-fire forever) —
    else :data:`LEDGER_ENV`, which the supervisor exports to every
    child so a run with NEITHER flag still gets fire-once semantics
    (a re-firing ``sigkill@N`` would otherwise crash-loop the whole
    restart budget away)."""
    if ckpt_dir:
        return ckpt_dir
    if not obs_dir:
        return os.environ.get(LEDGER_ENV) or None
    from dgmc_tpu.resilience.supervisor import is_attempt_dirname
    base = os.path.basename(os.path.normpath(obs_dir))
    if is_attempt_dirname(base):
        return os.path.dirname(os.path.normpath(obs_dir))
    return obs_dir


class FaultPlan:
    """The armed faults of one run, with the fire-once ledger.

    Args:
        specs: iterable of spec strings (or :class:`FaultSpec`).
        state_dir: where ``faults_fired.json`` lives — pass the
            checkpoint dir (survives supervised restarts) or the obs
            ROOT dir. ``None`` disables the ledger (every fault can
            re-fire; fine for single-shot tests).
        control_dir: the control-plane directory
            (``distributed_guard.control_dir(obs_dir)``) where
            ``peer-death`` writes its tombstone; defaults to
            ``<state_dir>/control`` when a ledger dir exists.
        host_index: this process's host index — the default tombstone
            target of ``peer-death@N`` and the identity
            ``coord-partition`` silences.
    """

    def __init__(self, specs=(), state_dir=None, control_dir=None,
                 host_index=0):
        self.specs = [s if isinstance(s, FaultSpec) else parse_spec(s)
                      for s in (specs or ())]
        self._state_dir = state_dir
        self._control_dir = control_dir or (
            os.path.join(state_dir, 'control') if state_dir else None)
        self.host_index = int(host_index)
        #: Set once ``coord-partition`` fires; :class:`HostChannel`
        #: checks it before every heartbeat write. Always starts False:
        #: a ledgered coord-partition does not re-fire after a restart,
        #: so the restart "heals" the partition by design (the restart
        #: IS the recovery under test).
        self.coord_partitioned = False
        self._fired = set(self._load_ledger())
        for spec in self.specs:
            if spec.kind == 'download-fail':
                arm_download_faults(spec.arg)

    @classmethod
    def from_args(cls, args, state_dir=None, control_dir=None,
                  host_index=0):
        return cls(getattr(args, 'inject_fault', ()) or (),
                   state_dir=state_dir, control_dir=control_dir,
                   host_index=host_index)

    def __bool__(self):
        return bool(self.specs)

    # -- ledger ------------------------------------------------------------

    def _ledger_path(self):
        if not self._state_dir:
            return None
        return os.path.join(self._state_dir, FIRED_LEDGER)

    def _load_ledger(self):
        path = self._ledger_path()
        if not path or not os.path.exists(path):
            return []
        try:
            with open(path) as f:
                return json.load(f).get('fired', [])
        except (OSError, ValueError):
            return []

    def _mark_fired(self, spec):
        self._fired.add(spec.key)
        path = self._ledger_path()
        if path:
            from dgmc_tpu.utils.io import write_json_atomic
            write_json_atomic(path, {'fired': sorted(self._fired)},
                              indent=1)

    # -- hooks -------------------------------------------------------------

    @property
    def nan_grads_step(self):
        """Step for ``make_train_step(fault_nan_step=...)`` (or None)."""
        for spec in self.specs:
            if spec.kind == 'nan-grads':
                return spec.step
        return None

    def before_step(self, step):
        """Fire any armed host-side fault scheduled for ``step``
        (1-based step/epoch counter). The ledger is written BEFORE the
        fault delivers, so a killed-and-restarted run does not re-fire.
        Condition kinds (``straggler``) re-fire on every step >= N by
        design — a slow host is slow on replays too."""
        for spec in self.specs:
            if spec.kind == 'straggler' and spec.step <= step:
                time.sleep(spec.arg / 1000.0)
        for spec in self.specs:
            if spec.kind not in _STEP_KINDS or spec.step != step \
                    or spec.key in self._fired:
                continue
            self._mark_fired(spec)
            print(f'[faults] firing {spec.key} at step {step}',
                  file=sys.stderr, flush=True)
            if spec.kind == 'raise':
                raise FaultInjected(f'injected fault {spec.key}')
            if spec.kind == 'stall':
                time.sleep(spec.arg)
            elif spec.kind == 'coord-partition':
                # From here on this host writes no heartbeats: it looks
                # dead to its peers while still computing.
                self.coord_partitioned = True
            elif spec.kind == 'peer-death':
                host = self.host_index if spec.arg is None \
                    else int(spec.arg)
                if self._control_dir:
                    from dgmc_tpu.resilience.distributed_guard import \
                        write_tombstone
                    write_tombstone(self._control_dir, host, step=step)
                os.kill(os.getpid(), signal.SIGKILL)
                time.sleep(30)
                raise FaultInjected(
                    f'{spec.key} delivered but the process survived')
            else:
                os.kill(os.getpid(), signal.SIGTERM
                        if spec.kind == 'sigterm' else signal.SIGKILL)
                # SIGTERM is delivered synchronously to this thread; if
                # a handler chain swallowed it, don't fall through as if
                # nothing happened.
                time.sleep(30)
                raise FaultInjected(
                    f'{spec.key} delivered but the process survived')

    def before_fence(self, step):
        """Fire any armed fence-scoped fault for ``step`` — called by
        :meth:`RunObserver.fence_devices
        <dgmc_tpu.obs.run.RunObserver.fence_devices>` INSIDE its
        deadline guard, so a ``collective-stall`` is seen by exactly the
        machinery that must convert it into a ``hang_report.json``."""
        for spec in self.specs:
            if spec.kind not in _FENCE_KINDS or spec.step != step \
                    or spec.key in self._fired:
                continue
            self._mark_fired(spec)
            print(f'[faults] firing {spec.key} inside the step-{step} '
                  f'fence', file=sys.stderr, flush=True)
            time.sleep(spec.arg)

    def after_checkpoint(self, ckpt, step):
        """Corrupt the just-saved checkpoint when a ``ckpt-*@step`` fault
        is armed. ``ckpt`` is a
        :class:`~dgmc_tpu.train.checkpoint.Checkpointer` (the save may be
        async; corruption waits for the commit)."""
        for spec in self.specs:
            if spec.kind not in _CKPT_KINDS or spec.step != step \
                    or spec.key in self._fired:
                continue
            ckpt.wait_until_finished()
            target = corrupt_checkpoint(
                ckpt.directory, step,
                mode='truncate' if spec.kind == 'ckpt-truncate'
                else 'corrupt')
            self._mark_fired(spec)
            print(f'[faults] {spec.key}: damaged {target}',
                  file=sys.stderr, flush=True)


def corrupt_checkpoint(directory, step, mode='corrupt'):
    """Damage the largest file of checkpoint ``step`` under ``directory``
    (truncate to half, or overwrite a span with flipped bytes). Returns
    the damaged path. The step's manifest is left intact on purpose:
    verification catching the damage IS the recovery path under test."""
    step_dir = os.path.join(directory, str(step))
    if not os.path.isdir(step_dir):
        raise FileNotFoundError(f'no checkpoint step dir {step_dir}')
    largest, size = None, -1
    for root, _dirs, files in os.walk(step_dir):
        for name in files:
            p = os.path.join(root, name)
            s = os.path.getsize(p)
            if s > size:
                largest, size = p, s
    if largest is None:
        raise FileNotFoundError(f'checkpoint step dir {step_dir} is empty')
    if mode == 'truncate':
        with open(largest, 'r+b') as f:
            f.truncate(max(1, size // 2))
    else:
        with open(largest, 'r+b') as f:
            span = min(64, size)
            head = f.read(span)
            f.seek(0)
            f.write(bytes(b ^ 0xFF for b in head))
    return largest


# -- transient-download faults (module-level: datasets/download.py pulls
# from here lazily, and subprocess tests arm it via the env var) ---------

_DOWNLOAD_FAULTS = {'remaining': int(
    os.environ.get('DGMC_TPU_FAULT_DOWNLOADS', '0') or 0)}


def arm_download_faults(n):
    """The next ``n`` download attempts fail with a transient error."""
    _DOWNLOAD_FAULTS['remaining'] = int(n)


def download_faults_remaining():
    return _DOWNLOAD_FAULTS['remaining']


def consume_download_fault():
    """True if this download attempt must fail (decrements the budget)."""
    if _DOWNLOAD_FAULTS['remaining'] > 0:
        _DOWNLOAD_FAULTS['remaining'] -= 1
        return True
    return False


def transient_jitter(base_s, jitter_frac=0.25, rng=random):
    """Backoff jitter helper shared with :mod:`dgmc_tpu.datasets.download`:
    ``base_s`` stretched by up to ``jitter_frac`` (never shrunk, so the
    documented floor holds)."""
    return base_s * (1.0 + jitter_frac * rng.random())
