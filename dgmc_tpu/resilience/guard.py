"""Host-side rollback policy over the in-graph non-finite guard.

``make_train_step(guard=True)`` (dgmc_tpu/train/steps.py) skips the
optimizer update on any step whose loss or gradient norm is non-finite
and counts skips in the :class:`~dgmc_tpu.train.state.GuardedTrainState`
ledger — entirely in-graph, no host sync. What it cannot do in-graph is
*rollback*: restoring the last good parameter snapshot is a host
decision (the snapshot lives host-side precisely so a poisoned device
state cannot taint it). :class:`RollbackGuard` is that decision,
evaluated wherever the training loop already fetches metrics (the
experiment CLIs fetch every print/eval boundary), so it adds zero
device round-trips of its own.
"""

import sys

__all__ = ['RollbackGuard']


class RollbackGuard:
    """Snapshot-on-good, rollback-after-M-consecutive-bad.

    Args:
        max_consecutive: M — rollback triggers when the in-graph
            ``consec_bad`` counter reaches M (0 disables).
        obs: optional :class:`~dgmc_tpu.obs.run.RunObserver`; rollbacks
            are logged as ``event='rollback'`` metric records so the
            recovery timeline shows them.
    """

    def __init__(self, max_consecutive, obs=None):
        self.max_consecutive = int(max_consecutive)
        self.obs = obs
        self.rollbacks = 0
        self._snapshot = None
        self._snapshot_step = None

    def note_good(self, state, step=None):
        """Record ``state`` as the newest known-good rollback target.
        Call after the host has CONFIRMED finite metrics for it."""
        from dgmc_tpu.train.checkpoint import snapshot_params
        self._snapshot = snapshot_params(state)
        self._snapshot_step = step

    def maybe_rollback(self, state, consec_bad, step=None):
        """``(state, rolled_back)`` — restores the last good snapshot
        (fresh optimizer, like the willow reset protocol) when
        ``consec_bad >= M``. The ``step`` counter and the cumulative
        ``skip_count`` ledger survive the rollback; ``consec_bad``
        resets. Without a snapshot yet (the run went bad before its
        first good fetch) the guarded step keeps holding params frozen,
        which is already safe — we just report that."""
        if not self.max_consecutive \
                or int(consec_bad) < self.max_consecutive:
            return state, False
        if self._snapshot is None:
            print('[guard] rollback wanted but no good snapshot exists '
                  'yet; params stay frozen by the in-graph guard',
                  file=sys.stderr, flush=True)
            return state, False
        import jax.numpy as jnp
        from dgmc_tpu.train.checkpoint import restore_params
        rolled = restore_params(state, self._snapshot)
        rolled = rolled.replace(step=state.step)
        if hasattr(rolled, 'consec_bad'):
            rolled = rolled.replace(
                skip_count=state.skip_count,
                consec_bad=jnp.zeros((), jnp.int32))
        self.rollbacks += 1
        print(f'[guard] {int(consec_bad)} consecutive non-finite steps: '
              f'rolled back to the step-{self._snapshot_step} snapshot '
              f'(fresh optimizer)', file=sys.stderr, flush=True)
        if self.obs is not None:
            self.obs.log(step if step is not None else -1,
                         event='rollback',
                         rollback_to=self._snapshot_step,
                         consec_bad=int(consec_bad))
            # A rollback is an anomaly: dump the flight recorder's
            # trailing context (the probe values and spans that led
            # into the non-finite streak) next to the rollback record.
            flight_dump = getattr(self.obs, 'flight_dump', None)
            if flight_dump is not None:
                flight_dump('guard-rollback', extra={
                    'rollback_to': self._snapshot_step,
                    'consec_bad': int(consec_bad),
                    'rollbacks': self.rollbacks})
        return rolled, True
