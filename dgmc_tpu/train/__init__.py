from dgmc_tpu.train.state import (TrainState, GuardedTrainState,
                                  create_train_state, init_variables,
                                  with_guard_counters)
from dgmc_tpu.train.steps import (make_train_step, make_eval_step,
                                  aggregate_eval)
from dgmc_tpu.train.checkpoint import (Checkpointer, CheckpointError,
                                       CheckpointCorruptError,
                                       resume_or_init, snapshot_params,
                                       restore_params)
# Deprecated aliases: the observability layer moved to dgmc_tpu.obs
# (which adds the registry, RunObserver and the report CLI); these names
# stay importable so existing experiment code and runs/ tooling keep
# working.
from dgmc_tpu.obs import MetricLogger, StepTimer, trace

__all__ = [
    'TrainState',
    'GuardedTrainState',
    'create_train_state',
    'init_variables',
    'with_guard_counters',
    'make_train_step',
    'make_eval_step',
    'aggregate_eval',
    'Checkpointer',
    'CheckpointError',
    'CheckpointCorruptError',
    'resume_or_init',
    'snapshot_params',
    'restore_params',
    'MetricLogger',
    'StepTimer',
    'trace',
]
