"""Jit-compiled train / eval step factories.

Capability parity with the reference's per-example ``train()``/``test()``
loops (reference ``examples/pascal.py:60-103``, ``examples/dbp15k.py:37-60``),
re-designed functionally: a factory closes over the model and the phase
config (``num_steps``/``detach`` — trace-time static, replacing the
reference's attribute-mutation schedule at reference
``examples/dbp15k.py:63-69``) and returns one donating jitted step. Each
phase of a schedule is its own compiled program; switching phases is
switching functions, not mutating state.
"""

import jax
import jax.numpy as jnp

from dgmc_tpu.models import metrics
from dgmc_tpu.obs import probes as _probes


def _variables(state):
    variables = {'params': state.params}
    if state.batch_stats:
        variables['batch_stats'] = state.batch_stats
    return variables


def make_train_step(model, loss_on_s0=False, num_steps=None, detach=None,
                    hits_ks=(), jit=True, pair_offset=0, guard=False,
                    fault_nan_step=None):
    """Build a jitted ``(state, batch, key) -> (state, metrics)`` step.

    Args:
        model: a :class:`~dgmc_tpu.models.DGMC` instance.
        loss_on_s0: add the initial-correspondence loss to the refined one,
            as the keypoint experiments do (reference
            ``examples/pascal.py:71-72``); the DBP15K experiment trains on
            the refined loss only (reference ``examples/dbp15k.py:43-46``).
        num_steps / detach: phase overrides (static).
        hits_ks: extra Hits@k metrics to report per step.
        pair_offset: static per-pair RNG stream offset (see
            :meth:`DGMC.__call__`) — the handle the ``--pairs-per-step``
            equivalence test uses to make ``B=1`` reference steps draw
            the exact noise of batched element ``pair_offset``.
        guard: in-graph non-finite guardrail. ``state`` must be a
            :class:`~dgmc_tpu.train.state.GuardedTrainState` (see
            :func:`~dgmc_tpu.train.state.with_guard_counters`). A step
            whose loss or gradient global-norm is non-finite keeps the
            old params/optimizer/batch_stats wholesale (``step`` still
            advances, so deterministic per-step streams stay aligned),
            increments the ``skip_count``/``consec_bad`` ledger, and
            reports ``bad_step`` in the metrics; a finite step resets
            ``consec_bad``. Rollback after M consecutive bad steps is
            host policy (:class:`dgmc_tpu.resilience.RollbackGuard`).
            Off (the default), the lowered step is unchanged.
        fault_nan_step: deterministic fault injection
            (``dgmc_tpu/resilience/faults.py`` — ``nan-grads@N``):
            poison every gradient leaf with NaN on the Nth optimizer
            step (1-based: fires when ``state.step == N - 1``). Trace-
            time constant; ``None`` (the default) adds nothing to the
            lowered program.

    The metrics dict carries ``loss`` (the scalar trained on — a masked
    mean over every valid correspondence in the batch) and
    ``loss_per_pair`` (``[B]``, each pair's own masked-mean NLL; for a
    ``--pairs-per-step`` batch these match the losses of independent
    ``B=1`` steps).
    """

    def train_step(state, batch, key):
        k_noise, k_neg, k_drop = jax.random.split(key, 3)

        def loss_fn(params):
            variables = dict(_variables(state), params=params)
            mutable = ['batch_stats'] if state.batch_stats else False
            out = model.apply(
                variables, batch.s, batch.t, y=batch.y, y_mask=batch.y_mask,
                train=True, num_steps=num_steps, detach=detach,
                pair_offset=pair_offset,
                rngs={'noise': k_noise, 'negatives': k_neg,
                      'dropout': k_drop},
                mutable=mutable)
            (S_0, S_L), new_vars = out if mutable else (out, {})
            # Stage scope for the obs/cost attribution (obs/cost.py): the
            # model stages (psi1, consensus_iter, ...) come annotated
            # from models/dgmc.py; 'loss' and 'optimizer' below complete
            # the train step's pipeline account.
            with jax.named_scope('loss'):
                loss = metrics.nll_loss(S_L, batch.y, batch.y_mask)
                if loss_on_s0:
                    loss = loss + metrics.nll_loss(S_0, batch.y,
                                                   batch.y_mask)
            return loss, (new_vars, S_L)

        (loss, (new_vars, S_L)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        if fault_nan_step is not None:
            fire = state.step == fault_nan_step - 1
            grads = jax.tree.map(
                lambda g: jnp.where(fire, jnp.asarray(jnp.nan, g.dtype),
                                    g), grads)
        if _probes.enabled():
            # Trace-time gate (obs/probes.py): a probe-free build lowers to
            # byte-identical HLO (tests/obs/test_probes.py).
            import optax
            gnorm = optax.global_norm(grads)
            _probes.emit('grad_norm', gnorm)
            # order: loss precedes grad in the pipeline (forward before
            # backward) — first-nonfinite attribution sorts on it.
            _probes.check_finite('loss', loss, order=1000)
            _probes.check_finite('grad', gnorm, order=1001)
        with jax.named_scope('optimizer'):
            new_state = state.apply_gradients(grads=grads)
        if state.batch_stats:
            new_state = new_state.replace(
                batch_stats=new_vars['batch_stats'])
        guard_out = {}
        if guard:
            import optax
            good = jnp.isfinite(loss) & jnp.isfinite(
                optax.global_norm(grads))

            def keep(new, old):
                return jnp.where(good, new, old)

            # Bad step: the whole update is discarded (params, optimizer
            # moments AND counts, batch stats) — exactly "old state
            # kept". `step` still advances (apply_gradients), so replay
            # determinism and fault_nan_step indexing survive skips.
            state = new_state.replace(
                params=jax.tree.map(keep, new_state.params, state.params),
                opt_state=jax.tree.map(keep, new_state.opt_state,
                                       state.opt_state),
                batch_stats=jax.tree.map(keep, new_state.batch_stats,
                                         state.batch_stats),
                skip_count=state.skip_count
                + (1 - good.astype(jnp.int32)),
                consec_bad=jnp.where(good, 0, state.consec_bad + 1))
            guard_out = {'bad_step': ~good,
                         'skip_count': state.skip_count,
                         'consec_bad': state.consec_bad}
        else:
            state = new_state

        # 'metrics' completes the stage account (obs/cost.py): on a
        # row-sharded giant pair the per-step metric reductions are real
        # work (masked means over 10⁶ rows) and should not be billed to
        # 'optimizer'.
        with jax.named_scope('metrics'):
            out = {**guard_out,
                   'loss': loss,
                   'loss_per_pair': metrics.nll_loss(
                       S_L, batch.y, batch.y_mask, reduction='per_pair'),
                   'acc': metrics.acc(S_L, batch.y, batch.y_mask)}
            for k in hits_ks:
                out[f'hits@{k}'] = metrics.hits_at_k(k, S_L, batch.y,
                                                     batch.y_mask)
        return state, out

    if jit:
        train_step = jax.jit(train_step, donate_argnums=(0,))
    return train_step


def make_eval_step(model, hits_ks=(1,), num_steps=None, detach=None,
                   jit=True, pair_offset=0):
    """Build a jitted ``(state, batch, key) -> metrics`` evaluation step.

    Metrics come back as *sums* plus the valid-correspondence count so
    callers can aggregate across batches exactly like the reference's
    sample-until-1000 protocol (reference ``examples/pascal.py:88-99``).
    The consensus iterations draw indicator noise at eval time too, as the
    reference does (reference ``dgmc/models/dgmc.py:169``), hence the key.
    """

    def eval_step(state, batch, key):
        S_0, S_L = model.apply(
            _variables(state), batch.s, batch.t, train=False,
            num_steps=num_steps, detach=detach, pair_offset=pair_offset,
            rngs={'noise': key})
        out = {'count': jnp.sum(batch.y_mask),
               'correct': metrics.acc(S_L, batch.y, batch.y_mask,
                                      reduction='sum')}
        for k in hits_ks:
            out[f'hits@{k}'] = metrics.hits_at_k(k, S_L, batch.y,
                                                 batch.y_mask,
                                                 reduction='sum')
        return out

    if jit:
        eval_step = jax.jit(eval_step)
    return eval_step


def aggregate_eval(totals):
    """Fold a list of summed eval-step outputs into rates."""
    if not totals:
        return {}
    keys = totals[0].keys()
    summed = {k: float(sum(t[k] for t in totals)) for k in keys}
    count = summed.pop('count')
    n = max(count, 1.0)
    out = {'acc': summed.pop('correct') / n}
    out.update({k: v / n for k, v in summed.items()})
    out['count'] = count
    return out
