"""Train state: parameters, optimizer state, and BatchNorm statistics.

The reference trains with raw ``torch.optim.Adam`` over a mutable
``nn.Module`` (e.g. reference ``examples/pascal.py:51-77``); weight snapshots
for the WILLOW transfer protocol are in-memory ``state_dict`` copies
(reference ``examples/willow.py:90,155``). The TPU-native equivalent is a
functional :class:`TrainState` pytree — params, optax state, and the
``batch_stats`` collection as explicit fields — which makes snapshots free
(the pytree is the snapshot) and checkpointing a pure serialization concern
(see ``dgmc_tpu/train/checkpoint.py``).
"""

from typing import Any

import jax
import optax
from flax import struct
from flax.training import train_state


class TrainState(train_state.TrainState):
    """Flax train state extended with the BatchNorm running statistics."""
    batch_stats: Any = struct.field(default_factory=dict)


class GuardedTrainState(TrainState):
    """:class:`TrainState` extended with the non-finite-guard ledger
    (``make_train_step(guard=True)`` — dgmc_tpu/train/steps.py): how many
    optimizer updates were skipped for a non-finite loss/grad, and how
    many of those skips are consecutive right now (the host-side rollback
    trigger, :class:`dgmc_tpu.resilience.RollbackGuard`)."""
    skip_count: Any = 0
    consec_bad: Any = 0


def with_guard_counters(state):
    """Upgrade a :class:`TrainState` to a :class:`GuardedTrainState` with
    device-resident int32 counters (concrete arrays, not weak Python
    ints, so the jitted step signature is stable across restores)."""
    import jax.numpy as jnp
    return GuardedTrainState(
        step=state.step, apply_fn=state.apply_fn, params=state.params,
        tx=state.tx, opt_state=state.opt_state,
        batch_stats=state.batch_stats,
        skip_count=jnp.zeros((), jnp.int32),
        consec_bad=jnp.zeros((), jnp.int32))


def init_variables(model, key, batch, num_steps=None):
    """Initialize all model variables on a sample batch.

    ``num_steps`` is forced to at least 1 during shape inference so ψ₂ and
    the consensus MLP materialize their parameters even when training starts
    in a ``num_steps=0`` phase — the reference constructs every submodule up
    front (reference ``dgmc/models/dgmc.py:64-78``), and the DBP15K schedule
    (reference ``examples/dbp15k.py:63-69``) relies on the optimizer seeing
    those parameters from epoch 1.
    """
    if num_steps is None:
        num_steps = model.num_steps
    num_steps = max(1, num_steps)
    k_params, k_noise, k_neg, k_drop = jax.random.split(key, 4)
    return model.init(
        {'params': k_params, 'noise': k_noise, 'negatives': k_neg,
         'dropout': k_drop},
        batch.s, batch.t, y=batch.y, y_mask=batch.y_mask, train=True,
        num_steps=num_steps)


def create_train_state(model, key, batch, tx=None, learning_rate=1e-3,
                       num_steps=None, init_batch=None):
    """Build a :class:`TrainState` for ``model`` from a sample batch.

    ``tx`` defaults to plain Adam at ``learning_rate`` — the optimizer every
    reference experiment uses (e.g. reference ``examples/dbp15k.py:34``).

    ``init_batch`` substitutes a smaller batch for the shape-inference
    forward: parameter shapes (and therefore values — each initializer
    draws from its own fold of ``key`` keyed on the param's shape) depend
    only on feature widths, never on node/edge counts, so a giant pair
    (the 10⁶-node streamed-S workload) can initialize on a tiny stand-in
    instead of tracing a million-row forward eagerly.
    """
    if tx is None:
        tx = optax.adam(learning_rate)
    variables = init_variables(model, key,
                               batch if init_batch is None else init_batch,
                               num_steps=num_steps)
    return TrainState.create(
        apply_fn=model.apply,
        params=variables['params'],
        batch_stats=variables.get('batch_stats', {}),
        tx=tx)
