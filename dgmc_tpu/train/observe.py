"""Deprecated alias of :mod:`dgmc_tpu.obs.observe`.

The observability primitives grew into a subsystem of their own
(``dgmc_tpu/obs/``: telemetry registry, ``RunObserver``/``--obs-dir``
artifacts, report CLI). This module remains so existing experiment code
and ``runs/`` tooling importing ``dgmc_tpu.train.{trace, StepTimer,
MetricLogger}`` keep working; new code should import from
:mod:`dgmc_tpu.obs`.
"""

from dgmc_tpu.obs.observe import (MetricLogger, StepTimer,  # noqa: F401
                                  trace)

__all__ = ['MetricLogger', 'StepTimer', 'trace']
