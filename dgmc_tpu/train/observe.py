"""Observability: profiler traces, per-step timing, metric logging.

The reference has no tracing, timing, or metric sink of any kind — training
progress is bare ``print()`` lines (SURVEY.md §5: reference
``examples/dbp15k.py:75-76``, ``examples/pascal.py:109-110``). Here these
are first-class:

- :func:`trace` — a ``jax.profiler`` trace of a step window, viewable in
  TensorBoard/Perfetto, for finding MXU idle time and HBM stalls.
- :class:`StepTimer` — wall-clock per-step timing with a device fence, so
  the numbers measure execution rather than dispatch.
- :class:`MetricLogger` — JSONL metric sink alongside (not replacing) the
  reference-parity stdout prints.
"""

import contextlib
import json
import os
import time

import jax


@contextlib.contextmanager
def trace(log_dir):
    """Profile the enclosed steps into ``log_dir`` (no-op if ``log_dir`` is
    falsy). The trace captures XLA device activity on the real TPU and
    host-side dispatch everywhere."""
    if not log_dir:
        yield
        return
    os.makedirs(log_dir, exist_ok=True)
    with jax.profiler.trace(log_dir):
        yield


class StepTimer:
    """Accumulates fenced per-step wall-clock times.

    ``fence`` should be a device scalar from the step's outputs (e.g. the
    loss); fetching it to host guarantees the step actually finished before
    the clock stops.
    """

    def __init__(self):
        self.times = []
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self, fence=None):
        if fence is not None:
            float(fence)
        self.times.append(time.perf_counter() - self._t0)
        return self.times[-1]

    @property
    def mean(self):
        return sum(self.times) / max(len(self.times), 1)

    def summary(self):
        if not self.times:
            return {}
        ts = sorted(self.times)
        return {
            'steps': len(ts),
            'mean_s': self.mean,
            'p50_s': ts[len(ts) // 2],
            'max_s': ts[-1],
        }


class MetricLogger:
    """Append-only JSONL metric sink (one object per ``log`` call).

    Cheap enough to leave on: one ``json.dumps`` + buffered write per step.
    Pass ``path=None`` to disable (all calls become no-ops).
    """

    def __init__(self, path):
        self.path = path
        self._fh = None
        if path:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._fh = open(path, 'a')

    def log(self, step, **metrics):
        if self._fh is None:
            return
        rec = {'step': step, 'time': time.time()}
        for k, v in metrics.items():
            rec[k] = float(v) if hasattr(v, '__float__') else v
        self._fh.write(json.dumps(rec) + '\n')
        self._fh.flush()

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
