"""Checkpoint / resume — a subsystem the reference lacks entirely.

The reference's only weight-persistence mechanism is an in-memory
``state_dict`` deep-copy for the WILLOW transfer protocol (reference
``examples/willow.py:90,155``); a crash loses everything (SURVEY.md §5).
Here checkpointing is first-class: orbax-backed save/restore of the full
:class:`~dgmc_tpu.train.TrainState` (params, optimizer state, BatchNorm
statistics), with retention and a latest-step query for resume. The willow
protocol's snapshot/rollback becomes trivial because the functional state
pytree *is* the snapshot — see :func:`snapshot_params` /
:func:`restore_params`.
"""

import os
from typing import Optional

import jax


class Checkpointer:
    """Thin orbax ``CheckpointManager`` wrapper for :class:`TrainState`."""

    def __init__(self, directory, max_to_keep: Optional[int] = 3):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep))

    def save(self, step: int, state, wait: bool = False):
        self._mgr.save(step, args=self._ocp.args.StandardSave(state))
        if wait:
            self._mgr.wait_until_finished()

    def restore(self, state, step: Optional[int] = None):
        """Restore into the structure of ``state`` (an abstract or concrete
        :class:`TrainState` with the right shapes/dtypes)."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(
                f'no checkpoint found under {self.directory}')
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=getattr(x, 'sharding', None))
            if hasattr(x, 'shape') else x, state)
        return self._mgr.restore(
            step, args=self._ocp.args.StandardRestore(abstract))

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def close(self):
        self._mgr.wait_until_finished()
        self._mgr.close()


def resume_or_init(ckpt_dir, state):
    """Shared workload resume glue: open a :class:`Checkpointer` under
    ``ckpt_dir`` (``None`` -> no checkpointing) and restore the latest saved
    state if one exists.

    Returns ``(ckpt, state, start_epoch)`` where ``start_epoch`` is the
    first epoch still to run (1 for a fresh start).
    """
    if not ckpt_dir:
        return None, state, 1
    ckpt = Checkpointer(ckpt_dir)
    latest = ckpt.latest_step()
    if latest is None:
        return ckpt, state, 1
    state = ckpt.restore(state, latest)
    print(f'Resumed from {ckpt.directory} at epoch {latest}.')
    return ckpt, state, latest + 1


def snapshot_params(state):
    """In-memory parameter snapshot (the reference's ``deepcopy(state_dict)``
    at ``examples/willow.py:90``). Buffers are copied, not aliased: the
    jitted train steps donate their input state, which would otherwise
    invalidate the snapshot on the next step."""
    import jax.numpy as jnp
    return jax.tree.map(
        lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x,
        {'params': state.params, 'batch_stats': state.batch_stats})


def restore_params(state, snapshot, tx=None):
    """Roll ``state`` back to a snapshot with a *fresh* optimizer, matching
    the per-run reset of reference ``examples/willow.py:155-157``. The
    snapshot leaves are copied into the new state (not aliased) so the
    snapshot survives donation by train steps on the restored state and can
    be restored again for the next run."""
    import jax.numpy as jnp
    tx = tx or state.tx
    fresh = jax.tree.map(
        lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x, snapshot)
    return type(state).create(
        apply_fn=state.apply_fn, params=fresh['params'],
        batch_stats=fresh['batch_stats'], tx=tx)
