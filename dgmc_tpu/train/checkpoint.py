"""Checkpoint / resume — a subsystem the reference lacks entirely.

The reference's only weight-persistence mechanism is an in-memory
``state_dict`` deep-copy for the WILLOW transfer protocol (reference
``examples/willow.py:90,155``); a crash loses everything (SURVEY.md §5).
Here checkpointing is first-class: orbax-backed save/restore of the full
:class:`~dgmc_tpu.train.TrainState` (params, optimizer state, BatchNorm
statistics), with retention and a latest-step query for resume. The willow
protocol's snapshot/rollback becomes trivial because the functional state
pytree *is* the snapshot — see :func:`snapshot_params` /
:func:`restore_params`.

Hardening (the fault-tolerance layer the run supervisor builds on —
``dgmc_tpu/resilience/``): every committed step gets a **checksummed
manifest** (sha256 + size per file, written atomically via tmp+rename
into ``<dir>/manifests/``), :meth:`Checkpointer.verify` re-hashes a step
against it, and :meth:`Checkpointer.restore` walks latest→oldest past
corrupt or torn steps instead of surfacing a raw orbax traceback — a
truncated file, a flipped byte, or a bare half-written step directory
falls back to the previous good checkpoint with a warning.
``restore(step=N)`` with a missing or corrupt N raises an actionable
error (no silent fallback when the caller pinned a step).
"""

import json
import os
import sys
from typing import Optional

import jax

from dgmc_tpu.utils.io import sha256_file


class CheckpointError(RuntimeError):
    """A checkpoint could not be restored; the message says what to do."""


class CheckpointCorruptError(CheckpointError):
    """A checkpoint failed manifest verification or deserialization."""


#: Subdirectory of the checkpoint root holding per-step manifests. Kept
#: OUTSIDE the orbax step directories so orbax's own item discovery and
#: retention never see an unexpected file.
MANIFEST_DIRNAME = 'manifests'


def _file_table(step_dir):
    """{relpath: {sha256, bytes}} over every regular file under a step."""
    out = {}
    for root, _dirs, files in os.walk(step_dir):
        for name in sorted(files):
            p = os.path.join(root, name)
            rel = os.path.relpath(p, step_dir)
            out[rel] = {'sha256': sha256_file(p),
                        'bytes': os.path.getsize(p)}
    return out


def _is_coordinator():
    """Manifests are written once per run, by process 0 (the checkpoint
    directory is a shared filesystem in multi-host runs)."""
    try:
        return jax.process_index() == 0
    except Exception:
        return True


class Checkpointer:
    """Thin orbax ``CheckpointManager`` wrapper for :class:`TrainState`
    with checksummed-manifest verification and corrupt-step fallback."""

    def __init__(self, directory, max_to_keep: Optional[int] = 3,
                 verify: bool = True):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep))
        self._verify = verify
        #: Step the most recent :meth:`restore` actually loaded (may be
        #: older than ``latest_step()`` after a corrupt-latest fallback).
        self.restored_step: Optional[int] = None
        #: Tag of the ``structures`` candidate the most recent
        #: :meth:`restore` deserialized with (``None`` for the plain
        #: requested structure).
        self.restored_structure = None

    # -- manifests ---------------------------------------------------------

    def _step_dir(self, step: int):
        return os.path.join(self.directory, str(step))

    def _manifest_path(self, step: int):
        return os.path.join(self.directory, MANIFEST_DIRNAME,
                            f'{step}.json')

    def write_manifest(self, step: int):
        """Hash every file of a committed step into
        ``manifests/<step>.json`` (atomic tmp+rename)."""
        from dgmc_tpu.utils.io import write_json_atomic
        path = self._manifest_path(step)
        write_json_atomic(path, {'step': int(step), 'files': _file_table(
            self._step_dir(step))}, indent=1, sort_keys=True)
        return path

    def finalize_manifests(self):
        """Write manifests for committed steps that lack one and drop
        manifests whose step was retired by retention. Called after every
        save and on close; async saves get their manifest on the next
        call once orbax reports them committed.

        The hash runs synchronously on the caller's thread — one pass
        over each newly committed step, deliberately: a manifest that
        lags its step is useless against a crash arriving before some
        background writer catches up, and verification is the whole
        point of the manifest. Pass ``verify=False`` to the
        :class:`Checkpointer` when save latency matters more."""
        if not (self._verify and _is_coordinator()):
            return
        steps = set(self.all_steps())
        for step in steps:
            # all_steps() lists an async save as soon as it is RECORDED,
            # before orbax's atomic tmp->rename commits the step dir.
            # Hashing then would pin an empty (or worse, mid-write) file
            # table that os.path.exists below makes permanent — the
            # manifest must wait for the rename; the next finalize (next
            # save, wait_until_finished, or close) picks the step up.
            if not os.path.isdir(self._step_dir(step)):
                continue
            mpath = self._manifest_path(step)
            if os.path.exists(mpath):
                # Heal empty manifests written by pre-fix versions of
                # this race (they verify vacuously, silently disabling
                # the hardening for that step).
                try:
                    with open(mpath) as f:
                        if json.load(f).get('files'):
                            continue
                except (OSError, ValueError):
                    pass  # unreadable manifest: rewrite it too
            try:
                self.write_manifest(step)
            except OSError as e:
                print(f'checkpoint: manifest for step {step} not '
                      f'written ({e}); verification will be skipped '
                      f'for it', file=sys.stderr)
        mdir = os.path.join(self.directory, MANIFEST_DIRNAME)
        if os.path.isdir(mdir):
            for name in os.listdir(mdir):
                base, ext = os.path.splitext(name)
                if ext == '.json' and base.isdigit() \
                        and int(base) not in steps:
                    try:
                        os.remove(os.path.join(mdir, name))
                    except OSError:
                        pass

    def verify(self, step: int):
        """Problems with ``step``'s on-disk files vs its manifest.

        Returns a list of human-readable problem strings — empty when the
        step matches its manifest, or when no manifest exists (an
        unverifiable step is not evidence of corruption; restore still
        guards it with its own try/except)."""
        mpath = self._manifest_path(step)
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except FileNotFoundError:
            return []
        except (OSError, ValueError) as e:
            return [f'manifest unreadable: {e}']
        problems = []
        step_dir = self._step_dir(step)
        for rel, want in sorted(manifest.get('files', {}).items()):
            p = os.path.join(step_dir, rel)
            if not os.path.isfile(p):
                problems.append(f'missing file {rel}')
                continue
            size = os.path.getsize(p)
            if size != want['bytes']:
                problems.append(
                    f'{rel}: size {size} != manifest {want["bytes"]}')
                continue
            if sha256_file(p) != want['sha256']:
                problems.append(f'{rel}: sha256 mismatch')
        return problems

    # -- save / restore ----------------------------------------------------

    def save(self, step: int, state, wait: bool = False):
        saved = self._mgr.save(step, args=self._ocp.args.StandardSave(state))
        if not saved and os.path.isdir(self._step_dir(step)):
            # orbax silently refuses save(step <= latest_step) — the
            # exact shape of a re-save after a corrupt-latest fallback
            # (resume at N-1, re-run epoch N, save(N) over the torn
            # step). The caller asked to persist THIS state: replace the
            # stale step, don't drop the save on the floor.
            self.delete_step(step)
            saved = self._mgr.save(
                step, args=self._ocp.args.StandardSave(state))
            if not saved:
                print(f'checkpoint: orbax refused to save step {step} '
                      f'even after clearing the old one; this state is '
                      f'NOT persisted', file=sys.stderr)
        if wait:
            self._mgr.wait_until_finished()
        self.finalize_manifests()

    def delete_step(self, step: int):
        """Remove a step and its manifest (clears a corrupt or stale
        step so the same step number can be saved again)."""
        try:
            self._mgr.delete(step)
        except Exception:
            import shutil
            shutil.rmtree(self._step_dir(step), ignore_errors=True)
        try:
            os.remove(self._manifest_path(step))
        except OSError:
            pass

    def wait_until_finished(self):
        """Block until any in-flight async save is committed, then
        finalize its manifest."""
        self._mgr.wait_until_finished()
        self.finalize_manifests()

    def _restore_one(self, step: int, state):
        import numpy as np

        def abstract(x):
            if not hasattr(x, 'shape'):
                return x
            sharding = getattr(x, 'sharding', None)
            if sharding is not None:
                # A target leaf carrying a sharding restores straight
                # onto it — including a DIFFERENT mesh than the one the
                # checkpoint was saved under (orbax reshards from file);
                # this is the elastic mesh-shrink restore path.
                return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                            sharding=sharding)
            # A target leaf with NO sharding (host numpy state, a bare
            # ShapeDtypeStruct) restores to host numpy. Passing
            # sharding=None instead would make orbax fall back to the
            # sharding RECORDED in the checkpoint, which names devices
            # that no longer exist after a mesh shrink (8->4 restore) —
            # and that placement failure then masquerades as
            # corruption in the fallback walk.
            return np.broadcast_to(np.zeros((), np.dtype(x.dtype)),
                                   x.shape)

        return self._mgr.restore(
            step, args=self._ocp.args.StandardRestore(
                jax.tree.map(abstract, state)))

    def restore(self, state, step: Optional[int] = None,
                fallback: Optional[bool] = None, structures=None):
        """Restore into the structure of ``state`` (an abstract or
        concrete :class:`TrainState` with the right shapes/dtypes).

        Without ``step``, tries the latest checkpoint and — unless
        ``fallback=False`` — walks back through older ones past any that
        fail manifest verification or deserialization (truncated or
        corrupt files, half-written step directories), warning per
        skipped step. With an explicit ``step``, a missing step raises
        :class:`FileNotFoundError` naming the available steps and a
        corrupt one raises :class:`CheckpointCorruptError`; fallback is
        off unless requested (``fallback=True`` walks back from ``N``
        through the older steps). The step actually loaded lands in
        :attr:`restored_step`.

        ``structures``: optional ordered ``(tag, candidate_state)``
        alternatives deserialized in turn at each step — manifest
        verification runs once per step, then every candidate structure
        is tried before the step is declared unrestorable. The winning
        tag lands in :attr:`restored_structure` (``None`` for the plain
        ``state``). :func:`resume_or_init` uses this for the
        ``--guard-bad-steps`` structure toggle."""
        steps = self.all_steps()
        if step is not None:
            if step not in steps:
                raise FileNotFoundError(
                    f'no checkpoint for step {step} under '
                    f'{self.directory}; available steps: '
                    f'{steps or "none"} (pass step=None to resume from '
                    f'the latest)')
            fallback = bool(fallback)
            candidates = [s for s in sorted(steps, reverse=True)
                          if s <= step] if fallback else [step]
        else:
            if not steps:
                raise FileNotFoundError(
                    f'no checkpoint found under {self.directory}')
            candidates = sorted(steps, reverse=True)
            fallback = True if fallback is None else fallback
        structures = structures or ((None, state),)
        failures = []
        for s in candidates:
            problems = self.verify(s) if self._verify else []
            if problems:
                failures.append(f'step {s}: {"; ".join(problems)}')
                if not fallback:
                    raise CheckpointCorruptError(
                        f'checkpoint step {s} under {self.directory} '
                        f'failed verification: {"; ".join(problems)}. '
                        f'Pick another step ({steps}) or delete the '
                        f'corrupt one.')
                print(f'checkpoint: step {s} failed verification '
                      f'({"; ".join(problems)}); falling back to the '
                      f'previous checkpoint', file=sys.stderr)
                continue
            restored, last_exc, errs = None, None, []
            for tag, cand in structures:
                try:
                    restored = self._restore_one(s, cand)
                    self.restored_structure = tag
                    break
                except Exception as e:  # torn/alien step dirs raise deep
                    last_exc = e
                    errs.append(f'{type(e).__name__}: {e}')
            if restored is None:
                detail = '; '.join(errs)
                failures.append(f'step {s}: {detail}')
                if not fallback:
                    raise CheckpointCorruptError(
                        f'checkpoint step {s} under {self.directory} '
                        f'could not be restored ({detail}). Pick another '
                        f'step ({steps}) or delete the broken one.'
                    ) from last_exc
                print(f'checkpoint: step {s} failed to restore '
                      f'({detail}); falling back to the previous '
                      f'checkpoint', file=sys.stderr)
                continue
            self.restored_step = s
            return restored
        raise CheckpointCorruptError(
            f'every checkpoint under {self.directory} failed to restore:'
            f'\n  ' + '\n  '.join(failures) +
            f'\nDelete {self.directory} to start fresh, or repair/replace '
            f'a step directory and retry.')

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def close(self):
        self._mgr.wait_until_finished()
        self.finalize_manifests()
        self._mgr.close()


def _toggle_guard_structure(state):
    """The alternate checkpoint structure for a ``--guard-bad-steps``
    toggle: guard counters stripped from a
    :class:`~dgmc_tpu.train.state.GuardedTrainState`, or zeroed counters
    added to a plain :class:`~dgmc_tpu.train.state.TrainState`."""
    from dgmc_tpu.train.state import (GuardedTrainState, TrainState,
                                      with_guard_counters)
    if isinstance(state, GuardedTrainState):
        return TrainState(
            step=state.step, apply_fn=state.apply_fn, params=state.params,
            tx=state.tx, opt_state=state.opt_state,
            batch_stats=state.batch_stats)
    return with_guard_counters(state)


def _place_for_mesh(state, mesh, rules):
    """Restore/initial placement for a mesh workload: every leaf lands
    with the layout the partition rules declare on the CURRENT mesh
    (plain replication when no rules). Restoring into these shardings
    is what makes the elastic mesh-shrink rung real: a checkpoint saved
    on the 8-device mesh deserializes directly onto the 4-device one,
    without bouncing the whole state through a single device — and
    without tripping the committed-single-device vs mesh-constraint
    placement error a bare restore hits."""
    if rules is not None:
        from dgmc_tpu.parallel.rules import shard_tree
        return shard_tree(state, rules.state, mesh)
    from jax.sharding import NamedSharding, PartitionSpec
    return jax.device_put(state, NamedSharding(mesh, PartitionSpec()))


def resume_or_init(ckpt_dir, state, mesh=None, rules=None):
    """Shared workload resume glue: open a :class:`Checkpointer` under
    ``ckpt_dir`` (``None`` -> no checkpointing) and restore the latest saved
    state if one exists — falling back past corrupt/torn checkpoints (see
    :meth:`Checkpointer.restore`).

    Returns ``(ckpt, state, start_epoch)`` where ``start_epoch`` is the
    first epoch still to run (1 for a fresh start). An empty or absent
    directory is a fresh start; a directory where every checkpoint is
    corrupt raises :class:`CheckpointCorruptError` with instructions
    rather than silently retraining from scratch.

    ``mesh`` (optionally with ``rules``, a
    :class:`~dgmc_tpu.parallel.rules.PartitionRules`) re-derives the
    target shardings on the CURRENT mesh before restoring, so a
    checkpoint written under a different (larger) mesh restores
    **resharded** — the elastic-restart path. Single-process only: a
    multi-process run must keep host-side state here and go through
    ``parallel.global_batch`` after.

    Toggling ``--guard-bad-steps`` between runs changes the state PYTREE
    STRUCTURE (``TrainState`` <-> ``GuardedTrainState``), and a structure
    mismatch fails deserialization exactly like corruption — so each
    step is tried with BOTH structures (newest step first, requested
    structure first) and a toggled restore is converted to the requested
    one (counters start fresh when the checkpoint predates the guard;
    the skip ledger is dropped when the guard was turned off). The walk
    is per-step rather than a whole-directory retry so retention holding
    a mix of both structures still resumes from the NEWEST restorable
    step instead of silently sliding back to an older same-structure
    one.
    """
    if mesh is not None:
        state = _place_for_mesh(state, mesh, rules)
    if not ckpt_dir:
        return None, state, 1
    ckpt = Checkpointer(ckpt_dir)
    steps = ckpt.all_steps()
    if not steps:
        return ckpt, state, 1
    from dgmc_tpu.train.state import GuardedTrainState, with_guard_counters
    restored = ckpt.restore(
        state,
        structures=((None, state),
                    ('toggled-guard', _toggle_guard_structure(state))))
    step = ckpt.restored_step
    if ckpt.restored_structure == 'toggled-guard':
        if isinstance(state, GuardedTrainState):
            restored = with_guard_counters(restored)
            why = 'written without guard counters; counters start at 0'
        else:
            restored = _toggle_guard_structure(restored)
            why = ('written with guard counters; the skip ledger is '
                   'dropped')
        print(f'checkpoint: step {step} under {ckpt.directory} was '
              f'{why} (--guard-bad-steps toggled between runs)',
              file=sys.stderr)
    state = restored
    note = '' if step == steps[-1] else \
        f' (latest step {steps[-1]} was unrestorable)'
    print(f'Resumed from {ckpt.directory} at epoch {step}.{note}')
    return ckpt, state, step + 1


def snapshot_params(state):
    """In-memory parameter snapshot (the reference's ``deepcopy(state_dict)``
    at ``examples/willow.py:90``). Buffers are copied, not aliased: the
    jitted train steps donate their input state, which would otherwise
    invalidate the snapshot on the next step."""
    import jax.numpy as jnp
    return jax.tree.map(
        lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x,
        {'params': state.params, 'batch_stats': state.batch_stats})


def restore_params(state, snapshot, tx=None):
    """Roll ``state`` back to a snapshot with a *fresh* optimizer, matching
    the per-run reset of reference ``examples/willow.py:155-157``. The
    snapshot leaves are copied into the new state (not aliased) so the
    snapshot survives donation by train steps on the restored state and can
    be restored again for the next run."""
    import jax.numpy as jnp
    tx = tx or state.tx
    fresh = jax.tree.map(
        lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x, snapshot)
    return type(state).create(
        apply_fn=state.apply_fn, params=fresh['params'],
        batch_stats=fresh['batch_stats'], tx=tx)
