"""dgmc_tpu — a TPU-native (JAX/XLA/Pallas) deep graph matching consensus
framework.

Re-implements the full capability surface of the PyTorch reference
``deep-graph-matching-consensus`` (Fey et al., ICLR 2020; see
``/root/reference/dgmc/__init__.py``) with a TPU-first design: padded
static-shape graph batches, functional modules with explicit PRNG keys,
segment-sum message passing, blockwise top-k instead of KeOps, and
``shard_map``-sharded correspondence matrices for multi-chip scale-out.
"""

try:  # models land after ops in the build order; keep ops importable alone.
    from dgmc_tpu.models.dgmc import DGMC
except ImportError:  # pragma: no cover
    DGMC = None

__version__ = '0.3.0'

__all__ = [
    'DGMC',
    '__version__',
]
