from dgmc_tpu.models import precision
from dgmc_tpu.models.mlp import MLP
from dgmc_tpu.models.norm import MaskedBatchNorm
from dgmc_tpu.models.gin import GIN, GINConv
from dgmc_tpu.models.rel import RelCNN, RelConv
from dgmc_tpu.models.spline import SplineCNN, SplineConv
from dgmc_tpu.models.dgmc import DGMC, Correspondence

__all__ = [
    'MLP',
    'MaskedBatchNorm',
    'GIN',
    'GINConv',
    'RelCNN',
    'RelConv',
    'SplineCNN',
    'SplineConv',
    'DGMC',
    'Correspondence',
    'precision',
]
