"""Multi-layer perceptron backbone.

Capability parity with the reference ``MLP`` (reference
``dgmc/models/mlp.py``): N Dense layers; ReLU and optional BatchNorm between
layers; dropout applied *before the final* Dense only. Works on padded
``[B, N, C]`` node tensors with an optional node mask (for BN statistics).
"""

from typing import Any, Optional

from flax import linen as nn

from dgmc_tpu.models.norm import MaskedBatchNorm
from dgmc_tpu.models.precision import compute_dtype_of


class MLP(nn.Module):
    in_channels: int
    out_channels: int
    num_layers: int
    batch_norm: bool = False
    dropout: float = 0.0
    # Mixed-precision compute dtype (e.g. jnp.bfloat16) or a
    # models/precision.Precision policy: matmuls run on the bf16 MXU while
    # parameters stay float32 (flax promotes per-op). BN statistics are
    # always float32 (see MaskedBatchNorm). None = float32.
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, node_mask=None, train=False):
        dtype = compute_dtype_of(self.dtype)
        for i in range(self.num_layers):
            last = i == self.num_layers - 1
            if last:
                x = nn.Dropout(self.dropout, deterministic=not train)(x)
            x = nn.Dense(self.out_channels, name=f'dense_{i}',
                         dtype=dtype)(x)
            if not last:
                x = nn.relu(x)
                if self.batch_norm:
                    x = MaskedBatchNorm(name=f'bn_{i}')(
                        x, node_mask, use_running_average=not train)
        return x

    def __repr__(self):
        return (f'{type(self).__name__}({self.in_channels}, '
                f'{self.out_channels}, num_layers={self.num_layers}, '
                f'batch_norm={self.batch_norm}, dropout={self.dropout})')
