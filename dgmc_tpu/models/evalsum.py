"""Shared host-side eval accounting: raw correct-counts to fractions.

Every experiment CLI evaluates the same way: device-side reductions
(``metrics.acc(..., reduction='sum')`` and friends) accumulate raw
correct COUNTS across batches, and the host divides by the number of
scored pairs at the end. Before this module each CLI hand-rolled that
division (four slightly different ``correct / max(n, 1)`` spellings);
now there is exactly one, and the quality plane
(:mod:`dgmc_tpu.obs.quality`) consumes its output directly.

Deliberately jax-free: the obs readers import it on boxes without an
accelerator stack.
"""

__all__ = ['eval_summary']


def eval_summary(count, loss=None, **counts):
    """Named eval fractions from raw summed counts.

    ``count`` is the number of scored pairs (the denominator); each
    keyword is a raw correct-count (e.g. ``hits1=correct_sum,
    hits10=hits10_sum``) and comes back as ``count``-normalized
    fraction under the same name. ``loss`` passes through unchanged
    (it is already a mean, not a count). The ``max(count, 1)`` guard
    keeps an empty eval split at 0.0 rather than NaN — but ``count``
    itself is reported as-is so an empty account stays visible.
    """
    n = float(count)
    denom = max(n, 1.0)
    out = {'count': n}
    if loss is not None:
        out['loss'] = float(loss)
    for name, c in counts.items():
        out[name] = float(c) / denom
    return out
