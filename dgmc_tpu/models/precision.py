"""Precision policy: bf16 compute / f32 accumulation, ON by default.

One object owns the repo's mixed-precision contract instead of a
``--bf16`` flag re-implemented per CLI:

- **compute dtype** — what the backbone/consensus matmuls run in on the
  MXU (``bfloat16`` under the default policy; ``None`` = float32).
- **accumulation contract** — correspondence logits, losses, segment /
  blocked reductions and the fused Pallas kernels' running sums stay
  float32 regardless of the compute dtype (``preferred_element_type`` on
  every contraction that feeds a logit; pinned by
  ``tests/models/test_precision.py``). A bf16 running sum stops
  absorbing contributions once it is ~256x any addend, so accumulation
  precision is a *correctness* contract, not a knob.
- **parameters / optimizer state** — always float32 (flax promotes
  per-op; the policy never touches storage dtypes).
- **gather dtype** — the blocked-aggregation message tables
  (``ops/blocked.py``) move as bf16 where the rows stay >= 512 bytes
  (the narrow-row guard in ``_routed`` keeps sub-cache-line tables f32
  by design).

The default policy is **bf16**: it measured 1.22x on the dense flagship
and 1.14x on the sparse DBP15K step at lower peak HBM
(``BENCH_r04.json``) with full-scale quality evidence committed
(``runs/dbp15k_syn_bf16.jsonl``; EXPERIMENTS.md). Every experiment CLI
exposes ``--f32`` as the explicit opt-out (``--precision f32``), and
``--bf16`` remains as a compatible no-op alias of the default.

Models consume the policy through :func:`compute_dtype_of`, so their
``dtype`` fields accept either a raw jnp dtype (back-compat) or a
:class:`Precision` object.
"""

import dataclasses
from typing import Any, Optional

__all__ = ['Precision', 'BF16', 'F32', 'get', 'compute_dtype_of',
           'gather_dtype_of', 'add_precision_args', 'from_args']


@dataclasses.dataclass(frozen=True)
class Precision:
    """An immutable mixed-precision policy (see module docstring).

    ``compute_dtype`` is ``None`` for pure-f32 compute (the flax
    convention for "no cast"); ``gather_dtype`` is the string dtype the
    blocked message tables travel as (``None`` = float32 traffic).
    Accumulation is float32 under every policy — there is deliberately
    no field for it.
    """
    name: str
    compute_dtype: Optional[Any]
    gather_dtype: Optional[str]

    @property
    def is_mixed(self):
        return self.compute_dtype is not None

    def __repr__(self):
        return f'Precision({self.name!r})'


def _bf16_dtype():
    import jax.numpy as jnp
    return jnp.bfloat16


# The two shipped policies. BF16 is the library default for training
# CLIs; benchmarks pin their per-leg policy explicitly so recorded
# numbers never depend on a library default. BF16 is materialized
# lazily through the module __getattr__ below (importing this module
# must not pull jax) — `precision.BF16` / `from ... import BF16` always
# yield the real policy object, never a placeholder.
F32 = Precision('f32', None, None)
_BF16 = None


def _bf16():
    global _BF16
    if _BF16 is None:
        _BF16 = Precision('bf16', _bf16_dtype(), 'bfloat16')
    return _BF16


def __getattr__(name):
    if name == 'BF16':
        return _bf16()
    raise AttributeError(f'module {__name__!r} has no attribute {name!r}')


def get(spec):
    """Normalize ``spec`` to a :class:`Precision`.

    Accepts a policy (returned as-is), ``'bf16'``/``'f32'`` names,
    ``None`` (→ f32), or a raw dtype (→ the matching policy; any
    non-f32 dtype maps to the bf16 policy's structure with that compute
    dtype).
    """
    if isinstance(spec, Precision):
        return spec
    if spec is None:
        return F32
    if isinstance(spec, str):
        name = spec.lower()
        if name in ('bf16', 'bfloat16'):
            return _bf16()
        if name in ('f32', 'fp32', 'float32'):
            return F32
        raise ValueError(f'unknown precision policy {spec!r} '
                         f"(expected 'bf16' or 'f32')")
    import jax.numpy as jnp
    dt = jnp.dtype(spec)
    if dt == jnp.float32:
        return F32
    if dt == jnp.bfloat16:
        return _bf16()
    return Precision(str(dt), spec, None)


def compute_dtype_of(spec):
    """The compute dtype a model should cast activations/matmuls to:
    ``None`` for float32. Accepts everything :func:`get` accepts, so a
    module's ``dtype`` field may hold a raw dtype OR a policy."""
    if spec is None:
        return None
    if isinstance(spec, (Precision, str)):
        return get(spec).compute_dtype
    return spec  # raw dtype: back-compat fast path


def gather_dtype_of(spec):
    """The blocked-aggregation gather dtype for ``spec`` (a policy,
    name, dtype, or an explicit gather-dtype string like
    ``'bfloat16'``)."""
    if spec is None:
        return None
    if isinstance(spec, Precision):
        return spec.gather_dtype
    if isinstance(spec, str) and spec not in ('bf16', 'f32', 'fp32',
                                              'float32'):
        return spec  # already a dtype string ('bfloat16')
    return get(spec).gather_dtype


def add_precision_args(parser):
    """Attach the shared precision flags to an ``argparse`` parser:
    ``--precision {bf16,f32}`` (default **bf16**), ``--f32`` as the
    explicit opt-out shorthand, and ``--bf16`` as the legacy alias of
    the default."""
    group = parser.add_argument_group('precision policy')
    group.add_argument('--precision', choices=['bf16', 'f32'],
                       default='bf16',
                       help='compute policy: bf16 matmuls with f32 '
                            'accumulation (default) or full f32')
    group.add_argument('--f32', dest='precision', action='store_const',
                       const='f32',
                       help='opt out of the bf16 default '
                            '(= --precision f32)')
    group.add_argument('--bf16', dest='precision', action='store_const',
                       const='bf16',
                       help='legacy alias of the bf16 default')
    return parser


def from_args(args):
    """The :class:`Precision` selected by :func:`add_precision_args`
    flags."""
    return get(getattr(args, 'precision', None) or 'f32')
