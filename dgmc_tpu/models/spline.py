"""SplineCNN backbone — MXU-first replacement for ``torch_spline_conv``.

Capability parity with the reference ``SplineCNN`` (reference
``dgmc/models/spline.py``): ``num_layers`` B-spline convolutions
(``kernel_size=5`` per pseudo-coordinate dim, degree 1, mean aggregation,
root weight + bias, as in PyG's ``SplineConv`` consumed at reference
``spline.py:21``), ReLU after each conv, jumping-knowledge concat, dropout,
optional final Dense.

TPU-native formulation of the conv itself: instead of a per-edge
gather-weights CUDA kernel, all ``K^D`` kernel matrices are applied to the
*node* features with one large ``[B*N, C_in] x [C_in, K^D*C_out]`` matmul
(node count is ~5x smaller than edge count for Delaunay graphs), then each
edge gathers its 2^D active (sender, knot) slices with a single fused index
and blends them with the closed-form basis weights from
``dgmc_tpu/ops/spline.py``. Everything is dense, static-shape, and
MXU-tileable; XLA fuses the basis blend into the gather.
"""

from typing import Any, Optional

import jax.numpy as jnp
from flax import linen as nn

from dgmc_tpu.models.precision import compute_dtype_of
from dgmc_tpu.ops.graph import scatter_to_nodes
from dgmc_tpu.ops.spline import open_spline_basis


class SplineConv(nn.Module):
    out_features: int
    dim: int
    kernel_size: int = 5
    degree: int = 1
    # Mixed-precision compute dtype for the kernel GEMM / root Dense;
    # parameters stay float32. None = float32.
    dtype: Optional[Any] = None
    # None = auto: on TPU, when the per-graph working set fits VMEM, route
    # and aggregate via the fused Pallas kernel (MXU matmuls per graph,
    # zero HBM gathers) instead of XLA gather + scatter — bit-identical
    # output, and it lifts the dense flagship from ~330 to ~1170 training
    # pairs/sec end to end (dgmc_tpu/ops/pallas/spline.py). Set False
    # inside GSPMD-partitioned programs (no partitioning rule).
    fused: Optional[bool] = None

    @nn.compact
    def __call__(self, x, graph, train=False):
        import jax

        B, N, C_in = x.shape
        dtype = compute_dtype_of(self.dtype)
        KD = self.kernel_size ** self.dim
        weight = self.param(
            'weight',
            nn.initializers.variance_scaling(1.0, 'fan_in',
                                             'truncated_normal',
                                             in_axis=1, out_axis=2),
            (KD, C_in, self.out_features))

        # [B, N, KD * C_out]: every node through every kernel matrix — one
        # MXU GEMM (in the compute dtype when the bf16 policy is on).
        if dtype is not None:
            x = x.astype(dtype)
            weight = weight.astype(dtype)
        t = x @ weight.transpose(1, 0, 2).reshape(C_in, KD * self.out_features)
        t = t.reshape(B, N * KD, self.out_features)

        basis, combo = open_spline_basis(graph.edge_attr, self.kernel_size,
                                         self.degree)      # [B, E, 2^D]
        # Fused (sender, knot) index into the flattened [N * KD] axis.
        flat = graph.senders[..., None] * KD + combo        # [B, E, 2^D]
        E, A = flat.shape[1], flat.shape[2]

        from dgmc_tpu.ops.pallas.dispatch import (auto_fused,
                                                  record_dispatch)
        from dgmc_tpu.ops.pallas.spline import (route_aggregate,
                                                route_aggregate_fits)
        use_fused = self.fused
        if use_fused is None:
            use_fused = auto_fused(
                'spline_route',
                size_ok=route_aggregate_fits(N, E, KD, self.out_features),
                size_reason='vmem')
        else:
            record_dispatch('spline_route',
                            'pallas' if use_fused else 'fallback',
                            'explicit')
        if use_fused:
            agg = route_aggregate(t, flat, basis, graph.receivers,
                                  graph.edge_mask, N)
        else:
            picked = jnp.take_along_axis(
                t, flat.reshape(B, E * A, 1), axis=1).reshape(
                    B, E, A, self.out_features)
            msgs = jnp.einsum('bea,beao->beo', basis.astype(x.dtype), picked)
            agg = scatter_to_nodes(msgs, graph.receivers, graph.edge_mask,
                                   N, aggr='mean')
        root = nn.Dense(self.out_features, use_bias=False, name='root',
                        dtype=dtype)(x)
        bias = self.param('bias', nn.initializers.zeros, (self.out_features,))
        return agg.astype(root.dtype) + root + bias.astype(root.dtype)


class SplineCNN(nn.Module):
    in_channels: int
    channels: int
    dim: int
    num_layers: int
    cat: bool = True
    lin: bool = True
    dropout: float = 0.0
    # Forwarded to every SplineConv. None = auto (fused Pallas routing on
    # TPU at fitting sizes); set False inside GSPMD-partitioned programs —
    # pallas_call has no partitioning rule (see DGMC.corr_sharding).
    fused: Optional[bool] = None
    # Mixed-precision compute dtype (or a precision.Precision policy);
    # parameters stay float32.
    dtype: Optional[Any] = None

    @property
    def out_channels(self):
        if self.lin:
            return self.channels
        if self.cat:
            return self.in_channels + self.num_layers * self.channels
        return self.channels

    @nn.compact
    def __call__(self, x, graph, train=False):
        import jax

        dtype = compute_dtype_of(self.dtype)
        xs = [x]
        for i in range(self.num_layers):
            # Named layer scopes so profiler traces attribute time to the
            # conv stack instead of anonymous fused XLA ops.
            with jax.named_scope(f'spline_conv_{i}'):
                h = SplineConv(self.channels, self.dim, fused=self.fused,
                               dtype=dtype,
                               name=f'conv_{i}')(xs[-1], graph, train=train)
            xs.append(nn.relu(h))
        out = jnp.concatenate(xs, axis=-1) if self.cat else xs[-1]
        out = nn.Dropout(self.dropout, deterministic=not train)(out)
        if self.lin:
            out = nn.Dense(self.channels, name='final',
                           dtype=dtype)(out)
        return out

    def __repr__(self):
        return (f'{type(self).__name__}({self.in_channels}, '
                f'{self.out_channels}, dim={self.dim}, '
                f'num_layers={self.num_layers}, cat={self.cat}, '
                f'lin={self.lin}, dropout={self.dropout})')
