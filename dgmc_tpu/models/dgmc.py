"""Deep Graph Matching Consensus — TPU-native core algorithm.

Capability parity with the reference ``DGMC`` module (reference
``dgmc/models/dgmc.py:32-319``): a two-stage matcher that (1) computes an
initial soft correspondence ``S^0`` from ψ₁ node embeddings and (2) refines
it for ``num_steps`` neighborhood-consensus iterations — per step, random
node indicator functions ``r_s`` are projected through ``S`` onto the target
graph, both graphs run ψ₂, and an MLP on the difference of the resulting
"consensus colourings" updates the correspondence logits. Dense
(``k == -1``) and sparse top-k variants are supported, with random negative
sampling and guaranteed ground-truth inclusion during sparse training
(reference ``dgmc.py:190-195``).

TPU-first design decisions:

- Padded static shapes everywhere; correspondences are a single
  :class:`Correspondence` pytree (``idx=None`` ⇒ dense) rather than
  ``torch.sparse_coo_tensor`` with smuggled ``__idx__``/``__val__`` attrs
  (the reference's downstream math only ever touches those two tensors, see
  reference ``dgmc.py:236-242``).
- Explicit PRNG streams: ``'noise'`` for per-step indicator functions,
  ``'negatives'`` for sparse negative sampling, ``'dropout'`` for the
  backbones. Dense and sparse paths draw identical per-step noise from the
  same stream, preserving the reference's dense≡sparse(k=N) behavioral
  contract (reference ``test/models/test_dgmc.py:29-84``) under explicit
  keys.
- Top-k runs blockwise over target tiles (``dgmc_tpu/ops/topk.py``) — the
  KeOps ``argKmin`` replacement — so the ``N_s x N_t`` score matrix is never
  materialized in the sparse path.
- ``num_steps`` / ``detach`` are call-time arguments (trace-time static),
  replacing the reference's mid-training module-attribute mutation
  (reference ``examples/dbp15k.py:63-69``) with explicit phase config.
"""

from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn
from flax import struct

from dgmc_tpu.obs import probes as _probes
from dgmc_tpu.ops.softmax import masked_softmax
from dgmc_tpu.ops.topk import chunked_topk

EPS = 1e-8

# Row-mass window for the ``topk_mass`` probe: how much probability the 10
# best entries of each correspondence row hold (10 = the k every sparse
# experiment ships with, reference ``examples/dbp15k.py:29-32``).
PROBE_TOPK = 10


def _probe_corr_stage(S, row_mask, stage):
    """Entropy + top-k mass of a correspondence snapshot (S0/SL) — one
    definition for the dense and sparse paths so their probe series stay
    comparable."""
    _probes.emit('corr_entropy', _probes.entropy(S, row_mask), stage=stage)
    _probes.emit('topk_mass', _probes.topk_mass(S, PROBE_TOPK, row_mask),
                 stage=stage)


def _probe_consensus_iter(S_next, S, row_mask, step):
    """Per-iteration correction norm + sharpening entropy."""
    _probes.emit('consensus_delta', _probes.delta_norm(S_next, S, row_mask),
                 iteration=step)
    _probes.emit('corr_entropy', _probes.entropy(S_next, row_mask),
                 iteration=step)


@struct.dataclass
class Correspondence:
    """Soft correspondence matrix, dense or sparse.

    Dense: ``val[B, N_s, N_t]`` with ``idx is None``.
    Sparse: ``val[B, N_s, K]`` probabilities over candidate targets
    ``idx[B, N_s, K]``.
    """
    val: jnp.ndarray
    idx: Optional[jnp.ndarray]
    src_mask: jnp.ndarray  # [B, N_s]
    tgt_mask: jnp.ndarray  # [B, N_t]

    @property
    def is_sparse(self):
        return self.idx is not None

    def to_dense(self):
        """Scatter a sparse correspondence back to ``[B, N_s, N_t]``."""
        if not self.is_sparse:
            return self.val
        B, N_s, K = self.val.shape
        N_t = self.tgt_mask.shape[1]
        out = jnp.zeros((B, N_s, N_t), self.val.dtype)
        b = jnp.arange(B)[:, None, None]
        s = jnp.arange(N_s)[None, :, None]
        return out.at[b, s, self.idx].add(self.val)


def include_gt(S_idx, y_col, y_mask, return_replaced=False):
    """Overwrite the *last* candidate slot with the ground-truth column for
    every valid row whose ground truth is not already present — the sparse
    training guarantee of the reference's ``__include_gt__`` (reference
    ``dgmc/models/dgmc.py:96-112``).

    S_idx: ``[B, N_s, K]``; y_col: ``[B, N_s]``; y_mask: ``[B, N_s]``.
    With ``return_replaced`` also returns the ``[B, N_s]`` bool mask of
    rows whose last slot was overwritten (used by the caller's
    arithmetic entry-mask so the injection rule lives in ONE place).
    """
    present = (S_idx == y_col[..., None]).any(axis=-1)
    replace = y_mask & ~present
    new_last = jnp.where(replace, y_col, S_idx[..., -1])
    out = S_idx.at[..., -1].set(new_last)
    return (out, replace) if return_replaced else out


class DGMC(nn.Module):
    """Two-stage graph matching with iterative neighborhood consensus.

    Args:
        psi_1: feature GNN; called as ``psi_1(x, graph, train=...)``.
        psi_2: consensus GNN; must expose ``in_channels``/``out_channels``
            (the indicator-function width and consensus-colouring width).
        num_steps: default number of consensus iterations.
        k: ``-1`` for the dense variant, else the top-k sparsity.
        detach: default for cutting ψ₁ gradients during refinement.
    """
    psi_1: nn.Module
    psi_2: nn.Module
    num_steps: int
    k: int = -1
    detach: bool = False
    topk_block: int = 256
    # Optional jax.sharding.NamedSharding for correspondence-shaped
    # intermediates [B, N_s, ...]: row-shards S_hat / S_idx over a mesh axis
    # so a single huge pair (DBP15K-scale) spreads its activation state
    # across chips. GSPMD propagates the layout through the consensus loop.
    corr_sharding: Optional[object] = None
    # Named activation shardings beyond S itself (parallel/rules.py sets
    # all three from one PartitionRules config via apply_to_model):
    # - topk_sharding constrains the candidate shortlist S_idx [B, N_s, K]
    #   and drives the shard-embedded distributed search; None falls back
    #   to corr_sharding (the pre-rules behavior).
    # - psi2_sharding constrains the psi_2 consensus intermediates that
    #   live on SOURCE rows (the indicator noise r_s and the stream-packed
    #   psi_2 source input/output, all [B, N_s, ...]), keeping the
    #   per-iteration difference tensors row-sharded by propagation.
    topk_sharding: Optional[object] = None
    psi2_sharding: Optional[object] = None
    # - psi1_sharding constrains the source ψ₁ embedding table h_s
    #   [B, N_s, C] to the row layout, so the embedding COMPUTE shards
    #   with the search instead of replicating per device (the 'psi1'
    #   activation rule; GSPMD inserts the edge-boundary comm).
    # - corpus_sharding constrains the target ψ₁ embedding table h_t
    #   [B, N_t, C] — the serving-corpus table — over the same axis:
    #   the ring-rotated search consumes h_t one shard per device, so
    #   producing it sharded removes the last per-device O(N_t) ψ₁
    #   replication (the 'corpus' activation rule; only set alongside
    #   ring_targets — the replicated-target search would just
    #   all-gather it back).
    psi1_sharding: Optional[object] = None
    corpus_sharding: Optional[object] = None
    # Source-node chunk streaming for the sparse candidate search
    # (ops/topk.streamed_topk; inside the shard-local region when a row
    # sharding is set): the N_s x N_t sweep only ever exists as one
    # [chunk, topk_block] score tile, the million-entity prerequisite.
    # None = unstreamed. Sparse (k >= 1) only.
    stream_chunk: Optional[int] = None
    # Rotate TARGET shards through the row mesh axis during the sharded
    # candidate search (parallel/topk.corr_sharded_topk ring mode): h_t
    # lives one shard per device instead of replicated, and the
    # shard-boundary collective-permute is issued a rotation ahead of
    # the compute that consumes it, so the transfer pipelines against
    # the per-tile top-k (bit-identical results; ignored without a
    # ringable row sharding). Set by PartitionRules.apply_to_model.
    ring_targets: bool = False
    # Mixed-precision compute dtype — a raw dtype or a
    # models/precision.Precision policy — for the matching stage itself
    # (the similarity GEMMs, candidate search operands and consensus MLP):
    # psi outputs are cast to it, matmuls run on the bf16 MXU, and the
    # correspondence logits S_hat accumulate in float32
    # (preferred_element_type) so softmax/loss numerics stay f32.
    # Parameters always stay float32. None = float32 throughout. Set the
    # same dtype/policy on the backbones for end-to-end mixed precision.
    dtype: Optional[Any] = None
    # Pallas kernel for the dense consensus update: bounds the
    # [B, N_s, N_t, R] difference tensor to one VMEM tile and rematerializes
    # it tile-by-tile in the backward. ``None`` (default) auto-enables it on
    # TPU whenever both sides fill the 128x128 kernel tile: measured
    # on-chip it then beats XLA's fusion of the unfused form at every size
    # tried — 7.0 vs 13.9 ms fwd+bwd at [8, 256, 256, 32] through 31.3 vs
    # 37.6 ms at [1, 4096, 4096, 128] (an 8 GiB D tensor it never
    # materializes); below tile size the padded tiles waste the MXU and the
    # unfused form wins (benchmarks/fused_consensus_tpu.json, bench.py).
    # Forced off when corr_sharding is set (GSPMD owns the layout there).
    fused_consensus: Optional[bool] = None
    # Sparse path: route every per-iteration scatter (the r_t projection's
    # segment-sum and the candidate gathers' scatter-add VJPs) through a
    # once-per-step blocked sort of S_idx (ops/corr_route.py) — matmuls
    # only, reused by every consensus iteration and the backward.
    # Default OFF per the measured dispatch-defaults table
    # (benchmarks/DISPATCH_DEFAULTS.md, `corr_route` row): the routed
    # form's padded-row gathers cost more than the scatters they remove
    # at DBP15K scale. Kept as an explicit option: it is
    # matmul/gather-only (no scatter anywhere), so it remains valid
    # under corr_sharding / shard_map where scatter performance or
    # partitioning rules differ.
    route_sparse: Optional[bool] = None
    # Fused Pallas path for the sparse consensus delta
    # (ops/pallas/sparse_consensus.py). ``True`` enables the WIDENED
    # fusion boundary (`fused_candidate_delta`): the candidate gather
    # joins the kernel's custom_vjp — residuals shrink from the
    # [B, N_s, K, R] candidate tensor to the [B, N_t, R] ψ₂ output, the
    # backward rematerializes the gather tile-style, and d_o_t reduces
    # through one fused f32 segment-sum per iteration. Default is the
    # auto decision recorded in benchmarks/DISPATCH_DEFAULTS.md
    # (`sparse_consensus` row — the narrow delta-only kernel measured
    # slower than XLA's fusion against the stream-packed
    # `prefetch_source` baseline; the widened boundary is the
    # re-measure candidate). Kept shard_map-compatible via vma.
    fused_sparse_consensus: Optional[bool] = None
    # Run a backbone ONCE per application point on the node-axis
    # disjoint union of the (source, target) pair instead of twice (once
    # per side). ``True`` merges both backbones, ``'psi_1'`` / ``'psi_2'``
    # merge one. Requires blocked-adjacency graphs (ops/blocked.py) and a
    # BatchNorm-free backbone (merged batch statistics would span both
    # sides, unlike the reference's separate calls, reference
    # ``dgmc/models/dgmc.py:149-150,173-176``). Default OFF for ψ₂:
    # measured at DBP15K scale the per-iteration union's halved op count
    # is cancelled by its combined row gather crossing a ~2^19-row
    # efficiency cliff (10 vs 31 GB/s), and merging ψ₂ also forfeits the
    # bigger stream-packed prefetch win; with plain gather/scatter
    # aggregation the union loses outright (58 vs 36 ms per consensus
    # iteration; batch-axis stacking loses harder still at 73 ms — TPU
    # scatters with a batched leading dim are the slow path). ``'psi_1'``
    # merges only the once-per-step feature encoder — measured at DBP15K
    # scale it ALSO loses (~293 vs ~268 ms wall; the union's combined
    # 1-1.2 KB-row gathers cost more than the halved launch count saves,
    # benchmarks/README.md), so nothing in-tree enables it; it remains an
    # explicit option for platforms where dispatch overhead dominates.
    batch_pair: Optional[Any] = None

    def _constrain(self, a):
        if self.corr_sharding is None:
            return a
        return jax.lax.with_sharding_constraint(a, self.corr_sharding)

    def _constrain_idx(self, a):
        """Shortlist constraint: the 'topk' activation rule, falling back
        to the correspondence rule (S_idx rides with S by default)."""
        sh = (self.topk_sharding if self.topk_sharding is not None
              else self.corr_sharding)
        return a if sh is None else jax.lax.with_sharding_constraint(a, sh)

    def _constrain_psi2(self, a):
        """Source-row ψ₂ intermediates ([B, N_s, ...]): the 'psi2'
        activation rule."""
        if self.psi2_sharding is None:
            return a
        return jax.lax.with_sharding_constraint(a, self.psi2_sharding)

    @property
    def _gspmd_sharded(self):
        """True when any activation-sharding constraint partitions the
        program (GSPMD auto-partitioning: Pallas gates must be silenced,
        except inside explicit shard_map regions)."""
        return (self.corr_sharding is not None
                or self.topk_sharding is not None
                or self.psi2_sharding is not None
                or self.psi1_sharding is not None
                or self.corpus_sharding is not None)

    @nn.compact
    def __call__(self, graph_s, graph_t, y=None, y_mask=None, train=False,
                 num_steps=None, detach=None, pair_offset=0, h_t=None,
                 S_idx=None, h_t_cand=None):
        """Compute initial and refined correspondences ``(S_0, S_L)``.

        Args:
            graph_s / graph_t: padded :class:`GraphBatch` pairs.
            y: optional ``[B, N_s]`` ground-truth target column per source
                node (used only by the sparse variant during training, to
                inject negatives + the ground truth).
            y_mask: ``[B, N_s]`` validity of ``y``.
            train: enables dropout / BN batch stats / negative sampling.
            num_steps / detach: per-call overrides of the module defaults —
                the explicit-phase replacement for the reference's
                attribute-mutation schedule.
            pair_offset: static global index of this batch's FIRST pair in
                the per-pair RNG stream: pair ``b`` draws its indicator
                noise / negative samples from
                ``fold_in(stream_key, pair_offset + b)``, so a batched
                step over pairs ``[i, i+N)`` is element-wise
                RNG-identical to ``N`` independent ``B=1`` calls at
                offsets ``i..i+N-1`` with the same stream keys — the
                ``--pairs-per-step`` equivalence contract
                (tests/models/test_pairs_per_step.py).
            h_t: optional precomputed ψ₁ target embedding table
                ``[B, N_t, C]`` — the serving corpus cache
                (``dgmc_tpu/serve/``). When given, ψ₁ runs on the source
                side only; ``graph_t.x`` is never read, so a serving
                process can ship a dummy feature array and keep the raw
                corpus features off the device entirely.
            S_idx: optional precomputed candidate shortlist
                ``[B, N_s, K]`` (sparse variant only, ``train=False``) —
                skips the in-graph candidate search. The host-driven
                offloaded corpus search
                (:func:`~dgmc_tpu.ops.offload.offloaded_corpus_topk`)
                produces these bit-identically to the in-graph paths.
            h_t_cand: optional pre-gathered candidate embedding rows
                ``[B, N_s, K, C]`` (``h_t[b, S_idx[b]]``), for serving
                modes whose full corpus table lives in HOST memory:
                together with ``S_idx`` it removes the last O(N_t)
                device operand of the matching stage (ψ₂ still runs on
                the corpus *graph structure*, which is O(E_t)).
        """
        num_steps = self.num_steps if num_steps is None else num_steps
        detach = self.detach if detach is None else detach

        if S_idx is not None or h_t_cand is not None:
            if train:
                raise ValueError(
                    'precomputed S_idx / h_t_cand are inference-serving '
                    'arguments: the training path extends the shortlist '
                    'with negatives and the injected ground truth '
                    '(train=False required)')
            if self.k < 1:
                raise ValueError(
                    'precomputed S_idx / h_t_cand require the sparse '
                    'variant (k >= 1); the dense variant has no '
                    'candidate shortlist')
            if h_t_cand is not None and S_idx is None:
                raise ValueError('h_t_cand (pre-gathered candidate rows) '
                                 'is meaningless without the S_idx it '
                                 'was gathered at')

        if self.stream_chunk is not None and self.k < 1:
            raise ValueError(
                'stream_chunk streams the sparse candidate search; the '
                'dense variant (k=-1) materializes S and cannot stream '
                '(set k >= 1 or stream_chunk=None)')

        if self._gspmd_sharded:
            # Pallas kernels have no GSPMD partitioning rule. DGMC forces
            # its own kernels off under corr_sharding, auto-dispatched
            # backbone kernels are silenced via the trace-time context
            # below, and an *explicit* fused=True is rejected loudly (a
            # silent pallas_call inside the partitioned program would
            # crash or replicate at partition time).
            for role, m in (('psi_1', self.psi_1), ('psi_2', self.psi_2)):
                if getattr(m, 'fused', None) is True:
                    raise ValueError(
                        f'corr_sharding is incompatible with {role} '
                        f'fused=True: Pallas routing kernels cannot run '
                        f'inside GSPMD-partitioned programs')
            for flag in ('fused_consensus', 'fused_sparse_consensus'):
                if getattr(self, flag) is True:
                    raise ValueError(
                        f'corr_sharding is incompatible with {flag}=True: '
                        f'pallas_call has no GSPMD partitioning rule '
                        f'(leave it at None/False for sharded execution)')

        def run_psi(m, *args, **kw):
            """Invoke a backbone; under an activation sharding, silence
            its auto-dispatched Pallas kernels for the GSPMD program."""
            if not self._gspmd_sharded:
                return m(*args, **kw)
            from dgmc_tpu.ops.pallas.dispatch import disable_fused_kernels
            with disable_fused_kernels():
                return m(*args, **kw)

        from dgmc_tpu.ops.blocked import UnionPair

        if self.batch_pair not in (None, False, True, 'psi_1', 'psi_2'):
            raise ValueError(
                f"batch_pair must be None/False/True/'psi_1'/'psi_2', "
                f'got {self.batch_pair!r}')
        can_stack = (
            self.batch_pair in (True, 'psi_1', 'psi_2')
            and (graph_s.edge_attr is None) == (graph_t.edge_attr is None)
            and (graph_s.edge_attr is None
                 or graph_s.edge_attr.shape[-1] == graph_t.edge_attr.shape[-1])
            and graph_s.blocks_in is not None
            and graph_t.blocks_in is not None
            and graph_s.blocks_in.rows == graph_t.blocks_in.rows
        )
        if self.batch_pair in (True, 'psi_1', 'psi_2') and not can_stack:
            # Mirror the loud BatchNorm rejection below: a user who
            # explicitly requested union mode must not silently benchmark
            # the two-call path.
            raise ValueError(
                'batch_pair requires blocked-adjacency graphs on both '
                'sides (ops/blocked.attach_blocks) with matching block '
                'rows and edge_attr widths; this pair cannot be stacked')

        def merges(m, role):
            if not can_stack or self.batch_pair not in (True, role):
                return False
            if getattr(m, 'batch_norm', False):
                raise ValueError(
                    'batch_pair is invalid with a BatchNorm '
                    'backbone: merged batch statistics would span '
                    'both graphs')
            return True

        merge_1 = merges(self.psi_1, 'psi_1')
        if merge_1 and graph_s.x.shape[-1] != graph_t.x.shape[-1]:
            raise ValueError(
                f'batch_pair={self.batch_pair!r} cannot union psi_1: '
                f'source/target feature widths differ '
                f'({graph_s.x.shape[-1]} vs {graph_t.x.shape[-1]})')
        merge_2 = merges(self.psi_2, 'psi_2')
        if (merge_1 or merge_2) and (h_t is not None
                                     or h_t_cand is not None):
            raise ValueError(
                'precomputed h_t / h_t_cand are incompatible with '
                'batch_pair union evaluation: the union stacks both '
                'sides through one backbone call, but a precomputed '
                'target table means the target side never runs ψ₁')
        pair = UnionPair(graph_s, graph_t) if (merge_1 or merge_2) else None

        def run_pair(m, x_s_in, x_t_in, merge):
            if not merge:
                return (run_psi(m, x_s_in, graph_s, train=train),
                        run_psi(m, x_t_in, graph_t, train=train))
            return pair.apply(
                lambda x, g: run_psi(m, x, g, train=train), x_s_in, x_t_in)

        # Stage scopes (psi1 / initial_corr / topk / consensus_iter / psi2)
        # name the matching pipeline's phases in profiler traces and
        # lowered HLO metadata — numerics are untouched.
        with jax.named_scope('psi1'):
            if h_t is None and h_t_cand is None:
                h_s, h_t = run_pair(self.psi_1, graph_s.x, graph_t.x,
                                    merge_1)
            else:
                # Serving split: the corpus table (or its candidate
                # rows) comes precomputed — ψ₁ runs on the query side
                # only. graph_t.x is dead here by design.
                h_s = run_psi(self.psi_1, graph_s.x, graph_s, train=train)
        # In-graph numerics probes (obs/probes.py). The switch is a Python
        # bool at trace time: disabled (default) traces NOTHING — neither
        # the metric math nor the host callback — so the lowered HLO stays
        # byte-identical to a probe-free build (tests/obs/test_probes.py).
        # Gated on `train` as well: the probe series documents the TRAIN
        # step (eval forwards would pollute the aggregates and could trip
        # the CI non-finite gate on an eval-only NaN).
        probe = _probes.enabled() and train
        if probe:
            _probes.check_finite('psi1', h_s,
                                 *(() if h_t is None else (h_t,)), order=0)
        from dgmc_tpu.models.precision import compute_dtype_of
        dtype = compute_dtype_of(self.dtype)
        if dtype is not None:
            h_s = h_s.astype(dtype)
            if h_t is not None:
                h_t = h_t.astype(dtype)
            if h_t_cand is not None:
                h_t_cand = h_t_cand.astype(dtype)
        # Embedding-table layout constraints (streamed million-entity
        # config): h_s follows the row sharding the search consumes, and
        # h_t — the corpus table — follows the ring's shard rotation, so
        # ψ₁ itself runs sharded instead of once per device.
        if self.psi1_sharding is not None:
            h_s = jax.lax.with_sharding_constraint(h_s,
                                                   self.psi1_sharding)
        if self.corpus_sharding is not None and h_t is not None:
            h_t = jax.lax.with_sharding_constraint(h_t,
                                                   self.corpus_sharding)
        if detach:
            h_s = jax.lax.stop_gradient(h_s)
            if h_t is not None:
                h_t = jax.lax.stop_gradient(h_t)

        s_mask, t_mask = graph_s.node_mask, graph_t.node_mask
        (B, N_s), N_t = s_mask.shape, t_mask.shape[1]
        R_in = self.psi_2.in_channels
        R_out = self.psi_2.out_channels

        # Explicit consensus-MLP params (not nn.Dense) so the fused Pallas
        # kernel and the jnp path share one parameter set.
        init = nn.initializers.lecun_normal()
        mlp_w1 = self.param('mlp_hidden_kernel', init, (R_out, R_out))
        mlp_b1 = self.param('mlp_hidden_bias', nn.initializers.zeros,
                            (R_out,))
        mlp_w2 = self.param('mlp_out_kernel', init, (R_out, 1))
        mlp_b2 = self.param('mlp_out_bias', nn.initializers.zeros, (1,))

        def consensus_mlp(d):
            w1, w2 = mlp_w1.astype(d.dtype), mlp_w2.astype(d.dtype)
            h = nn.relu(d @ w1 + mlp_b1.astype(d.dtype))
            out = jax.lax.dot_general(
                h, w2, (((h.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return out[..., 0] + mlp_b2[0]

        def consensus_factored(u_s, u_t_rows):
            """``relu(D @ W1 + b1) @ W2 + b2`` with the first matmul
            factored through linearity: ``D @ W1 = (o_s @ W1) -
            (o_t @ W1)`` — the ``[.., N_s, N_t, R] @ [R, R]`` contraction
            over every candidate pair becomes two node-level matmuls done
            BEFORE broadcasting (``u_s = o_s@W1+b1``, ``u_t = o_t@W1``),
            cutting dense unfused-step FLOPs ~24%. Measured WORTH IT only
            on the dense path; the sparse step got ~25 ms SLOWER factored
            (the leftover ``[.., K, R] @ [R, 1]`` matvec tail and the
            extra saved activations outweigh the removed matmul), so the
            sparse loop keeps the direct ``consensus_mlp(D)`` form."""
            h = nn.relu(u_s[:, :, None, :] - u_t_rows)
            out = jax.lax.dot_general(
                h, mlp_w2.astype(h.dtype), (((3,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return out[..., 0] + mlp_b2[0]

        def pair_keys(key):
            # One independent key per PAIR, folded from the stream key at
            # the pair's global index: batching pairs is then RNG-exact
            # against the equivalent run of B=1 steps (see `pair_offset`).
            return jax.vmap(jax.random.fold_in, in_axes=(None, 0))(
                key, pair_offset + jnp.arange(B))

        def noise(step):
            keys = pair_keys(self.make_rng('noise'))
            return self._constrain_psi2(jax.vmap(
                lambda k: jax.random.normal(k, (N_s, R_in), h_s.dtype))(
                    keys))

        def prefetch_source(num_steps):
            """Batch the source side of ψ₂ across ALL consensus iterations.

            Per iteration the loop runs ψ₂ twice (reference
            ``dgmc/models/dgmc.py:173-176``) — but the source-side input
            ``r_s`` is pre-drawable indicator noise, independent of the
            evolving correspondence; only the target side (``r_t = S·r_s``)
            is sequential. So all ``num_steps`` source applications run as
            ONE ψ₂ call on a step-tiled batch: identical values (same
            per-step PRNG draws, shared parameters), ~num_steps× fewer
            kernel launches and num_steps×-larger gathers/GEMMs on the
            source graph — the profiled sparse step spends >50% of its
            time in ψ₂ dispatch+gather (benchmarks/profile_sparse.py).

            Valid only when ψ₂ supports channel-packed evaluation
            (``streams``, currently RelCNN) and is batch-agnostic: no
            batch statistics and no active dropout (a packed evaluation
            would draw one mask across steps), and the pair isn't
            union-merged. A step-tiled *batch* fallback was measured and
            rejected: identical device time on the sparse workload (the
            gathers are row-bound, not launch-bound) and a 2.5× peak-HBM
            regression on the dense flagship.
            """
            if num_steps <= 1 or merge_2:
                return None
            if getattr(self.psi_2, 'batch_norm', False):
                return None
            if train and getattr(self.psi_2, 'dropout', 0.0):
                return None
            if not getattr(self.psi_2, 'supports_streams', False):
                return None
            r_all = jnp.stack([noise(i) for i in range(num_steps)])
            T = num_steps
            # Channel-packed form: the node tables the edge gathers read
            # become T× wider (1.28 KB rows instead of 128 B at the
            # DBP15K config), so the latency-bound random gathers run
            # once for all T iterations. The packed [B, N_s, T*R] tables
            # are source-row activations — the 'psi2' rule keeps them
            # row-sharded through the pack/unpack reshapes.
            x = self._constrain_psi2(
                r_all.transpose(1, 2, 0, 3).reshape(B, N_s, T * R_in))
            with jax.named_scope('psi2'):
                o = self._constrain_psi2(
                    run_psi(self.psi_2, x, graph_s, train=train, streams=T))
            return r_all, o.reshape(B, N_s, T, -1).transpose(2, 0, 1, 3)

        if self.k < 1:
            # ---- Dense variant ----
            with jax.named_scope('initial_corr'):
                S_hat = self._constrain(
                    jnp.einsum('bsc,btc->bst', h_s, h_t,
                               preferred_element_type=jnp.float32))
                S_mask = s_mask[:, :, None] & t_mask[:, None, :]
                S_0 = masked_softmax(S_hat, S_mask)
            if probe:
                _probes.check_finite('initial_corr', S_hat, order=1)
                _probe_corr_stage(S_0, s_mask, 'S0')

            # Resolve (and record) the kernel decision only when the
            # consensus loop actually runs — num_steps == 0 must not
            # claim a dispatch outcome for code that never executes.
            use_fused = False
            if num_steps > 0 and self.fused_consensus is None:
                if self._gspmd_sharded:
                    from dgmc_tpu.ops.pallas.dispatch import record_dispatch
                    record_dispatch('dense_consensus', 'fallback',
                                    'gspmd-silenced')
                else:
                    from dgmc_tpu.ops.pallas.consensus import TILE_S, TILE_T
                    from dgmc_tpu.ops.pallas.dispatch import auto_fused
                    # R ceiling: the kernel holds two [TILE_S*TILE_T, R]
                    # f32 tiles in VMEM (64 KiB x R each); measurements
                    # cover R <= 128
                    # (benchmarks/fused_consensus_tpu.json) and R = 256
                    # would blow the 16 MB scoped-VMEM limit.
                    use_fused = auto_fused(
                        'dense_consensus',
                        size_ok=(N_s >= TILE_S and N_t >= TILE_T
                                 and R_out <= 128))
            elif num_steps > 0:
                # Explicit True with corr_sharding was rejected loudly
                # above, so no silent clamp can happen here.
                from dgmc_tpu.ops.pallas.dispatch import record_dispatch
                use_fused = self.fused_consensus
                record_dispatch('dense_consensus',
                                'pallas' if use_fused else 'fallback',
                                'explicit')
            pre = prefetch_source(num_steps)

            def dense_iter(step, S_hat):
                with jax.named_scope('consensus_iter'):
                    S = masked_softmax(S_hat, S_mask)
                    r_s = pre[0][step] if pre is not None else noise(step)
                    r_t = jnp.einsum('bst,bsr->btr', S, r_s)
                    with jax.named_scope('psi2'):
                        if pre is not None:
                            o_s = pre[1][step]
                            o_t = run_psi(self.psi_2, r_t, graph_t,
                                          train=train)
                        else:
                            o_s, o_t = run_pair(self.psi_2, r_s, r_t,
                                                merge_2)
                    if use_fused:
                        from dgmc_tpu.ops.pallas import consensus_update
                        cast = lambda a: a.astype(o_s.dtype)  # noqa: E731
                        delta = consensus_update(
                            o_s, o_t, cast(mlp_w1), cast(mlp_b1),
                            cast(mlp_w2), cast(mlp_b2),
                            jax.default_backend() != 'tpu')  # interpret
                    else:
                        w1 = mlp_w1.astype(o_s.dtype)
                        delta = consensus_factored(
                            o_s @ w1 + mlp_b1.astype(o_s.dtype),
                            (o_t @ w1)[:, None, :, :])
                    S_hat_next = self._constrain(
                        S_hat + jnp.where(S_mask, delta, 0.0))
                    if probe:
                        S_next = masked_softmax(S_hat_next, S_mask)
                        _probe_consensus_iter(S_next, S, s_mask, step)
                        _probes.check_finite('consensus_iter', S_hat_next,
                                             order=2 + step,
                                             iteration=step)
                    return S_hat_next

            for step in range(num_steps):
                S_hat = dense_iter(step, S_hat)

            S_L = masked_softmax(S_hat, S_mask)
            if probe:
                _probe_corr_stage(S_L, s_mask, 'SL')
            return (Correspondence(S_0, None, s_mask, t_mask),
                    Correspondence(S_L, None, s_mask, t_mask))

        # ---- Sparse (top-k) variant ----
        # Under corr_sharding the candidate search runs as shard_map
        # manual code EMBEDDED in the GSPMD program: each (batch, row)
        # shard runs the streaming Pallas kernel locally (rows are
        # independent, no collectives) instead of the whole program
        # falling back to the ~4x slower scan — pallas_call has no GSPMD
        # partitioning rule, but it does run under shard_map
        # (parallel/topk.corr_sharded_topk). Ragged row counts are padded
        # inside the embedding; only a ragged batch axis falls back.
        with jax.named_scope('topk'):
            if S_idx is not None:
                # Precomputed shortlist (serving offload tier): the
                # search is skipped wholesale; validity/tie semantics
                # are the producer's contract
                # (offloaded_corpus_topk == chunked_topk, bit-exact).
                if S_idx.shape[-1] != self.k:
                    raise ValueError(
                        f'precomputed S_idx carries {S_idx.shape[-1]} '
                        f'candidates but the model was built with '
                        f'k={self.k}')
                S_idx = self._constrain_idx(S_idx.astype(jnp.int32))
            elif h_t is None:
                raise ValueError(
                    'the sparse candidate search needs the full h_t '
                    'table (or a precomputed S_idx shortlist)')
            idx_sharding = (self.topk_sharding
                            if self.topk_sharding is not None
                            else self.corr_sharding)
            if S_idx is None and idx_sharding is not None:
                from dgmc_tpu.parallel.topk import corr_sharded_topk
                S_idx = corr_sharded_topk(idx_sharding, h_s, h_t,
                                          self.k, t_mask,
                                          block=self.topk_block,
                                          chunk=self.stream_chunk,
                                          ring=self.ring_targets)
            if S_idx is None and self.stream_chunk is not None:
                from dgmc_tpu.ops.topk import streamed_topk
                S_idx = streamed_topk(h_s, h_t, self.k, self.stream_chunk,
                                      t_mask=t_mask, block=self.topk_block,
                                      pallas=False if self._gspmd_sharded
                                      else None,
                                      dispatch_reason='gspmd-silenced')
            if S_idx is None:
                S_idx = chunked_topk(h_s, h_t, self.k, t_mask=t_mask,
                                     block=self.topk_block,
                                     pallas=False
                                     if self._gspmd_sharded
                                     else None,
                                     dispatch_reason='gspmd-silenced')
            S_idx = self._constrain_idx(S_idx)

        # Candidate-slot validity WITHOUT gathering t_mask at S_idx (a
        # ~300k-row bool gather, ~2.4 ms/step at DBP15K scale), by
        # construction of each slot:
        # - top-k slot j is valid exactly when j < n_valid: masked columns
        #   score exactly finfo.min / -inf in every search path, strictly
        #   below any real inner product, so the k winners are the valid
        #   columns first;
        # - random negatives are drawn as floor(u * n_valid), always a
        #   valid column (invalid only in the degenerate n_valid == 0);
        # - an injected ground-truth column is valid by the GT contract
        #   (the reference overwrites blindly too, reference
        #   dgmc.py:96-112).
        n_valid_t = jnp.sum(t_mask, axis=-1).astype(jnp.int32)      # [B]
        entry_mask = jnp.broadcast_to(
            jnp.arange(self.k)[None, None, :] < n_valid_t[:, None, None],
            (B, N_s, self.k))

        if train and y is not None:
            if y_mask is None:
                y_mask = jnp.ones(y.shape, bool)
            num_rnd = min(self.k, N_t - self.k)
            if num_rnd > 0:
                keys = pair_keys(self.make_rng('negatives'))
                u = jax.vmap(
                    lambda k: jax.random.uniform(k, (N_s, num_rnd)))(keys)
                n_valid = n_valid_t.astype(u.dtype)                 # [B]
                rnd = jnp.floor(u * n_valid[:, None, None]).astype(jnp.int32)
                S_idx = jnp.concatenate([S_idx, rnd], axis=-1)
                entry_mask = jnp.concatenate(
                    [entry_mask,
                     jnp.broadcast_to((n_valid_t > 0)[:, None, None],
                                      (B, N_s, num_rnd))], axis=-1)
            S_idx, replaced = include_gt(S_idx, y, y_mask & s_mask,
                                         return_replaced=True)
            entry_mask = entry_mask.at[..., -1].set(
                entry_mask[..., -1] | replaced)

        def gather_t(feat, idx):
            # feat [B, N_t, C], idx [B, N_s, K] -> [B, N_s, K, C].
            # mode='clip': candidate indices come from top-k / uniform
            # negatives / ground-truth injection, all < N_t by
            # construction — the default 'fill' mode's select_n pass over
            # the gathered rows is measurable waste at DBP15K scale.
            # (The narrow-row upcast guard that pays off in the blocked
            # aggregation path was tried here too in r5 and measured
            # neutral-to-negative — the extra downcast pass on the
            # [B, N_s*K, C] result eats the gather saving.)
            Bk, Ns_, K_ = idx.shape
            flat = jnp.take_along_axis(feat, idx.reshape(Bk, Ns_ * K_, 1),
                                       axis=1, mode='clip')
            return flat.reshape(Bk, Ns_, K_, feat.shape[-1])

        # Scatter-free candidate routing (see route_sparse field): one
        # device-side blocked sort of the final S_idx serves every
        # consensus iteration and the whole backward pass.
        use_route = bool(self.route_sparse)
        if use_route:
            from dgmc_tpu.ops.corr_route import (build_corr_route,
                                                 sparse_gather,
                                                 sparse_project)
            route = build_corr_route(S_idx, N_t)
            cand_rows = lambda feat: sparse_gather(feat, S_idx, route)  # noqa: E731,E501
            project = lambda S, r_s: sparse_project(S, r_s, S_idx, route)  # noqa: E731,E501
        else:
            cand_rows = lambda feat: gather_t(feat, S_idx)  # noqa: E731

            def project(S, r_s):
                contrib = S[..., None] * r_s[:, :, None, :]
                K_ = S_idx.shape[-1]

                def scat(c, idx):
                    return jax.ops.segment_sum(c, idx, num_segments=N_t)

                return jax.vmap(scat)(contrib.reshape(B, N_s * K_, R_in),
                                      S_idx.reshape(B, N_s * K_))

        with jax.named_scope('initial_corr'):
            h_t_rows = h_t_cand if h_t_cand is not None else cand_rows(h_t)
            S_hat = jnp.einsum('bsc,bskc->bsk', h_s, h_t_rows,
                               preferred_element_type=jnp.float32)
            S_0 = masked_softmax(S_hat, entry_mask) * s_mask[..., None]
        if probe:
            _probes.check_finite('initial_corr', S_hat, order=1)
            _probe_corr_stage(S_0, s_mask, 'S0')

        # Fused consensus-delta path (ops/pallas/sparse_consensus.py):
        # forms the [TILE, K, R] difference block and MLP activations in
        # VMEM only, with a tile-recompute backward — and, via the
        # widened `fused_candidate_delta` boundary, keeps the candidate
        # gather inside the custom_vjp so the [B, N_s, K, R] tensor is
        # never saved across the fwd/bwd boundary (rematerialized;
        # d_o_t lands through one fused f32 segment-sum). GSPMD programs
        # keep the jnp form (no partitioning rule); shard_map is fine
        # (the kernel declares its vma).
        # Explicit True is honored (interpret mode off-TPU, like the
        # dense fused_consensus kernel); only an auto decision would
        # consult the trace-time contextvar — and the auto decision is
        # the recorded dispatch default (benchmarks/DISPATCH_DEFAULTS.md).
        # corr_sharding was rejected loudly earlier; an unsatisfiable
        # width is too.
        use_sc = self.fused_sparse_consensus is True
        if num_steps > 0:
            from dgmc_tpu.ops.pallas.dispatch import record_dispatch
            record_dispatch(
                'sparse_consensus', 'pallas' if use_sc else 'fallback',
                'explicit' if self.fused_sparse_consensus is not None
                else 'default-off')
        if use_sc and R_out > 128:
            raise ValueError(
                f'fused_sparse_consensus=True requires psi_2 out_channels '
                f'<= 128 (VMEM tile bound); got {R_out}')

        pre = prefetch_source(num_steps)

        def sparse_iter(step, S_hat):
            with jax.named_scope('consensus_iter'):
                S = masked_softmax(S_hat, entry_mask) * s_mask[..., None]
                r_s = pre[0][step] if pre is not None else noise(step)
                r_t = project(S, r_s)
                with jax.named_scope('psi2'):
                    if pre is not None:
                        o_s = pre[1][step]
                        o_t = run_psi(self.psi_2, r_t, graph_t, train=train)
                    else:
                        o_s, o_t = run_pair(self.psi_2, r_s, r_t, merge_2)
                if use_sc and not use_route:
                    # Widened fusion boundary: the candidate gather rides
                    # inside the kernel's custom_vjp (rematerialized in
                    # the backward) instead of materializing + saving
                    # [B, N_s, K, R] per iteration.
                    from dgmc_tpu.ops.pallas.sparse_consensus import (
                        fused_candidate_delta)
                    cast = lambda a: a.astype(o_s.dtype)  # noqa: E731
                    delta = fused_candidate_delta(
                        o_s, o_t.astype(o_s.dtype), S_idx, cast(mlp_w1),
                        cast(mlp_b1), cast(mlp_w2), cast(mlp_b2),
                        jax.default_backend() != 'tpu')
                elif use_sc:
                    # route_sparse composes with the narrow kernel: the
                    # routed gather owns the backward, the kernel the MLP.
                    from dgmc_tpu.ops.pallas.sparse_consensus import (
                        sparse_consensus_delta)
                    cast = lambda a: a.astype(o_s.dtype)  # noqa: E731
                    delta = sparse_consensus_delta(
                        o_s, cand_rows(o_t), cast(mlp_w1), cast(mlp_b1),
                        cast(mlp_w2), cast(mlp_b2),
                        jax.default_backend() != 'tpu')
                else:
                    delta = consensus_mlp(
                        o_s[:, :, None, :] - cand_rows(o_t))
                S_hat_next = self._constrain(S_hat + delta)
                if probe:
                    S_next = (masked_softmax(S_hat_next, entry_mask)
                              * s_mask[..., None])
                    _probe_consensus_iter(S_next, S, s_mask, step)
                    _probes.check_finite('consensus_iter', S_hat_next,
                                         order=2 + step,
                                         iteration=step)
                return S_hat_next

        for step in range(num_steps):
            S_hat = sparse_iter(step, S_hat)

        S_L = masked_softmax(S_hat, entry_mask) * s_mask[..., None]
        if probe:
            _probe_corr_stage(S_L, s_mask, 'SL')
        return (Correspondence(S_0, S_idx, s_mask, t_mask),
                Correspondence(S_L, S_idx, s_mask, t_mask))

    # -- Metrics (thin wrappers so the reference's model-level API surface,
    #    reference dgmc.py:246-311, exists here too) --

    @staticmethod
    def loss(S, y, y_mask=None, reduction='mean'):
        from dgmc_tpu.models import metrics
        return metrics.nll_loss(S, y, y_mask, reduction=reduction)

    @staticmethod
    def acc(S, y, y_mask=None, reduction='mean'):
        from dgmc_tpu.models import metrics
        return metrics.acc(S, y, y_mask, reduction=reduction)

    @staticmethod
    def hits_at_k(k, S, y, y_mask=None, reduction='mean'):
        from dgmc_tpu.models import metrics
        return metrics.hits_at_k(k, S, y, y_mask, reduction=reduction)

    def __repr__(self):
        return (f'{type(self).__name__}(\n'
                f'    psi_1={self.psi_1!r},\n'
                f'    psi_2={self.psi_2!r},\n'
                f'    num_steps={self.num_steps}, k={self.k}\n)')
