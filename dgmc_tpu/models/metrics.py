"""Correspondence losses and retrieval metrics.

Capability parity with the reference's model-level metrics (reference
``dgmc/models/dgmc.py:246-311``): NLL over the ground-truth correspondence
probability, Hits@1 (``acc``), and Hits@k — each for both dense and sparse
correspondences. Ground truths here are padded ``y[B, N_s]`` target columns
with a validity mask instead of the reference's ragged ``[2, num_gt]`` pair
lists (converters live in ``dgmc_tpu/utils/data.py``), so every reduction is
a masked mean/sum with static shapes.

Reference quirk preserved: for sparse correspondences, ground truths whose
column is absent from the candidate set contribute nothing to the loss (the
reference's boolean-mask gather simply selects fewer entries, reference
``dgmc.py:263-266``); during training absence cannot happen because
``include_gt`` injects the column.
"""

import jax.numpy as jnp
from jax import lax

EPS = 1e-8


def _prep(y, y_mask):
    if y_mask is None:
        y_mask = jnp.ones(y.shape, bool)
    return y, y_mask


def _gt_val(S, y):
    """Probability mass the correspondence assigns to the GT column, and
    whether the GT column is present in the candidate set at all."""
    if S.is_sparse:
        hit = S.idx == y[..., None]
        val = jnp.sum(S.val * hit, axis=-1)
        found = hit.any(axis=-1)
    else:
        val = jnp.take_along_axis(
            S.val, jnp.clip(y, 0)[..., None], axis=-1)[..., 0]
        found = jnp.ones(y.shape, bool)
    return val, found


def nll_loss(S, y, y_mask=None, reduction='mean'):
    """Negative log-likelihood of the ground-truth correspondences.

    ``reduction``: ``'mean'`` (over every valid correspondence in the
    batch), ``'sum'``, ``'none'`` (elementwise ``[B, N_s]``), or
    ``'per_pair'`` — a ``[B]`` masked mean per batch element, the
    quantity the ``--pairs-per-step`` equivalence contract pins (pair
    ``b`` of a batched step reports the same loss as its own ``B=1``
    step).
    """
    y, y_mask = _prep(y, y_mask)
    val, found = _gt_val(S, y)
    m = y_mask & found
    nll = -jnp.log(val + EPS) * m
    if reduction == 'none':
        return nll
    if reduction == 'per_pair':
        axes = tuple(range(1, nll.ndim))
        return nll.sum(axes) / jnp.maximum(m.sum(axes), 1)
    total = nll.sum()
    if reduction == 'sum':
        return total
    return total / jnp.maximum(m.sum(), 1)


def acc(S, y, y_mask=None, reduction='mean'):
    """Hits@1: fraction of valid ground truths whose argmax prediction is
    correct."""
    y, y_mask = _prep(y, y_mask)
    if S.is_sparse:
        best = jnp.argmax(S.val, axis=-1)
        pred = jnp.take_along_axis(S.idx, best[..., None], axis=-1)[..., 0]
    else:
        scores = jnp.where(S.tgt_mask[:, None, :], S.val,
                           jnp.finfo(S.val.dtype).min)
        pred = jnp.argmax(scores, axis=-1)
    correct = ((pred == y) & y_mask).sum()
    if reduction == 'sum':
        return correct
    return correct / jnp.maximum(y_mask.sum(), 1)


def hits_at_k(k, S, y, y_mask=None, reduction='mean'):
    """Hits@k: fraction of valid ground truths ranked in the top k."""
    y, y_mask = _prep(y, y_mask)
    if S.is_sparse:
        kk = min(k, S.val.shape[-1])
        _, pos = lax.top_k(S.val, kk)
        pred = jnp.take_along_axis(S.idx, pos, axis=-1)
    else:
        kk = min(k, S.val.shape[-1])
        scores = jnp.where(S.tgt_mask[:, None, :], S.val,
                           jnp.finfo(S.val.dtype).min)
        _, pred = lax.top_k(scores, kk)
    hit = (pred == y[..., None]).any(axis=-1)
    correct = (hit & y_mask).sum()
    if reduction == 'sum':
        return correct
    return correct / jnp.maximum(y_mask.sum(), 1)
