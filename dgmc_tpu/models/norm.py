"""Mask-aware batch normalization.

The reference uses ``torch.nn.BatchNorm1d`` over flat node lists (reference
``dgmc/models/mlp.py:2,21``, ``rel.py:57``), where every row is a real node.
In the padded representation, batch statistics must exclude padding or the
zero rows would bias mean/variance, so this is a BatchNorm that takes the
node mask into account. Running statistics live in the ``batch_stats``
collection as explicit state — the functional equivalent of torch's buffer
mutation.
"""

import jax.numpy as jnp
from flax import linen as nn


class MaskedBatchNorm(nn.Module):
    momentum: float = 0.9
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x, mask=None, use_running_average=True):
        """x: ``[..., C]``; mask: broadcastable to ``x.shape[:-1]`` or None."""
        C = x.shape[-1]
        ra_mean = self.variable('batch_stats', 'mean',
                                lambda: jnp.zeros(C, jnp.float32))
        ra_var = self.variable('batch_stats', 'var',
                               lambda: jnp.ones(C, jnp.float32))
        scale = self.param('scale', nn.initializers.ones, (C,))
        bias = self.param('bias', nn.initializers.zeros, (C,))

        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            xf = x.astype(jnp.float32).reshape(-1, C)
            if mask is None:
                n = jnp.asarray(xf.shape[0], jnp.float32)
                mean = xf.mean(axis=0)
                var = ((xf - mean) ** 2).mean(axis=0)
            else:
                w = mask.astype(jnp.float32).reshape(-1, 1)
                n = jnp.maximum(w.sum(), 1.0)
                mean = (xf * w).sum(axis=0) / n
                var = (((xf - mean) ** 2) * w).sum(axis=0) / n
            if not self.is_initializing():
                # Torch tracks running variance with Bessel's correction.
                unbiased = var * n / jnp.maximum(n - 1.0, 1.0)
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * mean
                ra_var.value = m * ra_var.value + (1 - m) * unbiased

        y = (x - mean) / jnp.sqrt(var + self.epsilon)
        return y * scale + bias
