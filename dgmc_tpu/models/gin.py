"""GIN backbone (Graph Isomorphism Network).

Capability parity with the reference ``GIN`` (reference
``dgmc/models/gin.py``): ``num_layers`` GIN convolutions with a learnable
epsilon (PyG ``GINConv(train_eps=True)``, reference ``gin.py:22``), each
wrapping a 2-layer MLP; jumping-knowledge concatenation of
``[x, h^1, ..., h^L]`` when ``cat``; optional final Dense.

TPU-native formulation: neighbor aggregation is a masked batched
segment-sum over padded edge arrays instead of torch_scatter.

Constructor note: the second positional argument is named ``channels``
(flax modules are frozen dataclasses, so the effective output width is the
``out_channels`` *property*, which accounts for ``cat``/``lin`` exactly like
the reference's reassigned ``out_channels`` attribute).
"""

from typing import Any, Optional

import jax.numpy as jnp
from flax import linen as nn

from dgmc_tpu.models.mlp import MLP
from dgmc_tpu.models.precision import compute_dtype_of
from dgmc_tpu.ops.graph import gather_nodes, scatter_to_nodes


class GINConv(nn.Module):
    """``h_i' = MLP((1+eps) * h_i + sum_{j -> i} h_j)``, learnable eps."""
    mlp: nn.Module

    @nn.compact
    def __call__(self, x, graph, train=False):
        eps = self.param('eps', nn.initializers.zeros, ())
        msgs = gather_nodes(x, graph.senders)
        agg = scatter_to_nodes(msgs, graph.receivers, graph.edge_mask,
                               x.shape[1], aggr='sum')
        out = (1.0 + eps) * x + agg
        return self.mlp(out, graph.node_mask, train=train)


class GIN(nn.Module):
    in_channels: int
    channels: int
    num_layers: int
    batch_norm: bool = False
    cat: bool = True
    lin: bool = True
    # Mixed-precision compute dtype (or a precision.Precision policy)
    # for the per-layer MLPs and final Dense; parameters stay float32.
    # None = float32.
    dtype: Optional[Any] = None

    @property
    def out_channels(self):
        if self.lin:
            return self.channels
        if self.cat:
            return self.in_channels + self.num_layers * self.channels
        return self.channels

    @nn.compact
    def __call__(self, x, graph, train=False):
        import jax

        dtype = compute_dtype_of(self.dtype)
        xs = [x]
        in_ch = self.in_channels
        for i in range(self.num_layers):
            mlp = MLP(in_ch, self.channels, 2, self.batch_norm, dropout=0.0,
                      dtype=dtype, name=f'mlp_{i}')
            # Named layer scopes for profiler-trace attribution.
            with jax.named_scope(f'gin_conv_{i}'):
                xs.append(GINConv(mlp, name=f'conv_{i}')(xs[-1], graph,
                                                         train=train))
            in_ch = self.channels
        out = jnp.concatenate(xs, axis=-1) if self.cat else xs[-1]
        if self.lin:
            out = nn.Dense(self.channels, name='final',
                           dtype=dtype)(out)
        return out

    def __repr__(self):
        return (f'{type(self).__name__}({self.in_channels}, '
                f'{self.out_channels}, num_layers={self.num_layers}, '
                f'batch_norm={self.batch_norm}, cat={self.cat}, '
                f'lin={self.lin})')
