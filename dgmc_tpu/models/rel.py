"""Directed-relation convolution backbone (used for DBP15K KGs).

Capability parity with the reference ``RelConv``/``RelCNN`` (reference
``dgmc/models/rel.py``): per layer,
``root(x) + mean_{j->i} lin1(x_j) + mean_{i->j} lin2(x_j)`` — i.e. separate
linear maps for the incoming and outgoing neighborhoods, realized there by
flow-flipping a PyG ``MessagePassing`` (reference ``rel.py:25-31``). Here
the two directions are two masked mean segment-reductions with swapped
sender/receiver roles. Stacked with ReLU / optional BatchNorm / dropout and
jumping-knowledge concat, like the reference ``rel.py:80-92``.

Constructor note: second positional arg is ``channels``; the effective
output width is the ``out_channels`` property (see ``gin.py`` note).
"""

import jax.numpy as jnp
from flax import linen as nn

from dgmc_tpu.models.norm import MaskedBatchNorm
from dgmc_tpu.ops.graph import gather_nodes, scatter_to_nodes


class RelConv(nn.Module):
    out_features: int

    @nn.compact
    def __call__(self, x, graph, train=False):
        h1 = nn.Dense(self.out_features, use_bias=False, name='lin1')(x)
        h2 = nn.Dense(self.out_features, use_bias=False, name='lin2')(x)
        if graph.blocks_in is not None:
            # Scatter-free MXU path: blocked one-hot contractions with a
            # matmul (never scatter-add) backward via the transposed
            # blocking (dgmc_tpu/ops/blocked.py). At DBP15K scale the
            # gather/scatter form below spends ~1.2 ms per scatter-add on
            # TPU; this path replaces all of them.
            from dgmc_tpu.ops.blocked import adj_matmul
            a_in = (adj_matmul(h1, graph.blocks_in, graph.blocks_out)
                    * graph.blocks_in.inv_degree)
            a_out = (adj_matmul(h2, graph.blocks_out, graph.blocks_in)
                     * graph.blocks_out.inv_degree)
        else:
            # Incoming: messages flow sender -> receiver.
            m_in = gather_nodes(h1, graph.senders)
            a_in = scatter_to_nodes(m_in, graph.receivers, graph.edge_mask,
                                    x.shape[1], aggr='mean')
            # Outgoing: same edges walked backwards.
            m_out = gather_nodes(h2, graph.receivers)
            a_out = scatter_to_nodes(m_out, graph.senders, graph.edge_mask,
                                     x.shape[1], aggr='mean')
        return nn.Dense(self.out_features, name='root')(x) + a_in + a_out


class RelCNN(nn.Module):
    in_channels: int
    channels: int
    num_layers: int
    batch_norm: bool = False
    cat: bool = True
    lin: bool = True
    dropout: float = 0.0

    @property
    def out_channels(self):
        if self.lin:
            return self.channels
        if self.cat:
            return self.in_channels + self.num_layers * self.channels
        return self.channels

    @nn.compact
    def __call__(self, x, graph, train=False):
        xs = [x]
        for i in range(self.num_layers):
            h = RelConv(self.channels, name=f'conv_{i}')(xs[-1], graph,
                                                         train=train)
            h = nn.relu(h)
            if self.batch_norm:
                h = MaskedBatchNorm(name=f'bn_{i}')(
                    h, graph.node_mask, use_running_average=not train)
            h = nn.Dropout(self.dropout, deterministic=not train)(h)
            xs.append(h)
        out = jnp.concatenate(xs, axis=-1) if self.cat else xs[-1]
        if self.lin:
            out = nn.Dense(self.channels, name='final')(out)
        return out

    def __repr__(self):
        return (f'{type(self).__name__}({self.in_channels}, '
                f'{self.out_channels}, num_layers={self.num_layers}, '
                f'batch_norm={self.batch_norm}, cat={self.cat}, '
                f'lin={self.lin}, dropout={self.dropout})')
