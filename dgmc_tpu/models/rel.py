"""Directed-relation convolution backbone (used for DBP15K KGs).

Capability parity with the reference ``RelConv``/``RelCNN`` (reference
``dgmc/models/rel.py``): per layer,
``root(x) + mean_{j->i} lin1(x_j) + mean_{i->j} lin2(x_j)`` — i.e. separate
linear maps for the incoming and outgoing neighborhoods, realized there by
flow-flipping a PyG ``MessagePassing`` (reference ``rel.py:25-31``). Here
the two directions are two masked mean segment-reductions with swapped
sender/receiver roles. Stacked with ReLU / optional BatchNorm / dropout and
jumping-knowledge concat, like the reference ``rel.py:80-92``.

Constructor note: second positional arg is ``channels``; the effective
output width is the ``out_channels`` property (see ``gin.py`` note).
"""

from typing import Any, Optional

import jax.numpy as jnp
from flax import linen as nn

from dgmc_tpu.models.norm import MaskedBatchNorm
from dgmc_tpu.models.precision import compute_dtype_of
from dgmc_tpu.ops.graph import gather_nodes, scatter_to_nodes


class RelConv(nn.Module):
    out_features: int
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, graph, train=False, streams=1):
        """``streams > 1`` evaluates the SAME convolution on ``streams``
        independent channel groups laid out channel-wise
        (``x: [B, N, streams * C]``). The per-group math is identical to
        ``streams`` separate calls (flax ``Dense`` maps the trailing axis;
        aggregation is channel-independent), but the node tables the edge
        gathers read become ``streams``× wider — at DBP15K scale the
        128-byte per-row gathers run at only ~10 GB/s (latency-bound), so
        packing the consensus iterations into channels is ~streams× fewer
        random rows for the same bytes. Used by DGMC's source-side
        iteration batching (``models/dgmc.py prefetch_source``).
        """
        B, N = x.shape[0], x.shape[1]
        dtype = compute_dtype_of(self.dtype)

        def grouped(dense, v):
            if streams == 1:
                return dense(v)
            g = dense(v.reshape(B, N, streams, -1))
            return g.reshape(B, N, -1)

        h1 = grouped(nn.Dense(self.out_features, use_bias=False,
                              name='lin1', dtype=dtype), x)
        h2 = grouped(nn.Dense(self.out_features, use_bias=False,
                              name='lin2', dtype=dtype), x)
        if graph.blocks_in is not None:
            # Scatter-free MXU path: blocked one-hot contractions with a
            # matmul (never scatter-add) backward via the transposed
            # blocking (dgmc_tpu/ops/blocked.py). At DBP15K scale the
            # gather/scatter form below spends ~1.2 ms per scatter-add on
            # TPU; this path replaces all of them.
            from dgmc_tpu.ops.blocked import adj_matmul
            a_in = (adj_matmul(h1, graph.blocks_in, graph.blocks_out)
                    * graph.blocks_in.inv_degree)
            a_out = (adj_matmul(h2, graph.blocks_out, graph.blocks_in)
                     * graph.blocks_out.inv_degree)
        else:
            # Incoming: messages flow sender -> receiver.
            m_in = gather_nodes(h1, graph.senders)
            a_in = scatter_to_nodes(m_in, graph.receivers, graph.edge_mask,
                                    x.shape[1], aggr='mean')
            # Outgoing: same edges walked backwards.
            m_out = gather_nodes(h2, graph.receivers)
            a_out = scatter_to_nodes(m_out, graph.senders, graph.edge_mask,
                                     x.shape[1], aggr='mean')
        root = grouped(nn.Dense(self.out_features, name='root',
                                dtype=dtype), x)
        return root + (a_in + a_out).astype(root.dtype)


class RelCNN(nn.Module):
    # Capability flag consumed by DGMC.prefetch_source: this backbone can
    # evaluate `streams` channel-packed inputs in one pass (see __call__).
    supports_streams = True

    in_channels: int
    channels: int
    num_layers: int
    batch_norm: bool = False
    cat: bool = True
    lin: bool = True
    dropout: float = 0.0
    # Mixed-precision compute dtype (or a precision.Precision policy)
    # for every Dense / aggregation matmul; parameters and BN statistics
    # stay float32. None = float32.
    dtype: Optional[Any] = None

    @property
    def out_channels(self):
        if self.lin:
            return self.channels
        if self.cat:
            return self.in_channels + self.num_layers * self.channels
        return self.channels

    @nn.compact
    def __call__(self, x, graph, train=False, streams=1):
        """``streams > 1``: evaluate ``streams`` channel-packed inputs in
        one pass with shared parameters (see :class:`RelConv`). Requires
        ``batch_norm=False`` and inactive dropout — both would couple the
        groups."""
        if streams > 1 and self.batch_norm:
            raise ValueError('streams>1 is invalid with batch_norm=True: '
                             'batch statistics would couple the streams')
        if streams > 1 and train and self.dropout > 0:
            raise ValueError(
                'streams>1 is invalid with active dropout: a packed '
                'evaluation draws ONE mask across the channel groups, '
                'coupling what should be independent iterations '
                '(DGMC.prefetch_source skips packing in this case)')
        import jax

        B, N = x.shape[0], x.shape[1]
        dtype = compute_dtype_of(self.dtype)
        xs = [x]
        for i in range(self.num_layers):
            # Named layer scopes for profiler-trace attribution.
            with jax.named_scope(f'rel_conv_{i}'):
                h = RelConv(self.channels, dtype=dtype,
                            name=f'conv_{i}')(xs[-1], graph, train=train,
                                              streams=streams)
            h = nn.relu(h)
            if self.batch_norm:
                h = MaskedBatchNorm(name=f'bn_{i}')(
                    h, graph.node_mask, use_running_average=not train)
            h = nn.Dropout(self.dropout, deterministic=not train)(h)
            xs.append(h)
        if streams == 1:
            out = jnp.concatenate(xs, axis=-1) if self.cat else xs[-1]
            if self.lin:
                out = nn.Dense(self.channels, name='final',
                               dtype=dtype)(out)
            return out
        # Grouped jumping-knowledge concat + final Dense: per group.
        if self.cat:
            parts = [v.reshape(B, N, streams, -1) for v in xs]
            out = jnp.concatenate(parts, axis=-1)
        else:
            out = xs[-1].reshape(B, N, streams, -1)
        if self.lin:
            out = nn.Dense(self.channels, name='final',
                           dtype=dtype)(out)
        return out.reshape(B, N, -1)

    def __repr__(self):
        return (f'{type(self).__name__}({self.in_channels}, '
                f'{self.out_channels}, num_layers={self.num_layers}, '
                f'batch_norm={self.batch_norm}, cat={self.cat}, '
                f'lin={self.lin}, dropout={self.dropout})')
