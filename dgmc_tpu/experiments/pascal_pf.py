"""PascalPF geometric matching: train on synthetic pairs, test zero-shot.

Capability parity with reference ``examples/pascal_pf.py``: SplineCNN ψ₁/ψ₂
over KNN(8) graphs with Cartesian pseudo-coordinates, trained purely on
random point-cloud pairs (30-60 inliers, 0-20 outliers, σ=0.05 jitter) and
evaluated zero-shot on real PascalPF pairs per category. The flag surface
covers the reference parser (``pascal_pf.py:12-20``) plus the framework's
observability extras (``--profile``, ``--metrics_log``).

Run: ``python examples/pascal_pf.py [--data_root ../data/PascalPF]``
(the real-data eval is skipped with a notice when the dataset is absent —
this environment does not download datasets).
"""

import argparse
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from dgmc_tpu.data import (Cartesian, Compose, Constant, KNNGraph,
                           RandomGraphPairs)
from dgmc_tpu.models import DGMC, SplineCNN, metrics
from dgmc_tpu.models.evalsum import eval_summary
from dgmc_tpu.obs import (RunObserver, add_obs_flag, add_profile_flag,
                          start_profile)
from dgmc_tpu.utils import PairLoader, pad_pair_batch
from dgmc_tpu.utils.data import GraphPair
from dgmc_tpu.train import (MetricLogger, create_train_state,
                            make_train_step, trace)


def parse_args(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument('--dim', type=int, default=256)
    parser.add_argument('--rnd_dim', type=int, default=64)
    parser.add_argument('--num_layers', type=int, default=2)
    parser.add_argument('--num_steps', type=int, default=10)
    parser.add_argument('--lr', type=float, default=0.001)
    parser.add_argument('--batch_size', type=int, default=64)
    parser.add_argument('--epochs', type=int, default=32)
    parser.add_argument('--data_root', type=str,
                        default=os.path.join('..', 'data', 'PascalPF'))
    parser.add_argument('--synthetic_eval', type=int, default=0,
                        help='ALSO evaluate on this many HELD-OUT synthetic '
                             'pairs per epoch (a disjoint generator stream) '
                             '— the offline stand-in for the real PascalPF '
                             'zero-shot eval when the dataset is absent')
    parser.add_argument('--seed', type=int, default=0)
    parser.add_argument('--profile', type=str, default=None,
                        help='emit a jax.profiler trace of one training '
                             'epoch into this directory')
    parser.add_argument('--metrics_log', type=str, default=None,
                        help='append per-epoch metrics to this JSONL file')
    from dgmc_tpu.models.precision import add_precision_args
    add_precision_args(parser)
    from dgmc_tpu.resilience import add_supervisor_args
    add_supervisor_args(parser)
    add_obs_flag(parser)
    add_profile_flag(parser)
    return parser.parse_args(argv)


def build(args):
    transform = Compose([Constant(), KNNGraph(k=8), Cartesian()])
    train_dataset = RandomGraphPairs(30, 60, 0, 20, transform=transform,
                                     seed=args.seed)
    train_loader = PairLoader(train_dataset, args.batch_size, shuffle=True,
                              seed=args.seed, num_nodes=80, num_edges=640)

    from dgmc_tpu.models.precision import from_args
    prec = from_args(args)  # bf16 compute / f32 accum unless --f32
    psi_1 = SplineCNN(1, args.dim, 2, args.num_layers, cat=False,
                      dropout=0.0, dtype=prec)
    psi_2 = SplineCNN(args.rnd_dim, args.rnd_dim, 2, args.num_layers,
                      cat=True, dropout=0.0, dtype=prec)
    model = DGMC(psi_1, psi_2, num_steps=args.num_steps, dtype=prec)
    return model, train_loader, transform


def main(argv=None):
    args = parse_args(argv)
    if args.supervise:
        # Crash/hang recovery loop (resilience/supervisor.py). This CLI
        # has no --ckpt_dir, so a restart re-runs from scratch (the
        # supervisor warns about it).
        from dgmc_tpu.resilience.supervisor import supervise_cli
        raise SystemExit(supervise_cli(
            'dgmc_tpu.experiments.pascal_pf', args, argv,
            ladder=('disable-fused', 'f32')))
    model, train_loader, transform = build(args)

    batch0 = next(iter(train_loader))
    state = create_train_state(model, jax.random.key(args.seed), batch0,
                               learning_rate=args.lr)
    # Reference trains on loss(S_0) + loss(S_L) when refining
    # (pascal_pf.py:102-103).
    step = make_train_step(model, loss_on_s0=True)
    eval_fn = jax.jit(lambda s, b, k: model.apply(
        {'params': s.params}, b.s, b.t, train=False, rngs={'noise': k}))

    try:
        from dgmc_tpu.datasets import PascalPF
        from dgmc_tpu.datasets.pascal_pf import CATEGORIES
        test_datasets = [PascalPF(args.data_root, c, transform)
                         for c in CATEGORIES]
    except FileNotFoundError as e:
        print(f'[pascal_pf] real-data eval disabled: {e}')
        test_datasets = []

    syn_eval_loader = None
    if args.synthetic_eval:
        # Held-out stream: same distribution as training, disjoint seed —
        # RandomGraphPairs resamples per epoch keyed on (seed, epoch), so
        # a far-offset seed never collides with any training epoch.
        from dgmc_tpu.train import make_eval_step
        eval_ds = RandomGraphPairs(30, 60, 0, 20, transform=transform,
                                   length=args.synthetic_eval,
                                   seed=args.seed + 10_000)
        syn_eval_loader = PairLoader(eval_ds, args.batch_size,
                                     shuffle=False, num_nodes=80,
                                     num_edges=640)
        syn_eval_step = make_eval_step(model)

    logger = MetricLogger(args.metrics_log)
    from dgmc_tpu.parallel import host_obs_dir
    obs = RunObserver(host_obs_dir(args.obs_dir), probes=args.probes,
                      watchdog_deadline_s=args.watchdog_deadline,
                      fence_deadline_s=args.fence_deadline,
                      obs_port=args.obs_port)
    # SLO/anomaly planes (obs/slo.py, obs/anomaly.py): judge the run
    # against --slo if given, watch step latency for silent drift.
    obs.attach_anomaly()
    obs.attach_slo(getattr(args, 'slo', None))
    # One extra trace, no extra XLA compile: the per-stage FLOPs/bytes +
    # MFU account in <obs-dir>/efficiency.json (obs/cost.py).
    obs.record_cost('train_step', step, state, batch0,
                    jax.random.key(args.seed + 2))
    prof = obs.attach_profiler(
        start_profile(args.profile_dir, steps=args.profile_steps))
    profile_epoch = min(2, args.epochs)
    key = jax.random.key(args.seed + 1)
    for epoch in range(1, args.epochs + 1):
        train_loader.dataset.set_epoch(epoch)
        t0 = time.time()
        # Accumulate device-side; a single batched fetch per epoch (every
        # scalar fetch is a full round trip on tunneled devices).
        tot_loss = jnp.zeros(())
        tot_correct = jnp.zeros(())
        tot_n = 0.0
        with trace(args.profile if epoch == profile_epoch else None), \
                obs.compile_label(f'epoch{epoch}'):
            for batch in train_loader:
                key, sub = jax.random.split(key)
                with obs.step():
                    state, out = step(state, batch, sub)
                tot_loss = tot_loss + out['loss']
                n_b = float(batch.y_mask.sum())
                tot_correct = tot_correct + out['acc'] * n_b
                tot_n += n_b
            if args.profile and epoch == profile_epoch:
                float(tot_loss)  # keep the trace open until execution ends
        # Per-device completion probe at the epoch boundary (a host
        # fetch happens right below anyway): feeds the straggler/skew
        # series obs.aggregate reports.
        obs.fence_devices(tot_loss)
        host = jax.device_get({'l': tot_loss, 'c': tot_correct})
        loss = float(host['l']) / len(train_loader)
        acc = eval_summary(tot_n, hits1=host['c'])['hits1']
        print(f'Epoch: {epoch:02d}, Loss: {loss:.4f},'
              f' Acc: {acc:.2f},'
              f' {time.time() - t0:.1f}s')
        logger.log(epoch, loss=loss, train_acc=acc)
        obs.log(epoch, loss=loss, train_acc=acc,
                epoch_s=round(time.time() - t0, 3))
        # Train-side account first: when an eval split follows below it
        # overwrites the run headline, so the headline is always the
        # most meaningful split this configuration ran.
        obs.quality_eval('pascal_pf_train', step=epoch, loss=loss,
                         hits1=acc)
        obs.snapshot_memory(f'epoch{epoch}')

        if syn_eval_loader is not None:
            # Dedicated RNG stream: drawing from the training key chain
            # here would make enabling the flag change the training
            # trajectory itself. Count accumulates from the HOST-side
            # masks (the device fetch per batch would be a ~120 ms round
            # trip each on the tunneled TPU); one fetch at the end.
            ekey = jax.random.fold_in(jax.random.key(args.seed + 20_000),
                                      epoch)
            correct = jnp.zeros(())
            n = 0.0
            for b in syn_eval_loader:
                ekey, sub = jax.random.split(ekey)
                out = syn_eval_step(state, b, sub)
                correct = correct + out['correct']
                n += float(np.asarray(b.y_mask).sum())
            eval_acc = eval_summary(n, hits1=correct)['hits1']
            print(f'Held-out synthetic: {100 * eval_acc:.2f}')
            # Logged as a 0-1 fraction, the same unit as train_acc in
            # this JSONL (the percentage is print-only, mirroring the
            # reference's printed tables).
            logger.log(epoch, synthetic_eval_acc=eval_acc)
            obs.log(epoch, synthetic_eval_acc=eval_acc)
            obs.quality_eval('pascal_pf', step=epoch, loss=loss,
                             hits1=eval_acc)

        if test_datasets:
            accs = []
            for ds in test_datasets:
                correct = n = 0.0
                # One static shape per category: pad every pair to the
                # category max so eval compiles once per category.
                n_pad = max(g.pos.shape[0] for g in ds.items.values())
                e_pad = 8 * n_pad
                for i, (g_s, g_t, y) in enumerate(ds.pair_graphs()):
                    pair = GraphPair(s=g_s, t=g_t, y_col=y)
                    b = pad_pair_batch([pair], n_pad, e_pad)
                    key, sub = jax.random.split(key)
                    _, S_L = eval_fn(state, b, sub)
                    correct = correct + metrics.acc(S_L, b.y, b.y_mask,
                                                    reduction='sum')
                    n += float(b.y_mask.sum())
                accs.append(100 * eval_summary(n, hits1=correct)['hits1'])
            accs.append(sum(accs) / len(accs))
            print(' '.join(c[:5].ljust(5) for c in CATEGORIES) + ' mean')
            print(' '.join(f'{a:.1f}'.ljust(5) for a in accs))
            logger.log(epoch, mean_acc=accs[-1])
            obs.quality_eval('pascal_pf', step=epoch, loss=loss,
                             hits1=accs[-1] / 100)
    prof.close()
    logger.close()
    obs.close()
    return state


if __name__ == '__main__':
    main()
