"""DBP15K cross-lingual entity alignment.

Capability parity with reference ``examples/dbp15k.py``: RelCNN ψ₁/ψ₂,
sparse top-k=10 correspondences with ground-truth injection, two-phase
schedule — 100 epochs of feature matching only (``num_steps=0``) then 100
epochs of consensus refinement with ψ₁ detached — expressed here as explicit
per-phase train steps instead of module-attribute mutation (reference
``dbp15k.py:63-69``). Metrics: Hits@1 and Hits@10 on the test alignments.

Optionally shards the correspondence activations over all available chips
(``--model_shards N``) — the scale-out axis the reference lacks.

Training defaults to the bf16-compute / f32-accumulation precision
policy (``--f32`` opts out; ``dgmc_tpu/models/precision.py``), and
``--pairs-per-step N`` batches N replicas of the pair per step, each
drawing independent per-pair indicator noise / negative samples — the
MXU sees a real batch axis instead of B=1 and one step averages N
independent gradient samples. The per-pair RNG streams (noise,
negatives) are fold_in-exact against independent B=1 steps
(``tests/models/test_pairs_per_step.py``); ψ₁'s dropout masks are the
one batch-drawn coupler, so this CLI's batched losses are equivalent in
distribution, not bitwise (phase 2 trains with ψ₁ detached but its
dropout still active, as the reference does).

Run: ``python examples/dbp15k.py --category zh_en``
(optionally ``--data_root ../data/DBP15K``)
"""

import argparse
import os
import time

import jax
import numpy as np

from dgmc_tpu.models import DGMC, RelCNN
from dgmc_tpu.models.evalsum import eval_summary
from dgmc_tpu.obs import (RunObserver, add_obs_flag, add_profile_flag,
                          start_profile)
from dgmc_tpu.train import (MetricLogger, create_train_state, make_eval_step,
                            make_train_step, resume_or_init, trace)
from dgmc_tpu.utils.data import GraphPair, pad_pair_batch


def parse_args(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument('--category', type=str, default=None,
                        choices=['zh_en', 'ja_en', 'fr_en'])
    # Protocol-faithful synthetic KG alignment at arbitrary scale: the
    # offline stand-in for the real raw release (which needs egress).
    # Same construction as the miniature quality gate
    # (tests/models/test_two_phase_quality.py), full DBP15K shapes by
    # default; the rest of the schedule/metrics/checkpoint machinery is
    # shared with the real-data path.
    parser.add_argument('--synthetic', action='store_true')
    parser.add_argument('--syn_nodes_s', type=int, default=15000)
    parser.add_argument('--syn_nodes_t', type=int, default=20000)
    parser.add_argument('--syn_edges_s', type=int, default=100000)
    parser.add_argument('--syn_edges_t', type=int, default=120000)
    parser.add_argument('--syn_dim', type=int, default=300)
    parser.add_argument('--syn_noise', type=float, default=2.5,
                        help='max feature-noise sigma on aligned entities')
    parser.add_argument('--syn_noise_min', type=float, default=0.5,
                        help='min feature-noise sigma; each aligned entity '
                             'draws its own sigma uniformly in '
                             '[min, max] — homogeneous noise has a sharp '
                             'all-or-nothing learnability transition at '
                             'C=300 (measured: sigma 1.5 saturates, 1.8 '
                             'never lifts off), while per-entity '
                             'heterogeneity yields the mid-range phase-1 '
                             'accuracy of the real embeddings')
    parser.add_argument('--syn_rewire', type=float, default=0.15,
                        help='fraction of source edges rewired on the '
                             'target side')
    parser.add_argument('--syn_seed_frac', type=float, default=0.3,
                        help='seed-alignment fraction (the reference '
                             'protocol trains on 30%%)')
    from dgmc_tpu.models.precision import add_precision_args
    add_precision_args(parser)
    parser.add_argument('--pairs-per-step', '--pairs_per_step',
                        dest='pairs_per_step', type=int, default=1,
                        metavar='N',
                        help='batch N replicas of the training pair per '
                             'step, each drawing independent per-pair '
                             'indicator noise and negative samples '
                             '(fold_in per batch element) — one step '
                             'averages N independent gradient samples '
                             'while the MXU sees a real batch axis '
                             'instead of B=1')
    parser.add_argument('--dim', type=int, default=256)
    parser.add_argument('--rnd_dim', type=int, default=32)
    parser.add_argument('--num_layers', type=int, default=3)
    parser.add_argument('--num_steps', type=int, default=10)
    parser.add_argument('--k', type=int, default=10)
    parser.add_argument('--lr', type=float, default=0.001)
    parser.add_argument('--epochs', type=int, default=200)
    parser.add_argument('--phase1_epochs', type=int, default=100)
    parser.add_argument('--model_shards', type=int, default=0,
                        help='shard correspondence rows over N devices '
                             '(0 = no sharding)')
    parser.add_argument('--row_shards', type=int, default=0,
                        help='million-entity layout (parallel/rules.py '
                             'streamed_rules): row-shard the '
                             'correspondence matrix, shortlist and ψ₂ '
                             'source intermediates over N devices on '
                             'the data axis, with the candidate search '
                             'streamed over source chunks; the whole '
                             'sharding config is the declarative '
                             'partition-rule object, not per-callsite '
                             'in_shardings. Mutually exclusive with '
                             '--model_shards')
    parser.add_argument('--aot_compile', action='store_true',
                        help='AOT-compile the executed phase/eval steps '
                             '(lower+compile up front, replacing the '
                             'lazy jit) and record each executable\'s '
                             'static per-device memory bound '
                             '(memory_analysis: argument+output+temp '
                             'bytes, post-GSPMD so PER DEVICE) into the '
                             'obs metrics — the peak-HBM evidence for '
                             'sharded scale runs, usable even where the '
                             'live allocator publishes nothing (CPU, '
                             'tunneled platforms)')
    parser.add_argument('--stream_chunk', type=int, default=0,
                        help='stream the sparse candidate search over '
                             'source-node chunks of this many rows, so '
                             'the N_s x N_t sweep never exists beyond '
                             'one [chunk, topk_block] tile (0 = off; '
                             'defaults to 8192 under --row_shards)')
    parser.add_argument('--blocked_adjacency', dest='blocked_adjacency',
                        choices=['auto', 'on', 'off'], default='auto',
                        help='scatter-free MXU aggregation tables '
                             '(ops/blocked.py): a measured single-chip '
                             'TPU win at DBP15K scale (sparse step 476 '
                             '-> ~371 ms), but the padded gather tables '
                             'scale O(E) and are REPLICATED per device '
                             '— at 10^6 nodes they dominate the '
                             'per-device memory budget (r7: psi_1 '
                             'forward temps 449 vs 52 MiB at 2^17 '
                             'nodes). "auto" = on, except under the '
                             'row-sharded/streamed layout '
                             '(--row_shards/--stream_chunk)')
    parser.add_argument('--offload-corpus', '--offload_corpus',
                        dest='offload_corpus', action='store_true',
                        help='host-RAM offload tier (ops/offload.py): '
                             'after training, rebuild the test-pair '
                             'shortlist with the source ψ₁ embedding '
                             'table resident in HOST memory, streamed '
                             'chunk-by-chunk through the N-deep device '
                             'prefetch ring, and assert bit-exact '
                             'equality against the device-resident '
                             'streamed search (logged as '
                             'offload_equal; the serving-corpus '
                             'mechanism at experiment scale)')
    parser.add_argument('--prefetch-depth', '--prefetch_depth',
                        dest='prefetch_depth', type=int, default=0,
                        metavar='N',
                        help='prefetch ring depth for --offload-corpus '
                             '(0 = the measured library default, '
                             'ops/offload.DEFAULT_PREFETCH_DEPTH; see '
                             'benchmarks/DISPATCH_DEFAULTS.md)')
    parser.add_argument('--topk_block', type=int, default=0,
                        help='candidate-search target-axis tile '
                             '(0 = the one measured library default, '
                             'parallel/rules.DEFAULT_TOPK_BLOCK; the '
                             'Pallas kernel ignores it — this tunes the '
                             'scan/streamed paths only)')
    parser.add_argument('--data_root', type=str,
                        default=os.path.join('..', 'data', 'DBP15K'))
    parser.add_argument('--seed', type=int, default=0)
    parser.add_argument('--ckpt_dir', type=str, default=None,
                        help='periodic checkpoint + auto-resume directory '
                             '(resumes mid-schedule at the saved epoch)')
    parser.add_argument('--ckpt_every', type=int, default=10)
    parser.add_argument('--profile', type=str, default=None,
                        help='emit a jax.profiler trace of one training '
                             'step into this directory')
    parser.add_argument('--metrics_log', type=str, default=None,
                        help='append per-epoch metrics to this JSONL file')
    parser.add_argument('--coordinator', type=str, default=None,
                        help='multi-host: coordinator address host:port '
                             '(auto-detected on TPU pods / SLURM)')
    parser.add_argument('--num_processes', type=int, default=None)
    parser.add_argument('--process_id', type=int, default=None)
    parser.add_argument('--guard-bad-steps', '--guard_bad_steps',
                        dest='guard_bad_steps', type=int, default=0,
                        metavar='M',
                        help='in-graph non-finite guardrail: a step with '
                             'a non-finite loss/grad keeps the old '
                             'params (skip counted); M consecutive bad '
                             'steps roll back to the last good snapshot '
                             'with a fresh optimizer (0 = off). See '
                             'dgmc_tpu/resilience/guard.py')
    from dgmc_tpu.resilience import add_fault_args, add_supervisor_args
    add_supervisor_args(parser)
    add_fault_args(parser)
    add_obs_flag(parser)
    add_profile_flag(parser)
    return parser.parse_args(argv)


def use_blocked_adjacency(args):
    """Resolve the ``--blocked_adjacency`` policy: the blocked tables are
    a single-chip TPU throughput win but an O(E) replicated memory cost,
    so 'auto' drops them exactly where memory is the budget — the
    row-sharded / streamed million-entity layout."""
    if args.blocked_adjacency == 'on':
        return True
    if args.blocked_adjacency == 'off':
        return False
    return not (args.row_shards > 1 or args.stream_chunk)


def synthetic_batches(args, shapes=None):
    """DBP15K-scale synthetic KG alignment (``--synthetic``).

    The pair construction itself lives in
    :func:`dgmc_tpu.data.synthetic.synthetic_kg_alignment` (shared with
    the streamed-S scale benchmark); this wrapper applies the CLI's
    precision policy, blocked-adjacency attachment and pairs-per-step
    collation. ``shapes`` overrides ``(n_s, n_t, e_s, e_t)`` — used for
    the tiny init stand-in of a giant pair.
    """
    from dgmc_tpu.data.synthetic import synthetic_kg_alignment
    from dgmc_tpu.ops.blocked import attach_blocks
    from dgmc_tpu.ops.graph import GraphBatch
    from dgmc_tpu.utils.data import PairBatch

    rng = np.random.RandomState(args.seed)
    n_s, n_t, e_s, e_t = shapes or (args.syn_nodes_s, args.syn_nodes_t,
                                    args.syn_edges_s, args.syn_edges_t)
    c = args.syn_dim
    kg = synthetic_kg_alignment(n_s, n_t, e_s, e_t, c,
                                noise_min=args.syn_noise_min,
                                noise_max=args.syn_noise,
                                rewire=args.syn_rewire,
                                seed_frac=args.syn_seed_frac, rng=rng)

    from dgmc_tpu.models.precision import from_args
    from dgmc_tpu.ops.blocked import repeat_graph
    prec = from_args(args)
    blocked = use_blocked_adjacency(args)

    def side(x, s, r, n):
        g = GraphBatch(x=x[None], senders=s[None].astype(np.int32),
                       receivers=r[None].astype(np.int32),
                       node_mask=np.ones((1, n), bool),
                       edge_mask=np.ones((1, s.shape[0]), bool),
                       edge_attr=None)
        return attach_blocks(g, gather_dtype=prec) if blocked else g

    # Train batch at B = pairs_per_step (replicas of the one pair, each
    # drawing its own per-pair indicator noise / negatives on device;
    # blocked ONCE at B=1, replicas tiled); eval keeps B=1 — replicated
    # metrics would just repeat themselves.
    reps = max(1, args.pairs_per_step)
    e_s1 = side(kg.x_s, kg.senders_s, kg.receivers_s, n_s)
    e_t1 = side(kg.x_t, kg.senders_t, kg.receivers_t, n_t)
    g_s, g_t = repeat_graph(e_s1, reps), repeat_graph(e_t1, reps)
    y_train = np.repeat(
        np.where(kg.train_mask, kg.perm, -1).astype(np.int32)[None],
        reps, 0)
    y_test = np.where(~kg.train_mask, kg.perm, -1).astype(np.int32)[None]
    return (PairBatch(s=g_s, t=g_t, y=y_train, y_mask=y_train >= 0),
            PairBatch(s=e_s1, t=e_t1, y=y_test, y_mask=y_test >= 0),
            c)


def load_batches(args):
    """One full-graph pair batch (B=1) with train GT, plus the test GT."""
    if args.synthetic:
        return synthetic_batches(args)
    if args.category is None:
        raise SystemExit('--category is required unless --synthetic')
    from dgmc_tpu.datasets import DBP15K
    data = DBP15K(args.data_root, args.category)
    g1, g2 = data.graphs(sum_embedding=True)

    n1, n2 = g1.num_nodes, g2.num_nodes
    y_train = np.full(n1, -1, np.int64)
    y_train[data.train_y[0]] = data.train_y[1]
    y_test = np.full(n1, -1, np.int64)
    y_test[data.test_y[0]] = data.test_y[1]

    from dgmc_tpu.models.precision import from_args
    from dgmc_tpu.ops.blocked import attach_blocks, repeat_graph
    from dgmc_tpu.utils.data import PairBatch

    prec = from_args(args)

    def batch(y_col):
        return pad_pair_batch([GraphPair(s=g1, t=g2, y_col=y_col)],
                              num_nodes_s=n1, num_edges_s=g1.num_edges,
                              num_nodes_t=n2, num_edges_t=g2.num_edges)

    reps = max(1, args.pairs_per_step)
    train_b, test_b = batch(y_train), batch(y_test)
    # Scatter-free MXU aggregation (ops/blocked.py) cuts the training step
    # ~22% at this scale (bench.py sparse leg). The graph sides are
    # identical in both batches — block them ONCE at B=1 and share; the
    # pairs-per-step train batch tiles the blocked sides (repeat_graph)
    # instead of re-running the host-side blocking per replica. Eval
    # stays B=1. Policy gate: see use_blocked_adjacency.
    if use_blocked_adjacency(args):
        e_s = attach_blocks(train_b.s, gather_dtype=prec)
        e_t = attach_blocks(train_b.t, gather_dtype=prec)
    else:
        e_s, e_t = train_b.s, train_b.t
    s_b, t_b = repeat_graph(e_s, reps), repeat_graph(e_t, reps)
    y_tr = np.repeat(train_b.y, reps, axis=0)
    m_tr = np.repeat(train_b.y_mask, reps, axis=0)
    return (PairBatch(s=s_b, t=t_b, y=y_tr, y_mask=m_tr),
            PairBatch(s=e_s, t=e_t, y=test_b.y, y_mask=test_b.y_mask),
            g1.x.shape[1])


def main(argv=None):
    args = parse_args(argv)
    if args.supervise:
        # Detection -> recovery loop (resilience/supervisor.py): this
        # process becomes the jax-free monitor; the actual run executes
        # in child processes that auto-resume via --ckpt_dir.
        from dgmc_tpu.resilience.supervisor import supervise_cli
        raise SystemExit(supervise_cli(
            'dgmc_tpu.experiments.dbp15k', args, argv))
    from dgmc_tpu.resilience import FaultPlan, HostChannel, RollbackGuard
    from dgmc_tpu.resilience.distributed_guard import control_dir
    from dgmc_tpu.resilience.faults import ledger_dir
    plan = FaultPlan.from_args(
        args, state_dir=ledger_dir(args.ckpt_dir, args.obs_dir),
        control_dir=control_dir(args.obs_dir) if args.obs_dir else None)
    # Multi-host bring-up before any backend touch (no-op single-process).
    # jax.devices() then spans every host, so --model_shards can spread the
    # correspondence activations across hosts' chips over DCN/ICI.
    # Under --fence-deadline the (C-level, unkillable-from-Python)
    # barrier runs guarded: one absent host becomes a hang_report.json +
    # FENCE_TIMEOUT_RC exit instead of every host hanging forever.
    from dgmc_tpu.parallel import (global_batch, host_obs_dir,
                                   initialize_distributed, is_coordinator)
    nproc = initialize_distributed(
        args.coordinator, args.num_processes, args.process_id,
        deadline_s=args.fence_deadline,
        hang_report_path=(os.path.join(args.obs_dir, 'hang_report.json')
                          if args.obs_dir else None))
    # Control-plane heartbeats (<obs>/control/host_<i>.json): each host
    # advertises liveness + its last completed fence; peers and the
    # supervisor read them for peer-death/straggler detection and for
    # naming the missing host in fence hang reports.
    channel = None
    if args.obs_dir:
        plan.host_index = jax.process_index()
        channel = HostChannel(args.obs_dir,
                              host_index=jax.process_index(),
                              num_hosts=nproc, fault_plan=plan).start()
    train_batch, test_batch, in_dim = load_batches(args)

    if args.row_shards > 1 and args.model_shards > 1:
        raise SystemExit('--row_shards (partition-rule streamed layout) '
                         'and --model_shards (legacy corr sharding) are '
                         'mutually exclusive')
    corr_sharding = None
    mesh = None
    rules = None
    if args.model_shards > 1:
        from dgmc_tpu.parallel import corr_sharding as mk_corr, make_mesh
        mesh = make_mesh(data=1, model=args.model_shards,
                         devices=jax.devices()[:args.model_shards])
        corr_sharding = mk_corr(mesh)
    elif args.row_shards > 1:
        # Million-entity layout: ONE declarative config — S rows over the
        # data axis, shortlist + ψ₂ source intermediates riding along,
        # candidate search streamed over source chunks — consumed by the
        # sharded step builders in place of hand-wired in_shardings.
        from dgmc_tpu.parallel import make_mesh, streamed_rules
        mesh = make_mesh(data=args.row_shards, model=1,
                         devices=jax.devices()[:args.row_shards])
        rules = streamed_rules(
            **({'stream_chunk': args.stream_chunk}
               if args.stream_chunk else {}),
            **({'topk_block': args.topk_block}
               if args.topk_block else {}))
    if nproc > 1:
        if rules is not None:
            raise SystemExit(
                '--row_shards (the partition-rule streamed layout) is '
                'single-process only for now: its state/batch placement '
                'device_puts host arrays onto a process-local mesh. Use '
                '--model_shards == total device count for multi-host '
                'runs, or run the streamed layout on one host')
        if mesh is None or args.model_shards < len(jax.devices()):
            raise SystemExit(
                'multi-process dbp15k requires --model_shards == total '
                'device count (the workload is one B=1 pair; only the '
                'correspondence-sharded axis spans hosts)')
        # Every process holds the full pair; arrays become mesh-global.
        train_batch = global_batch(train_batch, mesh, replicate=True)
        test_batch = global_batch(test_batch, mesh, replicate=True)

    from dgmc_tpu.models.precision import from_args
    prec = from_args(args)
    psi_1 = RelCNN(in_dim, args.dim, args.num_layers, batch_norm=False,
                   cat=True, lin=True, dropout=0.5, dtype=prec)
    psi_2 = RelCNN(args.rnd_dim, args.rnd_dim, args.num_layers,
                   batch_norm=False, cat=True, lin=True, dropout=0.0,
                   dtype=prec)
    from dgmc_tpu.parallel.rules import DEFAULT_TOPK_BLOCK
    model = DGMC(psi_1, psi_2, num_steps=args.num_steps, k=args.k,
                 corr_sharding=corr_sharding, dtype=prec,
                 topk_block=args.topk_block or DEFAULT_TOPK_BLOCK,
                 stream_chunk=(args.stream_chunk or None)
                 if rules is None else None)

    # A giant synthetic pair must not run its million-row forward EAGERLY
    # just to infer parameter shapes — parameter values depend only on
    # feature widths, so a tiny stand-in pair initializes identically
    # (train/state.create_train_state docs).
    init_batch = None
    if args.synthetic and args.syn_nodes_s * args.syn_nodes_t > 1 << 24:
        init_batch, _, _ = synthetic_batches(
            args, shapes=(64, 96, 256, 384))
    state = create_train_state(model, jax.random.key(args.seed), train_batch,
                               learning_rate=args.lr,
                               init_batch=init_batch)
    guard = args.guard_bad_steps > 0
    if guard:
        # Counters ride the state pytree (and its checkpoints), so the
        # skip ledger survives supervised restarts.
        from dgmc_tpu.train import with_guard_counters
        state = with_guard_counters(state)
    # Phase 1: feature matching only. Phase 2: refinement with psi_1 frozen
    # by stop_gradient — the reference's detach=True (dbp15k.py:67-68).
    if rules is not None:
        # Rules-driven sharded steps: the partition-rule config supplies
        # state/batch shardings AND the model's activation constraints +
        # streaming knobs (parallel/sharding._resolve_rules).
        from dgmc_tpu.parallel import (make_sharded_eval_step,
                                       make_sharded_train_step)
        phase1 = make_sharded_train_step(
            model, mesh, num_steps=0, rules=rules, state=state,
            guard=guard, fault_nan_step=plan.nan_grads_step)
        phase2 = make_sharded_train_step(
            model, mesh, num_steps=args.num_steps, detach=True,
            rules=rules, state=state, guard=guard,
            fault_nan_step=plan.nan_grads_step)
        eval1 = make_sharded_eval_step(model, mesh, hits_ks=(10,),
                                       num_steps=0, rules=rules,
                                       state=state)
        eval2 = make_sharded_eval_step(model, mesh, hits_ks=(10,),
                                       num_steps=args.num_steps,
                                       rules=rules, state=state)
    else:
        phase1 = make_train_step(model, num_steps=0, guard=guard,
                                 fault_nan_step=plan.nan_grads_step)
        phase2 = make_train_step(model, num_steps=args.num_steps,
                                 detach=True, guard=guard,
                                 fault_nan_step=plan.nan_grads_step)
        eval1 = make_eval_step(model, hits_ks=(10,), num_steps=0)
        eval2 = make_eval_step(model, hits_ks=(10,),
                               num_steps=args.num_steps)

    # Auto-resume: the epoch counter is the checkpoint step, and the
    # two-phase schedule position is a pure function of the epoch, so a
    # restart lands in the right phase with the right compiled step.
    # Orbax save/restore is a COLLECTIVE over global arrays: every process
    # must participate (ckpt_dir must be a shared filesystem multi-host);
    # only metric/stdout writes are coordinator-gated.
    # Passing the mesh re-derives the target shardings on the CURRENT
    # mesh before restoring, so a checkpoint saved on a larger mesh
    # resumes RESHARDED — the supervisor's elastic mesh-shrink path
    # (8 devices die down to 4; the run continues).
    ckpt, state, start_epoch = resume_or_init(
        args.ckpt_dir, state, mesh=mesh if nproc == 1 else None,
        rules=rules)
    if nproc > 1:
        state = global_batch(state, mesh, replicate=True)
    if rules is not None:
        # Rule-matched placement: every state leaf lands with the layout
        # its regex rule declares; the (replicated) giant pair follows
        # the config's batch rule.
        state, train_batch = rules.place(state, train_batch, mesh)
        test_batch = jax.device_put(test_batch,
                                    rules.batch_sharding(mesh))
    # Trace the second executed epoch (first is compile-heavy) unless only
    # one epoch will run at all.
    profile_epoch = min(start_epoch + 1, args.epochs)

    logger = MetricLogger(args.metrics_log if is_coordinator() else None)
    # Per-host obs subdir (obs-dir/host_<k>/ multi-process, the root
    # solo): every host records — the straggling host is the evidence —
    # and `python -m dgmc_tpu.obs.aggregate <obs-dir>` merges them.
    obs = RunObserver(host_obs_dir(args.obs_dir), probes=args.probes,
                      watchdog_deadline_s=args.watchdog_deadline,
                      fence_deadline_s=args.fence_deadline,
                      host_channel=channel, obs_port=args.obs_port)
    # SLO/anomaly planes: step latency and the quality headlines are
    # judged live (--slo) and watched for silent drift (always-on —
    # the detectors are O(1) and only the excursions cost anything).
    obs.attach_anomaly()
    obs.attach_slo(getattr(args, 'slo', None))
    # collective-stall@N fires INSIDE the fence guard, where a wedged
    # collective would actually block.
    obs.fence_hook = plan.before_fence
    guard_mon = RollbackGuard(args.guard_bad_steps, obs=obs) \
        if guard else None
    # Cost/MFU attribution for both phase programs (one extra trace
    # each, no extra XLA compile): the refinement step is the headline
    # 'train_step'; phase 1 keeps its own row.
    obs.record_cost('phase1_step', phase1, state, train_batch,
                    jax.random.key(args.seed + 2))
    obs.record_cost('train_step', phase2, state, train_batch,
                    jax.random.key(args.seed + 2))
    if args.aot_compile:
        # Compile the steps this schedule will actually execute (eval1
        # only runs on phase-1 epochs divisible by 10) and log each
        # executable's static per-device memory bound. The compiled
        # callables replace the lazy-jit ones — one compile either way.
        from dgmc_tpu.obs.memory import compiled_memory

        def aot(name, fn, *a):
            c = fn.lower(*a).compile()
            mem = compiled_memory(c)
            if mem:
                obs.log(0, event=f'aot_memory_{name}', **mem)
                if is_coordinator():
                    print(f'# {name}: per-device static memory '
                          f'{mem["total_bytes"] / 2**30:.3f} GiB '
                          f'(args {mem["argument_bytes"] >> 20} MiB, '
                          f'temps {mem["temp_bytes"] >> 20} MiB)')
            return c

        key0 = jax.random.key(args.seed + 3)
        # Clamp both gates to the epochs that will actually run: phase 1
        # ends at min(phase1_epochs, epochs), and a fully-resumed run
        # (start_epoch > epochs) executes nothing.
        p1_last = min(args.phase1_epochs, args.epochs)
        if start_epoch <= p1_last:
            phase1 = aot('phase1_step', phase1, state, train_batch, key0)
            if any(e % 10 == 0 for e in range(start_epoch, p1_last + 1)):
                eval1 = aot('eval1_step', eval1, state, test_batch, key0)
        if args.epochs > args.phase1_epochs and start_epoch <= args.epochs:
            phase2 = aot('train_step', phase2, state, train_batch, key0)
            eval2 = aot('eval_step', eval2, state, test_batch, key0)
    prof = obs.attach_profiler(
        start_profile(args.profile_dir, steps=args.profile_steps))
    if start_epoch > 1:
        logger.log(start_epoch - 1, event='resume')
    if is_coordinator():
        print('Optimize initial feature matching...')
    key = jax.random.key(args.seed + 1)
    last_print_epoch, t_span = start_epoch - 1, time.time()
    last_eval = {}
    for epoch in range(1, args.epochs + 1):
        # Keys are split unconditionally so a resumed run consumes the
        # PRNG stream exactly as an uninterrupted one would.
        key, sub = jax.random.split(key)
        refine = epoch > args.phase1_epochs
        if epoch < start_epoch:
            if epoch % 10 == 0 or refine:  # replay the eval split too
                key, _ = jax.random.split(key)
            continue
        if epoch == args.phase1_epochs + 1 and is_coordinator():
            print('Refine correspondence matrix...')
        # Armed host-side faults (raise/sigterm/sigkill/stall/
        # peer-death/straggler/coord-partition) fire here — on EXECUTED
        # epochs only, and once across supervised restarts (the ledger
        # in ckpt/obs dir survives the kill).
        if channel is not None:
            channel.beat('epoch', epoch)
        plan.before_step(epoch)
        step = phase2 if refine else phase1
        with trace(args.profile if epoch == profile_epoch else None), \
                obs.compile_label(f'phase{2 if refine else 1}'):
            with obs.step():
                state, out = step(state, train_batch, sub)
            # No host fetch here: on a tunneled/remote device every scalar
            # fetch costs a full round trip, so the loss rides device-side
            # until an epoch that actually prints — except when profiling,
            # where the trace must stay open until the step executes.
            if args.profile and epoch == profile_epoch:
                float(out['loss'])

        if epoch % 10 == 0 or refine:
            key, sub = jax.random.split(key)
            ev = (eval2 if refine else eval1)(state, test_batch, sub)
            # Per-device completion probe on an epoch that fetches
            # anyway: the straggler/skew series for obs.aggregate —
            # and the run's collective fence, deadline-guarded under
            # --fence-deadline (tag = the epoch a hang report names).
            obs.fence_devices(out['loss'], tag=epoch)
            # One batched fetch for loss + all eval metrics. This also
            # drains every epoch queued since the last print, so the
            # reported time is the average over that span.
            fetch = {'loss': out['loss'], **ev}
            if guard_mon is not None:
                fetch['skip_count'] = out['skip_count']
                fetch['consec_bad'] = out['consec_bad']
            host = jax.device_get(fetch)
            span = epoch - last_print_epoch
            per_epoch = (time.time() - t_span) / max(span, 1)
            last_print_epoch, t_span = epoch, time.time()
            loss = float(host['loss'])
            summary = eval_summary(host['count'], loss=loss,
                                   hits1=host['correct'],
                                   hits10=host['hits@10'])
            hits1, hits10 = summary['hits1'], summary['hits10']
            last_eval = {'loss': loss, 'hits1': hits1, 'hits10': hits10}
            obs.quality_eval('dbp15k', summary, step=epoch)
            guard_metrics = {}
            if guard_mon is not None:
                guard_metrics = {
                    'skipped_steps': int(host['skip_count']),
                    'consec_bad': int(host['consec_bad'])}
                # Publish to the live plane (/healthz gauges +
                # dgmc_guard_* metrics): the counters ride the state
                # pytree, so this print boundary is the one place the
                # host actually knows them.
                obs.set_gauge('guard_skip_count',
                              guard_metrics['skipped_steps'])
                obs.set_gauge('guard_consec_bad',
                              guard_metrics['consec_bad'])
                if int(host['consec_bad']) == 0 and np.isfinite(loss):
                    guard_mon.note_good(state, step=epoch)
                else:
                    state, rolled = guard_mon.maybe_rollback(
                        state, host['consec_bad'], step=epoch)
                    if rolled and is_coordinator():
                        logger.log(epoch, event='rollback',
                                   rollbacks=guard_mon.rollbacks)
            if is_coordinator():
                print(f'{epoch:03d}: Loss: {loss:.4f}, '
                      f'Hits@1: {hits1:.4f}, '
                      f'Hits@10: {hits10:.4f} '
                      f'({per_epoch:.1f}s/epoch)')
            logger.log(epoch, loss=loss, hits1=hits1, hits10=hits10,
                       phase=2 if refine else 1, **guard_metrics)
            obs.log(epoch, loss=loss, hits1=hits1, hits10=hits10,
                    phase=2 if refine else 1,
                    epoch_s=round(per_epoch, 3), **guard_metrics)
            obs.snapshot_memory(f'epoch{epoch}')
        if ckpt and (epoch % args.ckpt_every == 0 or epoch == args.epochs):
            ckpt.save(epoch, state)
            # Armed ckpt-truncate/ckpt-corrupt faults damage the step
            # that was just committed (waits out the async save).
            plan.after_checkpoint(ckpt, epoch)
    if args.offload_corpus and nproc > 1:
        # The prefetch ring device_puts onto addressable devices only;
        # a per-host pass would also duplicate the verification work.
        # Single-process covers the mechanism — skip loudly, not crash
        # after the whole training wall clock was spent.
        if is_coordinator():
            print('# offload shortlist: skipped (multi-process run; '
                  'the prefetch ring is single-host)')
    elif args.offload_corpus:
        # Host-RAM offload pass (the serving-corpus mechanism, exercised
        # at experiment scale): the trained ψ₁ table for the test pair's
        # source side moves to HOST memory and is re-shortlisted through
        # the prefetch ring, then compared BIT-EXACTLY against the
        # device-resident streamed search on the same embeddings. The
        # final eval metrics ride the same record so obs.diff
        # --require-equal can gate streamed-vs-offloaded runs on them.
        from dgmc_tpu.models.precision import compute_dtype_of
        from dgmc_tpu.ops.offload import (DEFAULT_PREFETCH_DEPTH,
                                          offloaded_streamed_topk)
        from dgmc_tpu.ops.topk import streamed_topk
        from dgmc_tpu.parallel.rules import DEFAULT_STREAM_CHUNK

        def embed(params, batch):
            h_s = model.psi_1.apply({'params': params['psi_1']},
                                    batch.s.x, batch.s, train=False)
            h_t = model.psi_1.apply({'params': params['psi_1']},
                                    batch.t.x, batch.t, train=False)
            dt = compute_dtype_of(model.dtype)
            if dt is not None:
                h_s, h_t = h_s.astype(dt), h_t.astype(dt)
            return h_s, h_t

        h_s, h_t = jax.jit(embed)(state.params, test_batch)
        chunk = (args.stream_chunk
                 or (rules.stream_chunk if rules is not None else None)
                 or DEFAULT_STREAM_CHUNK)
        chunk = min(int(chunk), h_s.shape[1])
        block = args.topk_block or model.topk_block
        depth = args.prefetch_depth or DEFAULT_PREFETCH_DEPTH
        ref_v, ref_i = streamed_topk(h_s, h_t, args.k, chunk,
                                     block=block, pallas=False,
                                     return_values=True)
        ov, oi, stats = offloaded_streamed_topk(
            np.asarray(jax.device_get(h_s)),
            np.asarray(jax.device_get(h_t)), args.k, chunk,
            block=block, depth=depth, devices=jax.local_devices())
        equal = bool(np.array_equal(oi, np.asarray(ref_i))
                     and np.array_equal(ov, np.asarray(ref_v)))
        if is_coordinator():
            print(f'# offload shortlist: equal={equal} '
                  f'rows={stats.rows} chunks={stats.chunks} '
                  f'depth={stats.prefetch_depth} '
                  f'host {stats.host_resident_bytes >> 20} MiB '
                  f'misses={stats.ring_misses} '
                  f'wall {stats.wall_s:.2f}s')
        obs.log(args.epochs, event='offload_shortlist',
                offload_equal=float(equal),
                offload_host_bytes=stats.host_resident_bytes,
                offload_prefetch_depth=stats.prefetch_depth,
                offload_ring_misses=stats.ring_misses,
                offload_wall_s=stats.wall_s, **last_eval)
        if not equal:
            raise SystemExit(
                'offloaded shortlist diverged from the device-resident '
                'streamed search — the offload tier must be pure '
                'scheduling')
    if ckpt:
        ckpt.close()
    prof.close()
    logger.close()
    obs.close()
    if channel is not None:
        channel.close()
    return state


if __name__ == '__main__':
    main()
