"""DBP15K cross-lingual entity alignment.

Capability parity with reference ``examples/dbp15k.py``: RelCNN ψ₁/ψ₂,
sparse top-k=10 correspondences with ground-truth injection, two-phase
schedule — 100 epochs of feature matching only (``num_steps=0``) then 100
epochs of consensus refinement with ψ₁ detached — expressed here as explicit
per-phase train steps instead of module-attribute mutation (reference
``dbp15k.py:63-69``). Metrics: Hits@1 and Hits@10 on the test alignments.

Optionally shards the correspondence activations over all available chips
(``--model_shards N``) — the scale-out axis the reference lacks.

Training defaults to the bf16-compute / f32-accumulation precision
policy (``--f32`` opts out; ``dgmc_tpu/models/precision.py``), and
``--pairs-per-step N`` batches N replicas of the pair per step, each
drawing independent per-pair indicator noise / negative samples — the
MXU sees a real batch axis instead of B=1 and one step averages N
independent gradient samples. The per-pair RNG streams (noise,
negatives) are fold_in-exact against independent B=1 steps
(``tests/models/test_pairs_per_step.py``); ψ₁'s dropout masks are the
one batch-drawn coupler, so this CLI's batched losses are equivalent in
distribution, not bitwise (phase 2 trains with ψ₁ detached but its
dropout still active, as the reference does).

Run: ``python examples/dbp15k.py --category zh_en``
(optionally ``--data_root ../data/DBP15K``)
"""

import argparse
import os
import time

import jax
import numpy as np

from dgmc_tpu.models import DGMC, RelCNN
from dgmc_tpu.obs import (RunObserver, add_obs_flag, add_profile_flag,
                          start_profile)
from dgmc_tpu.train import (MetricLogger, create_train_state, make_eval_step,
                            make_train_step, resume_or_init, trace)
from dgmc_tpu.utils.data import GraphPair, pad_pair_batch


def parse_args(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument('--category', type=str, default=None,
                        choices=['zh_en', 'ja_en', 'fr_en'])
    # Protocol-faithful synthetic KG alignment at arbitrary scale: the
    # offline stand-in for the real raw release (which needs egress).
    # Same construction as the miniature quality gate
    # (tests/models/test_two_phase_quality.py), full DBP15K shapes by
    # default; the rest of the schedule/metrics/checkpoint machinery is
    # shared with the real-data path.
    parser.add_argument('--synthetic', action='store_true')
    parser.add_argument('--syn_nodes_s', type=int, default=15000)
    parser.add_argument('--syn_nodes_t', type=int, default=20000)
    parser.add_argument('--syn_edges_s', type=int, default=100000)
    parser.add_argument('--syn_edges_t', type=int, default=120000)
    parser.add_argument('--syn_dim', type=int, default=300)
    parser.add_argument('--syn_noise', type=float, default=2.5,
                        help='max feature-noise sigma on aligned entities')
    parser.add_argument('--syn_noise_min', type=float, default=0.5,
                        help='min feature-noise sigma; each aligned entity '
                             'draws its own sigma uniformly in '
                             '[min, max] — homogeneous noise has a sharp '
                             'all-or-nothing learnability transition at '
                             'C=300 (measured: sigma 1.5 saturates, 1.8 '
                             'never lifts off), while per-entity '
                             'heterogeneity yields the mid-range phase-1 '
                             'accuracy of the real embeddings')
    parser.add_argument('--syn_rewire', type=float, default=0.15,
                        help='fraction of source edges rewired on the '
                             'target side')
    parser.add_argument('--syn_seed_frac', type=float, default=0.3,
                        help='seed-alignment fraction (the reference '
                             'protocol trains on 30%%)')
    from dgmc_tpu.models.precision import add_precision_args
    add_precision_args(parser)
    parser.add_argument('--pairs-per-step', '--pairs_per_step',
                        dest='pairs_per_step', type=int, default=1,
                        metavar='N',
                        help='batch N replicas of the training pair per '
                             'step, each drawing independent per-pair '
                             'indicator noise and negative samples '
                             '(fold_in per batch element) — one step '
                             'averages N independent gradient samples '
                             'while the MXU sees a real batch axis '
                             'instead of B=1')
    parser.add_argument('--dim', type=int, default=256)
    parser.add_argument('--rnd_dim', type=int, default=32)
    parser.add_argument('--num_layers', type=int, default=3)
    parser.add_argument('--num_steps', type=int, default=10)
    parser.add_argument('--k', type=int, default=10)
    parser.add_argument('--lr', type=float, default=0.001)
    parser.add_argument('--epochs', type=int, default=200)
    parser.add_argument('--phase1_epochs', type=int, default=100)
    parser.add_argument('--model_shards', type=int, default=0,
                        help='shard correspondence rows over N devices '
                             '(0 = no sharding)')
    parser.add_argument('--data_root', type=str,
                        default=os.path.join('..', 'data', 'DBP15K'))
    parser.add_argument('--seed', type=int, default=0)
    parser.add_argument('--ckpt_dir', type=str, default=None,
                        help='periodic checkpoint + auto-resume directory '
                             '(resumes mid-schedule at the saved epoch)')
    parser.add_argument('--ckpt_every', type=int, default=10)
    parser.add_argument('--profile', type=str, default=None,
                        help='emit a jax.profiler trace of one training '
                             'step into this directory')
    parser.add_argument('--metrics_log', type=str, default=None,
                        help='append per-epoch metrics to this JSONL file')
    parser.add_argument('--coordinator', type=str, default=None,
                        help='multi-host: coordinator address host:port '
                             '(auto-detected on TPU pods / SLURM)')
    parser.add_argument('--num_processes', type=int, default=None)
    parser.add_argument('--process_id', type=int, default=None)
    parser.add_argument('--guard-bad-steps', '--guard_bad_steps',
                        dest='guard_bad_steps', type=int, default=0,
                        metavar='M',
                        help='in-graph non-finite guardrail: a step with '
                             'a non-finite loss/grad keeps the old '
                             'params (skip counted); M consecutive bad '
                             'steps roll back to the last good snapshot '
                             'with a fresh optimizer (0 = off). See '
                             'dgmc_tpu/resilience/guard.py')
    from dgmc_tpu.resilience import add_fault_args, add_supervisor_args
    add_supervisor_args(parser)
    add_fault_args(parser)
    add_obs_flag(parser)
    add_profile_flag(parser)
    return parser.parse_args(argv)


def synthetic_batches(args):
    """DBP15K-scale synthetic KG alignment (``--synthetic``).

    A random source KG; the target KG holds an injectively mapped noisy
    copy of every source entity (``x_t[perm[i]] = x_s[i] + sigma*noise``)
    plus unaligned distractor entities, with ``syn_rewire`` of the mapped
    edges rewired and extra distractor edges — the miniature quality
    gate's construction (tests/models/test_two_phase_quality.py) at full
    protocol shapes. Seeds follow the reference's 30% split.
    """
    from dgmc_tpu.ops.blocked import attach_blocks
    from dgmc_tpu.ops.graph import GraphBatch
    from dgmc_tpu.utils.data import PairBatch

    rng = np.random.RandomState(args.seed)
    n_s, n_t = args.syn_nodes_s, args.syn_nodes_t
    e_s, e_t = args.syn_edges_s, args.syn_edges_t
    c = args.syn_dim
    assert n_t >= n_s and e_t >= e_s

    # Unit-NORM feature scale (1/sqrt(c) per dim), like the real pipeline's
    # summed word vectors (O(1) norms): N(0,1)^c features would give the
    # initial similarity logits a std of ~sqrt(dim)·O(1) ≈ 15+, a
    # saturated softmax whose escape is seed luck (measured: seed 0 trains,
    # seed 1 flatlines). With O(1) feature norms the initial softmax is
    # near-uniform and training takes off for every seed tried.
    x_s = (rng.randn(n_s, c) / np.sqrt(c)).astype(np.float32)
    snd = rng.randint(0, n_s, e_s).astype(np.int32)
    rcv = rng.randint(0, n_s, e_s).astype(np.int32)

    perm = rng.permutation(n_t)[:n_s].astype(np.int32)
    x_t = (rng.randn(n_t, c) / np.sqrt(c)).astype(np.float32)
    sigma = rng.uniform(args.syn_noise_min, args.syn_noise,
                        (n_s, 1)).astype(np.float32)
    # Variance-preserving blend: corr(x_s, x_t[perm]) = 1/sqrt(1+sigma^2)
    # per entity while every target row keeps unit feature variance —
    # un-normalized additive noise gives aligned entities systematically
    # larger norms, and those rows then dominate every similarity row's
    # softmax (measured: training never lifts off at full scale).
    noise = (rng.randn(n_s, c) / np.sqrt(c)).astype(np.float32)
    x_t[perm] = (x_s + sigma * noise) / np.sqrt(1.0 + sigma ** 2)
    keep = rng.rand(e_s) >= args.syn_rewire
    snd_t = np.where(keep, perm[snd], rng.randint(0, n_t, e_s))
    rcv_t = np.where(keep, perm[rcv], rng.randint(0, n_t, e_s))
    extra = e_t - e_s
    snd_t = np.concatenate([snd_t, rng.randint(0, n_t, extra)])
    rcv_t = np.concatenate([rcv_t, rng.randint(0, n_t, extra)])

    from dgmc_tpu.models.precision import from_args
    from dgmc_tpu.ops.blocked import repeat_graph
    prec = from_args(args)

    def side(x, s, r, n):
        g = GraphBatch(x=x[None], senders=s[None].astype(np.int32),
                       receivers=r[None].astype(np.int32),
                       node_mask=np.ones((1, n), bool),
                       edge_mask=np.ones((1, s.shape[0]), bool),
                       edge_attr=None)
        return attach_blocks(g, gather_dtype=prec)

    # Train batch at B = pairs_per_step (replicas of the one pair, each
    # drawing its own per-pair indicator noise / negatives on device;
    # blocked ONCE at B=1, replicas tiled); eval keeps B=1 — replicated
    # metrics would just repeat themselves.
    reps = max(1, args.pairs_per_step)
    e_s1, e_t1 = side(x_s, snd, rcv, n_s), side(x_t, snd_t, rcv_t, n_t)
    g_s, g_t = repeat_graph(e_s1, reps), repeat_graph(e_t1, reps)
    train_mask = np.zeros(n_s, bool)
    train_mask[:int(args.syn_seed_frac * n_s)] = True
    y_train = np.repeat(
        np.where(train_mask, perm, -1).astype(np.int32)[None], reps, 0)
    y_test = np.where(~train_mask, perm, -1).astype(np.int32)[None]
    return (PairBatch(s=g_s, t=g_t, y=y_train, y_mask=y_train >= 0),
            PairBatch(s=e_s1, t=e_t1, y=y_test, y_mask=y_test >= 0),
            c)


def load_batches(args):
    """One full-graph pair batch (B=1) with train GT, plus the test GT."""
    if args.synthetic:
        return synthetic_batches(args)
    if args.category is None:
        raise SystemExit('--category is required unless --synthetic')
    from dgmc_tpu.datasets import DBP15K
    data = DBP15K(args.data_root, args.category)
    g1, g2 = data.graphs(sum_embedding=True)

    n1, n2 = g1.num_nodes, g2.num_nodes
    y_train = np.full(n1, -1, np.int64)
    y_train[data.train_y[0]] = data.train_y[1]
    y_test = np.full(n1, -1, np.int64)
    y_test[data.test_y[0]] = data.test_y[1]

    from dgmc_tpu.models.precision import from_args
    from dgmc_tpu.ops.blocked import attach_blocks, repeat_graph
    from dgmc_tpu.utils.data import PairBatch

    prec = from_args(args)

    def batch(y_col):
        return pad_pair_batch([GraphPair(s=g1, t=g2, y_col=y_col)],
                              num_nodes_s=n1, num_edges_s=g1.num_edges,
                              num_nodes_t=n2, num_edges_t=g2.num_edges)

    reps = max(1, args.pairs_per_step)
    train_b, test_b = batch(y_train), batch(y_test)
    # Scatter-free MXU aggregation (ops/blocked.py) cuts the training step
    # ~22% at this scale (bench.py sparse leg). The graph sides are
    # identical in both batches — block them ONCE at B=1 and share; the
    # pairs-per-step train batch tiles the blocked sides (repeat_graph)
    # instead of re-running the host-side blocking per replica. Eval
    # stays B=1.
    e_s = attach_blocks(train_b.s, gather_dtype=prec)
    e_t = attach_blocks(train_b.t, gather_dtype=prec)
    s_b, t_b = repeat_graph(e_s, reps), repeat_graph(e_t, reps)
    y_tr = np.repeat(train_b.y, reps, axis=0)
    m_tr = np.repeat(train_b.y_mask, reps, axis=0)
    return (PairBatch(s=s_b, t=t_b, y=y_tr, y_mask=m_tr),
            PairBatch(s=e_s, t=e_t, y=test_b.y, y_mask=test_b.y_mask),
            g1.x.shape[1])


def main(argv=None):
    args = parse_args(argv)
    if args.supervise:
        # Detection -> recovery loop (resilience/supervisor.py): this
        # process becomes the jax-free monitor; the actual run executes
        # in child processes that auto-resume via --ckpt_dir.
        from dgmc_tpu.resilience.supervisor import supervise_cli
        raise SystemExit(supervise_cli(
            'dgmc_tpu.experiments.dbp15k', args, argv))
    from dgmc_tpu.resilience import FaultPlan, RollbackGuard
    from dgmc_tpu.resilience.faults import ledger_dir
    plan = FaultPlan.from_args(
        args, state_dir=ledger_dir(args.ckpt_dir, args.obs_dir))
    # Multi-host bring-up before any backend touch (no-op single-process).
    # jax.devices() then spans every host, so --model_shards can spread the
    # correspondence activations across hosts' chips over DCN/ICI.
    from dgmc_tpu.parallel import (global_batch, host_obs_dir,
                                   initialize_distributed, is_coordinator)
    nproc = initialize_distributed(args.coordinator, args.num_processes,
                                   args.process_id)
    train_batch, test_batch, in_dim = load_batches(args)

    corr_sharding = None
    mesh = None
    if args.model_shards > 1:
        from dgmc_tpu.parallel import corr_sharding as mk_corr, make_mesh
        mesh = make_mesh(data=1, model=args.model_shards,
                         devices=jax.devices()[:args.model_shards])
        corr_sharding = mk_corr(mesh)
    if nproc > 1:
        if mesh is None or args.model_shards < len(jax.devices()):
            raise SystemExit(
                'multi-process dbp15k requires --model_shards == total '
                'device count (the workload is one B=1 pair; only the '
                'correspondence-sharded axis spans hosts)')
        # Every process holds the full pair; arrays become mesh-global.
        train_batch = global_batch(train_batch, mesh, replicate=True)
        test_batch = global_batch(test_batch, mesh, replicate=True)

    from dgmc_tpu.models.precision import from_args
    prec = from_args(args)
    psi_1 = RelCNN(in_dim, args.dim, args.num_layers, batch_norm=False,
                   cat=True, lin=True, dropout=0.5, dtype=prec)
    psi_2 = RelCNN(args.rnd_dim, args.rnd_dim, args.num_layers,
                   batch_norm=False, cat=True, lin=True, dropout=0.0,
                   dtype=prec)
    model = DGMC(psi_1, psi_2, num_steps=args.num_steps, k=args.k,
                 corr_sharding=corr_sharding, dtype=prec)

    state = create_train_state(model, jax.random.key(args.seed), train_batch,
                               learning_rate=args.lr)
    guard = args.guard_bad_steps > 0
    if guard:
        # Counters ride the state pytree (and its checkpoints), so the
        # skip ledger survives supervised restarts.
        from dgmc_tpu.train import with_guard_counters
        state = with_guard_counters(state)
    # Phase 1: feature matching only. Phase 2: refinement with psi_1 frozen
    # by stop_gradient — the reference's detach=True (dbp15k.py:67-68).
    phase1 = make_train_step(model, num_steps=0, guard=guard,
                             fault_nan_step=plan.nan_grads_step)
    phase2 = make_train_step(model, num_steps=args.num_steps, detach=True,
                             guard=guard,
                             fault_nan_step=plan.nan_grads_step)
    eval1 = make_eval_step(model, hits_ks=(10,), num_steps=0)
    eval2 = make_eval_step(model, hits_ks=(10,), num_steps=args.num_steps)

    # Auto-resume: the epoch counter is the checkpoint step, and the
    # two-phase schedule position is a pure function of the epoch, so a
    # restart lands in the right phase with the right compiled step.
    # Orbax save/restore is a COLLECTIVE over global arrays: every process
    # must participate (ckpt_dir must be a shared filesystem multi-host);
    # only metric/stdout writes are coordinator-gated.
    ckpt, state, start_epoch = resume_or_init(args.ckpt_dir, state)
    if nproc > 1:
        state = global_batch(state, mesh, replicate=True)
    # Trace the second executed epoch (first is compile-heavy) unless only
    # one epoch will run at all.
    profile_epoch = min(start_epoch + 1, args.epochs)

    logger = MetricLogger(args.metrics_log if is_coordinator() else None)
    # Per-host obs subdir (obs-dir/host_<k>/ multi-process, the root
    # solo): every host records — the straggling host is the evidence —
    # and `python -m dgmc_tpu.obs.aggregate <obs-dir>` merges them.
    obs = RunObserver(host_obs_dir(args.obs_dir), probes=args.probes,
                      watchdog_deadline_s=args.watchdog_deadline)
    guard_mon = RollbackGuard(args.guard_bad_steps, obs=obs) \
        if guard else None
    # Cost/MFU attribution for both phase programs (one extra trace
    # each, no extra XLA compile): the refinement step is the headline
    # 'train_step'; phase 1 keeps its own row.
    obs.record_cost('phase1_step', phase1, state, train_batch,
                    jax.random.key(args.seed + 2))
    obs.record_cost('train_step', phase2, state, train_batch,
                    jax.random.key(args.seed + 2))
    prof = start_profile(args.profile_dir)
    if start_epoch > 1:
        logger.log(start_epoch - 1, event='resume')
    if is_coordinator():
        print('Optimize initial feature matching...')
    key = jax.random.key(args.seed + 1)
    last_print_epoch, t_span = start_epoch - 1, time.time()
    for epoch in range(1, args.epochs + 1):
        # Keys are split unconditionally so a resumed run consumes the
        # PRNG stream exactly as an uninterrupted one would.
        key, sub = jax.random.split(key)
        refine = epoch > args.phase1_epochs
        if epoch < start_epoch:
            if epoch % 10 == 0 or refine:  # replay the eval split too
                key, _ = jax.random.split(key)
            continue
        if epoch == args.phase1_epochs + 1 and is_coordinator():
            print('Refine correspondence matrix...')
        # Armed host-side faults (raise/sigterm/sigkill/stall) fire here
        # — on EXECUTED epochs only, and once across supervised restarts
        # (the ledger in ckpt/obs dir survives the kill).
        plan.before_step(epoch)
        step = phase2 if refine else phase1
        with trace(args.profile if epoch == profile_epoch else None), \
                obs.compile_label(f'phase{2 if refine else 1}'):
            with obs.step():
                state, out = step(state, train_batch, sub)
            # No host fetch here: on a tunneled/remote device every scalar
            # fetch costs a full round trip, so the loss rides device-side
            # until an epoch that actually prints — except when profiling,
            # where the trace must stay open until the step executes.
            if args.profile and epoch == profile_epoch:
                float(out['loss'])

        if epoch % 10 == 0 or refine:
            key, sub = jax.random.split(key)
            ev = (eval2 if refine else eval1)(state, test_batch, sub)
            # Per-device completion probe on an epoch that fetches
            # anyway: the straggler/skew series for obs.aggregate.
            obs.fence_devices(out['loss'])
            # One batched fetch for loss + all eval metrics. This also
            # drains every epoch queued since the last print, so the
            # reported time is the average over that span.
            fetch = {'loss': out['loss'], **ev}
            if guard_mon is not None:
                fetch['skip_count'] = out['skip_count']
                fetch['consec_bad'] = out['consec_bad']
            host = jax.device_get(fetch)
            span = epoch - last_print_epoch
            per_epoch = (time.time() - t_span) / max(span, 1)
            last_print_epoch, t_span = epoch, time.time()
            loss = float(host['loss'])
            n = max(float(host['count']), 1.0)
            hits1 = float(host['correct']) / n
            hits10 = float(host['hits@10']) / n
            guard_metrics = {}
            if guard_mon is not None:
                guard_metrics = {
                    'skipped_steps': int(host['skip_count']),
                    'consec_bad': int(host['consec_bad'])}
                if int(host['consec_bad']) == 0 and np.isfinite(loss):
                    guard_mon.note_good(state, step=epoch)
                else:
                    state, rolled = guard_mon.maybe_rollback(
                        state, host['consec_bad'], step=epoch)
                    if rolled and is_coordinator():
                        logger.log(epoch, event='rollback',
                                   rollbacks=guard_mon.rollbacks)
            if is_coordinator():
                print(f'{epoch:03d}: Loss: {loss:.4f}, '
                      f'Hits@1: {hits1:.4f}, '
                      f'Hits@10: {hits10:.4f} '
                      f'({per_epoch:.1f}s/epoch)')
            logger.log(epoch, loss=loss, hits1=hits1, hits10=hits10,
                       phase=2 if refine else 1, **guard_metrics)
            obs.log(epoch, loss=loss, hits1=hits1, hits10=hits10,
                    phase=2 if refine else 1,
                    epoch_s=round(per_epoch, 3), **guard_metrics)
            obs.snapshot_memory(f'epoch{epoch}')
        if ckpt and (epoch % args.ckpt_every == 0 or epoch == args.epochs):
            ckpt.save(epoch, state)
            # Armed ckpt-truncate/ckpt-corrupt faults damage the step
            # that was just committed (waits out the async save).
            plan.after_checkpoint(ckpt, epoch)
    if ckpt:
        ckpt.close()
    prof.close()
    logger.close()
    obs.close()
    return state


if __name__ == '__main__':
    main()
