"""The four experiment workloads (SURVEY.md §2.2), installable with console
entry points (``dgmc-dbp15k``, ``dgmc-pascal``, ``dgmc-willow``,
``dgmc-pascal-pf``) — capability parity with the reference's ``examples/``
scripts (reference ``examples/{dbp15k,pascal,willow,pascal_pf}.py``).

Each module exposes ``parse_args(argv)`` and ``main(argv=None)``; the
repo-root ``examples/`` directory keeps thin launchers for the reference's
``python examples/<name>.py`` invocation style. Workload modules are loaded
lazily so each console script pays only its own import cost.
"""

import importlib

__all__ = ['dbp15k', 'pascal', 'pascal_pf', 'willow']


def __getattr__(name):
    if name in __all__:
        return importlib.import_module(f'{__name__}.{name}')
    raise AttributeError(f'module {__name__!r} has no attribute {name!r}')
