"""PascalVOC + Berkeley keypoint matching across 20 categories.

Capability parity with reference ``examples/pascal.py``: SplineCNN ψ₁/ψ₂
over Delaunay graphs with Cartesian (or Distance, ``--isotropic``) edge
pseudo-coordinates; ``ValidPairDataset(sample=True)`` per category
concatenated into one loader; loss on both ``S_0`` and ``S_L``; per-category
eval sampling until ``--test_samples`` correspondences are seen
(reference ``pascal.py:84-99``).

Run: ``python examples/pascal.py [--data_root ../data/PascalVOC]``
"""

import argparse
import os
import time

import jax
import jax.numpy as jnp

from dgmc_tpu.data import Cartesian, Compose, Delaunay, Distance, FaceToEdge
from dgmc_tpu.models import DGMC, SplineCNN
from dgmc_tpu.models.evalsum import eval_summary
from dgmc_tpu.obs import (RunObserver, add_obs_flag, add_profile_flag,
                          start_profile)
from dgmc_tpu.train import (MetricLogger, create_train_state, make_eval_step,
                            make_train_step, resume_or_init, trace)
from dgmc_tpu.utils import (ConcatDataset, PairLoader, ValidPairDataset,
                            graph_limits)


def parse_args(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument('--isotropic', action='store_true')
    parser.add_argument('--dim', type=int, default=256)
    parser.add_argument('--rnd_dim', type=int, default=128)
    parser.add_argument('--num_layers', type=int, default=2)
    parser.add_argument('--num_steps', type=int, default=10)
    parser.add_argument('--lr', type=float, default=0.001)
    parser.add_argument('--batch_size', type=int, default=512)
    parser.add_argument('--epochs', type=int, default=15)
    parser.add_argument('--test_samples', type=int, default=1000)
    parser.add_argument('--data_root', type=str,
                        default=os.path.join('..', 'data', 'PascalVOC'))
    parser.add_argument('--vgg_weights', type=str, default='random',
                        help="'random', 'none', or path to converted .npz")
    parser.add_argument('--seed', type=int, default=0)
    parser.add_argument('--ckpt_dir', type=str, default=None,
                        help='per-epoch checkpoint + auto-resume directory')
    parser.add_argument('--profile', type=str, default=None,
                        help='emit a jax.profiler trace of one training '
                             'epoch into this directory')
    parser.add_argument('--metrics_log', type=str, default=None,
                        help='append per-epoch metrics to this JSONL file')
    parser.add_argument('--coordinator', type=str, default=None,
                        help='multi-host: coordinator address host:port '
                             '(auto-detected on TPU pods / SLURM; pass '
                             'explicitly elsewhere)')
    parser.add_argument('--num_processes', type=int, default=None)
    parser.add_argument('--process_id', type=int, default=None)
    from dgmc_tpu.models.precision import add_precision_args
    add_precision_args(parser)
    from dgmc_tpu.resilience import add_supervisor_args
    add_supervisor_args(parser)
    add_obs_flag(parser)
    add_profile_flag(parser)
    return parser.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    if args.supervise:
        # Crash/hang/preemption recovery loop (resilience/supervisor.py):
        # restarts auto-resume via --ckpt_dir. No --model_shards here, so
        # the ladder stops at the f32 rung.
        from dgmc_tpu.resilience.supervisor import supervise_cli
        raise SystemExit(supervise_cli(
            'dgmc_tpu.experiments.pascal', args, argv,
            ladder=('disable-fused', 'f32')))
    # Multi-host bring-up FIRST (no-op in a plain single-process launch):
    # after this, jax.devices() spans every host and one data mesh drives
    # cross-host gradient collectives (SURVEY.md §2.5's net-new backend).
    from dgmc_tpu.parallel import (global_batch, host_obs_dir,
                                   initialize_distributed,
                                   is_coordinator, local_batch_slice,
                                   make_mesh, make_sharded_eval_step,
                                   make_sharded_train_step)
    nproc = initialize_distributed(args.coordinator, args.num_processes,
                                   args.process_id)
    from dgmc_tpu.datasets import PascalVOCKeypoints, VGG16Features
    from dgmc_tpu.datasets.pascal_voc import CATEGORIES

    transform = Compose([
        Delaunay(), FaceToEdge(),
        Distance() if args.isotropic else Cartesian()])
    features = VGG16Features(weights=args.vgg_weights)
    pre_filter = lambda g: g.num_nodes > 0  # noqa: E731

    train_sets, test_sets = [], []
    for category in CATEGORIES:
        tr = PascalVOCKeypoints(args.data_root, category, train=True,
                                transform=transform, pre_filter=pre_filter,
                                features=features)
        te = PascalVOCKeypoints(args.data_root, category, train=False,
                                transform=transform, pre_filter=pre_filter,
                                features=features)
        train_sets.append(ValidPairDataset(tr, tr, sample=True,
                                           seed=args.seed))
        test_sets.append(ValidPairDataset(te, te, sample=True,
                                          seed=args.seed + 1))
    num_nodes, num_edges = graph_limits([s.dataset_s for s in train_sets] +
                                        [s.dataset_s for s in test_sets])
    in_dim = train_sets[0].dataset_s.num_node_features
    edge_dim = 1 if args.isotropic else 2

    train_loader = PairLoader(ConcatDataset(train_sets), args.batch_size,
                              shuffle=True, seed=args.seed,
                              num_nodes=num_nodes, num_edges=num_edges)

    from dgmc_tpu.models.precision import from_args
    prec = from_args(args)  # bf16 compute / f32 accum unless --f32
    psi_1 = SplineCNN(in_dim, args.dim, edge_dim, args.num_layers,
                      cat=False, dropout=0.5, dtype=prec)
    psi_2 = SplineCNN(args.rnd_dim, args.rnd_dim, edge_dim, args.num_layers,
                      cat=True, dropout=0.0, dtype=prec)
    model = DGMC(psi_1, psi_2, num_steps=args.num_steps, dtype=prec)

    batch0 = next(iter(train_loader))
    state = create_train_state(model, jax.random.key(args.seed), batch0,
                               learning_rate=args.lr)
    if nproc > 1:
        # Data-parallel over every device of every host. Each process runs
        # the SAME deterministic loader (same seed ⇒ same batch order) and
        # feeds only its contiguous slice of each batch; gradients combine
        # through GSPMD's cross-host collectives automatically.
        mesh = make_mesh(data=len(jax.devices()))
        step = make_sharded_train_step(model, mesh, loss_on_s0=True)
        eval_step = make_sharded_eval_step(model, mesh)
        state = global_batch(state, mesh, replicate=True)

        def feed(b):
            return global_batch(local_batch_slice(b), mesh)
    else:
        step = make_train_step(model, loss_on_s0=True)
        eval_step = make_eval_step(model)

        def feed(b):
            return b

    key = jax.random.key(args.seed + 2)

    def test(pairs):
        nonlocal key
        loader = PairLoader(pairs, args.batch_size, shuffle=False,
                            num_nodes=num_nodes, num_edges=num_edges)
        # Correct-counts accumulate device-side; only the running sample
        # count is fetched per batch (one round trip instead of two — the
        # count gates the reference's sample-until-N protocol, reference
        # pascal.py:88-99).
        correct = jnp.zeros(())
        n = 0.0
        while n < args.test_samples:
            seen = n
            for batch in loader:
                key, sub = jax.random.split(key)
                out = eval_step(state, feed(batch), sub)
                correct = correct + out['correct']
                n += float(out['count'])
                if n >= args.test_samples:
                    return eval_summary(n, hits1=correct)['hits1']
            if n == seen:  # empty split / no valid GT: avoid spinning
                break
        return eval_summary(n, hits1=correct)['hits1']

    # Auto-resume at epoch granularity. Unlike dbp15k the per-epoch PRNG
    # stream depends on the shuffled batch count, so a resumed run's stream
    # differs from an uninterrupted one — acceptable here (the reference
    # protocol has no cross-epoch RNG contract for this workload).
    # Orbax save/restore is a COLLECTIVE over global arrays: every process
    # must participate (ckpt_dir must be a shared filesystem multi-host);
    # only metric/stdout writes are coordinator-gated.
    ckpt, state, start_epoch = resume_or_init(args.ckpt_dir, state)
    profile_epoch = min(start_epoch + 1, args.epochs)

    logger = MetricLogger(args.metrics_log if is_coordinator() else None)
    # Per-host obs subdir (obs-dir/host_<k>/ multi-process, the root
    # solo); merge with `python -m dgmc_tpu.obs.aggregate <obs-dir>`.
    obs = RunObserver(host_obs_dir(args.obs_dir), probes=args.probes,
                      watchdog_deadline_s=args.watchdog_deadline,
                      fence_deadline_s=args.fence_deadline,
                      obs_port=args.obs_port)
    # SLO/anomaly planes (obs/slo.py, obs/anomaly.py): judge the run
    # against --slo if given, watch step latency for silent drift.
    obs.attach_anomaly()
    obs.attach_slo(getattr(args, 'slo', None))
    # Cost/MFU attribution (one extra trace, no extra XLA compile);
    # under data parallelism this is the sharded step, so the lowered
    # account covers the collective-carrying program.
    obs.record_cost('train_step', step, state, feed(batch0),
                    jax.random.key(args.seed + 3))
    prof = obs.attach_profiler(
        start_profile(args.profile_dir, steps=args.profile_steps))
    if start_epoch > 1:
        logger.log(start_epoch - 1, event='resume')
    for epoch in range(start_epoch, args.epochs + 1):
        t0 = time.time()
        total = jnp.zeros(())  # device-side; one fetch per epoch
        with trace(args.profile if epoch == profile_epoch else None), \
                obs.compile_label(f'epoch{epoch}'):
            for batch in train_loader:
                key, sub = jax.random.split(key)
                with obs.step():
                    state, out = step(state, feed(batch), sub)
                total = total + out['loss']
            if args.profile and epoch == profile_epoch:
                float(total)  # keep the trace open until execution ends
        # Per-device completion probe at the epoch boundary (the fetch
        # below syncs anyway): the straggler series for obs.aggregate.
        obs.fence_devices(total)
        loss = float(total) / len(train_loader)
        if is_coordinator():
            print(f'Epoch: {epoch:02d}, Loss: {loss:.4f}, '
                  f'{time.time() - t0:.1f}s')

        accs = [100 * test(ds) for ds in test_sets]
        accs.append(sum(accs) / len(accs))
        if is_coordinator():
            print(' '.join(c[:5].ljust(5) for c in CATEGORIES) + ' mean')
            print(' '.join(f'{a:.1f}'.ljust(5) for a in accs))
        logger.log(epoch, loss=loss, mean_acc=accs[-1])
        obs.log(epoch, loss=loss, mean_acc=accs[-1],
                epoch_s=round(time.time() - t0, 3))
        obs.quality_eval('pascal', step=epoch, loss=loss,
                         hits1=accs[-1] / 100)
        obs.snapshot_memory(f'epoch{epoch}')
        if ckpt:
            ckpt.save(epoch, state)
    if ckpt:
        ckpt.close()
    prof.close()
    logger.close()
    obs.close()
    return state


if __name__ == '__main__':
    main()
