"""WILLOW-ObjectClass transfer learning: VOC pretrain, 20 per-category runs.

Capability parity with reference ``examples/willow.py``: pretrain on
PascalVOC keypoints (filtering 2007-images out of car/motorbike, reference
``willow.py:28-31``), snapshot the weights, then ``--runs`` independent runs
that restore the snapshot with a fresh Adam, train on 20 graphs/category of
all-pairs products, and evaluate on pairs drawn from two independently
shuffled loaders zipped together (reference ``willow.py:125-130``); report
mean ± std accuracy over runs.

Run: ``python examples/willow.py [--voc_root ../data/PascalVOC-WILLOW]
[--willow_root ../data/WILLOW]``
"""

import argparse
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from dgmc_tpu.data import Cartesian, Compose, Delaunay, Distance, FaceToEdge
from dgmc_tpu.models import DGMC, SplineCNN
from dgmc_tpu.models.evalsum import eval_summary
from dgmc_tpu.obs import (RunObserver, add_obs_flag, add_profile_flag,
                          start_profile)
from dgmc_tpu.train import (Checkpointer, MetricLogger, create_train_state,
                            make_eval_step, make_train_step, restore_params,
                            snapshot_params, trace)
from dgmc_tpu.utils import (ConcatDataset, PairDataset, PairLoader,
                            ValidPairDataset, graph_limits)
from dgmc_tpu.utils.data import GraphPair, pad_pair_batch
from dgmc_tpu.utils.io import write_json_atomic

NUM_KP = 10  # every WILLOW item has exactly 10 keypoints


def parse_args(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument('--isotropic', action='store_true')
    parser.add_argument('--dim', type=int, default=256)
    parser.add_argument('--rnd_dim', type=int, default=128)
    parser.add_argument('--num_layers', type=int, default=2)
    parser.add_argument('--num_steps', type=int, default=10)
    parser.add_argument('--lr', type=float, default=0.001)
    parser.add_argument('--batch_size', type=int, default=512)
    parser.add_argument('--pre_epochs', type=int, default=15)
    parser.add_argument('--epochs', type=int, default=15)
    parser.add_argument('--runs', type=int, default=20)
    parser.add_argument('--test_samples', type=int, default=100)
    parser.add_argument('--voc_root', type=str,
                        default=os.path.join('..', 'data', 'PascalVOC-WILLOW'))
    parser.add_argument('--willow_root', type=str,
                        default=os.path.join('..', 'data', 'WILLOW'))
    parser.add_argument('--vgg_weights', type=str, default='random')
    parser.add_argument('--seed', type=int, default=0)
    parser.add_argument('--eval_batch_size', type=int, default=32,
                        help='test pairs evaluated per device batch (the '
                             'reference evaluates one pair at a time; on a '
                             'tunneled TPU each fetch costs a ~120 ms round '
                             'trip, so pairs are batched and ONE count is '
                             'fetched per batch)')
    parser.add_argument('--ckpt_dir', type=str, default=None,
                        help='checkpoint + auto-resume directory; the '
                             'pretrained snapshot and completed-run results '
                             'are persisted, so a restart resumes at the '
                             'next unfinished run')
    parser.add_argument('--profile', type=str, default=None,
                        help='emit a jax.profiler trace of one pretraining '
                             'step into this directory')
    parser.add_argument('--metrics_log', type=str, default=None,
                        help='append per-epoch/per-run metrics to this '
                             'JSONL file')
    from dgmc_tpu.models.precision import add_precision_args
    add_precision_args(parser)
    from dgmc_tpu.resilience import add_supervisor_args
    add_supervisor_args(parser)
    add_obs_flag(parser)
    add_profile_flag(parser)
    return parser.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    if args.supervise:
        # Crash/hang/preemption recovery loop (resilience/supervisor.py):
        # restarts resume at the next unfinished run via --ckpt_dir.
        from dgmc_tpu.resilience.supervisor import supervise_cli
        raise SystemExit(supervise_cli(
            'dgmc_tpu.experiments.willow', args, argv,
            ladder=('disable-fused', 'f32')))
    from dgmc_tpu.datasets import (PascalVOCKeypoints, VGG16Features,
                                   WILLOWObjectClass)
    from dgmc_tpu.datasets.pascal_voc import CATEGORIES as VOC_CATEGORIES
    from dgmc_tpu.datasets.willow import CATEGORIES as WILLOW_CATEGORIES

    transform = Compose([
        Delaunay(), FaceToEdge(),
        Distance() if args.isotropic else Cartesian()])
    features = VGG16Features(weights=args.vgg_weights)
    edge_dim = 1 if args.isotropic else 2

    # -- Pretraining data: VOC minus the 2007 car/motorbike images that
    # overlap WILLOW (reference willow.py:28-31).
    pre_filter1 = lambda g: g.num_nodes > 0  # noqa: E731
    pre_filter2 = lambda g: (g.num_nodes > 0 and  # noqa: E731
                             not (g.name or '').startswith('2007'))
    pretrain_sets = []
    for category in VOC_CATEGORIES:
        ds = PascalVOCKeypoints(
            args.voc_root, category, train=True, transform=transform,
            features=features,
            pre_filter=pre_filter2 if category in ('car', 'motorbike')
            else pre_filter1)
        pretrain_sets.append(ValidPairDataset(ds, ds, sample=True,
                                              seed=args.seed))
    num_nodes, num_edges = graph_limits(
        [s.dataset_s for s in pretrain_sets])
    num_nodes = max(num_nodes, NUM_KP)
    num_edges = max(num_edges, NUM_KP * (NUM_KP - 1))
    in_dim = pretrain_sets[0].dataset_s.num_node_features
    pretrain_loader = PairLoader(ConcatDataset(pretrain_sets),
                                 args.batch_size, shuffle=True,
                                 seed=args.seed, num_nodes=num_nodes,
                                 num_edges=num_edges)

    willow = [WILLOWObjectClass(args.willow_root, c, transform=transform,
                                features=features)
              for c in WILLOW_CATEGORIES]

    from dgmc_tpu.models.precision import from_args
    prec = from_args(args)  # bf16 compute / f32 accum unless --f32
    psi_1 = SplineCNN(in_dim, args.dim, edge_dim, args.num_layers,
                      cat=False, dropout=0.5, dtype=prec)
    psi_2 = SplineCNN(args.rnd_dim, args.rnd_dim, edge_dim, args.num_layers,
                      cat=True, dropout=0.0, dtype=prec)
    model = DGMC(psi_1, psi_2, num_steps=args.num_steps, dtype=prec)

    batch0 = next(iter(pretrain_loader))
    state = create_train_state(model, jax.random.key(args.seed), batch0,
                               learning_rate=args.lr)
    step = make_train_step(model, loss_on_s0=True)
    eval_step = make_eval_step(model)
    key = jax.random.key(args.seed + 3)

    # Run-granularity resume: the pretrained snapshot is checkpointed once
    # (step 0) and each completed run's accuracies are persisted next to
    # it, so a killed 20-run protocol restarts at the next unfinished run
    # instead of re-pretraining.
    logger = MetricLogger(args.metrics_log)
    from dgmc_tpu.parallel import host_obs_dir
    obs = RunObserver(host_obs_dir(args.obs_dir), probes=args.probes,
                      watchdog_deadline_s=args.watchdog_deadline,
                      fence_deadline_s=args.fence_deadline,
                      obs_port=args.obs_port)
    # SLO/anomaly planes (obs/slo.py, obs/anomaly.py): judge the run
    # against --slo if given, watch step latency for silent drift.
    obs.attach_anomaly()
    obs.attach_slo(getattr(args, 'slo', None))
    # Cost/MFU attribution in <obs-dir>/efficiency.json (one extra
    # trace, no extra XLA compile — obs/cost.py).
    obs.record_cost('train_step', step, state, batch0,
                    jax.random.key(args.seed + 4))
    prof = obs.attach_profiler(
        start_profile(args.profile_dir, steps=args.profile_steps))
    ckpt = Checkpointer(args.ckpt_dir) if args.ckpt_dir else None
    runs_path = (os.path.join(args.ckpt_dir, 'runs.json')
                 if args.ckpt_dir else None)
    done_accs = []
    if runs_path and os.path.exists(runs_path):
        with open(runs_path) as f:
            done_accs = json.load(f)

    # One profiler trace per invocation: normally the second pretraining
    # epoch's first step; when resume skips pretraining entirely, the
    # first step of the first executed run instead (so --profile is never
    # a silent no-op).
    need_profile = args.profile

    if ckpt and ckpt.latest_step() is not None:
        state = ckpt.restore(state, 0)
        print(f'Resumed pretrained snapshot from {args.ckpt_dir} '
              f'({len(done_accs)} runs already complete).')
    else:
        print('Pretraining model on PascalVOC...')
        for epoch in range(1, args.pre_epochs + 1):
            t0 = time.time()
            total = jnp.zeros(())  # device-side; one fetch per epoch
            first = True
            with obs.compile_label('pretrain'):
                for batch in pretrain_loader:
                    key, sub = jax.random.split(key)
                    # Trace the first step of the second epoch (the first
                    # epoch is compile-heavy).
                    arm = need_profile if epoch == 2 and first else None
                    with trace(arm):
                        with obs.step():
                            state, out = step(state, batch, sub)
                        if arm:
                            float(out['loss'])
                    if arm:
                        need_profile = None
                    first = False
                    total = total + out['loss']
            # Per-device completion probe at the epoch boundary (the
            # fetch below syncs anyway): obs.aggregate's skew series.
            obs.fence_devices(total)
            loss = float(total) / len(pretrain_loader)
            print(f'Epoch: {epoch:02d}, Loss: {loss:.4f}, '
                  f'{time.time() - t0:.1f}s')
            logger.log(epoch, loss=loss, stage='pretrain')
            obs.log(epoch, loss=loss, stage='pretrain',
                    epoch_s=round(time.time() - t0, 3))
            obs.snapshot_memory(f'pretrain_epoch{epoch}')
        if ckpt:
            ckpt.save(0, state, wait=True)
    snapshot = snapshot_params(state)
    print('Done!')

    def identity_pairs(train_ds):
        """All-pairs product with identity GT over the 10 keypoints
        (reference willow.py:94-97)."""
        pairs = PairDataset(train_ds, train_ds, sample=False)

        class WithY:
            def __len__(self):
                return len(pairs)

            def __getitem__(self, i):
                p = pairs[i]
                return GraphPair(s=p.s, t=p.t,
                                 y_col=np.arange(NUM_KP, dtype=np.int64))
        return WithY()

    def test(run_state, ds):
        """Zipped-shuffled-orders evaluation (reference willow.py:125-130),
        batched: ``eval_batch_size`` pairs per compiled step and ONE count
        fetch per batch instead of one per pair — ~eval_batch_size fewer
        host round trips (VERDICT round-2 item 5)."""
        nonlocal key
        rng = np.random.RandomState(int(jax.random.randint(
            key, (), 0, 2 ** 31 - 1)))
        gt = np.arange(NUM_KP, dtype=np.int64)
        eb = max(1, min(args.eval_batch_size, len(ds)))
        correct = n = 0.0
        while n < args.test_samples:
            seen = n
            o1, o2 = rng.permutation(len(ds)), rng.permutation(len(ds))
            pairs = [GraphPair(s=ds[int(i)], t=ds[int(j)], y_col=gt)
                     for i, j in zip(o1, o2)]
            # Fixed batch size so every batch reuses one compiled step; the
            # ragged tail is padded with masked pairs (y_col=-1 => zero
            # count) so every zipped pair of the sweep is evaluated,
            # matching the reference's per-pair protocol
            # (reference willow.py:125-130).
            mask_pair = GraphPair(s=pairs[0].s, t=pairs[0].t,
                                  y_col=np.full(NUM_KP, -1, np.int64))
            for c in range(0, len(pairs), eb):
                chunk = pairs[c:c + eb]
                chunk += [mask_pair] * (eb - len(chunk))
                b = pad_pair_batch(chunk, num_nodes, num_edges)
                key, sub = jax.random.split(key)
                out = eval_step(run_state, b, sub)
                correct = correct + out['correct']
                n += float(out['count'])  # one fetch per batch
                if n >= args.test_samples:
                    return eval_summary(n, hits1=correct)['hits1']
            if n == seen:  # empty split: avoid spinning forever
                break
        return eval_summary(n, hits1=correct)['hits1']

    def run(i):
        nonlocal key
        run_state = restore_params(state, snapshot)
        train_parts = []
        for ds in willow:
            train_ds, _ = ds.shuffled_split(20, seed=args.seed + i)
            train_parts.append(identity_pairs(train_ds))
        loader = PairLoader(ConcatDataset(train_parts), args.batch_size,
                            shuffle=True, seed=args.seed + i,
                            num_nodes=num_nodes, num_edges=num_edges)
        nonlocal need_profile
        with obs.compile_label(f'run{i}'):
            for epoch in range(args.epochs):
                for batch in loader:
                    key, sub = jax.random.split(key)
                    with trace(need_profile):
                        with obs.step():
                            run_state, out = step(run_state, batch, sub)
                        if need_profile:
                            float(out['loss'])
                    need_profile = None
        accs = []
        for ds in willow:
            _, test_ds = ds.shuffled_split(20, seed=args.seed + i)
            accs.append(100 * test(run_state, test_ds))
        print(f'Run {i:02d}:')
        print(' '.join(c.ljust(13) for c in WILLOW_CATEGORIES))
        print(' '.join(f'{a:.2f}'.ljust(13) for a in accs))
        logger.log(i, stage='run', accs=accs)
        obs.log(i, stage='run', mean_acc=sum(accs) / len(accs))
        obs.quality_eval('willow', step=i,
                         hits1=sum(accs) / len(accs) / 100)
        obs.snapshot_memory(f'run{i}')
        return accs

    for i in range(len(done_accs) + 1, args.runs + 1):
        done_accs.append(run(i))
        if runs_path:
            # Atomic: runs.json is the resume ledger — a crash mid-dump
            # must leave the previous runs readable, not a torn file.
            write_json_atomic(runs_path,
                              [list(map(float, a)) for a in done_accs])
    all_accs = np.array(done_accs)
    mean, std = all_accs.mean(axis=0), all_accs.std(axis=0, ddof=1)
    print('-' * 14 * 5)
    print(' '.join(c.ljust(13) for c in WILLOW_CATEGORIES))
    print(' '.join(f'{m:.2f} ± {s:.2f}'.ljust(13)
                   for m, s in zip(mean, std)))
    if ckpt:
        ckpt.close()
    prof.close()
    logger.close()
    obs.close()
    return all_accs


if __name__ == '__main__':
    main()
