"""Correspondence visualization (matplotlib, host-side).

The reference showcases rendered keypoint matches in its README
(reference ``README.md:51-56``, ``figures/best_car.png``); this module is
the equivalent utility for the TPU framework: draw a (source, target)
keypoint-graph pair side by side and the predicted correspondence as
lines, colored by correctness when ground truth is given.

Matplotlib is imported lazily — install the ``viz`` extra
(``pip install dgmc_tpu[viz]``).
"""

import numpy as np

__all__ = ['predicted_targets', 'plot_matches']


def predicted_targets(corr):
    """Per-source-row argmax target of a
    :class:`~dgmc_tpu.models.dgmc.Correspondence` (dense or sparse),
    returned as ``[B, N_s]`` numpy int array."""
    val = np.asarray(corr.val)
    if corr.idx is None:
        return val.argmax(axis=-1)
    idx = np.asarray(corr.idx)
    best = val.argmax(axis=-1)
    return np.take_along_axis(idx, best[..., None], axis=-1)[..., 0]


def plot_matches(pos_s, pos_t, pred, y=None, edges_s=None, edges_t=None,
                 ax=None, offset=None, point_color='#1f77b4',
                 edge_color='#cccccc'):
    """Render one pair's predicted matches.

    Args:
        pos_s / pos_t: ``[N_s, 2]`` / ``[N_t, 2]`` keypoint coordinates.
        pred: ``[N_s]`` predicted target index per source keypoint (see
            :func:`predicted_targets`), ``-1`` to skip a row.
        y: optional ``[N_s]`` ground-truth targets (``-1`` = no GT);
            correct matches draw green, wrong ones red, un-labeled gray.
        edges_s / edges_t: optional ``[E, 2]`` (sender, receiver) arrays
            drawn as light graph structure.
        offset: translation applied to the target cloud so the two graphs
            sit side by side; default shifts right by 1.5x the source
            width.
        ax: existing matplotlib axes (one is created otherwise).

    Returns the matplotlib axes.
    """
    import matplotlib.pyplot as plt

    pos_s = np.asarray(pos_s, float)
    pos_t = np.asarray(pos_t, float)
    pred = np.asarray(pred)
    if offset is None:
        width = max(pos_s[:, 0].max() - pos_s[:, 0].min(), 1e-6)
        offset = np.array([1.5 * width, 0.0])
    pos_t = pos_t + np.asarray(offset, float)

    if ax is None:
        _, ax = plt.subplots(figsize=(8, 4))

    for pos, edges in ((pos_s, edges_s), (pos_t, edges_t)):
        if edges is not None:
            for a, b in np.asarray(edges):
                ax.plot([pos[a, 0], pos[b, 0]], [pos[a, 1], pos[b, 1]],
                        color=edge_color, linewidth=0.8, zorder=1)
    ax.scatter(pos_s[:, 0], pos_s[:, 1], s=28, c=point_color, zorder=3)
    ax.scatter(pos_t[:, 0], pos_t[:, 1], s=28, c=point_color, zorder=3)

    for i, j in enumerate(pred):
        if j < 0 or j >= len(pos_t):
            continue
        if y is None or y[i] < 0:
            color = '#999999'
        else:
            color = '#2ca02c' if int(y[i]) == int(j) else '#d62728'
        ax.plot([pos_s[i, 0], pos_t[j, 0]], [pos_s[i, 1], pos_t[j, 1]],
                color=color, linewidth=1.2, alpha=0.85, zorder=2)

    ax.set_aspect('equal')
    ax.axis('off')
    return ax
