from dgmc_tpu.utils.data import (Graph, GraphPair, PairDataset,
                                 ValidPairDataset, pad_graphs,
                                 pad_pair_batch, PairLoader)

__all__ = [
    'Graph',
    'GraphPair',
    'PairDataset',
    'ValidPairDataset',
    'pad_graphs',
    'pad_pair_batch',
    'PairLoader',
]
