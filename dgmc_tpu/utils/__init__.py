from dgmc_tpu.utils.data import (Graph, GraphPair, PairDataset,
                                 ValidPairDataset, ConcatDataset,
                                 pad_graphs, pad_pair_batch, PairLoader,
                                 PrefetchLoader, graph_limits)

__all__ = [
    'Graph',
    'GraphPair',
    'PairDataset',
    'ValidPairDataset',
    'ConcatDataset',
    'pad_graphs',
    'pad_pair_batch',
    'PairLoader',
    'PrefetchLoader',
    'graph_limits',
]
