"""Host-side pair-data layer: graph containers, pair builders, padded
collation.

Capability parity with the reference's L2 (reference ``dgmc/utils/data.py``):
``PairDataset`` (product or sampled pairing of two datasets) and
``ValidPairDataset`` (only pairs whose source keypoint classes all exist in
the target, with a per-pair ground-truth mapping) — plus the collation the
reference gets from PyG's ``Batch``/``follow_batch`` machinery (reference
``data.py:9-16``, used at reference ``examples/pascal.py:42-43``).

TPU-first difference: collation here produces *padded, fixed-shape*
``GraphBatch`` pairs (the device-side data model, see
``dgmc_tpu/ops/graph.py``) instead of ragged concatenation with edge-index
offsets. All of this runs host-side in NumPy at batch-build time; nothing
here enters the jit path. Ground truths are padded ``y[B, N_s]`` target
columns with a validity mask instead of ragged ``[2, num_gt]`` index pairs.
"""

import dataclasses
from typing import List, Optional, Sequence

import jax
import numpy as np


@dataclasses.dataclass
class Graph:
    """A single host-side graph (NumPy, ragged — the pre-padding form)."""
    edge_index: np.ndarray                 # [2, E] int
    x: Optional[np.ndarray] = None         # [N, C] float
    edge_attr: Optional[np.ndarray] = None  # [E, D] float
    pos: Optional[np.ndarray] = None       # [N, d] float
    y: Optional[np.ndarray] = None         # [N] int (keypoint classes etc.)
    face: Optional[np.ndarray] = None      # [3, F] int (Delaunay triangles)
    name: Optional[str] = None

    @property
    def num_nodes(self):
        if self.x is not None:
            return self.x.shape[0]
        if self.pos is not None:
            return self.pos.shape[0]
        return int(self.edge_index.max()) + 1 if self.edge_index.size else 0

    @property
    def num_edges(self):
        return self.edge_index.shape[1]


@dataclasses.dataclass
class GraphPair:
    """A (source, target) pair with an optional ground-truth column map:
    ``y_col[i]`` is the target node matched to source node ``i`` (or -1)."""
    s: Graph
    t: Graph
    y_col: Optional[np.ndarray] = None


class PairDataset:
    """All (or sampled) source x target combinations of two graph datasets.

    Mirrors the reference ``PairDataset`` semantics (reference
    ``dgmc/utils/data.py:19-60``): ``sample=False`` holds the full product;
    ``sample=True`` pairs each source with one uniformly random target per
    access.
    """

    def __init__(self, dataset_s, dataset_t, sample=False, seed=0):
        self.dataset_s = dataset_s
        self.dataset_t = dataset_t
        self.sample = sample
        self._rng = np.random.RandomState(seed)

    def __len__(self):
        if self.sample:
            return len(self.dataset_s)
        return len(self.dataset_s) * len(self.dataset_t)

    def __getitem__(self, idx):
        if self.sample:
            g_s = self.dataset_s[idx]
            g_t = self.dataset_t[self._rng.randint(len(self.dataset_t))]
        else:
            g_s = self.dataset_s[idx // len(self.dataset_t)]
            g_t = self.dataset_t[idx % len(self.dataset_t)]
        return GraphPair(s=g_s, t=g_t)

    def __repr__(self):
        return (f'{type(self).__name__}({self.dataset_s}, {self.dataset_t}, '
                f'sample={self.sample})')


class ValidPairDataset:
    """Pairs in which every source node class also occurs in the target,
    with the induced ground-truth map.

    Mirrors the reference ``ValidPairDataset`` (reference
    ``dgmc/utils/data.py:63-133``): validity is precomputed from per-graph
    class-membership bitmasks; the emitted ground truth maps each source
    node to the target node position holding the same class (reference
    ``data.py:115-117``).
    """

    def __init__(self, dataset_s, dataset_t, sample=False, seed=0):
        self.dataset_s = dataset_s
        self.dataset_t = dataset_t
        self.sample = sample
        self._rng = np.random.RandomState(seed)
        self.pairs, self.cumdeg = self._compute_pairs()

    def _compute_pairs(self):
        num_classes = 0
        for g in list(self.dataset_s) + list(self.dataset_t):
            if g.y is not None and g.y.size:
                num_classes = max(num_classes, int(g.y.max()) + 1)

        mask_s = np.zeros((len(self.dataset_s), num_classes), bool)
        mask_t = np.zeros((len(self.dataset_t), num_classes), bool)
        for i, g in enumerate(self.dataset_s):
            mask_s[i, g.y] = True
        for i, g in enumerate(self.dataset_t):
            mask_t[i, g.y] = True

        # (i, j) is valid iff classes(i) ⊆ classes(j).
        subset = (mask_s[:, None, :] & ~mask_t[None, :, :]).sum(-1) == 0
        pairs = np.argwhere(subset)
        counts = np.bincount(pairs[:, 0], minlength=len(self.dataset_s))
        cumdeg = np.concatenate([[0], np.cumsum(counts)])
        return pairs, cumdeg

    def __len__(self):
        return len(self.dataset_s) if self.sample else len(self.pairs)

    def __getitem__(self, idx):
        if self.sample:
            lo, hi = self.cumdeg[idx], self.cumdeg[idx + 1]
            if hi <= lo:
                raise IndexError(f'source graph {idx} has no valid partner')
            g_s = self.dataset_s[idx]
            g_t = self.dataset_t[self.pairs[self._rng.randint(lo, hi)][1]]
        else:
            i, j = self.pairs[idx]
            g_s = self.dataset_s[int(i)]
            g_t = self.dataset_t[int(j)]

        # Target position of each class, then look up the source classes.
        class_to_pos = np.full(int(g_t.y.max()) + 1, -1, np.int64)
        class_to_pos[g_t.y] = np.arange(g_t.num_nodes)
        y_col = class_to_pos[g_s.y]
        return GraphPair(s=g_s, t=g_t, y_col=y_col)

    def __repr__(self):
        return (f'{type(self).__name__}({self.dataset_s}, {self.dataset_t}, '
                f'sample={self.sample})')


# ---------------------------------------------------------------------------
# Padded collation (host-side; NumPy)
# ---------------------------------------------------------------------------


def pad_graphs(graphs: Sequence[Graph], num_nodes: int, num_edges: int,
               feat_dim: Optional[int] = None, native: str = 'auto'):
    """Collate host graphs into the arrays of a device ``GraphBatch``.

    ``native='auto'`` routes through the C++ collation engine
    (``dgmc_tpu/native``) when its shared library is available, falling back
    to the NumPy loop below; ``'never'`` forces NumPy (used by the parity
    tests), ``'require'`` errors if the library is missing.
    """
    from dgmc_tpu.ops import GraphBatch

    B = len(graphs)
    if feat_dim is None:
        feat_dim = next(g.x.shape[1] for g in graphs if g.x is not None)
    edge_dim = None
    for g in graphs:
        if g.edge_attr is not None:
            edge_dim = g.edge_attr.shape[1]
            break

    if native != 'never':
        from dgmc_tpu import native as native_mod
        out = native_mod.pad_graphs_native(graphs, num_nodes, num_edges,
                                           feat_dim, edge_dim)
        if out is not None:
            return GraphBatch(**out)
        if native == 'require':
            raise RuntimeError('native collation library unavailable')

    x = np.zeros((B, num_nodes, feat_dim), np.float32)
    senders = np.zeros((B, num_edges), np.int32)
    receivers = np.zeros((B, num_edges), np.int32)
    node_mask = np.zeros((B, num_nodes), bool)
    edge_mask = np.zeros((B, num_edges), bool)
    edge_attr = (np.zeros((B, num_edges, edge_dim), np.float32)
                 if edge_dim is not None else None)

    for b, g in enumerate(graphs):
        n, e = g.num_nodes, g.num_edges
        if n > num_nodes or e > num_edges:
            raise ValueError(f'graph {b} ({n} nodes / {e} edges) exceeds '
                             f'padding ({num_nodes} / {num_edges})')
        if g.x is not None:
            x[b, :n] = g.x
        senders[b, :e] = g.edge_index[0]
        receivers[b, :e] = g.edge_index[1]
        node_mask[b, :n] = True
        edge_mask[b, :e] = True
        if edge_attr is not None and g.edge_attr is not None:
            edge_attr[b, :e] = g.edge_attr

    return GraphBatch(x=x, senders=senders, receivers=receivers,
                      node_mask=node_mask, edge_mask=edge_mask,
                      edge_attr=edge_attr)


@dataclasses.dataclass
class PairBatch:
    """A device-ready batch of graph pairs."""
    s: 'GraphBatch'  # noqa: F821
    t: 'GraphBatch'  # noqa: F821
    y: Optional[np.ndarray] = None       # [B, N_s] int32, -1 where invalid
    y_mask: Optional[np.ndarray] = None  # [B, N_s] bool


# Registered as a pytree so a whole PairBatch can cross the jit boundary
# (and be donated / sharded) as one argument.
jax.tree_util.register_pytree_node(
    PairBatch,
    lambda b: ((b.s, b.t, b.y, b.y_mask), None),
    lambda _, children: PairBatch(*children))


def pad_pair_batch(pairs: List[GraphPair], num_nodes_s, num_edges_s,
                   num_nodes_t=None, num_edges_t=None, native: str = 'auto',
                   pairs_per_step: int = 1):
    """Collate :class:`GraphPair` lists into a :class:`PairBatch`.

    ``pairs_per_step > 1`` tiles the pair list that many times along the
    batch axis (``B = len(pairs) * pairs_per_step``) — the collation
    half of the ``--pairs-per-step`` batched hot loop. For single-pair
    workloads (DBP15K trains ONE huge pair) the replicas are the same
    graphs but draw independent per-pair indicator noise and negative
    samples on device (``DGMC`` folds its RNG streams per batch
    element), so one step averages ``pairs_per_step`` independent
    gradient samples while the MXU sees a real batch axis instead of
    B=1.
    """
    if pairs_per_step > 1:
        pairs = list(pairs) * pairs_per_step
    num_nodes_t = num_nodes_t or num_nodes_s
    num_edges_t = num_edges_t or num_edges_s
    # Telemetry: every distinct padding bucket is a distinct XLA program
    # for whatever jitted step consumes the batch — recording the bucket
    # per collation makes recompile churn from unstable padding visible
    # next to the compile-event counter (obs.report renders both). The
    # real (pre-padding) totals ride beside the bucket counter so pad
    # waste / goodput (obs.goodput) is recomputable from any recorded
    # obs dir, not just a live process.
    from dgmc_tpu.obs.registry import record_padding
    record_padding(batch=len(pairs),
                   nodes=f'{num_nodes_s}x{num_nodes_t}',
                   edges=f'{num_edges_s}x{num_edges_t}',
                   real={'nodes_s': sum(p.s.num_nodes for p in pairs),
                         'nodes_t': sum(p.t.num_nodes for p in pairs),
                         'edges_s': sum(p.s.num_edges for p in pairs),
                         'edges_t': sum(p.t.num_edges for p in pairs)})
    g_s = pad_graphs([p.s for p in pairs], num_nodes_s, num_edges_s,
                     native=native)
    g_t = pad_graphs([p.t for p in pairs], num_nodes_t, num_edges_t,
                     native=native)

    if native != 'never':
        from dgmc_tpu import native as native_mod
        out = native_mod.pad_ground_truth_native(
            [p.y_col for p in pairs], num_nodes_s)
        if out is not None:
            return PairBatch(s=g_s, t=g_t, y=out[0], y_mask=out[1])

    B = len(pairs)
    y = np.full((B, num_nodes_s), -1, np.int32)
    y_mask = np.zeros((B, num_nodes_s), bool)
    for b, p in enumerate(pairs):
        if p.y_col is not None:
            n = len(p.y_col)
            y[b, :n] = p.y_col
            y_mask[b, :n] = p.y_col >= 0
    return PairBatch(s=g_s, t=g_t, y=y, y_mask=y_mask)


def graph_limits(datasets):
    """Max node / edge counts across graph datasets — the static padding a
    :class:`PairLoader` needs so one XLA program serves every batch."""
    n = e = 1
    for ds in datasets:
        for i in range(len(ds)):
            g = ds[i]
            n = max(n, g.num_nodes)
            e = max(e, g.num_edges)
    return n, e


class ConcatDataset:
    """Concatenation of several pair datasets (the reference uses
    ``torch.utils.data.ConcatDataset`` across categories, reference
    ``examples/pascal.py:41``)."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        self._cum = np.cumsum([0] + [len(d) for d in self.datasets])

    def __len__(self):
        return int(self._cum[-1])

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        d = int(np.searchsorted(self._cum, idx, side='right')) - 1
        return self.datasets[d][idx - int(self._cum[d])]


class PairLoader:
    """Minimal shuffling batch iterator over a pair dataset, emitting
    fixed-shape :class:`PairBatch` es (one XLA program per loader).

    The fixed padding is computed once from the dataset (or given
    explicitly); the final short batch is dropped when ``drop_last`` else
    padded with repeated pairs and a zeroed ``y_mask``.
    """

    def __init__(self, dataset, batch_size, shuffle=True, seed=0,
                 num_nodes=None, num_edges=None, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self._rng = np.random.RandomState(seed)
        if num_nodes is None or num_edges is None:
            n_max = e_max = 1
            for i in range(len(dataset)):
                p = dataset[i]
                n_max = max(n_max, p.s.num_nodes, p.t.num_nodes)
                e_max = max(e_max, p.s.num_edges, p.t.num_edges)
            num_nodes = num_nodes or n_max
            num_edges = num_edges or e_max
        self.num_nodes = num_nodes
        self.num_edges = num_edges

    def __len__(self):
        n = len(self.dataset)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def __iter__(self):
        order = np.arange(len(self.dataset))
        if self.shuffle:
            self._rng.shuffle(order)
        for start in range(0, len(order), self.batch_size):
            chunk = order[start:start + self.batch_size]
            if len(chunk) < self.batch_size:
                if self.drop_last:
                    return
                # Repeat pairs to keep the shape static; mask out their GT.
                fill = np.resize(chunk, self.batch_size - len(chunk))
                pairs = [self.dataset[int(i)] for i in chunk]
                filler = [self.dataset[int(i)] for i in fill]
                batch = pad_pair_batch(pairs + filler, self.num_nodes,
                                       self.num_edges)
                batch.y_mask[len(chunk):] = False
                yield batch
                return
            yield pad_pair_batch([self.dataset[int(i)] for i in chunk],
                                 self.num_nodes, self.num_edges)


class PrefetchLoader:
    """Background-thread prefetch around any batch iterable: batch b+1 is
    collated on host while batch b trains on device — the role the
    reference delegates to torch DataLoader worker processes."""

    def __init__(self, loader, depth=2):
        self.loader = loader
        self.depth = depth

    def __len__(self):
        return len(self.loader)

    def __iter__(self):
        import queue
        import threading

        q = queue.Queue(maxsize=self.depth)
        DONE = object()
        stop = threading.Event()

        def put(item):
            # Bounded put that gives up when the consumer is gone, so an
            # abandoned iteration (break / exception) cannot pin the worker
            # thread and its queued batches for the process lifetime.
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                for batch in self.loader:
                    if not put(batch):
                        return
                put(DONE)
            except BaseException as e:  # surface errors on the consumer side
                put(e)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is DONE:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()
