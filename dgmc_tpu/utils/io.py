"""Atomic JSON writes, shared by every artifact the resilience loop
reads across process boundaries (recovery.json, heartbeat.json, fault
ledgers, checkpoint manifests): a reader must see either the previous
complete file or the new complete file, never a torn write — tmp file in
the same directory, then ``os.replace``.

Kept import-light on purpose (stdlib only): the supervisor's monitor
loop and the fault module use it in processes that must stay responsive
while a jax backend wedges.
"""

import hashlib
import json
import os

__all__ = ['write_json_atomic', 'sha256_file']


def sha256_file(path, chunk=1 << 20):
    """Chunked sha256 of one file — the manifest-integrity hash shared
    by checkpoint manifests (``train/checkpoint.py``) and the serving
    corpus cache (``serve/corpus.py``): ONE definition, so the two
    manifest disciplines can never silently diverge."""
    h = hashlib.sha256()
    with open(path, 'rb') as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def write_json_atomic(path, payload, *, indent=None, sort_keys=False,
                      quiet=False, default=None):
    """Write ``payload`` as JSON to ``path`` via tmp+rename (atomic on
    POSIX within one filesystem). Creates parent directories. With
    ``quiet=True`` an ``OSError`` is swallowed and reported as a
    ``False`` return — for telemetry writers that must never take the
    run down with them. ``default`` passes through to ``json.dump``
    (e.g. ``str`` for payloads that may carry arbitrary objects)."""
    tmp = f'{path}.tmp.{os.getpid()}'
    try:
        os.makedirs(os.path.dirname(path) or '.', exist_ok=True)
        with open(tmp, 'w') as f:
            json.dump(payload, f, indent=indent, sort_keys=sort_keys,
                      default=default)
        os.replace(tmp, path)
        return True
    except OSError:
        if quiet:
            return False
        raise
