"""Recompile-hazard pass: abstract step signatures across padding buckets.

Every distinct padding bucket the host-side collation emits
(``utils/data.pad_pair_batch`` — ``(batch, N_s x N_t, E_s x E_t)``) is a
distinct abstract signature for whatever jitted step consumes the batch,
i.e. one more XLA program: compile time, executable memory, and — with
donation in play — one more executable that must round-trip any
persistent cache correctly.

Two findings:

``RCP201`` avoidable-compile-churn
    A bucket is *dominated* by another (every padded dimension <= at the
    SAME pair-batch size): collating into the bigger bucket's padding
    would serve both batches with ONE program at the cost of a few
    masked rows. Dominated buckets are pure churn. The pair-batch axis
    (``B`` — ``--pairs-per-step`` replicas x pairs, PR 6's batched hot
    loop) is deliberately NOT a padding axis: padding ``B`` up
    replicates the entire per-pair cost (not a few masked rows) and
    changes how many independent gradient samples one step averages, so
    buckets that differ only in ``B`` are distinct programs by design,
    never churn.
``RCP202`` compile-churn-telemetry
    Cross-check against a recorded ``obs`` run (``--obs-dir``): the run
    compiled far more programs than its distinct padding buckets can
    explain — recompiles are coming from somewhere else (unstable static
    args, trace-time Python values, dtype flips), which the padding
    analysis alone cannot see.

The signature hash is over flattened ``(shape, dtype)`` leaves only — by
design the same thing jax's jit cache keys on for array arguments.
"""

import hashlib
import json
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

from dgmc_tpu.analysis.findings import Finding, Severity


def signature_of(avals: Sequence[Tuple[Tuple[int, ...], str]]) -> str:
    """Stable hash of a flattened abstract signature:
    ``[(shape, dtype), ...]``."""
    ident = ';'.join(f'{tuple(s)}:{d}' for s, d in avals)
    return hashlib.sha256(ident.encode()).hexdigest()[:16]


def pair_batch_avals(batch: int, nodes_s: int, nodes_t: int, edges_s: int,
                     edges_t: int, feat_dim: int = 32,
                     edge_dim: Optional[int] = None, dtype: str = 'float32',
                     ) -> List[Tuple[Tuple[int, ...], str]]:
    """The abstract leaves of a collated ``PairBatch`` for one padding
    bucket — mirrors ``utils/data.pad_pair_batch`` exactly (same arrays,
    same dtypes), without building a single array."""
    def side(n, e):
        leaves = [((batch, n, feat_dim), dtype),        # x
                  ((batch, e), 'int32'),                # senders
                  ((batch, e), 'int32'),                # receivers
                  ((batch, n), 'bool'),                 # node_mask
                  ((batch, e), 'bool')]                 # edge_mask
        if edge_dim:
            leaves.append(((batch, e, edge_dim), dtype))
        return leaves

    return (side(nodes_s, edges_s) + side(nodes_t, edges_t)
            + [((batch, nodes_s), 'int32'),             # y
               ((batch, nodes_s), 'bool')])             # y_mask


def bucket_signature(bucket: Dict) -> str:
    """Signature of one padding-bucket dict
    (``{batch, nodes: 'AxB', edges: 'CxD'}`` — the obs telemetry row
    format of ``registry.padding_bucket_table``)."""
    ns, nt = _split_pair(bucket['nodes'])
    es, et = _split_pair(bucket['edges'])
    return signature_of(pair_batch_avals(int(bucket['batch']), ns, nt,
                                         es, et))


def _split_pair(v) -> Tuple[int, int]:
    if isinstance(v, (list, tuple)):
        return int(v[0]), int(v[1])
    m = re.match(r'^(\d+)x(\d+)$', str(v))
    if m:
        return int(m.group(1)), int(m.group(2))
    n = int(v)
    return n, n


def _dims(bucket: Dict) -> Tuple[int, ...]:
    ns, nt = _split_pair(bucket['nodes'])
    es, et = _split_pair(bucket['edges'])
    return (int(bucket['batch']), ns, nt, es, et)


def _bucket_label(bucket: Dict) -> str:
    return (f'B={bucket["batch"]},nodes={bucket["nodes"]},'
            f'edges={bucket["edges"]}')


def analyze_buckets(buckets: Sequence[Dict], *, specimen='padding',
                    compile_events: Optional[int] = None,
                    programs_per_bucket: int = 8) -> List[Finding]:
    """Churn findings over padding-bucket rows.

    Args:
        buckets: rows of ``{batch, nodes, edges[, count]}`` (obs
            telemetry format).
        compile_events: compile-event count of a recorded run (obs
            ``timings.json``), for the RCP202 cross-check.
        programs_per_bucket: how many compiles one bucket legitimately
            feeds (train + eval + init + the nested op jits underneath;
            a clean 1-epoch obs-smoke run measures 5 for one bucket);
            the telemetry check allows ``distinct_signatures * this``
            before flagging.
    """
    findings = []
    dims = [(_dims(b), b) for b in buckets]
    for d, b in dims:
        # Domination holds the pair-batch axis fixed (od[0] == d[0]):
        # B is a structural axis — a B=1 batch cannot ride a B=2
        # program without doubling the step's work and changing its
        # gradient semantics — so only the node/edge PADDING axes are
        # collatable.
        dominators = [ob for od, ob in dims
                      if od != d and od[0] == d[0]
                      and all(x >= y for x, y in zip(od, d))]
        if dominators:
            dom = max(dominators, key=lambda ob: _dims(ob))
            findings.append(Finding(
                rule='RCP201', severity=Severity.WARNING,
                where=f'{specimen}:{_bucket_label(b)}',
                message=(f'padding bucket ({_bucket_label(b)}) is '
                         f'dominated by ({_bucket_label(dom)}) — '
                         f'collating into the larger padding removes '
                         f'one XLA program per consuming step'),
                detail=f'seen {b.get("count", "?")} time(s); each '
                       f'distinct bucket recompiles every jitted step '
                       f'that consumes the batch'))
    if compile_events is not None and buckets:
        distinct = len({bucket_signature(b) for b in buckets})
        budget = max(1, distinct) * programs_per_bucket
        if compile_events > budget:
            findings.append(Finding(
                rule='RCP202', severity=Severity.WARNING,
                where=f'{specimen}:telemetry',
                message=(f'{compile_events} compile events for '
                         f'{distinct} distinct padding signature(s) '
                         f'(budget {budget}) — recompiles not explained '
                         f'by padding (unstable static args / trace-time '
                         f'Python values?)'),
                detail='cross-checked against obs compile telemetry '
                       '(timings.json compile.events)'))
    return findings


def load_obs_buckets(obs_dir: str) -> Tuple[List[Dict], Optional[int]]:
    """``(padding_bucket_rows, compile_events)`` from a recorded obs run
    directory (``timings.json``); ``([], None)`` when absent."""
    path = os.path.join(obs_dir, 'timings.json')
    if not os.path.exists(path):
        return [], None
    with open(path) as f:
        t = json.load(f)
    events = (t.get('compile') or {}).get('events')
    return list(t.get('padding_buckets') or []), events
