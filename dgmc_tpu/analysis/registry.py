"""Hot-function registry: what the trace tier lowers, and under which
shape/dtype/mesh configs.

Each :class:`Specimen` names one jit boundary the production pipeline
actually crosses — the DGMC forward (dense and sparse top-k), the
donating train step and the eval step from ``train/steps.py``, the fused
ops underneath them, and (devices permitting) the GSPMD-sharded donating
train step from ``parallel/sharding.py`` — the exact configuration of
the jax-0.4.37 persistent-cache aliasing bug (PR 3).

Probes are forced OFF while specimens trace: the lint asserts the
probes-disabled contract (zero host callbacks in the lowered step,
extending PR 3's byte-identical-HLO guarantee to a static CI check), so
a probe-enabled lint process must not leak callbacks into the programs
under analysis.

Shapes are deliberately tiny (the smallest sizes the model accepts):
every hazard the rules detect — dtype introduction, callbacks, dropped
aliasing, scatter forms — is shape-independent, and small specimens keep
``dgmc-lint`` fast enough to run on every CI push.
"""

import contextlib
import dataclasses
from typing import Callable, Dict, List, Optional

import numpy as np

from dgmc_tpu.analysis.findings import Finding
from dgmc_tpu.analysis.jaxpr_rules import (TraceContext, analyze_closed_jaxpr,
                                           analyze_donation)


@dataclasses.dataclass
class Specimen:
    """One registered hot function + config.

    ``build()`` returns ``{'fn': callable, 'args': tuple}`` plus
    optional ``'donate_argnums'`` (tuple — run the donation-aliasing
    rule) and ``'expect_no_callbacks'`` (default True).
    """
    name: str
    build: Callable[[], Dict]
    #: None = always runnable; else the minimum jax.devices() count.
    min_devices: int = 0


@contextlib.contextmanager
def probes_forced_off():
    """Trace-time: probe call sites must not lower into specimens."""
    from dgmc_tpu.obs import probes
    prev = probes.enabled()
    probes.disable()
    try:
        yield
    finally:
        if prev:
            probes.enable()


# ---------------------------------------------------------------------------
# Tiny concrete fixtures (host-side numpy; shapes are the minimum the
# model accepts)
# ---------------------------------------------------------------------------


def _graph_side(rng, n, e, c=4):
    from dgmc_tpu.ops.graph import GraphBatch
    return GraphBatch(
        x=rng.randn(1, n, c).astype(np.float32),
        senders=rng.randint(0, n, (1, e)).astype(np.int32),
        receivers=rng.randint(0, n, (1, e)).astype(np.int32),
        node_mask=np.ones((1, n), bool),
        edge_mask=np.ones((1, e), bool),
        edge_attr=None)


def _pair_batch(rng, n_s=8, e_s=16, n_t=10, e_t=20):
    from dgmc_tpu.utils.data import PairBatch
    return PairBatch(
        s=_graph_side(rng, n_s, e_s), t=_graph_side(rng, n_t, e_t),
        y=(np.arange(n_s, dtype=np.int32) % n_t)[None],
        y_mask=np.ones((1, n_s), bool))


def _model_state_batch(k, num_steps=2):
    import jax
    from dgmc_tpu.models import DGMC, RelCNN
    from dgmc_tpu.train import create_train_state
    rng = np.random.RandomState(0)
    batch = _pair_batch(rng)
    model = DGMC(RelCNN(4, 8, num_layers=1), RelCNN(4, 4, num_layers=1),
                 num_steps=num_steps, k=k)
    state = create_train_state(model, jax.random.key(0), batch,
                               learning_rate=1e-3)
    return model, state, batch


def _forward_specimen(k):
    def build():
        import jax
        model, state, batch = _model_state_batch(k)

        def forward(params, batch, key):
            return model.apply({'params': params}, batch.s, batch.t,
                               train=False, rngs={'noise': key})

        return {'fn': forward,
                'args': (state.params, batch, jax.random.key(1))}
    return build


def _train_step_specimen(k):
    def build():
        import jax
        from dgmc_tpu.train import make_train_step
        model, state, batch = _model_state_batch(k)
        step = make_train_step(model, jit=False)
        return {'fn': step,
                'args': (state, batch, jax.random.key(1)),
                'donate_argnums': (0,)}
    return build


def _eval_step_specimen():
    def build():
        import jax
        from dgmc_tpu.train import make_eval_step
        model, state, batch = _model_state_batch(k=-1)
        step = make_eval_step(model, jit=False)
        return {'fn': step, 'args': (state, batch, jax.random.key(1))}
    return build


def _topk_specimen(dtype):
    def build():
        from dgmc_tpu.ops.topk import chunked_topk
        rng = np.random.RandomState(1)
        h_s = rng.randn(1, 16, 8).astype(dtype)
        h_t = rng.randn(1, 24, 8).astype(dtype)

        def topk(h_s, h_t):
            return chunked_topk(h_s, h_t, 4, block=8, pallas=False)

        return {'fn': topk, 'args': (h_s, h_t)}
    return build


def _softmax_specimen():
    def build():
        from dgmc_tpu.ops.softmax import masked_softmax
        rng = np.random.RandomState(2)
        s = rng.randn(1, 8, 10).astype(np.float32)
        mask = np.ones((1, 8, 10), bool)
        return {'fn': masked_softmax, 'args': (s, mask)}
    return build


def _segment_specimen():
    def build():
        from dgmc_tpu.ops.segment import segment_sum
        rng = np.random.RandomState(3)
        vals = rng.randn(1, 16, 4).astype(np.float32)
        idx = rng.randint(0, 8, (1, 16)).astype(np.int32)

        def seg(vals, idx):
            return segment_sum(vals, idx, 8)

        return {'fn': seg, 'args': (vals, idx)}
    return build


def _sharded_train_step_specimen():
    def build():
        import jax
        from dgmc_tpu.parallel import make_mesh, replicate, shard_batch
        from dgmc_tpu.parallel.sharding import make_sharded_train_step
        n_data = 2
        from dgmc_tpu.utils.data import PairBatch
        one = _pair_batch(np.random.RandomState(0))
        batch = PairBatch(
            s=jax.tree.map(lambda x: np.repeat(x, n_data, 0), one.s),
            t=jax.tree.map(lambda x: np.repeat(x, n_data, 0), one.t),
            y=np.repeat(one.y, n_data, 0),
            y_mask=np.repeat(one.y_mask, n_data, 0))
        from dgmc_tpu.models import DGMC, RelCNN
        from dgmc_tpu.train import create_train_state
        model = DGMC(RelCNN(4, 8, num_layers=1), RelCNN(4, 4, num_layers=1),
                     num_steps=1, k=-1)
        state = create_train_state(model, jax.random.key(0), one,
                                   learning_rate=1e-3)
        mesh = make_mesh(data=n_data, model=1,
                         devices=jax.devices()[:n_data])
        step = make_sharded_train_step(model, mesh)
        return {'fn': step,
                'args': (replicate(state, mesh), shard_batch(batch, mesh),
                         jax.random.key(1)),
                'prejitted': True,
                'donate_argnums': (0,)}
    return build


def default_specimens() -> List[Specimen]:
    """The registered hot-function matrix (order = report order)."""
    return [
        Specimen('forward_dense', _forward_specimen(k=-1)),
        Specimen('forward_sparse_k3', _forward_specimen(k=3)),
        Specimen('train_step_dense', _train_step_specimen(k=-1)),
        Specimen('train_step_sparse_k3', _train_step_specimen(k=3)),
        Specimen('eval_step_dense', _eval_step_specimen()),
        Specimen('ops.chunked_topk_f32', _topk_specimen(np.float32)),
        Specimen('ops.masked_softmax', _softmax_specimen()),
        Specimen('ops.segment_sum', _segment_specimen()),
        Specimen('parallel.sharded_train_step',
                 _sharded_train_step_specimen(), min_devices=2),
    ]


def run_specimen(spec: Specimen, *, const_bytes=None) -> List[Finding]:
    """Trace + (when donating) compile one specimen and run every
    trace-tier rule over it."""
    import jax
    kw = {}
    if const_bytes is not None:
        kw['const_bytes'] = const_bytes
    with probes_forced_off():
        built = spec.build()
        fn, args = built['fn'], built['args']
        ctx = TraceContext(specimen=spec.name, **kw)
        if built.get('prejitted'):
            # Already a jitted callable (e.g. the sharded step with its
            # in_shardings): trace through its wrapper for the jaxpr
            # rules, and reuse its own lowering for donation.
            closed = jax.make_jaxpr(lambda *a: fn(*a))(*args)
        else:
            closed = jax.make_jaxpr(fn)(*args)
        findings = analyze_closed_jaxpr(closed, ctx)
        donate = built.get('donate_argnums')
        if donate:
            if built.get('prejitted'):
                findings += _donation_of_prejitted(fn, args, donate,
                                                  spec.name)
            else:
                findings += analyze_donation(fn, args,
                                             donate_argnums=donate,
                                             specimen=spec.name)
    return findings


def _donation_of_prejitted(fn, args, donate, specimen) -> List[Finding]:
    import warnings
    from dgmc_tpu.analysis.jaxpr_rules import compiled_donation_findings
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter('always')
        compiled = fn.lower(*args).compile()
    return compiled_donation_findings(caught, compiled, donate, specimen)


def run_trace_tier(specimens: Optional[List[Specimen]] = None, *,
                   const_bytes=None,
                   on_progress: Optional[Callable[[str], None]] = None,
                   skipped: Optional[List[str]] = None) -> List[Finding]:
    """Run every runnable specimen; skips mesh specimens when the
    process has too few devices (reported via ``on_progress``, and
    appended to ``skipped`` when given — baseline writers use that to
    preserve the skipped specimens' prior entries)."""
    import jax
    findings = []
    n_dev = len(jax.devices())
    for spec in (specimens if specimens is not None
                 else default_specimens()):
        if spec.min_devices and n_dev < spec.min_devices:
            if on_progress:
                on_progress(f'skip {spec.name} '
                            f'(needs >= {spec.min_devices} devices, '
                            f'have {n_dev})')
            if skipped is not None:
                skipped.append(spec.name)
            continue
        if on_progress:
            on_progress(f'trace {spec.name}')
        findings.extend(run_specimen(spec, const_bytes=const_bytes))
    return findings
