"""Hot-function registry: what the trace tier lowers, and under which
shape/dtype/mesh configs.

Each :class:`Specimen` names one jit boundary the production pipeline
actually crosses — the DGMC forward (dense and sparse top-k), the
donating train step and the eval step from ``train/steps.py``, the fused
ops underneath them, and (devices permitting) the GSPMD-sharded donating
train step from ``parallel/sharding.py`` — the exact configuration of
the jax-0.4.37 persistent-cache aliasing bug (PR 3).

Probes are forced OFF while specimens trace: the lint asserts the
probes-disabled contract (zero host callbacks in the lowered step,
extending PR 3's byte-identical-HLO guarantee to a static CI check), so
a probe-enabled lint process must not leak callbacks into the programs
under analysis.

Shapes are deliberately tiny (the smallest sizes the model accepts):
every hazard the rules detect — dtype introduction, callbacks, dropped
aliasing, scatter forms — is shape-independent, and small specimens keep
``dgmc-lint`` fast enough to run on every CI push.
"""

import contextlib
import dataclasses
import warnings
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from dgmc_tpu.analysis.findings import Finding
from dgmc_tpu.analysis.jaxpr_rules import (TraceContext,
                                           analyze_closed_jaxpr,
                                           compiled_donation_findings)


@dataclasses.dataclass
class Specimen:
    """One registered hot function + config.

    ``build()`` returns ``{'fn': callable, 'args': tuple}`` plus
    optional ``'donate_argnums'`` (tuple — run the donation-aliasing
    rule), ``'prejitted'`` (the callable is already jitted, e.g. with
    its own ``in_shardings``), ``'corr_bytes'`` (full correspondence-
    matrix payload in bytes — arms the SHD302 replication rule),
    ``'comm_budget_bytes'`` (per-step collective-byte budget — arms
    SHD304, recorded here like the recompile pass's compiles-per-bucket
    budget), ``'overlap_budget'`` (minimum modeled collective overlap
    fraction — arms SCH402), ``'peak_bytes_budget'`` (static peak-live
    byte budget — arms MEM404), and ``'stream_full'``/``'stream_chunk'``
    (the streamed axis and its chunk — arm the MEM405 residual
    accounting).
    """
    name: str
    build: Callable[[], Dict]
    #: None = always runnable; else the minimum jax.devices() count.
    min_devices: int = 0
    #: Which lint tiers analyze this specimen: ``'trace'`` (jaxpr +
    #: donation rules), ``'shd'`` (post-GSPMD sharded-HLO rules), and/or
    #: ``'sched'`` (schedule & liveness rules over the same compiled
    #: text).
    tiers: Tuple[str, ...] = ('trace',)


@contextlib.contextmanager
def probes_forced_off():
    """Trace-time: probe call sites must not lower into specimens."""
    from dgmc_tpu.obs import probes
    prev = probes.enabled()
    probes.disable()
    try:
        yield
    finally:
        if prev:
            probes.enable()


# ---------------------------------------------------------------------------
# Tiny concrete fixtures (host-side numpy; shapes are the minimum the
# model accepts)
# ---------------------------------------------------------------------------


def _graph_side(rng, n, e, c=4):
    from dgmc_tpu.ops.graph import GraphBatch
    return GraphBatch(
        x=rng.randn(1, n, c).astype(np.float32),
        senders=rng.randint(0, n, (1, e)).astype(np.int32),
        receivers=rng.randint(0, n, (1, e)).astype(np.int32),
        node_mask=np.ones((1, n), bool),
        edge_mask=np.ones((1, e), bool),
        edge_attr=None)


def _pair_batch(rng, n_s=8, e_s=16, n_t=10, e_t=20):
    from dgmc_tpu.utils.data import PairBatch
    return PairBatch(
        s=_graph_side(rng, n_s, e_s), t=_graph_side(rng, n_t, e_t),
        y=(np.arange(n_s, dtype=np.int32) % n_t)[None],
        y_mask=np.ones((1, n_s), bool))


def _model_state_batch(k, num_steps=2):
    import jax
    from dgmc_tpu.models import DGMC, RelCNN
    from dgmc_tpu.train import create_train_state
    rng = np.random.RandomState(0)
    batch = _pair_batch(rng)
    model = DGMC(RelCNN(4, 8, num_layers=1), RelCNN(4, 4, num_layers=1),
                 num_steps=num_steps, k=k)
    state = create_train_state(model, jax.random.key(0), batch,
                               learning_rate=1e-3)
    return model, state, batch


def _forward_specimen(k):
    def build():
        import jax
        model, state, batch = _model_state_batch(k)

        def forward(params, batch, key):
            return model.apply({'params': params}, batch.s, batch.t,
                               train=False, rngs={'noise': key})

        return {'fn': forward,
                'args': (state.params, batch, jax.random.key(1))}
    return build


def _train_step_specimen(k):
    def build():
        import jax
        from dgmc_tpu.train import make_train_step
        model, state, batch = _model_state_batch(k)
        step = make_train_step(model, jit=False)
        return {'fn': step,
                'args': (state, batch, jax.random.key(1)),
                'donate_argnums': (0,)}
    return build


def _eval_step_specimen():
    def build():
        import jax
        from dgmc_tpu.train import make_eval_step
        model, state, batch = _model_state_batch(k=-1)
        step = make_eval_step(model, jit=False)
        return {'fn': step, 'args': (state, batch, jax.random.key(1))}
    return build


def _topk_specimen(dtype):
    def build():
        from dgmc_tpu.ops.topk import chunked_topk
        rng = np.random.RandomState(1)
        h_s = rng.randn(1, 16, 8).astype(dtype)
        h_t = rng.randn(1, 24, 8).astype(dtype)

        def topk(h_s, h_t):
            return chunked_topk(h_s, h_t, 4, block=8, pallas=False)

        return {'fn': topk, 'args': (h_s, h_t)}
    return build


def _softmax_specimen():
    def build():
        from dgmc_tpu.ops.softmax import masked_softmax
        rng = np.random.RandomState(2)
        s = rng.randn(1, 8, 10).astype(np.float32)
        mask = np.ones((1, 8, 10), bool)
        return {'fn': masked_softmax, 'args': (s, mask)}
    return build


def _segment_specimen():
    def build():
        from dgmc_tpu.ops.segment import segment_sum
        rng = np.random.RandomState(3)
        vals = rng.randn(1, 16, 4).astype(np.float32)
        idx = rng.randint(0, 8, (1, 16)).astype(np.int32)

        def seg(vals, idx):
            return segment_sum(vals, idx, 8)

        return {'fn': seg, 'args': (vals, idx)}
    return build


def _sharded_train_step_specimen():
    def build():
        import jax
        from dgmc_tpu.parallel import make_mesh, replicate, shard_batch
        from dgmc_tpu.parallel.sharding import make_sharded_train_step
        n_data = 2
        from dgmc_tpu.utils.data import PairBatch
        one = _pair_batch(np.random.RandomState(0))
        batch = PairBatch(
            s=jax.tree.map(lambda x: np.repeat(x, n_data, 0), one.s),
            t=jax.tree.map(lambda x: np.repeat(x, n_data, 0), one.t),
            y=np.repeat(one.y, n_data, 0),
            y_mask=np.repeat(one.y_mask, n_data, 0))
        from dgmc_tpu.models import DGMC, RelCNN
        from dgmc_tpu.train import create_train_state
        model = DGMC(RelCNN(4, 8, num_layers=1), RelCNN(4, 4, num_layers=1),
                     num_steps=1, k=-1)
        state = create_train_state(model, jax.random.key(0), one,
                                   learning_rate=1e-3)
        mesh = make_mesh(data=n_data, model=1,
                         devices=jax.devices()[:n_data])
        step = make_sharded_train_step(model, mesh)
        return {'fn': step,
                'args': (replicate(state, mesh), shard_batch(batch, mesh),
                         jax.random.key(1)),
                'prejitted': True,
                'donate_argnums': (0,)}
    return build


def _sharded_forward_rows_specimen():
    """Row-sharded S forward (ROADMAP item 3's layout): the dense DGMC
    forward with the correspondence matrix constrained to
    ``corr_spec()`` — batch over ``data``, source-node rows over
    ``model`` — compiled on a ``data=2 x model=2`` mesh. The SHD tier
    watches its partitioned HLO for an all-gather that would silently
    re-materialize the full ``[B, N_s, N_t]`` S it is supposed to keep
    sharded (SHD302)."""
    def build():
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from dgmc_tpu.models import DGMC, RelCNN
        from dgmc_tpu.parallel import make_mesh
        from dgmc_tpu.parallel.mesh import corr_sharding
        from dgmc_tpu.train import create_train_state
        from dgmc_tpu.utils.data import PairBatch
        one = _pair_batch(np.random.RandomState(0))
        n_data = 2
        batch = PairBatch(
            s=jax.tree.map(lambda x: np.repeat(x, n_data, 0), one.s),
            t=jax.tree.map(lambda x: np.repeat(x, n_data, 0), one.t),
            y=np.repeat(one.y, n_data, 0),
            y_mask=np.repeat(one.y_mask, n_data, 0))
        mesh = make_mesh(data=2, model=2, devices=jax.devices()[:4])
        model = DGMC(RelCNN(4, 8, num_layers=1),
                     RelCNN(4, 4, num_layers=1),
                     num_steps=1, k=-1,
                     corr_sharding=corr_sharding(mesh))
        # Init under the FULL batch: the corr constraint pins B to the
        # data-axis size, so a B=1 init batch cannot trace.
        state = create_train_state(model, jax.random.key(0), batch,
                                   learning_rate=1e-3)
        repl = NamedSharding(mesh, P())
        batched = NamedSharding(mesh, P('data'))

        def forward(params, batch, key):
            return model.apply({'params': params}, batch.s, batch.t,
                               train=False, rngs={'noise': key})

        step = jax.jit(forward, in_shardings=(repl, batched, repl))
        # The full-S payload this layout must never materialize,
        # derived from the batch itself so it tracks fixture-shape
        # changes: [B, N_s, N_t] x f32.
        b, n_s = batch.y.shape
        n_t = batch.t.x.shape[1]
        return {'fn': step,
                'args': (jax.device_put(state.params, repl),
                         jax.device_put(batch, batched),
                         jax.device_put(jax.random.key(1), repl)),
                'prejitted': True,
                'corr_bytes': b * n_s * n_t * 4,
                'comm_budget_bytes': 1 << 20}
    return build


def _sharded_train_step_pairs_specimen():
    """Pairs-per-step >= 2 donating train step on the full
    ``data x model`` mesh — the exact program family of the rc:124
    multichip hangs (ROADMAP item 1: the ``data=4, model=2`` path).
    ``B = 4`` = 2 pair replicas x 2 data shards, matching the
    ``--pairs-per-step 2`` collation of ``utils/data.pad_pair_batch``."""
    def build():
        import jax

        from dgmc_tpu.models import DGMC, RelCNN
        from dgmc_tpu.parallel import make_mesh, replicate, shard_batch
        from dgmc_tpu.parallel.sharding import make_sharded_train_step
        from dgmc_tpu.train import create_train_state
        from dgmc_tpu.utils.data import PairBatch
        one = _pair_batch(np.random.RandomState(0))
        reps = 4
        batch = PairBatch(
            s=jax.tree.map(lambda x: np.repeat(x, reps, 0), one.s),
            t=jax.tree.map(lambda x: np.repeat(x, reps, 0), one.t),
            y=np.repeat(one.y, reps, 0),
            y_mask=np.repeat(one.y_mask, reps, 0))
        model = DGMC(RelCNN(4, 8, num_layers=1),
                     RelCNN(4, 4, num_layers=1), num_steps=1, k=-1)
        state = create_train_state(model, jax.random.key(0), one,
                                   learning_rate=1e-3)
        mesh = make_mesh(data=2, model=2, devices=jax.devices()[:4])
        step = make_sharded_train_step(model, mesh)
        return {'fn': step,
                'args': (replicate(state, mesh),
                         shard_batch(batch, mesh), jax.random.key(1)),
                'prejitted': True,
                'donate_argnums': (0,),
                'comm_budget_bytes': 1 << 20}
    return build


def _streamed_train_step_specimen():
    """Streamed-S donating train step (ROADMAP item 3's million-entity
    layout at fixture scale): the partition-rule config from
    ``parallel/rules.streamed_rules`` — S/shortlist/ψ₂-source rows
    sharded over ``data``, candidate search streamed over source chunks
    — compiled on a 4-device data mesh. Its declared ``corr_bytes`` is
    the full dense ``[B, N_s, N_t]`` S the design must never
    materialize (SHD302: an all-gather that size is the defeat), and
    ``comm_budget_bytes`` pins the per-step collective payload (SHD304)
    so communication growth in the streamed path fails
    ``--fail-on new``. Budget basis: the compiled fixture program moves
    ~7.5 KiB of collectives per step — 30 all-reduces (grad psums,
    7.19 KiB) + 8 shard-boundary collective-permutes (320 B), measured
    via ``python -m dgmc_tpu.obs.cost --specimens
    parallel.streamed_train_step``; 64 KiB holds ~8x headroom for
    layout jitter while still failing on a structural regression (one
    extra all-gathered activation at fixture scale adds tens of KiB;
    an S-sized replication additionally trips SHD302).

    Schedule & liveness budgets (the SCH402/MEM404/MEM405 face of
    ROADMAP item 4, measured via ``python -m dgmc_tpu.analysis.
    hlo_sched --specimens parallel.streamed_train_step``): since the
    chunk-pipeline rewrite, the fixture compiles the PHASE-2 refinement
    step the scale rounds actually spend their wall clock in
    (``detach=True`` — ψ₁ frozen, exactly ``dbp15k.py``'s streamed
    phase-2 builder), with the double-buffered chunk scan and the
    ring-rotated target shards (``streamed_rules`` defaults): the
    boundary ``collective-permute`` rides the loop carry one rotation
    ahead of the compute that consumes it, and the trip-amplified
    schedule model measures **0.3118** collective overlap (the
    single-buffered, replicated-target ancestor modeled 0.1353), so
    ``overlap_budget=0.24`` — 2x the pre-rewrite 0.12 pin, with ~30%
    headroom — fails CI the moment an edit re-serializes the loop or
    drops the ring. The static peak-live bound is **27,232 B**, so
    ``peak_bytes_budget=40 KiB`` (~1.5x headroom) fails on a
    structural blowup — the fixture-scale face of the SCALE_r07/r08
    per-device memory claims. ``stream_full``/``stream_chunk`` mirror
    the ``streamed_rules(stream_chunk=8)`` config over the n_s=16
    source axis, arming MEM405's residual accounting with
    ``residual_min_bytes=4 KiB`` (largest legitimate carry — the ring
    target buffer + the prefetched chunk slot — stays well under 2 KiB
    at this scale). ``double_buffer_min_bytes=128`` is now LOW on
    purpose: the per-iteration fetches here are a few hundred bytes,
    and with the floor armed SCH403 stays SILENT only because the
    rewritten loops keep every fetch off the carry-chained critical
    path — a regression to the serial shape fires it (pinned by
    ``tests/analysis/test_sched_rules.py``)."""
    def build():
        import jax

        from dgmc_tpu.models import DGMC, RelCNN
        from dgmc_tpu.parallel import make_mesh, streamed_rules
        from dgmc_tpu.parallel.sharding import make_sharded_train_step
        from dgmc_tpu.train import create_train_state
        one = _pair_batch(np.random.RandomState(0), n_s=16, e_s=32,
                          n_t=32, e_t=64)
        model = DGMC(RelCNN(4, 8, num_layers=1),
                     RelCNN(4, 4, num_layers=1), num_steps=1, k=4)
        state = create_train_state(model, jax.random.key(0), one,
                                   learning_rate=1e-3)
        mesh = make_mesh(data=4, model=1, devices=jax.devices()[:4])
        rules = streamed_rules(stream_chunk=8)
        step = make_sharded_train_step(model, mesh, num_steps=1,
                                       detach=True, rules=rules,
                                       state=state)
        state_sh, batch_sh = rules.place(state, one, mesh)
        b, n_s = one.y.shape
        n_t = one.t.x.shape[1]
        return {'fn': step,
                'args': (state_sh, batch_sh, jax.random.key(1)),
                'prejitted': True,
                'donate_argnums': (0,),
                'corr_bytes': b * n_s * n_t * 4,
                'comm_budget_bytes': 64 << 10,
                'overlap_budget': 0.24,
                'peak_bytes_budget': 40 << 10,
                'stream_full': n_s,
                'stream_chunk': 8,
                'residual_min_bytes': 4 << 10,
                'double_buffer_min_bytes': 128}
    return build


def _sharded_topk_cols_specimen():
    """``parallel/topk.py`` distributed top-k, column-sharded: local
    blockwise top-k per shard + one candidate all_gather. Its declared
    ``corr_bytes`` is the ``N_s x N_t`` score matrix the design must
    never materialize — an all-gather that big is exactly the defeat
    SHD302 exists to catch."""
    def build():
        import jax

        from dgmc_tpu.parallel import make_mesh
        from dgmc_tpu.parallel.topk import sharded_topk_cols
        rng = np.random.RandomState(1)
        h_s = rng.randn(1, 16, 8).astype(np.float32)
        h_t = rng.randn(1, 24, 8).astype(np.float32)
        mesh = make_mesh(data=1, model=2, devices=jax.devices()[:2])

        def topk(h_s, h_t):
            return sharded_topk_cols(mesh, h_s, h_t, 4, block=8)

        return {'fn': topk, 'args': (h_s, h_t),
                'corr_bytes':
                    h_s.shape[0] * h_s.shape[1] * h_t.shape[1] * 4,
                'comm_budget_bytes': 64 << 10}
    return build


def default_specimens() -> List[Specimen]:
    """The registered hot-function matrix (order = report order).

    The multi-device specimens registered for the ``shd`` tier only do
    not feed the trace tier: their jaxpr-level content duplicates the
    single-device specimens' (same model code), and keeping them out of
    the trace tier keeps the baseline's TRC entries stable while the
    SHD tier grows."""
    return [
        Specimen('forward_dense', _forward_specimen(k=-1)),
        Specimen('forward_sparse_k3', _forward_specimen(k=3)),
        Specimen('train_step_dense', _train_step_specimen(k=-1)),
        Specimen('train_step_sparse_k3', _train_step_specimen(k=3)),
        Specimen('eval_step_dense', _eval_step_specimen()),
        Specimen('ops.chunked_topk_f32', _topk_specimen(np.float32)),
        Specimen('ops.masked_softmax', _softmax_specimen()),
        Specimen('ops.segment_sum', _segment_specimen()),
        Specimen('parallel.sharded_train_step',
                 _sharded_train_step_specimen(), min_devices=2,
                 tiers=('trace', 'shd', 'sched')),
        Specimen('parallel.sharded_forward_rows',
                 _sharded_forward_rows_specimen(), min_devices=4,
                 tiers=('shd', 'sched')),
        Specimen('parallel.sharded_train_step_pairs2',
                 _sharded_train_step_pairs_specimen(), min_devices=4,
                 tiers=('shd', 'sched')),
        Specimen('parallel.streamed_train_step',
                 _streamed_train_step_specimen(), min_devices=4,
                 tiers=('shd', 'sched')),
        Specimen('parallel.sharded_topk_cols',
                 _sharded_topk_cols_specimen(), min_devices=2,
                 tiers=('shd', 'sched')),
    ]


def iter_runnable_specimens(tier, *, names=None, specimens=None,
                            on_progress=None, skipped=None):
    """The one specimen-selection loop every compiled tier shares:
    yields each registered specimen belonging to ``tier`` that this
    process has enough devices for, reporting skips via ``on_progress``
    and appending them to ``skipped`` (the baseline writers'
    preservation signal). ``names`` optionally restricts to a name set
    (the report CLIs' ``--specimens``). One implementation — the SCH/MEM
    tier driver and the schedule-report artifact must never disagree
    about WHICH programs were analyzed."""
    import jax
    n_dev = len(jax.devices())
    for spec in (specimens if specimens is not None
                 else default_specimens()):
        if tier not in spec.tiers:
            continue
        if names is not None and spec.name not in names:
            continue
        if spec.min_devices and n_dev < spec.min_devices:
            if on_progress:
                on_progress(f'skip {spec.name} (needs >= '
                            f'{spec.min_devices} devices, have {n_dev})')
            if skipped is not None and spec.name not in skipped:
                skipped.append(spec.name)
            continue
        yield spec


class SpecimenArtifacts:
    """Per-lint-run shared build/trace/lower/compile of one specimen.

    Every tier that looks at the same program pulls its view from here:
    the trace tier reads :meth:`closed_jaxpr`, the donation rule and
    the SHD tier read :meth:`compiled` (plus the warnings captured on
    the way — jax reports unusable donations at lowering time). Each
    stage runs AT MOST ONCE per lint process however many tiers ask —
    pinned by the compile-count test
    (``tests/analysis/test_lowering_cache.py``); before this cache the
    trace tier and the sharded analyses each traced and compiled their
    own copy of every donating specimen."""

    def __init__(self, spec: Specimen):
        self.spec = spec
        self.stats = {'builds': 0, 'traces': 0, 'lowerings': 0,
                      'compiles': 0}
        #: Warnings captured during lowering + compile (the donation
        #: rule reads these).
        self.warnings = []
        self._built = None
        self._jitted = None
        self._traced = None
        self._lowered = None
        self._compiled = None

    def built(self) -> Dict:
        if self._built is None:
            with probes_forced_off():
                self._built = self.spec.build()
            self.stats['builds'] += 1
        return self._built

    def _jit(self):
        if self._jitted is None:
            import jax
            built = self.built()
            if built.get('prejitted'):
                self._jitted = built['fn']
            else:
                donate = tuple(built.get('donate_argnums') or ())
                self._jitted = jax.jit(built['fn'],
                                       donate_argnums=donate)
        return self._jitted

    def traced(self):
        """``jax.stages.Traced`` — ONE trace serves both the jaxpr view
        (``.jaxpr``) and the lowering."""
        if self._traced is None:
            with probes_forced_off():
                self._traced = self._jit().trace(*self.built()['args'])
            self.stats['traces'] += 1
        return self._traced

    def closed_jaxpr(self):
        return self.traced().jaxpr

    def lowered(self):
        if self._lowered is None:
            with probes_forced_off(), \
                    warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter('always')
                self._lowered = self.traced().lower()
            self.warnings.extend(caught)
            self.stats['lowerings'] += 1
        return self._lowered

    def compiled(self):
        if self._compiled is None:
            lowered = self.lowered()
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter('always')
                self._compiled = lowered.compile()
            self.warnings.extend(caught)
            self.stats['compiles'] += 1
        return self._compiled


class SpecimenCache:
    """Shared :class:`SpecimenArtifacts` across lint tiers: one
    build/trace/lower/compile per specimen per lint run."""

    def __init__(self):
        self._arts: Dict[str, SpecimenArtifacts] = {}

    def artifacts(self, spec: Specimen) -> SpecimenArtifacts:
        art = self._arts.get(spec.name)
        if art is None:
            art = self._arts[spec.name] = SpecimenArtifacts(spec)
        return art

    def stats(self) -> Dict[str, Dict[str, int]]:
        return {name: dict(a.stats) for name, a in self._arts.items()}


def run_specimen(spec: Specimen, *, const_bytes=None,
                 artifacts: Optional[SpecimenArtifacts] = None,
                 ) -> List[Finding]:
    """Trace + (when donating) compile one specimen and run every
    trace-tier rule over it. Pass ``artifacts`` (from a
    :class:`SpecimenCache`) to reuse the trace/lowering across tiers."""
    kw = {}
    if const_bytes is not None:
        kw['const_bytes'] = const_bytes
    art = artifacts if artifacts is not None else SpecimenArtifacts(spec)
    ctx = TraceContext(specimen=spec.name, **kw)
    findings = analyze_closed_jaxpr(art.closed_jaxpr(), ctx)
    donate = art.built().get('donate_argnums')
    if donate:
        findings += compiled_donation_findings(art.warnings,
                                               art.compiled(), donate,
                                               spec.name)
    return findings


def run_trace_tier(specimens: Optional[List[Specimen]] = None, *,
                   const_bytes=None,
                   on_progress: Optional[Callable[[str], None]] = None,
                   skipped: Optional[List[str]] = None,
                   cache: Optional[SpecimenCache] = None) -> List[Finding]:
    """Run every runnable trace-tier specimen; skips mesh specimens when
    the process has too few devices (reported via ``on_progress``, and
    appended to ``skipped`` when given — baseline writers use that to
    preserve the skipped specimens' prior entries). ``cache`` shares
    each specimen's single trace/lowering with the other tiers."""
    findings = []
    cache = cache if cache is not None else SpecimenCache()
    for spec in iter_runnable_specimens('trace', specimens=specimens,
                                        on_progress=on_progress,
                                        skipped=skipped):
        if on_progress:
            on_progress(f'trace {spec.name}')
        findings.extend(run_specimen(spec, const_bytes=const_bytes,
                                     artifacts=cache.artifacts(spec)))
    return findings
