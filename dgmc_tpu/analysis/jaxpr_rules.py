"""Trace-tier rules: walk ClosedJaxprs and compiled executables.

Rule ids (see ``docs/source/modules/analysis.rst`` for the catalog):

``TRC001`` dtype-promotion
    An equation *introduces* a 64-bit result (f64 / i64 / u64 / c128)
    from non-64-bit inputs. The whole pipeline is 32-bit-or-narrower by
    design (TPUs have no f64 units — XLA emulates at >10x cost), so any
    64-bit value is drift, flagged at the equation that created it with
    per-equation source provenance.
``TRC002`` giant-constant
    A constant folded into the program exceeds a byte threshold. Big
    baked-in arrays bloat every serialized executable, defeat donation,
    and usually mean a dataset/table was closed over instead of being
    passed as an argument.
``TRC003`` host-callback
    A host-callback equation (``debug_callback`` / ``pure_callback`` /
    ``io_callback``...) is present in a program expected to be
    callback-free. The obs probe layer guarantees byte-identical HLO
    with probes disabled (PR 3); a callback here means a probe (or a
    stray ``jax.debug.print``) leaked past its trace-time gate and will
    fence device->host every step.
``TRC004`` donation-dropped
    An argument was donated but the compiled executable retains no
    input-output aliasing for it. Donation silently degrades to a copy
    — and dropped/broken aliasing is exactly the defect class of the
    jax-0.4.37 persistent-cache bug root-caused in PR 3 (executables
    deserialized with broken aliasing read freed buffers). This is the
    static tripwire: a *fresh* compile must alias, or the step was never
    entitled to donate.
``TRC005`` pathological-scatter
    A scatter without ``unique_indices`` — lowered serially (or via
    atomics) on TPU. Inherent to GNN aggregation in places; the
    committed baseline carries the reviewed ones, the rule catches new
    introductions.
``TRC006`` large-sort
    A ``sort``/``top_k``-free path regressed into sorting a large axis
    (e.g. a dense argsort where the streaming top-k was intended).
"""

import dataclasses
import re
import warnings
from typing import Iterator, List, Optional, Tuple

import jax
from jax import core as jax_core

from dgmc_tpu.analysis.findings import (Finding, Severity,
                                        disambiguate_contexts,
                                        read_source_line)

#: Primitive names that fence the host. Matched exactly or by suffix.
CALLBACK_PRIMITIVES = ('debug_callback', 'pure_callback', 'io_callback',
                       'outside_call', 'debug_print')

#: 64-bit dtypes that must never appear (the repo is <=32-bit by design).
_WIDE = ('float64', 'int64', 'uint64', 'complex128')

DEFAULT_CONST_BYTES = 1 << 20       # 1 MiB
DEFAULT_SORT_DIM = 4096


@dataclasses.dataclass
class TraceContext:
    """Provenance prefix + thresholds for one analyzed program."""
    specimen: str = 'program'
    const_bytes: int = DEFAULT_CONST_BYTES
    sort_dim: int = DEFAULT_SORT_DIM
    expect_no_callbacks: bool = True


# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------


def _closed_subjaxprs(params) -> Iterator[jax_core.ClosedJaxpr]:
    for v in params.values():
        if isinstance(v, jax_core.ClosedJaxpr):
            yield v
        elif isinstance(v, (tuple, list)):
            for x in v:
                if isinstance(x, jax_core.ClosedJaxpr):
                    yield x


def iter_equations(jaxpr) -> Iterator[jax_core.JaxprEqn]:
    """Every equation of ``jaxpr`` (Jaxpr or ClosedJaxpr), recursively
    through call/scan/cond/pjit sub-jaxprs."""
    if isinstance(jaxpr, jax_core.ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _closed_subjaxprs(eqn.params):
            yield from iter_equations(sub)


def _iter_consts(closed) -> Iterator[Tuple[object, str]]:
    """(const, owner) for the closed jaxpr and every nested ClosedJaxpr."""
    for c in closed.consts:
        yield c, 'top-level'
    for eqn in iter_equations(closed):
        for sub in _closed_subjaxprs(eqn.params):
            for c in sub.consts:
                yield c, eqn.primitive.name


def eqn_provenance(eqn) -> str:
    """``relative/file.py:line`` of the first user frame that created the
    equation; ``<unknown>`` when source info is unavailable."""
    try:
        from jax._src import source_info_util
        frame = source_info_util.user_frame(eqn.source_info)
    except Exception:
        frame = None
    if frame is None:
        return '<unknown>'
    fname = frame.file_name
    # Stable across checkouts/venvs: keep the path from the last
    # site-packages / repo-root-ish component.
    for marker in ('site-packages/', 'dist-packages/'):
        if marker in fname:
            fname = fname.split(marker, 1)[1]
            break
    else:
        parts = fname.split('/')
        for anchor in ('dgmc_tpu', 'tests', 'examples', 'benchmarks'):
            if anchor in parts:
                fname = '/'.join(parts[parts.index(anchor):])
                break
    return f'{fname}:{frame.start_line}'


def _prov_context(prov: str, fallback: str) -> str:
    """Line-independent context snippet for a ``file.py:line``
    provenance: the source line's stripped text when readable, else a
    structural ``fallback`` (op kind + shapes) — what the fingerprint
    hashes in place of the line number (findings.py)."""
    path, sep, line = prov.rpartition(':')
    if sep:
        try:
            text = read_source_line(path, int(line))
        except ValueError:
            text = None
        if text:
            return text
    return fallback


def _aval_of(var):
    aval = getattr(var, 'aval', None)
    return aval


def _is_wide(aval) -> bool:
    dtype = getattr(aval, 'dtype', None)
    return dtype is not None and str(dtype) in _WIDE


# ---------------------------------------------------------------------------
# Rules over a ClosedJaxpr
# ---------------------------------------------------------------------------


def check_dtype_promotion(closed, ctx: TraceContext) -> List[Finding]:
    sites = {}
    for eqn in iter_equations(closed):
        wide_out = [v for v in eqn.outvars if _is_wide(_aval_of(v))]
        if not wide_out:
            continue
        if any(_is_wide(_aval_of(v)) for v in eqn.invars):
            continue  # propagation, not introduction — flagged upstream
        dtypes = tuple(sorted({str(_aval_of(v).dtype) for v in wide_out}))
        key = (eqn.primitive.name, eqn_provenance(eqn), dtypes)
        n, example = sites.get(key, (0, str(eqn)[:300]))
        sites[key] = (n + 1, example)
    return [
        Finding(
            rule='TRC001', severity=Severity.ERROR,
            where=f'{ctx.specimen}:{prov}',
            message=(f'64-bit value introduced by `{prim}` '
                     f'({", ".join(dtypes)}) in a <=32-bit pipeline'),
            detail=f'{n} equation(s) at this site; e.g. {example}',
            context=_prov_context(prov, f'{prim} {" ".join(dtypes)}'))
        for (prim, prov, dtypes), (n, example) in sorted(sites.items())]


def check_giant_constants(closed, ctx: TraceContext) -> List[Finding]:
    # Identity fields (where/message) carry only the structural facts —
    # shape, dtype, and an index discriminating same-shaped constants —
    # so fingerprints neither drift with byte-size rounding nor collide
    # when a SECOND identically-shaped giant constant appears (which
    # must show up as a new finding, not hide under the baselined one).
    out = []
    seen_ids = set()
    per_shape = {}
    for const, owner in _iter_consts(closed):
        nbytes = getattr(const, 'nbytes', 0)
        shape = tuple(getattr(const, 'shape', ()) or ())
        if not nbytes or nbytes < ctx.const_bytes:
            continue
        if id(const) in seen_ids:
            continue
        seen_ids.add(id(const))
        dtype = getattr(const, 'dtype', '?')
        idx = per_shape.get((shape, str(dtype)), 0)
        per_shape[(shape, str(dtype))] = idx + 1
        out.append(Finding(
            rule='TRC002', severity=Severity.WARNING,
            where=f'{ctx.specimen}:const{list(shape)}#{idx}',
            message=(f'giant constant (shape {shape}, dtype {dtype}) '
                     f'baked into the program'),
            detail=f'{nbytes / (1 << 20):.1f} MiB, captured under '
                   f'`{owner}`; pass it as an argument instead of '
                   f'closing over it'))
    return out


def callback_equations(closed) -> List[Tuple[str, str]]:
    """``(primitive_name, provenance)`` for every host-callback equation
    — empty on a program honoring the probes-off byte-identical-HLO
    guarantee."""
    hits = []
    for eqn in iter_equations(closed):
        name = eqn.primitive.name
        if name in CALLBACK_PRIMITIVES or name.endswith('_callback'):
            hits.append((name, eqn_provenance(eqn)))
    return hits


def check_host_callbacks(closed, ctx: TraceContext) -> List[Finding]:
    if not ctx.expect_no_callbacks:
        return []
    sites = {}
    for name, prov in callback_equations(closed):
        sites[(name, prov)] = sites.get((name, prov), 0) + 1
    return [
        Finding(
            rule='TRC003', severity=Severity.ERROR,
            where=f'{ctx.specimen}:{prov}',
            message=(f'host callback `{name}` in a program expected '
                     f'callback-free (probes disabled) — fences '
                     f'device->host every step'),
            detail=f'{n} equation(s) at this site',
            context=_prov_context(prov, name))
        for (name, prov), n in sorted(sites.items())]


def check_pathological_lowerings(closed, ctx: TraceContext) -> List[Finding]:
    # One finding per code SITE (specimen + provenance + primitive), not
    # per traced equation: a GNN layer's scatter appears once per layer,
    # iteration, and gradient — the hazard (and its fix) lives at the
    # source line. Occurrence counts and example shapes ride in `detail`
    # so fingerprints stay stable as the model config changes.
    scatters = {}
    sorts = {}
    for eqn in iter_equations(closed):
        name = eqn.primitive.name
        if name.startswith('scatter'):
            if eqn.params.get('unique_indices', False):
                continue
            aval = _aval_of(eqn.outvars[0])
            key = (name, eqn_provenance(eqn))
            n, shapes = scatters.get(key, (0, set()))
            shapes.add(tuple(getattr(aval, 'shape', ())))
            scatters[key] = (n + 1, shapes)
        elif name == 'sort':
            aval = _aval_of(eqn.invars[0])
            shape = tuple(getattr(aval, 'shape', ()))
            dim = eqn.params.get('dimension', len(shape) - 1 if shape else 0)
            if shape and shape[dim] >= ctx.sort_dim:
                key = (name, eqn_provenance(eqn))
                n, dims_seen = sorts.get(key, (0, set()))
                dims_seen.add(shape[dim])
                sorts[key] = (n + 1, dims_seen)
    out = []
    for (name, prov), (n, shapes) in sorted(scatters.items()):
        out.append(Finding(
            rule='TRC005', severity=Severity.INFO,
            where=f'{ctx.specimen}:{prov}',
            message=(f'`{name}` without unique_indices — serial/atomic '
                     f'lowering on TPU'),
            detail=(f'{n} equation(s) at this site, out shapes '
                    f'{sorted(shapes)}; inherent to unsorted segment '
                    f'aggregation — prefer sorted/blocked forms on hot '
                    f'paths'),
            context=_prov_context(prov, name)))
    for (name, prov), (n, dims_seen) in sorted(sorts.items()):
        out.append(Finding(
            rule='TRC006', severity=Severity.WARNING,
            where=f'{ctx.specimen}:{prov}',
            message=(f'sort over axis of >= {ctx.sort_dim} elements — on '
                     f'TPU prefer top_k / the streaming blockwise top-k'),
            detail=f'{n} equation(s) at this site, axis sizes '
                   f'{sorted(dims_seen)}',
            context=_prov_context(prov, name)))
    return out


def analyze_closed_jaxpr(closed, ctx: Optional[TraceContext] = None,
                         ) -> List[Finding]:
    """All jaxpr-level rules over one ClosedJaxpr."""
    ctx = ctx or TraceContext()
    out = []
    out += check_dtype_promotion(closed, ctx)
    out += check_giant_constants(closed, ctx)
    out += check_host_callbacks(closed, ctx)
    out += check_pathological_lowerings(closed, ctx)
    return disambiguate_contexts(out)


# ---------------------------------------------------------------------------
# Compiled-executable rules (donation aliasing)
# ---------------------------------------------------------------------------

_DONATION_WARNING = 'donated buffers were not usable'
_ALIAS_RE = re.compile(r'input_output_alias\s*=\s*\{')


def analyze_donation(fn, args, kwargs=None, *, donate_argnums,
                     specimen='program') -> List[Finding]:
    """Compile ``fn`` with donation and verify the executable kept the
    input-output aliasing (TRC004).

    Two failure shapes are reported:

    - lowering declared some donated buffers unusable (shape/dtype of the
      donated input matches no output — the donation was never real);
    - the *optimized executable* retains no ``input_output_alias`` entry
      at all even though donation was requested — the static face of the
      PR 3 cache-aliasing bug class (an executable without aliasing
      copies; one with *broken* aliasing reads freed buffers).
    """
    kwargs = kwargs or {}
    jitted = jax.jit(fn, donate_argnums=donate_argnums)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter('always')
        compiled = jitted.lower(*args, **kwargs).compile()
    return compiled_donation_findings(caught, compiled, donate_argnums,
                                      specimen)


def compiled_donation_findings(caught_warnings, compiled, donate_argnums,
                               specimen) -> List[Finding]:
    """The TRC004 analysis over one compile's captured warnings + its
    compiled executable — the single implementation shared by
    :func:`analyze_donation` (plain functions the analyzer jits itself)
    and the registry's pre-jitted specimens (e.g. the sharded train step
    with its own ``in_shardings``), so the warning text and alias-syntax
    probes cannot drift apart between the two entry points."""
    findings = []
    dropped = [str(w.message) for w in caught_warnings
               if _DONATION_WARNING in str(w.message)]
    for msg in dropped:
        findings.append(Finding(
            rule='TRC004', severity=Severity.ERROR,
            where=f'{specimen}:donate{tuple(donate_argnums)}',
            message='donated argument unusable for aliasing — donation '
                    'silently degrades to a copy',
            detail=msg.split('\n')[0][:300]))
    if not dropped:
        try:
            text = compiled.as_text()
        except Exception:
            text = None
        if text is not None and not _ALIAS_RE.search(text):
            findings.append(Finding(
                rule='TRC004', severity=Severity.ERROR,
                where=f'{specimen}:donate{tuple(donate_argnums)}',
                message='compiled executable retains NO input-output '
                        'aliasing despite donation — donated buffers '
                        'are copied, not reused',
                detail='fresh compile lost aliasing; if this executable '
                       'round-trips a persistent cache, broken aliasing '
                       'is the PR 3 garbage-read bug class'))
    return findings
