"""Source-tier lints: ``ast`` passes over the package source.

These catch the hazards that never make it into a jaxpr because they
blow up (or silently sync) at trace time:

``SRC101`` tracer-leak
    A jit-compiled function stores into ``self.<attr>`` or a module
    global. The stored value is a tracer; it escapes the trace and
    poisons the next call (``UnexpectedTracerError`` at best, stale
    constants at worst).
``SRC102`` host-sync-in-jit
    ``float(x)`` / ``int(x)`` / ``bool(x)`` / ``x.item()`` /
    ``np.asarray(x)`` on a traced value inside jitted code — each forces
    concretization: a trace-time error under jit, or a silent
    device->host fence where tracing is avoided.
``SRC103`` jit-in-loop
    ``jax.jit`` constructed inside a loop body: every iteration builds a
    fresh wrapper whose cache is thrown away — the textbook recompile
    churn generator.
``SRC104`` unhashable-static-arg
    ``static_argnums``/``static_argnames`` naming a parameter whose
    default is a mutable literal (list/dict/set). Static args are jit
    cache keys and must be hashable; the default explodes the first time
    it is actually used.

The scanner refuses bytecode: ``__pycache__`` directories are never
descended into, and pointing it at a ``.pyc`` (or anything inside
``__pycache__``) raises rather than silently analyzing stale bytecode.
"""

import ast
import dataclasses
import os
from typing import Iterator, List, Optional, Sequence

from dgmc_tpu.analysis.findings import (Finding, Severity,
                                        disambiguate_contexts)

_JIT_NAMES = {'jit'}          # bare `jit` (from jax import jit)
_NP_MODULES = {'np', 'numpy', 'onp'}
_CONCRETIZERS = {'float', 'int', 'bool'}
_SKIP_DIRS = {'__pycache__', '.git', '.pytest_cache', '.hypothesis',
              'build', 'dist', '.jax_compile_cache'}


def _is_jax_jit(node: ast.AST) -> bool:
    """True for expressions naming the jit transform itself: ``jax.jit``
    or a bare ``jit``."""
    if isinstance(node, ast.Attribute) and node.attr == 'jit':
        return True
    if isinstance(node, ast.Name) and node.id in _JIT_NAMES:
        return True
    return False


def _jit_call(node: ast.AST) -> Optional[ast.Call]:
    """The ``jax.jit(...)``/``partial(jax.jit, ...)`` Call under ``node``,
    or None."""
    if not isinstance(node, ast.Call):
        return None
    if _is_jax_jit(node.func):
        return node
    # functools.partial(jax.jit, ...) used as a decorator / wrapper.
    f = node.func
    is_partial = ((isinstance(f, ast.Attribute) and f.attr == 'partial')
                  or (isinstance(f, ast.Name) and f.id == 'partial'))
    if is_partial and node.args and _is_jax_jit(node.args[0]):
        return node
    return None


def _jitted_function_defs(tree: ast.Module):
    """FunctionDefs that are jit-compiled: decorated with ``jax.jit`` /
    ``partial(jax.jit, ...)``, or rebound via ``f = jax.jit(f, ...)`` in
    an enclosing scope (the factory idiom of ``train/steps.py``).
    Yields ``(def_node, jit_call_or_None)``."""
    defs = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)

    seen = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                call = _jit_call(dec)
                if call is not None or _is_jax_jit(dec):
                    if id(node) not in seen:
                        seen.add(id(node))
                        yield node, call
        elif isinstance(node, ast.Assign):
            call = _jit_call(node.value)
            if call is None or not call.args:
                continue
            first = call.args[0]
            # partial(jax.jit, ...)(f) has the fn elsewhere; only handle
            # the direct jax.jit(f, ...) rebind.
            if not _is_jax_jit(call.func):
                continue
            if isinstance(first, ast.Name):
                for d in defs.get(first.id, []):
                    if id(d) not in seen:
                        seen.add(id(d))
                        yield d, call


def _finding(rule, severity, rel, node, message, detail=None) -> Finding:
    return Finding(rule=rule, severity=severity,
                   where=f'{rel}:{getattr(node, "lineno", 0)}',
                   message=message, detail=detail)


def _check_tracer_leaks(tree, rel) -> List[Finding]:
    out = []
    for fdef, _ in _jitted_function_defs(tree):
        globals_declared = set()
        for node in ast.walk(fdef):
            if isinstance(node, ast.Global):
                globals_declared.update(node.names)
            elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == 'self'):
                        out.append(_finding(
                            'SRC101', Severity.ERROR, rel, node,
                            f'jitted `{fdef.name}` stores a traced value '
                            f'on `self.{t.attr}` — the tracer escapes the '
                            f'trace'))
                    elif (isinstance(t, ast.Name)
                          and t.id in globals_declared):
                        out.append(_finding(
                            'SRC101', Severity.ERROR, rel, node,
                            f'jitted `{fdef.name}` assigns module global '
                            f'`{t.id}` — the tracer escapes the trace'))
    return out


def _check_host_syncs(tree, rel) -> List[Finding]:
    out = []
    for fdef, _ in _jitted_function_defs(tree):
        for node in ast.walk(fdef):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if (isinstance(f, ast.Name) and f.id in _CONCRETIZERS
                    and node.args
                    and not isinstance(node.args[0], ast.Constant)):
                out.append(_finding(
                    'SRC102', Severity.WARNING, rel, node,
                    f'`{f.id}(...)` on a traced value inside jitted '
                    f'`{fdef.name}` — concretization error / host sync'))
            elif isinstance(f, ast.Attribute) and f.attr == 'item':
                out.append(_finding(
                    'SRC102', Severity.WARNING, rel, node,
                    f'`.item()` inside jitted `{fdef.name}` — '
                    f'concretization error / host sync'))
            elif (isinstance(f, ast.Attribute)
                  and f.attr in ('asarray', 'array')
                  and isinstance(f.value, ast.Name)
                  and f.value.id in _NP_MODULES):
                out.append(_finding(
                    'SRC102', Severity.WARNING, rel, node,
                    f'`{f.value.id}.{f.attr}(...)` inside jitted '
                    f'`{fdef.name}` — pulls the traced value to host '
                    f'(use jnp)'))
    return out


def _check_jit_in_loop(tree, rel) -> List[Finding]:
    out = []

    class LoopVisitor(ast.NodeVisitor):
        def __init__(self):
            self.loop_depth = 0

        def _loop(self, node):
            self.loop_depth += 1
            self.generic_visit(node)
            self.loop_depth -= 1

        visit_For = visit_While = visit_AsyncFor = _loop

        def visit_FunctionDef(self, node):
            # A def inside a loop resets loop context for its own body
            # (the function runs later, not per-iteration).
            depth, self.loop_depth = self.loop_depth, 0
            self.generic_visit(node)
            self.loop_depth = depth

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Call(self, node):
            if self.loop_depth and _is_jax_jit(node.func):
                out.append(_finding(
                    'SRC103', Severity.WARNING, rel, node,
                    'jax.jit constructed inside a loop — a fresh wrapper '
                    '(and compile cache) per iteration'))
            self.generic_visit(node)

    LoopVisitor().visit(tree)
    return out


def _check_static_arg_hashability(tree, rel) -> List[Finding]:
    out = []
    for fdef, call in _jitted_function_defs(tree):
        if call is None:
            continue
        static_names = set()
        static_nums = []
        for kw in call.keywords:
            if kw.arg == 'static_argnames':
                for e in ast.walk(kw.value):
                    if isinstance(e, ast.Constant) and isinstance(e.value,
                                                                  str):
                        static_names.add(e.value)
            elif kw.arg == 'static_argnums':
                for e in ast.walk(kw.value):
                    if isinstance(e, ast.Constant) and isinstance(e.value,
                                                                  int):
                        static_nums.append(e.value)
        if not static_names and not static_nums:
            continue
        # Positional params: posonly args come first and shift
        # static_argnums indexing; defaults covers the TAIL of the
        # combined posonly+regular list.
        pos = list(fdef.args.posonlyargs) + list(fdef.args.args)
        defaults = fdef.args.defaults
        offset = len(pos) - len(defaults)
        checks = []
        for i, arg in enumerate(pos):
            if (arg.arg in static_names or i in static_nums) \
                    and i >= offset:
                checks.append((arg, defaults[i - offset]))
        # Keyword-only params: reachable via static_argnames only;
        # kw_defaults aligns 1:1 with kwonlyargs (None = no default).
        for arg, default in zip(fdef.args.kwonlyargs,
                                fdef.args.kw_defaults):
            if arg.arg in static_names and default is not None:
                checks.append((arg, default))
        for arg, default in checks:
            if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                kind = type(default).__name__.lower()
                out.append(_finding(
                    'SRC104', Severity.WARNING, rel, default,
                    f'static arg `{arg.arg}` of jitted `{fdef.name}` '
                    f'defaults to a mutable {kind} — static args are '
                    f'cache keys and must be hashable'))
    return out


# ---------------------------------------------------------------------------
# File / tree drivers
# ---------------------------------------------------------------------------


def _refuse_bytecode(path: str):
    norm = os.path.normpath(path)
    if norm.endswith(('.pyc', '.pyo')) or '__pycache__' in norm.split(os.sep):
        raise ValueError(
            f'{path}: refusing to scan bytecode — the source tier lints '
            f'.py sources only (and never descends into __pycache__)')


def lint_source_file(path: str, rel: Optional[str] = None) -> List[Finding]:
    """All source rules over one ``.py`` file. ``rel`` overrides the
    location prefix used in findings (defaults to ``path``)."""
    _refuse_bytecode(path)
    rel = rel or path
    with open(path, encoding='utf-8') as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding(rule='SRC100', severity=Severity.ERROR,
                        where=f'{rel}:{e.lineno or 0}',
                        message=f'syntax error: {e.msg}')]
    out = []
    out += _check_tracer_leaks(tree, rel)
    out += _check_host_syncs(tree, rel)
    out += _check_jit_in_loop(tree, rel)
    out += _check_static_arg_hashability(tree, rel)
    return disambiguate_contexts(_with_line_context(f, src) for f in out)


def _with_line_context(finding: Finding, src: str) -> Finding:
    """Attach the flagged line's stripped text as the finding's
    ``context`` — the line-number-independent fingerprint discriminator
    (findings.py): an edit above the line relocates the finding without
    churning the baseline, while a change to the flagged statement
    itself releases the suppression."""
    try:
        lineno = int(finding.where.rsplit(':', 1)[1])
    except (IndexError, ValueError):
        return finding
    lines = src.splitlines()
    if not 1 <= lineno <= len(lines):
        return finding
    text = lines[lineno - 1].strip()
    if not text:
        return finding
    return dataclasses.replace(finding, context=text)


def iter_source_files(root: str) -> Iterator[str]:
    """``.py`` files under ``root``, never entering bytecode/cache dirs."""
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
        for fn in sorted(filenames):
            if fn.endswith('.py'):
                yield os.path.join(dirpath, fn)


def lint_source_tree(root: str,
                     exclude: Sequence[str] = ()) -> List[Finding]:
    """Source rules over every ``.py`` under ``root`` (recursively),
    reporting repo-relative locations."""
    root = os.path.abspath(root)
    base = os.path.dirname(root)
    out = []
    for path in iter_source_files(root):
        rel = os.path.relpath(path, base)
        if any(rel.startswith(e) for e in exclude):
            continue
        out.extend(lint_source_file(path, rel=rel))
    return out


def lint_source_paths(paths: Sequence[str]) -> List[Finding]:
    """Source rules over a mix of files and directories — the
    multi-root scan the CLI drives (the package plus the repo-root
    bench drivers and ``benchmarks/``, which gained jit-wrapping and
    threading logic but were invisible to a single-root scan). A bare
    file reports under its basename (repo-relative for repo-root
    drivers); a directory reports as :func:`lint_source_tree` does."""
    out = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isdir(p):
            out.extend(lint_source_tree(p))
        else:
            out.extend(lint_source_file(p, rel=os.path.basename(p)))
    return out
