"""Static concurrency model: thread entry points, lock sets, and
per-class attribute access — the substrate the CON rules read.

The serve plane (PRs 15-18) is a persistent multithreaded process:
``ThreadingHTTPServer`` handler threads, a shadow-audit thread, the
watchdog daemon and its signal path, all mutating Python objects the
main thread also reads. Two race classes were caught by hand before
this tier existed (PR 15: non-atomic ``+=`` on serve counters from
handler threads; PR 16: per-class counters needing pre-seeding); this
module turns the review checklist into a model ``con_rules.py`` can
lint mechanically, before ROADMAP item 1 multiplies the concurrency
with an admission queue and a replica fleet.

The model is built per module from the ``ast`` alone:

- **Thread entry points** — functions that run off the main path:
  targets of ``threading.Thread(target=...)`` / ``threading.Timer``,
  ``ThreadPoolExecutor.submit`` callables, ``do_GET``/``do_POST``-style
  HTTP handler methods (``ThreadingHTTPServer`` runs one per request
  thread), ``signal.signal`` handlers and ``atexit.register`` hooks
  (asynchronous entry on the MAIN thread — same discipline applies).
- **Per-class attribute model** — for every class: which attributes
  are lock objects (``threading.Lock/RLock/Condition/Semaphore`` in
  any method), which methods are reachable from an entry point through
  ``self.<m>()`` calls (the *entry closure*), every ``self.<attr>``
  write with the lock set lexically held at the site (``with
  self._lock:`` blocks plus linear ``.acquire()``/``.release()``
  tracking in statement order), whether the write is a read-modify-
  write (``+=`` / ``self.x = self.x + ...``), container growth calls
  (``.append``/``.add``/keyed stores) and the cap evidence that
  bounds them (``deque(maxlen=...)``, ``len()`` checks, eviction).
- **Lock-order edges** — ordered pairs ``(A, B)`` meaning lock B was
  acquired while A was held, collected lexically and one call level
  deep through ``self.<m>()``.

Known limits, by design (documented in ``docs/.../analysis.rst``): the
model is per-module and name-based. Dynamic dispatch (a bound method
stored in a dict and called later — the telemetry route table),
``getattr`` indirection, and cross-class call chains (the service
calling the engine) are invisible; locks passed as arguments or held
in locals are not tracked. The rules therefore under-approximate:
everything they DO flag is structurally evident in one module.
"""

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

__all__ = ['ModuleModel', 'ClassModel', 'AttrWrite', 'GrowthSite',
           'SignalHandler', 'build_module_model', 'LOCK_FACTORIES',
           'HTTP_HANDLER_METHODS']

#: ``threading`` constructors whose result is a lock in the "must be
#: held to touch shared state" sense. Condition counts: ``with
#: self._cond:`` acquires its underlying lock.
LOCK_FACTORIES = {'Lock', 'RLock', 'Condition', 'Semaphore',
                  'BoundedSemaphore'}

#: ``BaseHTTPRequestHandler`` entry methods: under
#: ``ThreadingHTTPServer`` each runs on a fresh per-request thread.
HTTP_HANDLER_METHODS = {'do_GET', 'do_POST', 'do_PUT', 'do_DELETE',
                        'do_PATCH', 'do_HEAD'}

_CONTAINER_CALLS = {'list', 'dict', 'set', 'deque', 'OrderedDict',
                    'defaultdict', 'Counter'}
_GROWTH_METHODS = {'append', 'appendleft', 'extend', 'add', 'insert',
                   'setdefault'}
_EVICT_METHODS = {'pop', 'popleft', 'popitem', 'clear', 'remove',
                  'discard'}


def _call_name(func: ast.AST) -> Optional[str]:
    """Trailing name of a call target: ``threading.Thread`` -> Thread,
    ``Thread`` -> Thread."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    """``attr`` when node is ``self.<attr>``, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == 'self'):
        return node.attr
    return None


def _mentions_tmp(node: ast.AST) -> bool:
    """Whether a path expression names a temp file: a ``tmp`` substring
    in any identifier or string constant under it (the watchdog's
    ``f'{path}.tmp.{pid}'`` and findings.py's ``path + '.tmp'`` both
    read this way)."""
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and 'tmp' in n.id.lower():
            return True
        if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                and 'tmp' in n.value.lower():
            return True
        if isinstance(n, ast.Attribute) and 'tmp' in n.attr.lower():
            return True
    return False


@dataclasses.dataclass(frozen=True)
class AttrWrite:
    """One ``self.<attr>`` store site."""
    attr: str
    node: ast.AST
    method: str
    rmw: bool                    # += / self.x = self.x op ...
    locks_held: FrozenSet[str]
    in_init: bool


@dataclasses.dataclass(frozen=True)
class GrowthSite:
    """One container-growth site: ``self.<attr>.append(...)`` or
    ``self.<attr>[k] = v``."""
    attr: str
    node: ast.AST
    method: str
    op: str


@dataclasses.dataclass(frozen=True)
class SignalHandler:
    """One registered ``signal.signal`` handler (function, method, or
    lambda) with the lock names visible at its registration scope."""
    name: str
    node: ast.AST
    lock_names: FrozenSet[str]


@dataclasses.dataclass
class ClassModel:
    name: str
    node: ast.ClassDef
    methods: Dict[str, ast.AST] = dataclasses.field(default_factory=dict)
    lock_attrs: Set[str] = dataclasses.field(default_factory=set)
    #: method -> (entry kind, entry method) for every method reachable
    #: from a thread entry point through ``self.<m>()`` calls.
    entry_closure: Dict[str, Tuple[str, str]] = dataclasses.field(
        default_factory=dict)
    writes: List[AttrWrite] = dataclasses.field(default_factory=list)
    growth: List[GrowthSite] = dataclasses.field(default_factory=list)
    #: container attrs assigned in __init__ -> True when capped at
    #: construction (deque(maxlen=...)).
    container_attrs: Dict[str, bool] = dataclasses.field(
        default_factory=dict)
    #: attrs with cap/eviction evidence anywhere in the class.
    bounded_attrs: Set[str] = dataclasses.field(default_factory=set)
    #: (held, acquired) -> first site node.
    lock_edges: Dict[Tuple[str, str], ast.AST] = dataclasses.field(
        default_factory=dict)

    def writes_by_attr(self) -> Dict[str, List[AttrWrite]]:
        out: Dict[str, List[AttrWrite]] = {}
        for w in self.writes:
            out.setdefault(w.attr, []).append(w)
        return out


@dataclasses.dataclass
class ModuleModel:
    classes: List[ClassModel] = dataclasses.field(default_factory=list)
    signal_handlers: List[SignalHandler] = dataclasses.field(
        default_factory=list)
    module_locks: Set[str] = dataclasses.field(default_factory=set)


# ---------------------------------------------------------------------------
# Entry-point registration
# ---------------------------------------------------------------------------

def _entry_registrations(tree: ast.AST):
    """Yield ``(kind, handler_expr)`` for every thread/async entry
    registration in the (sub)tree: Thread/Timer targets, executor
    submissions, signal handlers, atexit hooks."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node.func)
        if name == 'Thread':
            for kw in node.keywords:
                if kw.arg == 'target':
                    yield 'thread', kw.value
        elif name == 'Timer':
            if len(node.args) >= 2:
                yield 'timer', node.args[1]
            for kw in node.keywords:
                if kw.arg == 'function':
                    yield 'timer', kw.value
        elif name == 'submit' and node.args:
            yield 'executor', node.args[0]
        elif name == 'signal' and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == 'signal':
            if len(node.args) >= 2:
                yield 'signal', node.args[1]
        elif name == 'register' and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == 'atexit':
            if node.args:
                yield 'atexit', node.args[0]


def _lock_factory_call(value: ast.AST) -> bool:
    return (isinstance(value, ast.Call)
            and _call_name(value.func) in LOCK_FACTORIES)


def _container_init(value: ast.AST) -> Optional[bool]:
    """``True``/``False`` = container assigned, capped/uncapped;
    ``None`` = not a container constructor."""
    if isinstance(value, (ast.List, ast.Dict, ast.Set)):
        return False
    if isinstance(value, ast.Call):
        name = _call_name(value.func)
        if name in _CONTAINER_CALLS:
            if name == 'deque':
                return any(kw.arg == 'maxlen' and not (
                    isinstance(kw.value, ast.Constant)
                    and kw.value.value is None)
                    for kw in value.keywords)
            return False
    return None


# ---------------------------------------------------------------------------
# Lock-aware statement walk
# ---------------------------------------------------------------------------

class _FunctionScan:
    """One method/function body walked in statement order with the
    lexically-held lock set: ``with self._lock:`` blocks plus linear
    ``self._lock.acquire()``/``.release()`` tracking (the engine's
    explicit acquire style). Records writes, growth calls, lock-order
    edges, and ``self.<m>()`` call sites with the locks held there."""

    def __init__(self, cls: ClassModel, method: str, lock_attrs):
        self.cls = cls
        self.method = method
        self.lock_attrs = set(lock_attrs)
        self.in_init = method == '__init__'
        #: (held_locks, callee) — for the one-level interprocedural
        #: lock-order pass.
        self.calls_under: List[Tuple[FrozenSet[str], str, ast.AST]] = []
        #: locks this function acquires anywhere (with or .acquire()).
        self.acquires: Set[str] = set()

    # -- helpers -----------------------------------------------------------

    def _lock_of(self, expr: ast.AST) -> Optional[str]:
        attr = _self_attr(expr)
        if attr is not None and attr in self.lock_attrs:
            return attr
        return None

    def _record_stmt(self, stmt: ast.stmt, held: FrozenSet[str]):
        """Record the accesses a single (non-compound) statement makes."""
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for t in targets:
                attr = _self_attr(t)
                if attr is not None:
                    rmw = isinstance(stmt, ast.AugAssign) or (
                        not isinstance(stmt, ast.AugAssign)
                        and stmt.value is not None
                        and any(_self_attr(n) == attr
                                for n in ast.walk(stmt.value)))
                    self.cls.writes.append(AttrWrite(
                        attr=attr, node=stmt, method=self.method,
                        rmw=rmw, locks_held=held, in_init=self.in_init))
                elif isinstance(t, ast.Subscript):
                    base = _self_attr(t.value)
                    if base is not None and not self.in_init:
                        self.cls.growth.append(GrowthSite(
                            attr=base, node=stmt, method=self.method,
                            op='setitem'))
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Attribute):
                base = _self_attr(node.func.value)
                if base is not None:
                    if node.func.attr in _GROWTH_METHODS \
                            and not self.in_init:
                        self.cls.growth.append(GrowthSite(
                            attr=base, node=node, method=self.method,
                            op=node.func.attr))
                    elif node.func.attr in _EVICT_METHODS:
                        self.cls.bounded_attrs.add(base)
                # self.<m>(...) same-class call with held locks.
                if isinstance(node.func.value, ast.Name) \
                        and node.func.value.id == 'self' \
                        and node.func.attr in self.cls.methods:
                    self.calls_under.append(
                        (held, node.func.attr, node))
            # len(self.attr) in a comparison / min / capacity check
            # counts as bound evidence for that attr.
            if isinstance(node.func, ast.Name) and node.func.id == 'len' \
                    and node.args:
                base = _self_attr(node.args[0])
                if base is not None:
                    self.cls.bounded_attrs.add(base)
        for node in ast.walk(stmt):
            if isinstance(node, ast.Delete):
                for t in node.targets:
                    base = _self_attr(
                        t.value if isinstance(t, ast.Subscript) else t)
                    if base is not None:
                        self.cls.bounded_attrs.add(base)

    def _acquire_release_delta(self, stmt: ast.stmt,
                               held: Set[str]) -> Set[str]:
        """Apply explicit ``.acquire()``/``.release()`` calls found
        anywhere in the statement, in source order, to the running
        held-set (the engine.match acquire ... try/finally release
        idiom)."""
        events = []
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ('acquire', 'release'):
                lock = self._lock_of(node.func.value)
                if lock is not None:
                    events.append((node.lineno, node.func.attr, lock,
                                   node))
        for _, op, lock, node in sorted(events, key=lambda e: e[0]):
            if op == 'acquire':
                self.acquires.add(lock)
                for h in held:
                    if h != lock:
                        self.cls.lock_edges.setdefault((h, lock), node)
                held = held | {lock}
            else:
                held = held - {lock}
        return held

    def walk(self, body: List[ast.stmt],
             held: FrozenSet[str] = frozenset()):
        running = set(held)
        for stmt in body:
            self._record_stmt(stmt, frozenset(running))
            if isinstance(stmt, ast.With):
                new = set()
                for item in stmt.items:
                    lock = self._lock_of(item.context_expr)
                    if lock is not None:
                        self.acquires.add(lock)
                        new.add(lock)
                        for h in running:
                            if h != lock:
                                self.cls.lock_edges.setdefault(
                                    (h, lock), item.context_expr)
                self.walk(stmt.body, frozenset(running | new))
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                self.walk(stmt.body, frozenset(running))
                self.walk(stmt.orelse, frozenset(running))
            elif isinstance(stmt, ast.If):
                self.walk(stmt.body, frozenset(running))
                self.walk(stmt.orelse, frozenset(running))
            elif isinstance(stmt, ast.Try):
                self.walk(stmt.body, frozenset(running))
                for h in stmt.handlers:
                    self.walk(h.body, frozenset(running))
                self.walk(stmt.orelse, frozenset(running))
                self.walk(stmt.finalbody, frozenset(running))
            # Nested defs run later, on their own; they are scanned as
            # their own methods/functions, never inline.
            running = self._acquire_release_delta(stmt, running)


def _dedupe_recorded(cls: ClassModel):
    """The compound-statement recursion records a nested simple
    statement once per enclosing level; keep the DEEPEST record (the
    one whose held-lock set includes the enclosing ``with`` blocks)."""
    best: Dict[int, AttrWrite] = {}
    for w in cls.writes:
        prev = best.get(id(w.node))
        if prev is None or len(w.locks_held) > len(prev.locks_held):
            best[id(w.node)] = w
    cls.writes = sorted(best.values(),
                        key=lambda w: getattr(w.node, 'lineno', 0))
    seen_growth: Dict[Tuple[int, str], GrowthSite] = {}
    for g in cls.growth:
        seen_growth.setdefault((id(g.node), g.op), g)
    cls.growth = sorted(seen_growth.values(),
                        key=lambda g: getattr(g.node, 'lineno', 0))


# ---------------------------------------------------------------------------
# Model construction
# ---------------------------------------------------------------------------

def _resolve_entry(cls: ClassModel, handler: ast.AST) -> Optional[str]:
    """Method name when a registration target is ``self.<m>`` of this
    class, else None (lambdas and foreign callables are analyzed where
    they appear, not through the closure)."""
    attr = _self_attr(handler)
    if attr is not None and attr in cls.methods:
        return attr
    return None


def _class_model(node: ast.ClassDef) -> ClassModel:
    cls = ClassModel(name=node.name, node=node)
    for item in node.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cls.methods[item.name] = item
    # Pass 1: lock attrs + container inits (any method; __init__ is
    # where both live in practice).
    for m in cls.methods.values():
        for stmt in ast.walk(m):
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    attr = _self_attr(t)
                    if attr is None:
                        continue
                    if _lock_factory_call(stmt.value):
                        cls.lock_attrs.add(attr)
                    capped = _container_init(stmt.value)
                    if capped is not None and m.name == '__init__':
                        cls.container_attrs[attr] = capped
    # Pass 2: entry points.
    entries: Dict[str, str] = {}
    for name in cls.methods:
        if name in HTTP_HANDLER_METHODS:
            entries[name] = 'http-handler'
    for m in cls.methods.values():
        for kind, handler in _entry_registrations(m):
            target = _resolve_entry(cls, handler)
            if target is not None:
                entries.setdefault(target, kind)
    # Pass 3: scan every method with lock tracking.
    scans: Dict[str, _FunctionScan] = {}
    for name, m in cls.methods.items():
        scan = _FunctionScan(cls, name, cls.lock_attrs)
        scan.walk(m.body)
        scans[name] = scan
    _dedupe_recorded(cls)
    # Pass 4: one-level interprocedural lock-order edges — a call made
    # while holding A to a method that acquires B is an (A, B) edge.
    for scan in scans.values():
        for held, callee, site in scan.calls_under:
            callee_scan = scans.get(callee)
            if callee_scan is None:
                continue
            for h in held:
                for acquired in callee_scan.acquires:
                    if acquired != h:
                        cls.lock_edges.setdefault((h, acquired), site)
    # Pass 5: entry closure — fixed point over self-calls.
    closure: Dict[str, Tuple[str, str]] = {
        m: (kind, m) for m, kind in entries.items()}
    frontier = list(closure)
    while frontier:
        cur = frontier.pop()
        kind, origin = closure[cur]
        for held, callee, _site in scans[cur].calls_under:
            if callee not in closure:
                closure[callee] = (kind, origin)
                frontier.append(callee)
    cls.entry_closure = closure
    # Rebinding a container attr outside __init__ is rotation/reset
    # evidence (the attr does not grow monotonically).
    for w in cls.writes:
        if not w.in_init and not w.rmw \
                and w.attr in cls.container_attrs:
            cls.bounded_attrs.add(w.attr)
    return cls


def build_module_model(tree: ast.Module) -> ModuleModel:
    """The whole-module concurrency model the CON rules read."""
    model = ModuleModel()
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            model.classes.append(_class_model(node))
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            if _lock_factory_call(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        model.module_locks.add(t.id)
    # Signal handlers: resolved to their def (method or module
    # function) or kept as the lambda node.
    defs: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
    class_locks: Set[str] = set()
    for cls in model.classes:
        class_locks |= cls.lock_attrs
    lock_names = frozenset(model.module_locks | class_locks)
    for kind, handler in _entry_registrations(tree):
        if kind != 'signal':
            continue
        if isinstance(handler, ast.Lambda):
            model.signal_handlers.append(SignalHandler(
                name='<lambda>', node=handler, lock_names=lock_names))
        elif isinstance(handler, ast.Name):
            for d in defs.get(handler.id, []):
                model.signal_handlers.append(SignalHandler(
                    name=handler.id, node=d, lock_names=lock_names))
        else:
            attr = _self_attr(handler)
            if attr is not None:
                for d in defs.get(attr, []):
                    model.signal_handlers.append(SignalHandler(
                        name=attr, node=d, lock_names=lock_names))
    return model
