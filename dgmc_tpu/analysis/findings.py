"""Finding/severity model and the baseline-suppression file.

A :class:`Finding` is one detected hazard: a rule id, a severity, a
``where`` (stable location — ``specimen:file:line`` for trace findings,
``file:line`` for source findings), and a message. Its
:attr:`~Finding.fingerprint` is a stable hash of the identity fields
(never the free-text detail), so a committed baseline keeps suppressing
a finding across unrelated edits but releases it the moment the finding
moves or changes class.

Fingerprints are **line-number independent** (baseline version 2): the
trailing ``:line`` of ``where`` is stripped before hashing, and the
finding's :attr:`~Finding.context` — a normalized snippet of what was
actually flagged (the source line's text, an HLO op's kind+shape) —
takes its place as the within-file discriminator. Pure line relocation
(an edit above the finding) leaves the fingerprint unchanged; the
finding moving to different code (new context) releases it. Version-1
baselines hashed the raw line number and churned on every relocation;
``dgmc-lint --write-baseline`` is the one-shot migration.

The baseline file (``lint-baseline.json``) is the reviewed debt ledger:
``dgmc-lint --write-baseline`` records the current findings;
``dgmc-lint --fail-on new`` then fails only on findings whose
fingerprint is not in the ledger. Pure Python — no jax — so the CLI can
report and diff baselines anywhere.
"""

import dataclasses
import enum
import hashlib
import json
import linecache
import os
import re
from typing import Dict, Iterable, List, Optional, Tuple


class Severity(enum.IntEnum):
    """Ordered severity; comparisons follow the int value."""
    INFO = 10
    WARNING = 20
    ERROR = 30

    @classmethod
    def parse(cls, name):
        try:
            return cls[str(name).upper()]
        except KeyError:
            raise ValueError(
                f'unknown severity {name!r}; expected one of '
                f'{[s.name.lower() for s in cls]}') from None


#: Trailing ``:line`` of a ``where`` string — stripped before hashing so
#: pure line relocation never churns the fingerprint.
_WHERE_LINE = re.compile(r':\d+$')


@dataclasses.dataclass(frozen=True)
class Finding:
    """One detected hazard.

    Args:
        rule: stable rule id (``TRC001``, ``SRC101``, ``RCP201``...).
        severity: :class:`Severity`.
        where: stable location string; trace findings use
            ``specimen:relative/file.py:line``, source findings
            ``relative/file.py:line``.
        message: one-line human description (identity-bearing: part of
            the fingerprint, so keep it deterministic).
        detail: free-form extra context (NOT fingerprinted — safe to
            enrich without invalidating baselines).
        context: normalized snippet of what was flagged — the source
            line's stripped text for source-located findings, an HLO
            op's kind+shape for trace/HLO findings. Identity-bearing:
            together with the line-stripped ``where`` it replaces the
            line number in the fingerprint, so relocation keeps the
            suppression but a different flagged construct releases it.
    """
    rule: str
    severity: Severity
    where: str
    message: str
    detail: Optional[str] = None
    context: Optional[str] = None

    @property
    def fingerprint(self) -> str:
        where = _WHERE_LINE.sub('', self.where)
        ident = f'{self.rule}|{where}|{self.message}'
        if self.context:
            ident += f'|{self.context}'
        return hashlib.sha256(ident.encode()).hexdigest()[:16]

    def to_json(self) -> dict:
        out = {
            'rule': self.rule,
            'severity': self.severity.name.lower(),
            'where': self.where,
            'message': self.message,
            'fingerprint': self.fingerprint,
        }
        if self.detail:
            out['detail'] = self.detail
        if self.context:
            out['context'] = self.context
        return out


def disambiguate_contexts(findings: Iterable[Finding]) -> List[Finding]:
    """Suffix an occurrence ordinal onto the context of every
    same-identity duplicate (same rule, line-stripped where, message,
    and context) so two IDENTICAL violating statements in one file keep
    distinct fingerprints — without it, a copy-pasted duplicate of a
    baselined violation would silently ride its suppression. The first
    occurrence keeps the bare context (stable under relocation); every
    producer calls this on its per-program output, so ordering — and
    with it, which occurrence is first — is the program's deterministic
    walk order."""
    seen: Dict[tuple, int] = {}
    out = []
    for f in findings:
        key = (f.rule, _WHERE_LINE.sub('', f.where), f.message,
               f.context)
        n = seen.get(key, 0)
        seen[key] = n + 1
        if n and f.context:
            f = dataclasses.replace(f, context=f'{f.context} #{n + 1}')
        out.append(f)
    return out


def sort_findings(findings: Iterable[Finding]) -> List[Finding]:
    """Severity-descending, then stable by (rule, where, message)."""
    return sorted(findings,
                  key=lambda f: (-int(f.severity), f.rule, f.where,
                                 f.message))


# ---------------------------------------------------------------------------
# Baseline file
# ---------------------------------------------------------------------------

#: Version 2 = line-number-independent (context-hash) fingerprints.
#: Version-1 ledgers hold line-hashed fingerprints that can never match
#: a v2 finding — loading one for a CHECK is an error (everything would
#: silently report as new-and-unsuppressed or stale); the one-shot
#: migration is ``dgmc-lint --write-baseline``, which re-records the
#: same reviewed findings under their v2 fingerprints.
BASELINE_VERSION = 2
_MIGRATABLE_VERSIONS = (1,)
DEFAULT_BASELINE_NAME = 'lint-baseline.json'


def read_source_line(rel_path: str, lineno: int) -> Optional[str]:
    """The stripped text of ``rel_path:lineno`` — the normalized context
    snippet line-located findings fingerprint on. ``rel_path`` is the
    repo-relative spelling provenance uses (``dgmc_tpu/ops/graph.py``),
    resolved against the tree this package was imported from, then the
    cwd; None when the file or line cannot be read (callers fall back
    to a structural snippet). Reads ride :mod:`linecache`, so N
    findings in one module cost one file read, not N scans."""
    if not rel_path or lineno <= 0:
        return None
    pkg_parent = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    for root in (pkg_parent, os.getcwd()):
        cand = os.path.join(root, rel_path)
        if not os.path.isfile(cand):
            continue
        line = linecache.getline(cand, lineno)
        return line.strip() or None
    return None


def default_baseline_path(start: Optional[str] = None) -> str:
    """Walk up from ``start`` (default cwd) looking for an existing
    baseline file; fall back to the repo root guess (the directory
    holding the ``dgmc_tpu`` package), else ``cwd/<name>``."""
    d = os.path.abspath(start or os.getcwd())
    while True:
        cand = os.path.join(d, DEFAULT_BASELINE_NAME)
        if os.path.exists(cand):
            return cand
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo_guess = os.path.join(os.path.dirname(pkg_root),
                              DEFAULT_BASELINE_NAME)
    if os.path.exists(repo_guess):
        return repo_guess
    return os.path.join(os.path.abspath(start or os.getcwd()),
                        DEFAULT_BASELINE_NAME)


def baseline_version(path: str) -> Optional[int]:
    """The ``version`` field of a baseline file, or None when absent or
    unreadable — the migration-warning probe (lint.py warns when a
    ``--write-baseline`` over a v1 ledger must preserve entries it
    cannot re-fingerprint)."""
    if not path or not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f).get('version')
    except (OSError, ValueError):
        return None


def load_baseline(path: str, migrate: bool = False) -> Dict[str, dict]:
    """``{fingerprint: recorded entry}`` — empty when the file is absent.

    A version-1 ledger (legacy line-hashed fingerprints) raises unless
    ``migrate`` is set: its fingerprints can never match a v2 finding,
    so checking against one silently un-suppresses everything. The
    baseline *rewriters* (``--write-baseline`` / ``--prune-baseline``)
    pass ``migrate=True`` — they only need the old entries to preserve
    unanalyzed tiers, and re-record everything else under v2
    fingerprints (the one-shot migration).
    """
    if not path or not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    version = data.get('version')
    if version == BASELINE_VERSION:
        return {e['fingerprint']: e for e in data.get('findings', [])}
    if version in _MIGRATABLE_VERSIONS:
        if migrate:
            return {e['fingerprint']: e for e in data.get('findings', [])}
        raise ValueError(
            f'{path}: baseline version {version} carries legacy '
            f'line-number fingerprints; run `dgmc-lint --write-baseline` '
            f'once to migrate it to version {BASELINE_VERSION} '
            f'(line-independent context fingerprints)')
    raise ValueError(
        f'{path}: unsupported baseline version {version!r} '
        f'(this dgmc-lint writes version {BASELINE_VERSION})')


def write_baseline(path: str, findings: Iterable[Finding],
                   preserved_entries: Iterable[dict] = ()) -> dict:
    """Write the suppression ledger (sorted, stable) and return it.

    ``preserved_entries`` are raw prior-baseline entries to carry over
    verbatim — the entries of tiers/specimens the writing run did not
    analyze (skipped tier, too few devices), so refreshing the baseline
    in a smaller environment cannot silently un-suppress findings that
    a bigger environment (CI's 8-device mesh) will still produce.
    """
    entries = {e['fingerprint']: dict(e) for e in preserved_entries}
    for f in sort_findings(findings):
        entries[f.fingerprint] = f.to_json()
    payload = {
        'version': BASELINE_VERSION,
        'tool': 'dgmc-lint',
        'findings': sorted(entries.values(),
                           key=lambda e: (e['rule'], e['where'],
                                          e['message'])),
    }
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write('\n')
    os.replace(tmp, path)
    return payload


def split_by_baseline(findings: Iterable[Finding],
                      baseline: Dict[str, dict],
                      ) -> Tuple[List[Finding], List[Finding]]:
    """Partition into (new, suppressed) against a loaded baseline."""
    new, suppressed = [], []
    for f in findings:
        (suppressed if f.fingerprint in baseline else new).append(f)
    return new, suppressed
