"""Finding/severity model and the baseline-suppression file.

A :class:`Finding` is one detected hazard: a rule id, a severity, a
``where`` (stable location — ``specimen:file:line`` for trace findings,
``file:line`` for source findings), and a message. Its
:attr:`~Finding.fingerprint` is a stable hash of the identity fields
(never the free-text detail), so a committed baseline keeps suppressing
a finding across unrelated edits but releases it the moment the finding
moves or changes class.

The baseline file (``lint-baseline.json``) is the reviewed debt ledger:
``dgmc-lint --write-baseline`` records the current findings;
``dgmc-lint --fail-on new`` then fails only on findings whose
fingerprint is not in the ledger. Pure Python — no jax — so the CLI can
report and diff baselines anywhere.
"""

import dataclasses
import enum
import hashlib
import json
import os
from typing import Dict, Iterable, List, Optional, Tuple


class Severity(enum.IntEnum):
    """Ordered severity; comparisons follow the int value."""
    INFO = 10
    WARNING = 20
    ERROR = 30

    @classmethod
    def parse(cls, name):
        try:
            return cls[str(name).upper()]
        except KeyError:
            raise ValueError(
                f'unknown severity {name!r}; expected one of '
                f'{[s.name.lower() for s in cls]}') from None


@dataclasses.dataclass(frozen=True)
class Finding:
    """One detected hazard.

    Args:
        rule: stable rule id (``TRC001``, ``SRC101``, ``RCP201``...).
        severity: :class:`Severity`.
        where: stable location string; trace findings use
            ``specimen:relative/file.py:line``, source findings
            ``relative/file.py:line``.
        message: one-line human description (identity-bearing: part of
            the fingerprint, so keep it deterministic).
        detail: free-form extra context (NOT fingerprinted — safe to
            enrich without invalidating baselines).
    """
    rule: str
    severity: Severity
    where: str
    message: str
    detail: Optional[str] = None

    @property
    def fingerprint(self) -> str:
        ident = f'{self.rule}|{self.where}|{self.message}'
        return hashlib.sha256(ident.encode()).hexdigest()[:16]

    def to_json(self) -> dict:
        out = {
            'rule': self.rule,
            'severity': self.severity.name.lower(),
            'where': self.where,
            'message': self.message,
            'fingerprint': self.fingerprint,
        }
        if self.detail:
            out['detail'] = self.detail
        return out


def sort_findings(findings: Iterable[Finding]) -> List[Finding]:
    """Severity-descending, then stable by (rule, where, message)."""
    return sorted(findings,
                  key=lambda f: (-int(f.severity), f.rule, f.where,
                                 f.message))


# ---------------------------------------------------------------------------
# Baseline file
# ---------------------------------------------------------------------------

BASELINE_VERSION = 1
DEFAULT_BASELINE_NAME = 'lint-baseline.json'


def default_baseline_path(start: Optional[str] = None) -> str:
    """Walk up from ``start`` (default cwd) looking for an existing
    baseline file; fall back to the repo root guess (the directory
    holding the ``dgmc_tpu`` package), else ``cwd/<name>``."""
    d = os.path.abspath(start or os.getcwd())
    while True:
        cand = os.path.join(d, DEFAULT_BASELINE_NAME)
        if os.path.exists(cand):
            return cand
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo_guess = os.path.join(os.path.dirname(pkg_root),
                              DEFAULT_BASELINE_NAME)
    if os.path.exists(repo_guess):
        return repo_guess
    return os.path.join(os.path.abspath(start or os.getcwd()),
                        DEFAULT_BASELINE_NAME)


def load_baseline(path: str) -> Dict[str, dict]:
    """``{fingerprint: recorded entry}`` — empty when the file is absent."""
    if not path or not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    if data.get('version') != BASELINE_VERSION:
        raise ValueError(
            f'{path}: unsupported baseline version {data.get("version")!r} '
            f'(this dgmc-lint writes version {BASELINE_VERSION})')
    return {e['fingerprint']: e for e in data.get('findings', [])}


def write_baseline(path: str, findings: Iterable[Finding],
                   preserved_entries: Iterable[dict] = ()) -> dict:
    """Write the suppression ledger (sorted, stable) and return it.

    ``preserved_entries`` are raw prior-baseline entries to carry over
    verbatim — the entries of tiers/specimens the writing run did not
    analyze (skipped tier, too few devices), so refreshing the baseline
    in a smaller environment cannot silently un-suppress findings that
    a bigger environment (CI's 8-device mesh) will still produce.
    """
    entries = {e['fingerprint']: dict(e) for e in preserved_entries}
    for f in sort_findings(findings):
        entries[f.fingerprint] = f.to_json()
    payload = {
        'version': BASELINE_VERSION,
        'tool': 'dgmc-lint',
        'findings': sorted(entries.values(),
                           key=lambda e: (e['rule'], e['where'],
                                          e['message'])),
    }
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write('\n')
    os.replace(tmp, path)
    return payload


def split_by_baseline(findings: Iterable[Finding],
                      baseline: Dict[str, dict],
                      ) -> Tuple[List[Finding], List[Finding]]:
    """Partition into (new, suppressed) against a loaded baseline."""
    new, suppressed = [], []
    for f in findings:
        (suppressed if f.fingerprint in baseline else new).append(f)
    return new, suppressed
