"""Buffer-liveness model: static peak-live bytes over post-GSPMD HLO.

PR 9's AD-residual blowup (a 2 GiB/device stack of select masks carried
as loop residuals) was caught at runtime, by watching a scale run die.
The information was in the compiled program the whole time: every
buffer's definition point, its last use, and the region structure that
keeps a while body's working set alive on top of its caller's. This
module walks that structure and produces a **static peak-live-bytes
bound** per program, attributed to the ``jax.named_scope`` pipeline
stages ``obs/cost.py`` already buckets by (the ``op_name`` loc metadata
GSPMD copies onto every partitioned op):

- Each op's result allocates its ``result_bytes`` at its definition
  index and frees after its last use. Aliasing bookkeeping
  (``get-tuple-element`` / ``tuple`` / ``bitcast``) is zero-byte but
  **propagates liveness** to the storage it aliases.
- Region ops (``while`` / ``conditional`` / ``call``) add their region's
  peak on top of the live set at the call point — a while body's working
  set rides on everything the caller still holds. Fusion interiors are
  folded into the fusion op's result (the backend never materializes
  them).
- Parameters are live from entry until their last use. Donation aliasing
  is deliberately ignored: the model is a conservative *upper* bound,
  and a bound that assumed donation would under-report exactly when
  donation silently breaks (the TRC004 class).

The MEM rules (:mod:`~dgmc_tpu.analysis.sched_rules`) gate per-specimen
budgets on this bound (the streamed specimen's budget pins the
SCALE_r07 1.04 GiB/device claim's static face), and ``obs/cost.py``
publishes it into ``efficiency.json`` as ``static_peak_bytes``.

Pure text analysis — no jax import.
"""

import dataclasses
from typing import Dict, List, Optional, Tuple

from dgmc_tpu.analysis.hlo_comm import (DTYPE_BYTES, HloModule, HloOp,
                                        _HLO_SHAPE, parse_hlo_module,
                                        stage_of)

__all__ = [
    'ALIAS_OPS', 'REGION_OPS', 'LiveBuffer', 'ComputationLiveness',
    'computation_liveness', 'module_peak', 'peak_summary',
    'while_carry_elements',
]

#: Zero-byte bookkeeping that aliases existing storage (keeps its
#: operands alive for as long as it is referenced).
ALIAS_OPS = frozenset({'get-tuple-element', 'tuple', 'bitcast',
                       'parameter', 'after-all'})

#: Ops whose region's working set stacks on the caller's live set.
#: ``fusion`` is deliberately absent: its interior never materializes.
REGION_OPS = frozenset({'while', 'conditional', 'call'})


@dataclasses.dataclass
class LiveBuffer:
    """One buffer live at the peak point."""
    index: int
    op: HloOp
    nbytes: int

    @property
    def stage(self) -> str:
        return stage_of(self.op.op_name)


@dataclasses.dataclass
class ComputationLiveness:
    """One computation's liveness account."""
    name: str
    #: Static peak-live bytes, region peaks included.
    peak_bytes: int
    #: Program index of the peak point.
    peak_index: int
    #: Buffers live at the peak (excluding region interiors).
    live_at_peak: List[LiveBuffer]
    #: Bytes the region entered at the peak point contributed (0 when
    #: the peak is a flat op).
    region_bytes: int
    #: The region computation charged at the peak, if any.
    region_name: Optional[str]
    #: Pipeline stage of the region op itself (where its bytes charge).
    region_stage: Optional[str] = None

    def stage_bytes(self) -> Dict[str, int]:
        """Live bytes at the peak, grouped by pipeline stage; the
        region's contribution is charged to the region op's stage, so
        the buckets sum to :attr:`peak_bytes` and reconcile against the
        headline bound."""
        out: Dict[str, int] = {}
        for buf in self.live_at_peak:
            out[buf.stage] = out.get(buf.stage, 0) + buf.nbytes
        if self.region_bytes:
            stage = self.region_stage or 'other'
            out[stage] = out.get(stage, 0) + self.region_bytes
        return out


def _alloc_bytes(op: HloOp) -> int:
    """Bytes this op's result genuinely allocates (0 for aliases)."""
    if op.opcode in ALIAS_OPS and op.opcode != 'parameter':
        return 0
    return op.result_bytes


def computation_liveness(module: HloModule, name: str,
                         _memo: Optional[dict] = None,
                         _stack: Optional[frozenset] = None,
                         ) -> ComputationLiveness:
    """Liveness walk of one computation (regions recursed, memoized)."""
    memo = _memo if _memo is not None else {}
    if name in memo:
        return memo[name]
    stack = (_stack or frozenset()) | {name}
    comp = module.computations.get(name)
    if comp is None:
        empty = ComputationLiveness(name=name, peak_bytes=0,
                                    peak_index=-1, live_at_peak=[],
                                    region_bytes=0, region_name=None)
        memo[name] = empty
        return empty

    ops = comp.ops
    n = len(ops)
    defs = {op.result: i for i, op in enumerate(ops)}
    dep_idx: List[Tuple[int, ...]] = []
    for op in ops:
        dep_idx.append(tuple(sorted(
            {defs[r] for r in op.operand_refs() if r in defs})))

    # Last use with alias propagation: an alias op's operands stay live
    # as long as the alias itself is referenced. Reverse walk makes each
    # op's own last_use final before it extends its operands'.
    last_use = list(range(n))
    root = next((i for i in range(n - 1, -1, -1) if ops[i].is_root), n - 1)
    if n:
        last_use[root] = n            # the result outlives the program
    for i in range(n - 1, -1, -1):
        reach = last_use[i] if ops[i].opcode in ALIAS_OPS else i
        for d in dep_idx[i]:
            if last_use[d] < reach:
                last_use[d] = reach
    frees_at: Dict[int, List[int]] = {}
    for i in range(n):
        frees_at.setdefault(last_use[i], []).append(i)

    live: Dict[int, int] = {}
    current = 0
    peak = 0
    peak_i = -1
    peak_live: Dict[int, int] = {}
    peak_region = 0
    peak_region_name = None
    for i, op in enumerate(ops):
        nbytes = _alloc_bytes(op)
        if nbytes:
            live[i] = nbytes
            current += nbytes
        extra = 0
        extra_name = None
        if op.opcode in REGION_OPS:
            for sub in op.called_computations():
                if sub in stack:
                    continue
                sub_live = computation_liveness(module, sub, memo, stack)
                if sub_live.peak_bytes > extra:
                    extra = sub_live.peak_bytes
                    extra_name = sub
        if current + extra > peak:
            peak = current + extra
            peak_i = i
            peak_live = dict(live)
            peak_region = extra
            peak_region_name = extra_name
        for j in frees_at.get(i, ()):
            current -= live.pop(j, 0)

    result = ComputationLiveness(
        name=name, peak_bytes=peak, peak_index=peak_i,
        live_at_peak=[LiveBuffer(index=j, op=ops[j], nbytes=b)
                      for j, b in sorted(peak_live.items())],
        region_bytes=peak_region, region_name=peak_region_name,
        region_stage=(stage_of(ops[peak_i].op_name)
                      if peak_region_name and 0 <= peak_i < n else None))
    memo[name] = result
    return result


def module_peak(text_or_module) -> ComputationLiveness:
    """The ENTRY computation's liveness account (regions included) —
    the program's static peak-live bound."""
    module = (text_or_module if isinstance(text_or_module, HloModule)
              else parse_hlo_module(text_or_module))
    entry = module.entry or (next(iter(module.computations), None))
    if entry is None:
        return ComputationLiveness(name='<empty>', peak_bytes=0,
                                   peak_index=-1, live_at_peak=[],
                                   region_bytes=0, region_name=None)
    return computation_liveness(module, entry)


def peak_summary(text_or_module) -> dict:
    """The fields ``obs/cost.py`` merges into ``efficiency.json``:
    ``static_peak_bytes`` (the ONE key this number carries on every
    surface — efficiency.json, obs.diff rows, the schedule-report
    artifact — so cross-artifact grep works), the peak point's
    per-stage byte attribution, and the charged region (if the peak
    sits inside a while body)."""
    lv = module_peak(text_or_module)
    out = {'static_peak_bytes': lv.peak_bytes}
    stages = {k: v for k, v in sorted(lv.stage_bytes().items(),
                                      key=lambda kv: -kv[1]) if v}
    if stages:
        out['peak_stage_bytes'] = stages
    if lv.region_name:
        out['peak_region'] = lv.region_name
        out['peak_region_bytes'] = lv.region_bytes
    return out


def while_carry_elements(op: HloOp) -> List[Tuple[str, Tuple[int, ...], int]]:
    """``(dtype, dims, nbytes)`` per element of a while op's carried
    tuple — the loop-carried state MEM405's residual accounting walks.
    Parsed from the while's result type (identical to the carry type by
    HLO's while contract)."""
    out = []
    for m in _HLO_SHAPE.finditer(op.result_type):
        dims = tuple(int(d) for d in m.group(2).split(',') if d)
        n = 1
        for d in dims:
            n *= d
        out.append((m.group(1), dims, n * DTYPE_BYTES.get(m.group(1), 4)))
    return out
