"""Shared post-GSPMD HLO walker: computations, collectives, schedules.

One parser owns every place this repo reads cross-device communication
out of compiled programs:

- :func:`collective_table` — op-kind counts and payload bytes, the
  account ``obs/cost.py`` publishes into ``efficiency.json`` (it used to
  carry its own line scanner; that implementation now lives here, and
  cost imports it).
- :func:`parse_hlo_module` / :func:`collective_schedule` — the
  structured view the SHD lint tier
  (:mod:`~dgmc_tpu.analysis.shd_rules`) needs: every computation
  (ENTRY, while bodies/conditions, conditional branches, called
  subroutines) with its ops in program order, each collective carrying
  its kind, ``channel_id``, ``replica_groups``, payload bytes, scope
  ``op_name`` and source provenance.

Input is the text of a compiled executable (``compiled.as_text()``,
post-SPMD-partitioning HLO — ops spelt ``all-reduce(...)``, or the
async ``all-reduce-start``/``-done`` pair real TPU executables overlap
with compute; a pair counts as ONE collective) or lowered StableHLO asm
(manual ``shard_map`` collectives spelt ``stablehlo.all_reduce`` —
handled by :func:`collective_table` only; StableHLO regions carry no
collective schedule worth walking before partitioning).

Pure text parsing — importing this module must never bring up a jax
backend, so the CLI can analyze saved dumps anywhere.
"""

import dataclasses
import re
from typing import Dict, Iterator, List, Optional, Tuple

__all__ = [
    'COLLECTIVE_OPS', 'DTYPE_BYTES', 'STAGE_NAMES', 'hlo_shape_bytes',
    'mlir_tensor_info', 'HloOp', 'HloComputation', 'HloModule',
    'CollectiveOp', 'parse_hlo_module', 'collective_schedule',
    'collective_table', 'stage_of', 'trim_source_path',
]

#: Cross-device collective ops, HLO spelling (the StableHLO spelling
#: substitutes ``_`` for ``-``).
COLLECTIVE_OPS = ('all-reduce', 'all-gather', 'reduce-scatter',
                  'all-to-all', 'collective-permute',
                  'collective-broadcast')

DTYPE_BYTES = {
    'f64': 8, 'f32': 4, 'f16': 2, 'bf16': 2, 'f8e4m3fn': 1, 'f8e5m2': 1,
    'c64': 8, 'c128': 16,
    's64': 8, 's32': 4, 's16': 2, 's8': 1,
    'i64': 8, 'i32': 4, 'i16': 2, 'i8': 1, 'i4': 1, 'i1': 1,
    'u64': 8, 'u32': 4, 'u16': 2, 'u8': 1, 'ui64': 8, 'ui32': 4,
    'ui16': 2, 'ui8': 1, 'pred': 1,
}

# `f32[128,4]` — layout suffixes (`{1,0}`) deliberately unmatched.
_HLO_SHAPE = re.compile(r'([a-z][a-z0-9]*)\[([0-9,]*)\]')
# MLIR `tensor<8x16xf32>` types (StableHLO asm).
_MLIR_TENSOR = re.compile(r'tensor<(?:([0-9x?]*)x)?([a-z][a-z0-9]*)>')


def hlo_shape_bytes(text: str) -> int:
    """Sum of payload bytes over every HLO shape literal in ``text``."""
    total = 0
    for dtype, dims in _HLO_SHAPE.findall(text):
        n = 1
        for d in dims.split(','):
            if d:
                n *= int(d)
        total += n * DTYPE_BYTES.get(dtype, 4)
    return total


def mlir_tensor_info(dims: str, dtype: str) -> Tuple[int, int]:
    """(element_count, bytes) for one parsed MLIR ``tensor<...>`` type."""
    n = 1
    if dims:
        for d in dims.split('x'):
            if d in ('', '?'):
                continue
            n *= int(d)
    return n, n * DTYPE_BYTES.get(dtype, 4)


def _shape_dims(type_text: str) -> Optional[Tuple[str, Tuple[int, ...]]]:
    """(dtype, dims) of the FIRST array shape in an HLO type string;
    None for token/opaque/empty types."""
    m = _HLO_SHAPE.search(type_text)
    if not m:
        return None
    dims = tuple(int(d) for d in m.group(2).split(',') if d)
    return m.group(1), dims


def trim_source_path(fname: str) -> str:
    """Stabilize an absolute source path across checkouts/venvs — keep
    everything from the last ``site-packages``/repo-ish component (the
    same normalization :func:`~dgmc_tpu.analysis.jaxpr_rules.
    eqn_provenance` applies to jaxpr source info)."""
    for marker in ('site-packages/', 'dist-packages/'):
        if marker in fname:
            return fname.split(marker, 1)[1]
    parts = fname.split('/')
    for anchor in ('dgmc_tpu', 'tests', 'examples', 'benchmarks'):
        if anchor in parts:
            return '/'.join(parts[parts.index(anchor):])
    return fname


#: Pipeline stages the per-stage attributions bucket ops into,
#: innermost-scope wins (``psi2`` is nested inside ``consensus_iter``;
#: ``loss`` and ``optimizer`` come from ``train/steps.py``). Lives here
#: — next to the op-name metadata parsing — so both the ``obs/cost.py``
#: account (which re-exports it) and the liveness model bucket
#: identically.
STAGE_NAMES = ('psi1', 'psi2', 'initial_corr', 'topk', 'consensus_iter',
               'loss', 'optimizer')


def stage_of(op_name: str) -> str:
    """Map one op-name scope path to its pipeline stage (innermost
    matching scope wins; ``'other'`` when none matches). Transposed
    (backward) ops carry the primal scope inside ``transpose(...)``
    segments, so they attribute to the same stage."""
    for seg in reversed(op_name.split('/')):
        for stage in STAGE_NAMES:
            if stage in seg:
                return stage
    return 'other'


#: Serving-path span vocabulary (``obs/qtrace.py``): the fixed set of
#: per-query spans a ``/match`` request decomposes into, in pipeline
#: order. Lives HERE, next to :data:`STAGE_NAMES`, because the two
#: vocabularies must reconcile rather than fork: each span that wraps
#: device work maps onto the model stages via
#: :data:`SERVE_SPAN_STAGES`, so the static cost account, the profiler
#: trace, and the served span tree all speak one dialect.
SERVE_SPAN_NAMES = ('admission_queue_wait', 'bucket_resolve',
                    'pad_and_stage', 'device_execute', 'shortlist_merge',
                    'consensus_rerank', 'serialize')

#: Which model stages (:data:`STAGE_NAMES` members) each serve span
#: covers. Host-only spans (queueing, routing, padding, JSON) map to
#: the empty tuple — they have no device-stage twin by construction.
#: ``device_execute`` is the fused forward on the device corpus tier;
#: the host-offload tier splits it from the candidate gather
#: (``shortlist_merge``) and the rerank (``consensus_rerank``).
SERVE_SPAN_STAGES = {
    'admission_queue_wait': (),
    'bucket_resolve': (),
    'pad_and_stage': (),
    'device_execute': ('psi1', 'initial_corr', 'topk'),
    'shortlist_merge': ('topk',),
    'consensus_rerank': ('consensus_iter', 'psi2'),
    'serialize': (),
}


# ---------------------------------------------------------------------------
# Structured HLO module parsing
# ---------------------------------------------------------------------------

# `ENTRY %main.10_spmd (param: f32[4,4]) -> f32[] {` / `%region_2.30 (...`
_COMP_HEADER = re.compile(
    r'^(ENTRY\s+)?%?([\w.\-]+)\s*\(.*\)\s*->\s*.+\{\s*$')
# `  %x = f32[4,4]{1,0} all-reduce(...)`, `  ROOT %y = (s32[], f32[]) ...`
_OP_LINE = re.compile(
    r'^\s*(ROOT\s+)?%?([\w.\-]+)\s*=\s*(\(.*?\)|\S+)\s+([\w\-]+)\(')
_CHANNEL_ID = re.compile(r'channel_id=(\d+)')
# `backend_config={"known_trip_count":{"n":"32"}}` on while ops whose
# trip count XLA proved constant (every lax.scan lowers this way).
_TRIP_COUNT = re.compile(r'"known_trip_count":\s*\{"n":"(\d+)"\}')
_REGION_REF = re.compile(
    r'\b(condition|body|true_computation|false_computation|to_apply|'
    r'calls)=%?([\w.\-]+)')
_BRANCHES = re.compile(r'branch_computations=\{([^}]*)\}')
_METADATA_OP_NAME = re.compile(r'op_name="([^"]*)"')
_METADATA_SOURCE = re.compile(
    r'source_file="([^"]*)"(?:\s+source_line=(\d+))?')


def _replica_groups(line: str) -> Optional[str]:
    """The raw ``replica_groups=`` value: either the brace list
    ``{{0,1},{2,3}}`` or the iota form ``[2,2]<=[4]`` /
    ``[2,2]<=[2,2]T(1,0)`` — consumed with bracket balancing, not a
    regex, because the brace form nests commas."""
    key = 'replica_groups='
    start = line.find(key)
    if start < 0:
        return None
    i = start + len(key)
    depth = 0
    out = []
    while i < len(line):
        c = line[i]
        if c in '{[(':
            depth += 1
        elif c in '}])':
            depth -= 1
            if depth < 0:
                break
        elif c == ',' and depth == 0:
            break
        elif c == ' ' and depth == 0 and out and out[-1] not in '<=':
            break
        out.append(c)
        i += 1
    return ''.join(out) or None


@dataclasses.dataclass
class HloOp:
    """One parsed HLO instruction."""
    result: str
    result_type: str
    opcode: str
    line: str
    is_root: bool = False

    @property
    def collective_kind(self) -> Optional[str]:
        """Base collective kind (``-start`` normalized away; ``-done``
        and non-collectives return None — an async pair is counted at
        its ``-start``)."""
        op = self.opcode
        if op.endswith('-done'):
            return None
        if op.endswith('-start'):
            op = op[:-len('-start')]
        return op if op in COLLECTIVE_OPS else None

    @property
    def async_done_kind(self) -> Optional[str]:
        """Base collective kind of a ``-done`` op (None otherwise) —
        the half of an async pair :attr:`collective_kind` deliberately
        ignores. Needed to count a pair whose ``-start`` lives in a
        DIFFERENT computation (a collective threaded through a while
        boundary) exactly once."""
        if not self.opcode.endswith('-done'):
            return None
        base = self.opcode[:-len('-done')]
        return base if base in COLLECTIVE_OPS else None

    @property
    def is_async_start(self) -> bool:
        return (self.opcode.endswith('-start')
                and self.collective_kind is not None)

    @property
    def channel_id(self) -> Optional[int]:
        m = _CHANNEL_ID.search(self.line)
        return int(m.group(1)) if m else None

    @property
    def known_trip_count(self) -> Optional[int]:
        """Constant trip count of a ``while`` op, from the
        ``known_trip_count`` backend config XLA stamps on loops it
        proved bounded (``lax.scan``'s counted loop always is). None
        when absent or not a while — callers treating None as 1 get
        the conservative single-execution reading."""
        if self.opcode != 'while':
            return None
        m = _TRIP_COUNT.search(self.line)
        return int(m.group(1)) if m else None

    @property
    def replica_groups(self) -> Optional[str]:
        return _replica_groups(self.line)

    @property
    def op_name(self) -> str:
        """The scope path from ``metadata={op_name=...}`` (GSPMD copies
        it from the op that demanded the communication)."""
        m = _METADATA_OP_NAME.search(self.line)
        return m.group(1) if m else ''

    @property
    def source_loc(self) -> Optional[str]:
        """``relative/file.py:line`` from op metadata, or None."""
        m = _METADATA_SOURCE.search(self.line)
        if not m or not m.group(1):
            return None
        path = trim_source_path(m.group(1))
        return f'{path}:{m.group(2)}' if m.group(2) else path

    @property
    def result_bytes(self) -> int:
        """Payload bytes of the result type (tuple results — e.g. an
        async ``-start`` wrapping bookkeeping shapes — sum every listed
        shape: an upper bound close enough for attribution)."""
        return hlo_shape_bytes(self.result_type)

    @property
    def result_shape(self) -> Optional[Tuple[str, Tuple[int, ...]]]:
        return _shape_dims(self.result_type)

    def operands(self) -> List[Tuple[str, Tuple[int, ...], str]]:
        """``(dtype, dims, %name)`` for each typed operand in the call
        parens — HLO text carries operand types inline."""
        start = self.line.find(self.opcode + '(')
        if start < 0:
            return []
        start += len(self.opcode) + 1
        depth = 1
        i = start
        while i < len(self.line) and depth:
            if self.line[i] == '(':
                depth += 1
            elif self.line[i] == ')':
                depth -= 1
            i += 1
        args = self.line[start:i - 1]
        # Split on top-level commas only — shape dims (`f32[4,8]`) and
        # nested tuples carry commas of their own.
        pieces, depth, cur = [], 0, []
        for c in args:
            if c in '([{':
                depth += 1
            elif c in ')]}':
                depth -= 1
            if c == ',' and depth == 0:
                pieces.append(''.join(cur))
                cur = []
            else:
                cur.append(c)
        if cur:
            pieces.append(''.join(cur))
        out = []
        for piece in pieces:
            m = re.search(r'([a-z][a-z0-9]*)\[([0-9,]*)\][^%]*%([\w.\-]+)',
                          piece)
            if m:
                dims = tuple(int(d) for d in m.group(2).split(',') if d)
                out.append((m.group(1), dims, m.group(3)))
        return out

    def operand_refs(self) -> List[str]:
        """Every ``%name`` referenced inside the call parens — typed or
        not — in operand order. The dependency edges the schedule and
        liveness models walk (``operands()`` keeps only typed operands,
        which drops e.g. ``get-tuple-element``'s bare tuple ref)."""
        start = self.line.find(self.opcode + '(')
        if start < 0:
            return []
        start += len(self.opcode) + 1
        depth = 1
        i = start
        while i < len(self.line) and depth:
            if self.line[i] == '(':
                depth += 1
            elif self.line[i] == ')':
                depth -= 1
            i += 1
        return re.findall(r'%([\w.\-]+)', self.line[start:i - 1])

    def called_computations(self) -> List[str]:
        """Region computations this op enters: while body/condition,
        conditional branches, ``call``/``fusion`` targets. ``to_apply``
        is a region only for ``call``-like ops — on reductions and
        collectives it names the scalar combiner, which cannot hold
        collectives and whose shared clones would be double-walked."""
        out = []
        for kind, name in _REGION_REF.findall(self.line):
            if kind == 'to_apply' and self.opcode not in ('call',
                                                         'async-start'):
                continue
            out.append(name)
        m = _BRANCHES.search(self.line)
        if m:
            out.extend(n.strip().lstrip('%')
                       for n in m.group(1).split(',') if n.strip())
        return out

    def branch_computations(self) -> List[str]:
        """Branch regions of a ``conditional`` (either spelling), in
        branch order; empty for other ops."""
        if self.opcode != 'conditional':
            return []
        m = _BRANCHES.search(self.line)
        if m:
            return [n.strip().lstrip('%')
                    for n in m.group(1).split(',') if n.strip()]
        refs = dict((k, v) for k, v in _REGION_REF.findall(self.line))
        out = []
        for key in ('true_computation', 'false_computation'):
            if key in refs:
                out.append(refs[key])
        return out


@dataclasses.dataclass
class HloComputation:
    name: str
    is_entry: bool
    ops: List[HloOp]


@dataclasses.dataclass
class HloModule:
    computations: Dict[str, HloComputation]
    entry: Optional[str]

    def iter_ops(self) -> Iterator[Tuple[HloComputation, HloOp]]:
        for comp in self.computations.values():
            for op in comp.ops:
                yield comp, op

    def while_bodies(self) -> List[Tuple[HloOp, str]]:
        """``(while_op, body_computation_name)`` for every while."""
        out = []
        for _, op in self.iter_ops():
            if op.opcode != 'while':
                continue
            refs = dict(_REGION_REF.findall(op.line))
            if 'body' in refs:
                out.append((op, refs['body']))
        return out

    def orphan_done_ids(self) -> frozenset:
        """``id()`` of every ``-done`` op whose matching ``-start`` is
        absent from this module — the start lives across a while/call
        boundary the dump did not carry (or a saved fragment cut it).
        Pairing is two-stage: a done consumes its same-computation start
        through its operand; an unconsumed done then claims any
        same-kind start with the same ``channel_id`` anywhere in the
        module (the while-boundary case). What remains is an orphan,
        and stands in for its whole pair wherever collectives are
        counted — so a split pair counts exactly once, never zero."""
        starts_by_channel = {}
        unmatched = []
        for comp in self.computations.values():
            defs = {op.result: op for op in comp.ops}
            for op in comp.ops:
                if op.is_async_start:
                    key = (op.collective_kind, op.channel_id)
                    starts_by_channel[key] = \
                        starts_by_channel.get(key, 0) + 1
            for op in comp.ops:
                kind = op.async_done_kind
                if kind is None:
                    continue
                operands = op.operands()
                producer = (defs.get(operands[0][2]) if operands
                            else None)
                if producer is not None and producer.is_async_start:
                    key = (kind, producer.channel_id)
                    if starts_by_channel.get(key, 0) > 0:
                        starts_by_channel[key] -= 1
                    continue
                unmatched.append((kind, op))
        orphans = []
        for kind, op in unmatched:
            key = (kind, op.channel_id)
            if starts_by_channel.get(key, 0) > 0:
                starts_by_channel[key] -= 1       # cross-computation pair
                continue
            orphans.append(id(op))
        return frozenset(orphans)

    def flatten_collectives(self, comp_name: str,
                            _seen: Optional[frozenset] = None,
                            _orphans: Optional[frozenset] = None,
                            ) -> List['CollectiveOp']:
        """Collectives reachable from ``comp_name``, program order,
        descending into regions (a while body contributes once — its
        per-iteration repetition is a schedule property, not an op
        count). An async pair counts at its ``-start``; a ``-done``
        whose start is absent from the module (while-boundary split,
        truncated dump) stands in for its pair instead of vanishing."""
        comp = self.computations.get(comp_name)
        if comp is None:
            return []
        if _orphans is None:
            _orphans = self.orphan_done_ids()
        seen = (_seen or frozenset()) | {comp_name}
        out = []
        for op in comp.ops:
            kind = op.collective_kind
            if kind is None and id(op) in _orphans:
                kind = op.async_done_kind
            if kind is not None:
                out.append(CollectiveOp.from_op(kind, op, comp_name))
            for sub in op.called_computations():
                if sub not in seen:
                    out.extend(self.flatten_collectives(sub, seen,
                                                        _orphans))
        return out


@dataclasses.dataclass
class CollectiveOp:
    """One collective in a program's communication schedule."""
    kind: str
    channel_id: Optional[int]
    replica_groups: Optional[str]
    nbytes: int
    computation: str
    op_name: str
    source_loc: Optional[str]
    line: str

    @classmethod
    def from_op(cls, kind: str, op: HloOp, comp_name: str):
        return cls(kind=kind, channel_id=op.channel_id,
                   replica_groups=op.replica_groups,
                   nbytes=op.result_bytes, computation=comp_name,
                   op_name=op.op_name, source_loc=op.source_loc,
                   line=op.line)


def parse_hlo_module(text: str) -> HloModule:
    """Parse compiled-HLO text into computations of ops (program order
    preserved). Lines outside any computation (module header, config)
    are ignored; a malformed line is skipped, never fatal — the walker
    is a reader of compiler output, not a validator."""
    computations: Dict[str, HloComputation] = {}
    entry = None
    current: Optional[HloComputation] = None
    for raw in text.splitlines():
        stripped = raw.strip()
        m = _COMP_HEADER.match(stripped)
        if m and ' = ' not in stripped:
            current = HloComputation(name=m.group(2),
                                     is_entry=bool(m.group(1)), ops=[])
            computations[current.name] = current
            if current.is_entry:
                entry = current.name
            continue
        if stripped == '}':
            current = None
            continue
        m = _OP_LINE.match(raw)
        if m:
            if current is None:
                # Headerless fragments (saved snippets, test fixtures):
                # collect loose ops under an implicit computation.
                current = computations.setdefault(
                    '<module>', HloComputation('<module>', False, []))
            current.ops.append(HloOp(
                result=m.group(2), result_type=m.group(3),
                opcode=m.group(4), line=stripped,
                is_root=bool(m.group(1))))
    return HloModule(computations=computations, entry=entry)


def collective_schedule(text_or_module) -> List[CollectiveOp]:
    """The program's collective schedule: every collective reachable
    from ENTRY in program order, descending through while bodies/
    conditions, conditional branches, and calls. This is what the SHD
    rules consume — op kind, replica groups, channel ids, payload
    bytes, and the region each collective sits in."""
    module = (text_or_module if isinstance(text_or_module, HloModule)
              else parse_hlo_module(text_or_module))
    if module.entry is None:
        # Fixture fragments without an ENTRY marker: treat the first
        # computation as the program.
        names = list(module.computations)
        if not names:
            return []
        return module.flatten_collectives(names[0])
    return module.flatten_collectives(module.entry)


# ---------------------------------------------------------------------------
# Aggregate table (the obs/cost.py account)
# ---------------------------------------------------------------------------


def _stablehlo_collective_table(text: str) -> Dict[str, Dict[str, int]]:
    ops: Dict[str, Dict[str, int]] = {}
    for line in text.splitlines():
        for name in COLLECTIVE_OPS:
            if 'stablehlo.' + name.replace('-', '_') not in line:
                continue
            row = ops.setdefault(name, {'count': 0, 'bytes': 0})
            row['count'] += 1
            tensors = _MLIR_TENSOR.findall(line)
            if tensors:
                _, nbytes = mlir_tensor_info(tensors[-1][0] or '',
                                             tensors[-1][1])
                row['bytes'] += nbytes
            break
    return ops


def collective_table(text: str) -> Dict:
    """Collective-op counts and result bytes from program text.

    Accepts post-GSPMD compiled HLO (structured parse — every
    computation's ops, async ``-start``/``-done`` pairs counted once)
    and StableHLO asm (manual ``shard_map`` collectives, line scan).
    Returns ``{'ops': {name: {'count', 'bytes'}}, 'count', 'bytes'}``
    (empty ``ops`` when the program moves nothing between devices).
    This is the single collective accounting both ``obs/cost.py`` and
    the SHD lint tier build on.
    """
    if 'stablehlo.' in text:
        ops = _stablehlo_collective_table(text)
    else:
        ops = {}
        module = parse_hlo_module(text)
        orphans = module.orphan_done_ids()
        for _, op in module.iter_ops():
            kind = op.collective_kind
            if kind is None and id(op) in orphans:
                # A -done whose -start fell across a computation
                # boundary (or off the dump): stands in for its pair —
                # counted once, never zero, never twice.
                kind = op.async_done_kind
            if kind is None:
                continue
            row = ops.setdefault(kind, {'count': 0, 'bytes': 0})
            row['count'] += 1
            row['bytes'] += op.result_bytes
    return {'ops': ops,
            'count': sum(r['count'] for r in ops.values()),
            'bytes': sum(r['bytes'] for r in ops.values())}
