"""SCH/MEM tier: schedule & liveness rules over post-GSPMD HLO.

The SHD tier (PR 8) reads *what* a partitioned program communicates;
this tier reads *when* and *how much lives*: the schedule model
(:mod:`~dgmc_tpu.analysis.hlo_sched` — dependency DAG, async intervals,
conservative two-stream list schedule) and the liveness model
(:mod:`~dgmc_tpu.analysis.hlo_liveness` — static peak-live bytes with
region stacking). Five rules run over each registered sched-tier
specimen's compiled HLO:

``SCH401`` serialized-async-collective (error)
    An async ``-start``/``-done`` pair inside a while body with NO
    compute between start and done in program order: the program paid
    for asynchrony and then immediately blocked on it. The streamed-S
    shard-boundary collective-permutes exist to overlap the per-tile
    top-k compute — a pair that serializes is the chunk loop regressing
    to lockstep.
``SCH402`` overlap-budget (warning)
    The program's modeled collective overlap fraction fell below the
    specimen's recorded ``overlap_budget`` (declared in the registry
    beside SHD304's ``comm_budget_bytes``). The model is dependency
    slack, not wall clock: a drop means an edit added a dependence that
    FORCES serialization, whatever the runtime does.
``SCH403`` double-buffer-opportunity (info)
    A fetch-class op (gather / dynamic-slice / collective-permute)
    inside a while body that is on the body's critical path, feeds the
    body's compute, re-issues off the loop carry every iteration, and
    moves at least ``double_buffer_min_bytes`` — the classic
    single-buffered chunk loop ROADMAP item 4 wants pipelined
    (double-buffer the source chunks so iteration k+1's fetch overlaps
    iteration k's compute).
``MEM404`` peak-budget (error)
    Static peak-live bytes exceed the specimen's recorded
    ``peak_bytes_budget``. The streamed specimen's budget is the static
    face of SCALE_r07's 1.04 GiB/device claim: a regression fails CI
    before any scale run is launched.
``MEM405`` residual-blowup (error)
    A loop-carried buffer whose shape scales with the FULL streamed axis
    (``stream_full``) instead of the chunk (``stream_chunk``) and whose
    bytes clear ``residual_min_bytes`` — the PR 9 class (per-tile select
    masks stacked as backward residuals, 2 GiB/device at 2^20 targets
    for a search whose real state was ``[rows, k]``) as a lint.
"""

import dataclasses
import math
from typing import List, Optional

from dgmc_tpu.analysis.findings import (Finding, Severity,
                                        disambiguate_contexts)
from dgmc_tpu.analysis.hlo_comm import HloModule, parse_hlo_module
from dgmc_tpu.analysis.hlo_liveness import (module_peak,
                                            while_carry_elements)
from dgmc_tpu.analysis.hlo_sched import (FETCH_OPS, module_schedules,
                                         schedule_summary)
from dgmc_tpu.analysis.shd_rules import _loc, _pow2_bucket

__all__ = ['SchedContext', 'analyze_schedule_hlo', 'run_sched_tier',
           'check_serialized_async', 'check_overlap_budget',
           'check_double_buffer', 'check_peak_budget',
           'check_residual_blowup']


@dataclasses.dataclass
class SchedContext:
    """Provenance prefix + budgets for one partitioned program."""
    specimen: str = 'program'
    #: Minimum modeled collective overlap fraction (0..1); SCH402 runs
    #: only with it set (recorded per specimen like SHD304's budget).
    overlap_budget: Optional[float] = None
    #: Static peak-live byte budget; MEM404 runs only with it set.
    peak_bytes_budget: Optional[int] = None
    #: Full length of the streamed axis and the chunk it streams in;
    #: MEM405 runs only with both set.
    stream_full: Optional[int] = None
    stream_chunk: Optional[int] = None
    #: A loop-carried full-axis buffer below this is not worth an ERROR
    #: (fixture-scale specimens carry tiny legitimate state; the defect
    #: class is measured in GiB).
    residual_min_bytes: int = 1 << 20
    #: A serialized in-loop fetch below this is not worth a report.
    double_buffer_min_bytes: int = 1 << 20


# ---------------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------------


def check_serialized_async(module: HloModule, ctx: SchedContext,
                           scheds=None) -> List[Finding]:
    """SCH401: async pair in a while body with nothing overlappable
    between start and done as written."""
    out = []
    if scheds is None:
        scheds = module_schedules(module)
    # while_bodies() order, deduped — NOT a set: finding order feeds
    # disambiguate_contexts' occurrence ordinals, which must be the
    # program's deterministic walk order, never hash order.
    bodies = list(dict.fromkeys(b for _, b in module.while_bodies()))
    for name in bodies:
        sched = scheds.get(name)
        if sched is None:
            continue
        idx = 0
        for coll in sched.collectives:
            if coll.program_gap_cost is None:
                continue                      # sync op, not a pair
            if coll.done_index is None:
                # Start whose done lives across the loop back-edge (the
                # pipelined/double-buffered pattern): the transfer
                # overlaps the NEXT iteration's compute — exactly what
                # this rule's remediation recommends, never an error.
                continue
            idx += 1
            if coll.program_gap_cost > 0:
                continue
            op = coll.op
            out.append(Finding(
                rule='SCH401', severity=Severity.ERROR,
                where=f'{ctx.specimen}:'
                      f'{_loc(op, f"{op.opcode}#{idx - 1}")}',
                message=(f'async `{coll.kind}` inside a loop body is '
                         f'serialized — its -done immediately follows '
                         f'the -start with no compute in between'),
                detail=(f'{coll.nbytes} B in flight in computation '
                        f'`{name}` with zero overlappable work; move '
                        f'independent per-tile compute between the '
                        f'start/done pair (or double-buffer the chunk '
                        f'loop) so the transfer hides behind it'),
                context=f'{op.opcode} {op.result_type}'))
    return out


def check_overlap_budget(module: HloModule, ctx: SchedContext,
                         scheds=None) -> List[Finding]:
    """SCH402: modeled overlap fraction under the recorded budget."""
    if ctx.overlap_budget is None:
        return []
    summary = schedule_summary(module, scheds=scheds)
    measured = summary.get('overlap_fraction')
    if measured is None or measured >= ctx.overlap_budget:
        return []
    return [Finding(
        rule='SCH402', severity=Severity.WARNING,
        where=f'{ctx.specimen}:sched-overlap',
        message=(f'modeled collective overlap fraction fell below the '
                 f'recorded budget {ctx.overlap_budget} — a dependency '
                 f'now forces serialization'),
        detail=(f'measured {measured} over '
                f'{summary.get("collective_count", 0)} collective(s) '
                f'({summary.get("serialized_collectives", 0)} fully '
                f'serialized, {summary.get("collective_bytes", 0)} B '
                f'payload); either the serialization is intended '
                f'(lower the overlap_budget in the registry and '
                f're-baseline) or an edit chained the chunk loop'))]


def check_double_buffer(module: HloModule, ctx: SchedContext,
                        scheds=None) -> List[Finding]:
    """SCH403: a big critical-path fetch re-issued per iteration off the
    loop carry — the single-buffered chunk loop."""
    out = []
    if scheds is None:
        scheds = module_schedules(module)
    for w_i, (while_op, body) in enumerate(module.while_bodies()):
        sched = scheds.get(body)
        if sched is None:
            continue
        params = {i for i, s in enumerate(sched.ops)
                  if s.op.opcode == 'parameter'}
        # Transitive carry-derived set (ops fed by the loop state).
        carried = set(params)
        for s in sched.ops:
            if any(d in carried for d in s.deps):
                carried.add(s.index)
        hits = 0
        for s in sched.ops:
            op = s.op
            if op.opcode not in FETCH_OPS:
                continue
            if s.duration < ctx.double_buffer_min_bytes:
                continue
            if s.index not in carried or s.index not in sched.critical_ops:
                continue
            # Feeds compute: some compute op downstream of the fetch.
            feeds = any(s.index in t.deps and t.stream == 'compute'
                        for t in sched.ops)
            if not feeds:
                downstream = {s.index}
                for t in sched.ops:
                    if any(d in downstream for d in t.deps):
                        downstream.add(t.index)
                        if t.stream == 'compute':
                            feeds = True
                            break
            if not feeds:
                continue
            out.append(Finding(
                rule='SCH403', severity=Severity.INFO,
                where=f'{ctx.specimen}:'
                      f'{_loc(op, f"{op.opcode}#{w_i}.{hits}")}',
                message=(f'`{op.opcode}` fetching '
                         f'{_pow2_bucket(s.duration)} per iteration is '
                         f'strictly serialized behind the loop-carried '
                         f'state — double-buffer opportunity'),
                detail=(f'the fetch sits on the critical path of loop '
                        f'body `{body}` and feeds its compute: '
                        f"iteration k+1's fetch cannot start until "
                        f'iteration k finishes. Restructure the body to '
                        f"fetch chunk k+1 while computing chunk k "
                        f'(ROADMAP item 4) to hide the latency'),
                context=f'{op.opcode} {op.result_type}'))
            hits += 1
    return out


def check_peak_budget(module: HloModule,
                      ctx: SchedContext) -> List[Finding]:
    """MEM404: static peak-live bytes over the recorded budget."""
    if not ctx.peak_bytes_budget:
        return []
    lv = module_peak(module)
    if lv.peak_bytes <= ctx.peak_bytes_budget:
        return []
    stages = ', '.join(f'{k}: {v} B'
                       for k, v in sorted(lv.stage_bytes().items(),
                                          key=lambda kv: -kv[1])[:5])
    region = (f'; +{lv.region_bytes} B inside region '
              f'`{lv.region_name}`' if lv.region_name else '')
    return [Finding(
        rule='MEM404', severity=Severity.ERROR,
        where=f'{ctx.specimen}:peak-live',
        message=(f'static peak-live bytes {_pow2_bucket(lv.peak_bytes)} '
                 f'exceed the recorded {ctx.peak_bytes_budget} B '
                 f'device budget'),
        detail=(f'exact peak {lv.peak_bytes} B at program index '
                f'{lv.peak_index} — top stages: {stages}{region}; '
                f'either the growth is intended (raise '
                f'peak_bytes_budget in the registry and re-baseline) '
                f'or a buffer began outliving its consumer'))]


def check_residual_blowup(module: HloModule,
                          ctx: SchedContext) -> List[Finding]:
    """MEM405: loop-carried buffer scaling with the full streamed axis."""
    if not ctx.stream_full or not ctx.stream_chunk:
        return []
    full, chunk = ctx.stream_full, ctx.stream_chunk
    trips = math.ceil(full / chunk)
    out = []
    for w_i, (while_op, body) in enumerate(module.while_bodies()):
        for dtype, dims, nbytes in while_carry_elements(while_op):
            # rank-1 full-axis carries are excluded BY DESIGN: a 1-D
            # [stream_full] vector is the legitimate per-row OUTPUT
            # class (row maxima, shortlist scores) whose size is the
            # answer, not a residual; the PR 9 blowup class is rank>=2
            # slabs (full axis x per-chunk working set).
            if nbytes < ctx.residual_min_bytes or len(dims) < 2:
                continue
            n = 1
            for d in dims:
                n *= d
            # A dim IS the streamed axis only when it equals its length
            # — `>=` would flag any big unrelated feature/hidden dim on
            # a carried accumulator as "the corpus axis".
            full_dim = any(d == full for d in dims)
            stacked = (trips > 1 and dims[0] == trips
                       and n >= full * chunk)
            if not (full_dim or stacked):
                continue
            shape = f'{dtype}[{",".join(map(str, dims))}]'
            spelling = ('carries a full streamed-axis dimension'
                        if full_dim else
                        f'stacks one slab per chunk (leading dim '
                        f'{dims[0]} = trip count)')
            out.append(Finding(
                rule='MEM405', severity=Severity.ERROR,
                where=f'{ctx.specimen}:'
                      f'{_loc(while_op, f"while#{w_i}")}',
                message=(f'loop-carried {shape} ({nbytes} B) scales '
                         f'with the full streamed axis ({full}) instead '
                         f'of the chunk ({chunk}) — AD-residual blowup '
                         f'class'),
                detail=(f'the carried buffer {spelling}; at streamed '
                        f'scale this is the PR 9 select-mask defect '
                        f'(2 GiB/device of residuals for a [rows, k] '
                        f'search state). Make the producing search '
                        f'AD-opaque (custom_jvp + stop_gradient) or '
                        f'rematerialize in the backward pass instead '
                        f'of carrying full-axis residuals'),
                context=f'while carry {shape}'))
    return out


def analyze_schedule_hlo(hlo_text,
                         ctx: Optional[SchedContext] = None,
                         ) -> List[Finding]:
    """All SCH/MEM rules over one partitioned program (parsed once)."""
    ctx = ctx or SchedContext()
    module = (hlo_text if isinstance(hlo_text, HloModule)
              else parse_hlo_module(hlo_text))
    # ONE schedule build serves all three SCH rules (the dominant cost
    # of this tier after the specimen compile itself).
    scheds = module_schedules(module)
    out = []
    out += check_serialized_async(module, ctx, scheds)
    out += check_overlap_budget(module, ctx, scheds)
    out += check_double_buffer(module, ctx, scheds)
    out += check_peak_budget(module, ctx)
    out += check_residual_blowup(module, ctx)
    return disambiguate_contexts(out)


# ---------------------------------------------------------------------------
# Tier driver
# ---------------------------------------------------------------------------


def run_sched_tier(specimens=None, *, cache=None, on_progress=None,
                   skipped=None) -> List[Finding]:
    """Compile every sched-registered specimen under its mesh (reusing
    the lint run's shared SpecimenCache lowerings — the same compiled
    text the SHD tier read) and run the SCH/MEM rules. Mesh specimens
    below the process's device count are skipped and reported, like the
    other compiled tiers."""
    from dgmc_tpu.analysis.registry import (SpecimenCache,
                                            iter_runnable_specimens)

    cache = cache if cache is not None else SpecimenCache()
    findings = []
    for spec in iter_runnable_specimens('sched', specimens=specimens,
                                        on_progress=on_progress,
                                        skipped=skipped):
        if on_progress:
            on_progress(f'schedule {spec.name}')
        art = cache.artifacts(spec)
        built = art.built()
        module = parse_hlo_module(art.compiled().as_text())
        ctx = SchedContext(
            specimen=spec.name,
            overlap_budget=built.get('overlap_budget'),
            peak_bytes_budget=built.get('peak_bytes_budget'),
            stream_full=built.get('stream_full'),
            stream_chunk=built.get('stream_chunk'))
        # The byte floors default to GiB-class scale-run values; a
        # fixture-scale specimen must scale them down with itself or
        # the rules it arms are inert in CI (the streamed specimen
        # declares a floor just above its largest legitimate carry).
        if built.get('residual_min_bytes') is not None:
            ctx.residual_min_bytes = built['residual_min_bytes']
        if built.get('double_buffer_min_bytes') is not None:
            ctx.double_buffer_min_bytes = built['double_buffer_min_bytes']
        findings.extend(analyze_schedule_hlo(module, ctx))
    return findings
