"""Static TPU-hostility analysis over jaxprs, compiled executables, and
repo source.

Every hazard this repo has been bitten by so far — the jax-0.4.37
persistent-cache donation-aliasing corruption, silent Pallas→XLA kernel
fallbacks, compile churn across padding buckets, host callbacks leaking
into "probe-free" steps — surfaced at *runtime*, usually
nondeterministically. This subsystem catches those defect classes before
a run is launched, in two tiers:

- **trace tier** (:mod:`~dgmc_tpu.analysis.jaxpr_rules`,
  :mod:`~dgmc_tpu.analysis.registry`): lower the registered hot
  functions (DGMC forward, train/eval steps, fused ops, sharded steps)
  under representative shape/dtype/mesh configs and walk the
  ClosedJaxpr + compiled executable for dtype drift, giant baked-in
  constants, host-sync callbacks, dropped donation aliasing, and
  TPU-pathological lowerings.
- **source tier** (:mod:`~dgmc_tpu.analysis.source_rules`): ``ast``
  lints over the package source for tracer leaks, host syncs inside
  jitted code, jit-inside-loop construction, and static-arg
  hashability traps.
- **sharded-HLO tier** (:mod:`~dgmc_tpu.analysis.shd_rules`, on the
  shared post-GSPMD walker :mod:`~dgmc_tpu.analysis.hlo_comm`): compile
  the registered multi-device specimens under their meshes and run
  communication rules over the partitioned HLO's collective schedule —
  branch-divergent collectives (the static face of the multichip-hang
  class), implicit full replication of correspondence-shaped tensors,
  resharding churn inside the consensus loop, per-specimen
  communication-byte budgets, and bf16-accumulation precision-contract
  violations.
- **schedule & liveness tier** (:mod:`~dgmc_tpu.analysis.sched_rules`,
  on the schedule model :mod:`~dgmc_tpu.analysis.hlo_sched` and the
  liveness model :mod:`~dgmc_tpu.analysis.hlo_liveness`): over the same
  compiled specimens, a dependency-DAG list schedule measures each
  collective's dependence-allowed overlap (serialized async pairs,
  per-specimen overlap budgets, double-buffer opportunities in streamed
  chunk loops) and a buffer-liveness walk bounds static peak-live bytes
  per device (per-specimen budgets — the static face of the
  million-entity memory claims — and the AD-residual-blowup class of
  loop-carried full-axis buffers).
- **concurrency tier** (:mod:`~dgmc_tpu.analysis.con_rules`, on the
  thread-entry/lock model :mod:`~dgmc_tpu.analysis.concurrency`):
  ``ast`` lints over the serving source — which class attributes are
  touched from thread entry points (Thread/Timer targets,
  ``do_GET``/``do_POST`` handlers, signal/atexit hooks) and which
  locks guard them — for unlocked read-modify-writes (the PR-15
  serve-counter race class), lock-order inversions, non-atomic
  artifact writes, unsafe signal-handler work, and unbounded shared
  container growth.

A recompile-hazard pass (:mod:`~dgmc_tpu.analysis.recompile`) hashes
abstract step signatures across padding buckets and cross-checks them
against the ``obs`` compile telemetry of a recorded run.

CLI: ``python -m dgmc_tpu.analysis.lint`` (installed as ``dgmc-lint``),
with ``--json``, severity levels, ``--select``/``--ignore`` rule
filters, per-rule ``--explain`` docs, and a committed
baseline-suppression file (``lint-baseline.json``) so known findings
don't fail CI while new ones do (``--fail-on new``;
``--prune-baseline`` retires entries that stopped reproducing).
"""

from dgmc_tpu.analysis.findings import (Finding, Severity, load_baseline,
                                        write_baseline, split_by_baseline)
from dgmc_tpu.analysis.jaxpr_rules import (analyze_closed_jaxpr,
                                           analyze_donation,
                                           callback_equations)
from dgmc_tpu.analysis.source_rules import (lint_source_tree,
                                            lint_source_file,
                                            lint_source_paths)
from dgmc_tpu.analysis.con_rules import (lint_concurrency_tree,
                                         lint_concurrency_file,
                                         lint_concurrency_paths)
from dgmc_tpu.analysis.recompile import analyze_buckets, bucket_signature
from dgmc_tpu.analysis.registry import (SpecimenCache, default_specimens,
                                        run_trace_tier)
from dgmc_tpu.analysis.hlo_comm import collective_schedule, parse_hlo_module
from dgmc_tpu.analysis.shd_rules import analyze_sharded_hlo, run_sharded_tier
from dgmc_tpu.analysis.hlo_sched import module_schedules, schedule_summary
from dgmc_tpu.analysis.hlo_liveness import module_peak, peak_summary
from dgmc_tpu.analysis.sched_rules import (analyze_schedule_hlo,
                                           run_sched_tier)

__all__ = [
    'Finding',
    'Severity',
    'load_baseline',
    'write_baseline',
    'split_by_baseline',
    'analyze_closed_jaxpr',
    'analyze_donation',
    'callback_equations',
    'lint_source_tree',
    'lint_source_file',
    'lint_source_paths',
    'lint_concurrency_tree',
    'lint_concurrency_file',
    'lint_concurrency_paths',
    'analyze_buckets',
    'bucket_signature',
    'SpecimenCache',
    'default_specimens',
    'run_trace_tier',
    'collective_schedule',
    'parse_hlo_module',
    'analyze_sharded_hlo',
    'run_sharded_tier',
    'module_schedules',
    'schedule_summary',
    'module_peak',
    'peak_summary',
    'analyze_schedule_hlo',
    'run_sched_tier',
]
