"""The rule catalog: every lint rule's id, tier, severity, and doc.

One structured table owns what a rule IS (``dgmc-lint --list-rules``),
what it means (``dgmc-lint --explain RULE`` — what/why/fix), and the
reference page (``docs/source/modules/lint-rules.rst`` enumerates the
same entries; a test pins the two in sync). Pure data — no jax — so the
CLI can answer ``--explain`` without bringing up a backend.
"""

import dataclasses
from typing import Dict

__all__ = ['RuleDoc', 'RULES', 'RULE_CATALOG', 'TIERS', 'explain_rule']

#: Tier key -> human name (the order tiers report in). SCH and MEM are
#: two rule families of ONE tier (the schedule & liveness pass over the
#: same compiled specimens; ``--skip-sched`` skips both).
TIERS = {
    'TRC': 'trace (lowered jaxpr / compiled executable)',
    'SRC': 'source (ast lints over the package source)',
    'RCP': 'recompile (padding-bucket churn + obs telemetry)',
    'SHD': 'sharded HLO (post-GSPMD partitioned programs)',
    'SCH': 'schedule (list-schedule overlap over partitioned HLO)',
    'MEM': 'liveness (static peak-live bytes over partitioned HLO)',
    'CON': 'concurrency (thread-entry/lock model over serving source)',
}


@dataclasses.dataclass(frozen=True)
class RuleDoc:
    """One rule's documentation: a one-line title plus what/why/fix."""
    rule: str
    severity: str
    title: str
    what: str
    why: str
    fix: str

    @property
    def tier(self) -> str:
        return TIERS[self.rule[:3]]


def _r(rule, severity, title, what, why, fix):
    return RuleDoc(rule=rule, severity=severity, title=title, what=what,
                   why=why, fix=fix)


RULES: Dict[str, RuleDoc] = {d.rule: d for d in [
    # --- trace tier ------------------------------------------------------
    _r('TRC001', 'error',
       'dtype promotion: 64-bit value introduced in a <=32-bit pipeline',
       'An equation introduces an f64/i64/u64/c128 result from '
       'non-64-bit inputs.',
       'The pipeline is 32-bit-or-narrower by design; TPUs have no f64 '
       'units, XLA emulates them at >10x cost, and one wide value '
       'poisons everything downstream of it.',
       'Find the introducing op (the finding carries per-equation '
       'source provenance) and pin its dtype — usually a Python float '
       'default, np.float64 constant, or an int64 index helper.'),
    _r('TRC002', 'warning',
       'giant constant folded into the program',
       'A constant above --max-const-bytes (default 1 MiB) is baked '
       'into the traced program.',
       'Big baked-in arrays bloat every serialized executable, defeat '
       'donation, and usually mean a dataset or lookup table was '
       'closed over at trace time instead of being passed in.',
       'Pass the array as an argument (donatable, shardable) instead '
       'of closing over it.'),
    _r('TRC003', 'error',
       'host callback in a program expected callback-free '
       '(probes disabled)',
       'A host-callback equation (debug_callback / pure_callback / '
       'io_callback) appears although probes are disabled.',
       'The obs probe layer guarantees byte-identical HLO with probes '
       'off; a callback here means a probe or stray jax.debug.print '
       'leaked past its trace-time gate and will fence device->host '
       'every step.',
       'Gate the callback behind the probe switch (obs/probes.py) or '
       'delete it; re-run dgmc-lint to confirm zero callback '
       'equations.'),
    _r('TRC004', 'error',
       'donated argument lost its input-output aliasing',
       'An argument was donated but the compiled executable retains no '
       'input-output aliasing for it.',
       'Donation silently degrades to a copy — and broken aliasing is '
       'the defect class of the jax-0.4.37 persistent-cache bug '
       '(executables deserialized with broken aliasing read freed '
       'buffers).',
       'Make the donated input shape/dtype match an output, or stop '
       'donating it; a fresh compile must alias or the step was never '
       'entitled to donate.'),
    _r('TRC005', 'info',
       'scatter without unique_indices (serial/atomic on TPU)',
       'A scatter op without unique_indices=True.',
       'TPU lowers it serially (or via atomics). Inherent to unsorted '
       'GNN segment aggregation in places — the committed baseline '
       'carries the reviewed sites; the rule catches new ones.',
       'Prefer sorted/blocked aggregation forms (ops/blocked.py) on '
       'hot paths; where the scatter is inherent, review and '
       'baseline it.'),
    _r('TRC006', 'warning',
       'large sort where a top-k selection was intended',
       'A sort over an axis of >= 4096 elements.',
       'A full sort of a large axis on TPU is a multi-pass '
       'bandwidth-bound operation; every such site in this codebase '
       'was meant to be a streaming top-k shortlist.',
       'Use jax.lax.top_k or the blockwise running top-k '
       '(ops/topk.py) instead of argsort/sort.'),
    # --- source tier -----------------------------------------------------
    _r('SRC100', 'error', 'source file failed to parse',
       'The source tier could not ast-parse a .py file.',
       'An unparseable file is invisible to every source rule — the '
       'lint would silently stop covering it.',
       'Fix the syntax error (the finding carries the location).'),
    _r('SRC101', 'error',
       'tracer leak: jitted function stores to self/global',
       'A jit-compiled function assigns a traced value to self.<attr> '
       'or a declared global.',
       'The stored tracer escapes the trace and poisons the next call '
       '(UnexpectedTracerError at best, stale constants at worst).',
       'Return the value instead of storing it, or move the store '
       'outside the jitted function.'),
    _r('SRC102', 'warning',
       'host sync inside jitted code (float/int/bool/.item/np.asarray)',
       'float(x) / int(x) / bool(x) / x.item() / np.asarray(x) on a '
       'traced value inside jitted code.',
       'Each forces concretization: a trace-time error under jit, or a '
       'silent device->host fence where tracing is avoided.',
       'Keep the value on device (jnp ops, lax.cond for control flow); '
       'pull to host only outside the jit boundary.'),
    _r('SRC103', 'warning', 'jax.jit constructed inside a loop',
       'jax.jit(...) is called inside a loop body.',
       'Every iteration builds a fresh wrapper whose compile cache is '
       'thrown away — the textbook recompile-churn generator.',
       'Hoist the jit construction out of the loop and reuse the '
       'wrapper.'),
    _r('SRC104', 'warning',
       'static arg with an unhashable (mutable) default',
       'static_argnums/static_argnames names a parameter whose default '
       'is a mutable list/dict/set literal.',
       'Static args are jit cache keys and must be hashable; the '
       'default explodes the first time it is actually used.',
       'Use a hashable default (tuple, frozenset, None-sentinel).'),
    # --- recompile pass --------------------------------------------------
    _r('RCP201', 'warning',
       'padding bucket dominated by another (avoidable compile churn)',
       'A padding bucket every one of whose padded dimensions is <= '
       'another bucket of the SAME pair-batch size.',
       'Collating into the bigger padding serves both batches with ONE '
       'XLA program at the cost of a few masked rows; the dominated '
       'bucket is pure compile churn. The pair-batch axis (B, '
       '--pairs-per-step) is deliberately NOT a padding axis: padding '
       'B replicates the whole per-pair cost and changes how many '
       'gradient samples a step averages.',
       'Collate into the larger node/edge padding (utils/data.'
       'pad_pair_batch limits) so the dominated bucket disappears.'),
    _r('RCP202', 'warning',
       'compile events exceed what padding buckets explain',
       'An obs-recorded run compiled more programs than its distinct '
       'padding signatures * the per-bucket budget.',
       'Recompiles are coming from somewhere the padding analysis '
       'cannot see: unstable static args, trace-time Python values, '
       'dtype flips.',
       'Diff the compile-event labels in the obs run (timings.json) '
       'against the padding buckets; stabilize whatever argument is '
       'changing identity.'),
    # --- sharded-HLO tier ------------------------------------------------
    _r('SHD301', 'error',
       'collective sequence diverges across sibling branches',
       'A conditional whose branches carry different collective '
       'sequences in the partitioned program — a collective reachable '
       'on one control path but not its sibling.',
       'If the predicate ever disagrees across devices (non-replicated '
       'input, NaN-path divergence), part of the mesh posts a '
       'collective its peers never enter and every participant blocks '
       'forever: the static face of the rc:124 multichip-hang class '
       '(ROADMAP item 1).',
       'Hoist the collective out of the conditional, or make both '
       'branches communicate identically (same kinds, same order).'),
    _r('SHD302', 'error',
       'implicit full replication of a correspondence-shaped tensor',
       'An all-gather / collective-broadcast whose result is a full '
       '[B, N_s, N_t]-shaped tensor at least as large as the '
       "specimen's declared correspondence payload.",
       'GSPMD inserts these silently at sharding boundaries; one of '
       'them re-materializes on every device the S matrix the sharded '
       'layout exists to split — at the million-entity scale of '
       'ROADMAP item 3 that is an instant OOM.',
       'Add a with_sharding_constraint at the producing op, or '
       'reformulate the consumer to operate shard-locally '
       '(shard_map, as parallel/topk.py does).'),
    _r('SHD303', 'warning',
       'resharding churn inside the consensus iteration body',
       'Two or more resharding collectives that BOUNCE the layout '
       'inside one while-loop body: all-to-alls, and collective-'
       'permutes composed through the body dataflow (one permute fed '
       'by another — the data left and came back in one iteration). '
       'Independent per-iteration permutes are exempt: they are the '
       'pipelined streamed-S ring rotation (the boundary transfer '
       'deliberately re-issued each iteration, overlapped with the '
       'per-tile top-k — at any ring size; a 2-device rotation is its '
       'own inverse, so churn cannot be read off source_target_pairs).',
       'The layout is bounced back and forth on EVERY consensus '
       'iteration — communication cost that scales with num_steps '
       'instead of being paid once.',
       'Settle the layout before the loop: put matching sharding '
       'constraints on the loop-carried state so GSPMD keeps one '
       'layout through the body.'),
    _r('SHD304', 'warning',
       'per-step collective payload exceeds the specimen budget',
       "The program's total collective bytes exceed the specimen's "
       'recorded comm_budget_bytes (analysis/registry.py).',
       'Communication budgets are recorded next to the specimen like '
       'the recompile pass records compiles-per-bucket: silent growth '
       'in moved bytes is how sharding regressions land unnoticed.',
       'If the new communication is intended, raise the budget in the '
       'registry and re-baseline; otherwise find the moved sharding '
       'boundary (the finding lists the per-kind byte breakdown).'),
    _r('SHD305', 'error',
       'precision contract: f32->bf16 downcast feeds an accumulation',
       'A reduce/dot accumulating in bf16 — worst when an explicit '
       'f32->bf16 convert feeds it.',
       "models/precision.py's contract is bf16 COMPUTE with f32 "
       'ACCUMULATION: a bf16 running sum stops absorbing addends once '
       'it is ~256x any contribution, so long reductions silently '
       'lose mass. This is a correctness rule, not a style rule.',
       'Set preferred_element_type=f32 on the contraction, or keep '
       'the reduction input in f32 (cast AFTER the accumulation).'),
    # --- schedule & liveness tier ----------------------------------------
    _r('SCH401', 'error',
       'async collective serialized inside a loop body',
       'An async -start/-done pair inside a while body with no compute '
       'between the start and its done in program order.',
       'The program paid for asynchrony and then immediately blocked '
       'on it: the streamed-S shard-boundary collective-permutes exist '
       'to overlap the per-tile top-k compute, and a pair that '
       'serializes is the chunk loop regressing to lockstep '
       '(ROADMAP item 4).',
       'Move independent per-tile compute between the start/done pair, '
       'or double-buffer the chunk loop so the transfer hides behind '
       "the previous chunk's work."),
    _r('SCH402', 'warning',
       'modeled collective overlap below the specimen budget',
       "The program's dependency-allowed collective overlap fraction "
       '(conservative two-stream list schedule, analysis/hlo_sched.py) '
       "fell below the specimen's recorded overlap_budget "
       '(analysis/registry.py, beside the SHD304 comm budget).',
       'The model measures what the dependency structure PERMITS, not '
       'wall clock: a drop means an edit added a dependence that '
       'forces serialization on every backend, including the TPU runs '
       'the CPU CI cannot time.',
       'If the serialization is intended, lower the overlap_budget in '
       'the registry and re-baseline; otherwise find the new '
       'dependence chaining the chunk loop (the finding counts the '
       'fully-serialized collectives).'),
    _r('SCH403', 'info',
       'per-iteration fetch serialized behind the loop carry '
       '(double-buffer opportunity)',
       'A gather / dynamic-slice / collective-permute on a while '
       "body's critical path that re-issues off the loop-carried state "
       'every iteration, feeds the body compute, and moves at least '
       'double_buffer_min_bytes.',
       "Iteration k+1's fetch cannot start until iteration k finishes "
       '— the strictly-serial chunk loop ROADMAP item 4 wants '
       'pipelined. The INFO severity marks an optimization '
       'opportunity, not a defect.',
       'Restructure the body to fetch chunk k+1 while computing chunk '
       'k (double buffering); the fetch then overlaps compute and '
       'SCH402 can pin the win.'),
    _r('MEM404', 'error',
       'static peak-live bytes exceed the specimen device budget',
       "The liveness model's static peak-live bound "
       '(analysis/hlo_liveness.py: defs to last uses, region peaks '
       "stacked, aliasing bookkeeping zero-byte) exceeds the specimen's "
       'recorded peak_bytes_budget.',
       "The streamed specimen's budget is the static face of "
       "SCALE_r07's 1.04 GiB/device claim: memory regressions at "
       'million-entity scale must fail CI before a scale run is '
       'launched, not during one.',
       'If the growth is intended, raise peak_bytes_budget in the '
       'registry and re-baseline; otherwise the finding names the top '
       'stages holding bytes at the peak point — find the buffer that '
       'began outliving its consumer.'),
    _r('MEM405', 'error',
       'loop-carried residual scales with the full streamed axis',
       'A while-carried buffer of rank >= 2 and at least '
       'residual_min_bytes whose shape carries a full streamed-axis '
       'dimension (or stacks one slab per chunk across the whole axis) '
       'in a specimen that declares stream_full/stream_chunk. Rank-1 '
       'full-axis vectors are excluded by design: a [stream_full] '
       'vector is the legitimate per-row output class, not a residual '
       'slab.',
       'The PR 9 defect class as a lint: under value_and_grad the '
       'chunked candidate search stacked per-tile select masks as loop '
       'residuals — 2 GiB/device at 2^20 targets for a search whose '
       'real state is [rows, k]. Residual bytes must scale with the '
       'chunk, never the corpus.',
       'Make the producing search AD-opaque (custom_jvp + '
       'stop_gradient, as ops/topk.py does) or rematerialize in the '
       'backward pass instead of carrying full-axis residuals.'),
    # --- concurrency tier ------------------------------------------------
    _r('CON501', 'error',
       'shared attribute read-modify-written from a thread with no lock',
       'A class attribute is read-modify-written (`+=` / `self.x = '
       'self.x + ...`) from a method reachable from a thread entry '
       'point (Thread/Timer target, do_GET/do_POST handler, '
       'signal/atexit hook) while no write site of that attribute in '
       'the class holds a lock. Plain rebinding is exempt: a single '
       'STORE_ATTR is atomic under the GIL.',
       'Python `+=` on an attribute is read-op-write, not atomic: '
       'concurrent handler threads interleave between the read and the '
       'store and increments vanish silently — the PR-15 serve-counter '
       'bug (queries_served undercounted under load) as a rule class.',
       'Guard every write of the attribute with the class lock '
       '(`with self._lock: self.n += 1` — StreamingHistogram.observe '
       'in obs/live.py is the in-repo model), or make the state '
       'thread-local and merge on read.'),
    _r('CON502', 'error',
       'nested lock acquisition order inconsistent across call paths',
       'Two locks of one class are acquired nested in both orders — '
       'A then B on one path, B then A on another — lexically or one '
       '`self.<m>()` call level deep.',
       'Opposite acquisition orders deadlock by construction: the '
       'first time two threads interleave between the outer and inner '
       'acquire, each holds what the other needs, forever. The serve '
       'engine already carries two locks and the continuous batcher '
       'adds a queue lock — order discipline has to be mechanical.',
       'Pick one canonical order for every pair of locks and '
       'restructure the second path to match (or release the first '
       'lock before taking the second, as engine.match does between '
       'its admission and stats sections).'),
    _r('CON503', 'warning',
       'consumed artifact written in place (no tmp+rename)',
       "open(path, 'w') on an artifact path in a function that never "
       'calls os.replace/os.rename and whose path expression does not '
       'name a temp file.',
       'The write is torn twice over: a concurrent reader (supervisor, '
       'scraper, restarted worker) can open the file mid-write, and a '
       'crash leaves a truncated artifact that poisons the next run. '
       'Every obs artifact writer in this repo uses tmp+os.replace for '
       'exactly this reason.',
       'Write through utils/io.write_json_atomic, or an explicit '
       "f'{path}.tmp.{pid}' + os.replace; append mode is exempt."),
    _r('CON504', 'error',
       'unsafe work inside a signal handler',
       'A registered signal.signal handler acquires a lock, performs '
       'buffered IO (open/print/logging), or builds allocation-heavy '
       'formatted output (json.dumps, str.format, traceback.format_*) '
       'directly in its body.',
       'The handler runs with the interrupted thread stopped at an '
       'arbitrary bytecode: any lock it takes may already be held '
       '(instant deadlock), and buffered IO can re-enter stream '
       'internals mid-update. The watchdog signal path is lock-free '
       'by contract for exactly this reason.',
       'Set a flag/Event and do the work on a thread, or restrict the '
       'handler to pre-cached state and lock-free writes (the '
       'watchdog `_on_signal` -> `dump(use_locks=False)` model).'),
    _r('CON505', 'warning',
       'shared container grows without bound from a serving thread',
       'A list/dict/set/deque attribute built in __init__ grows '
       '(.append/.add/keyed store) from a thread-entry method while '
       'the class shows no cap: no deque(maxlen=...), no len() check, '
       'no eviction or rotation.',
       'A long-lived serving process accretes per-query state forever '
       'until the OOM killer arrives — hours or days after the deploy, '
       'far from the cause. The bounded-ring discipline (FlightRecorder '
       'deque(maxlen), qtrace capacity with drop accounting) exists '
       'for this.',
       'Use collections.deque(maxlen=...) for rings, or an explicit '
       'capacity check with drop/evict accounting on every growth '
       'path.'),
]}

#: ``{rule: one-line title}`` — the ``--list-rules`` table (kept under
#: the historical name; lint.py re-exports it).
RULE_CATALOG = {rule: doc.title for rule, doc in RULES.items()}


def explain_rule(rule: str) -> str:
    """The ``--explain`` rendering of one rule (raises KeyError on an
    unknown id)."""
    d = RULES[rule]
    return (f'{d.rule} — {d.title}\n'
            f'  severity: {d.severity}    tier: {d.tier}\n'
            f'  What: {d.what}\n'
            f'  Why:  {d.why}\n'
            f'  Fix:  {d.fix}')
