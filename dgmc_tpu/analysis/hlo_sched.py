"""Schedule model over post-GSPMD HLO: overlap, serialization, critical
path.

ROADMAP item 4 asks for the streamed-S chunk loop's compute/communication
overlap to be verified *statically* — the weak-scaling gap (0.894 at
SCALE_r07) is the chunk loop waiting on gather/collective and the
collective waiting on compute, and that serialization is visible in the
compiled program's dependency structure long before a run is launched.
This module builds that view:

- **Dependency DAG** per computation over
  :func:`~dgmc_tpu.analysis.hlo_comm.parse_hlo_module` output: every op's
  ``%operand`` references become edges (``HloOp.operand_refs``).
- **Async intervals**: ``-start``/``-done`` pairs are widened into
  in-flight intervals (paired through the done's operand chain inside a
  computation; a cross-computation pair — the while-boundary split
  ``hlo_comm`` counts once — degrades to a zero-length join here, which
  is the conservative reading).
- **Conservative list schedule**: ops run in program order on two
  streams — one compute stream, one communication stream — each op
  starting no earlier than its dependencies finish. Durations are byte
  proxies (result bytes for compute, payload bytes for collectives):
  deterministic, machine-free, and comparable run over run. Under this
  model a *synchronous* collective still occupies only the comm stream;
  whether any compute lands inside its window is decided purely by the
  dependency structure — which is exactly the question "could this
  communication overlap?". A serial chunk loop (fetch k -> compute k ->
  fetch k+1) shows zero overlap because its chain forces it; a
  double-buffered body (fetch k+1 independent of compute k) shows the
  overlap the rewrite bought, statically.
- **Per-collective overlap fraction**: the fraction of a collective's
  modeled in-flight window covered by busy compute-stream time; the
  program's ``overlap_fraction`` is the payload-weighted mean, with
  in-loop collectives weighted once per modeled trip
  (:func:`computation_trip_factors` — a ring body's boundary permute
  at 8 rotations moves more bytes than a one-shot gradient psum, and
  the weighting must say so). A collective with zero overlappable
  compute is **serialized**.
- **Critical-path share**: longest dependency-path cost over total cost
  — how much of the program is chain, not width. 1.0 = fully serial.

``python -m dgmc_tpu.analysis.hlo_sched`` renders the schedule report
over the registered multi-device specimens (the artifact CI uploads);
the SCH rules (:mod:`~dgmc_tpu.analysis.sched_rules`) consume the same
model, and ``obs/cost.py`` publishes ``overlap_fraction`` into
``efficiency.json`` from it — one model, three consumers, no drift.

Pure text analysis — importing this module must never bring up a jax
backend (the CLI entry point imports the registry lazily).
"""

import dataclasses
from typing import Dict, List, Optional, Tuple

from dgmc_tpu.analysis.hlo_comm import (HloComputation, HloModule, HloOp,
                                        parse_hlo_module)

__all__ = [
    'FREE_OPS', 'FETCH_OPS', 'ScheduledOp', 'CollectiveInterval',
    'ComputationSchedule', 'schedule_computation', 'module_schedules',
    'computation_trip_factors', 'schedule_summary', 'main',
]

#: Ops that neither move nor produce bytes worth modeling: bookkeeping
#: that any backend folds away. Zero duration, no stream occupancy.
FREE_OPS = frozenset({
    'parameter', 'constant', 'get-tuple-element', 'tuple', 'bitcast',
    'after-all', 'partition-id', 'replica-id', 'iota', 'broadcast',
    'reshape',
})

#: Ops that FETCH the next chunk's data in a streamed loop body — the
#: double-buffer candidates SCH403 watches: gathers/slices re-issued per
#: iteration off the carry, and the shard-boundary permutes.
FETCH_OPS = frozenset({
    'gather', 'dynamic-slice', 'collective-permute',
    'collective-permute-start', 'all-gather', 'all-gather-start',
})


@dataclasses.dataclass
class ScheduledOp:
    """One op's placement in the modeled schedule."""
    index: int
    op: HloOp
    stream: str               # 'compute' | 'comm' | 'free'
    duration: int             # byte proxy
    start: float
    finish: float
    deps: Tuple[int, ...]


@dataclasses.dataclass
class CollectiveInterval:
    """One collective's modeled in-flight window."""
    op: HloOp
    kind: str
    nbytes: int
    computation: str
    start: float
    finish: float
    #: Busy compute-stream time inside [start, finish).
    overlapped: float
    #: ``overlapped / duration`` (0..1); 0.0 = fully serialized.
    overlap_fraction: float
    #: For an async pair: compute cost of ops strictly between the
    #: ``-start`` and its ``-done`` in PROGRAM order (what the program as
    #: written can hide the latency behind). None for sync collectives.
    program_gap_cost: Optional[int] = None
    #: The matched ``-done`` op's index; None for sync collectives and
    #: cross-computation pairs.
    done_index: Optional[int] = None


@dataclasses.dataclass
class ComputationSchedule:
    """The schedule model of one computation."""
    name: str
    ops: List[ScheduledOp]
    collectives: List[CollectiveInterval]
    compute_cost: int
    comm_cost: int
    #: Longest dependency-path cost (infinite-resource bound).
    critical_path_cost: int
    #: ``critical_path_cost / (compute_cost + comm_cost)`` — 1.0 means
    #: the computation is one chain: nothing can overlap anything.
    critical_path_share: float
    #: Indices (into ``ops``) on at least one critical path.
    critical_ops: frozenset

    @property
    def overlap_fraction(self) -> Optional[float]:
        """Payload-weighted mean per-collective overlap; None without
        collectives."""
        total = sum(c.nbytes for c in self.collectives)
        if not total:
            return None
        return sum(c.overlap_fraction * c.nbytes
                   for c in self.collectives) / total


def _duration(op: HloOp) -> int:
    if op.opcode in FREE_OPS or op.opcode.endswith('-done'):
        return 0
    return max(op.result_bytes, 1)


def _dep_indices(comp: HloComputation) -> List[Tuple[int, ...]]:
    defs = {op.result: i for i, op in enumerate(comp.ops)}
    out = []
    for op in comp.ops:
        deps = []
        for name in op.operand_refs():
            j = defs.get(name)
            if j is not None:
                deps.append(j)
        out.append(tuple(sorted(set(deps))))
    return out


def _pair_async_in_comp(comp: HloComputation) -> Dict[int, int]:
    """``{start_index: done_index}`` for async pairs joined through the
    done's operand chain within one computation. A done whose producer
    is not a start (the start crossed a while boundary) stays unpaired —
    the schedule treats it as an instant join, the conservative
    reading."""
    defs = {op.result: i for i, op in enumerate(comp.ops)}
    pairs = {}
    for i, op in enumerate(comp.ops):
        if op.async_done_kind is None:
            continue
        refs = op.operand_refs()
        j = defs.get(refs[0]) if refs else None
        if j is not None and comp.ops[j].is_async_start:
            pairs[j] = i
    return pairs


def schedule_computation(comp: HloComputation) -> ComputationSchedule:
    """Run the conservative list schedule over one computation.

    Program order is preserved per stream (no reordering — the model
    never claims more overlap than a scheduler keeping HLO order could
    achieve); an op starts at ``max(stream frontier, deps ready)``.
    Collectives occupy the comm stream, everything else with bytes the
    compute stream; consumers of a collective wait for its finish
    through the dependency edge, so a dependence-serialized program
    shows serialized collectives no matter which stream they sit on.
    """
    deps = _dep_indices(comp)
    async_pairs = _pair_async_in_comp(comp)
    done_to_start = {d: s for s, d in async_pairs.items()}

    finish: Dict[int, float] = {}
    scheduled: List[ScheduledOp] = []
    busy: List[Tuple[float, float]] = []     # compute-stream segments
    t_compute = 0.0
    t_comm = 0.0
    coll_windows = []                        # (index, start, finish)

    for i, op in enumerate(comp.ops):
        dur = _duration(op)
        ready = max((finish[d] for d in deps[i] if d in finish),
                    default=0.0)
        if i in done_to_start:
            # Join point of an async pair: completes when the start's
            # transfer does (already folded into finish[start]).
            s = f = max(ready, finish.get(done_to_start[i], 0.0))
            stream = 'free'
        elif op.collective_kind is not None:
            s = max(t_comm, ready)
            f = s + dur
            t_comm = f
            stream = 'comm'
            coll_windows.append((i, s, f))
        elif op.async_done_kind is not None:
            # Done without a local start (cross-computation pair):
            # instant join — hlo_comm's module-level pairing owns the
            # byte accounting for these.
            s = f = ready
            stream = 'free'
        elif dur == 0:
            s = f = ready
            stream = 'free'
        else:
            s = max(t_compute, ready)
            f = s + dur
            t_compute = f
            busy.append((s, f))
            stream = 'compute'
        finish[i] = f
        scheduled.append(ScheduledOp(index=i, op=op, stream=stream,
                                     duration=dur, start=s, finish=f,
                                     deps=deps[i]))

    collectives = []
    for i, s, f in coll_windows:
        op = comp.ops[i]
        overlapped = sum(max(0.0, min(f, b1) - max(s, b0))
                         for b0, b1 in busy)
        dur = max(f - s, 1e-9)
        gap_cost = None
        done_idx = async_pairs.get(i)
        if op.is_async_start:
            end = done_idx if done_idx is not None else len(comp.ops)
            gap_cost = sum(_duration(comp.ops[j])
                           for j in range(i + 1, end)
                           if scheduled[j].stream == 'compute')
        collectives.append(CollectiveInterval(
            op=op, kind=op.collective_kind, nbytes=_duration(op),
            computation=comp.name, start=s, finish=f,
            overlapped=overlapped,
            overlap_fraction=min(1.0, overlapped / dur),
            program_gap_cost=gap_cost, done_index=done_idx))

    compute_cost = sum(o.duration for o in scheduled
                       if o.stream == 'compute')
    comm_cost = sum(o.duration for o in scheduled if o.stream == 'comm')

    # Critical path: longest dependency-path cost, infinite resources.
    # (A -done's dependency on its -start rides the operand edge, so the
    # transfer cost is on the path without special casing.)
    lp: List[float] = []
    for i in range(len(comp.ops)):
        base = max((lp[d] for d in deps[i]), default=0.0)
        lp.append(base + scheduled[i].duration)
    cp = max(lp, default=0.0)
    total = compute_cost + comm_cost
    # Backward pass marks ops on at least one critical path.
    critical = set()
    if cp > 0:
        consumers: List[List[int]] = [[] for _ in comp.ops]
        for j, ds in enumerate(deps):
            for d in ds:
                consumers[d].append(j)
        down: List[float] = [0.0] * len(comp.ops)
        for i in range(len(comp.ops) - 1, -1, -1):
            down[i] = max((down[j] + scheduled[j].duration
                           for j in consumers[i]), default=0.0)
            if lp[i] + down[i] >= cp - 1e-9:
                critical.add(i)

    return ComputationSchedule(
        name=comp.name, ops=scheduled, collectives=collectives,
        compute_cost=compute_cost, comm_cost=comm_cost,
        critical_path_cost=int(cp),
        critical_path_share=(cp / total if total else 0.0),
        critical_ops=frozenset(critical))


def module_schedules(text_or_module) -> Dict[str, ComputationSchedule]:
    """Per-computation schedules for every computation reachable from
    ENTRY (while bodies/conditions, conditional branches, calls — each
    modeled once; fusion interiors are folded into their fusion op like
    the backend folds them)."""
    module = (text_or_module if isinstance(text_or_module, HloModule)
              else parse_hlo_module(text_or_module))
    roots = [module.entry] if module.entry else list(module.computations)[:1]
    out: Dict[str, ComputationSchedule] = {}

    def walk(name):
        comp = module.computations.get(name)
        if comp is None or name in out:
            return
        out[name] = schedule_computation(comp)
        for op in comp.ops:
            if op.opcode == 'fusion':
                continue
            for sub in op.called_computations():
                walk(sub)

    for r in roots:
        if r:
            walk(r)
    return out


def computation_trip_factors(text_or_module) -> Dict[str, int]:
    """Static execution multiplier per reachable computation: the
    product of ``known_trip_count`` over the while nests enclosing it
    (1 at the entry; an unknown trip count conservatively multiplies
    by 1). A collective inside a chunk loop runs once PER TRIP — a
    ring body's 200-byte boundary permute at 8 rotations moves more
    than a one-shot 1 KiB all-reduce — so the payload weighting in
    :func:`schedule_summary` must amplify by these factors or the
    model systematically understates exactly the loops ROADMAP item 4
    pipelines. A computation reachable along several nests keeps the
    LARGEST factor (shared combiner clones)."""
    module = (text_or_module if isinstance(text_or_module, HloModule)
              else parse_hlo_module(text_or_module))
    factors: Dict[str, int] = {}
    roots = [module.entry] if module.entry else list(module.computations)[:1]

    def walk(name, factor):
        comp = module.computations.get(name)
        if comp is None or factors.get(name, 0) >= factor:
            return
        factors[name] = factor
        for op in comp.ops:
            if op.opcode == 'fusion':
                continue
            sub_factor = factor
            if op.opcode == 'while':
                sub_factor = factor * (op.known_trip_count or 1)
            for sub in op.called_computations():
                walk(sub, sub_factor)

    for r in roots:
        if r:
            walk(r, 1)
    return factors


def schedule_summary(text_or_module, scheds=None) -> dict:
    """The program-level account ``obs/cost.py`` publishes and the SCH
    rules gate on: payload-weighted ``overlap_fraction`` over every
    reachable collective, the serialized subset, and the entry
    computation's ``critical_path_share``. Payload weights are
    **loop-amplified**: a collective inside a while body counts its
    bytes once per modeled trip (:func:`computation_trip_factors`), so
    ``collective_bytes`` reads as bytes moved per program execution and
    an overlapped in-loop boundary permute carries its real weight
    against one-shot gradient reductions. ``overlap_fraction`` is
    omitted when the program moves nothing between devices. Pass
    ``scheds`` (a :func:`module_schedules` result) to reuse an
    already-built model instead of rebuilding it."""
    module = (text_or_module if isinstance(text_or_module, HloModule)
              else parse_hlo_module(text_or_module))
    if scheds is None:
        scheds = module_schedules(module)
    factors = computation_trip_factors(module)
    colls: List[Tuple[CollectiveInterval, int]] = []
    for name, sched in scheds.items():
        f = factors.get(name, 1)
        colls.extend((c, c.nbytes * f) for c in sched.collectives)
    out = {'computations': len(scheds)}
    entry = scheds.get(module.entry) if module.entry else None
    if entry is None and scheds:
        entry = next(iter(scheds.values()))
    if entry is not None:
        out['critical_path_share'] = round(entry.critical_path_share, 4)
    if colls:
        total = sum(w for _, w in colls)
        out['collective_count'] = len(colls)
        out['collective_bytes'] = total
        out['loop_collectives'] = sum(
            1 for c, w in colls if w != c.nbytes)
        out['overlap_fraction'] = round(
            sum(c.overlap_fraction * w for c, w in colls) / total, 4)
        out['serialized_collectives'] = sum(
            1 for c, _ in colls if c.overlap_fraction <= 0.0)
    return out


# ---------------------------------------------------------------------------
# CLI: the schedule report over the registered specimens
# ---------------------------------------------------------------------------


def _specimen_report(names=None, on_progress=None) -> dict:
    """``{specimen: schedule_summary + static peak}`` over the
    registered multi-device specimens (compiled under their meshes via
    the shared registry artifacts) — the ``schedule-report`` artifact CI
    uploads next to the lint report."""
    from dgmc_tpu.analysis.hlo_liveness import peak_summary
    from dgmc_tpu.analysis.registry import (SpecimenCache,
                                            iter_runnable_specimens)
    cache = SpecimenCache()
    out = {}
    for spec in iter_runnable_specimens('sched', names=names,
                                        on_progress=on_progress):
        if on_progress:
            on_progress(f'schedule {spec.name}')
        try:
            module = parse_hlo_module(
                cache.artifacts(spec).compiled().as_text())
            row = schedule_summary(module)
            row.update(peak_summary(module))
            out[spec.name] = row
        except Exception as e:
            out[spec.name] = {'error': f'{type(e).__name__}: {e}'}
    return out


def main(argv=None):
    import argparse
    import json
    import sys

    parser = argparse.ArgumentParser(
        prog='python -m dgmc_tpu.analysis.hlo_sched',
        description='Schedule/liveness report over the registered '
                    'multi-device specimens: modeled collective overlap, '
                    'serialized collectives, critical-path share, and '
                    'static peak-live bytes per program.')
    parser.add_argument('--specimens', default=None,
                        help='comma-separated specimen names '
                             '(default: all runnable sched-tier '
                             'specimens)')
    parser.add_argument('--json', action='store_true',
                        help='print the machine-readable report')
    args = parser.parse_args(argv)

    quiet = args.json

    def progress(msg):
        if not quiet:
            print(f'[hlo_sched] {msg}', file=sys.stderr)

    names = ({n.strip() for n in args.specimens.split(',') if n.strip()}
             if args.specimens else None)
    report = _specimen_report(names=names, on_progress=progress)
    if not report:
        print('hlo_sched: no runnable sched-tier specimens matched',
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report, indent=1, sort_keys=True))
        return 0
    for name, row in report.items():
        if 'error' in row:
            print(f'-- {name}: ERROR {row["error"]}')
            continue
        print(f'-- {name} --')
        ov = row.get('overlap_fraction')
        print(f'   overlap_fraction     '
              f'{"-" if ov is None else f"{ov:.4f}"}   '
              f'({row.get("collective_count", 0)} collective(s), '
              f'{row.get("serialized_collectives", 0)} serialized)')
        print(f'   critical_path_share  '
              f'{row.get("critical_path_share", 0):.4f}')
        print(f'   static_peak_bytes    '
              f'{row.get("static_peak_bytes", 0)}')
    return 0


if __name__ == '__main__':
    import sys
    sys.exit(main())
