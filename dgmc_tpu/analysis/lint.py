"""``dgmc-lint`` — the TPU-hostility linter CLI.

Usage::

    python -m dgmc_tpu.analysis.lint [--json] [--fail-on new]
    dgmc-lint --write-baseline          # record current findings
    dgmc-lint --json --fail-on new      # CI gate: fail on un-baselined
    dgmc-lint --obs-dir runs/obs_pf     # + recompile telemetry cross-check
    dgmc-lint --explain SHD301          # one rule's what/why/fix
    dgmc-lint --select SHD301,SHD305    # only these rules
    dgmc-lint --ignore TRC005           # drop these rules
    dgmc-lint --prune-baseline          # drop stale baseline entries

Tiers (each skippable): ``--skip-trace`` (lower + walk the registered
hot functions), ``--skip-source`` (ast lints over the package source),
``--skip-recompile`` (padding-bucket churn), ``--skip-sharded`` (SHD
rules over the post-GSPMD partitioned HLO of the multi-device
specimens — needs enough devices; CI forces 8 virtual CPU devices so
the tier runs on every push), ``--skip-sched`` (SCH/MEM schedule &
liveness rules over the same partitioned HLO: modeled collective
overlap, serialized async pairs, double-buffer opportunities, static
peak-live-byte budgets, AD-residual blowup), ``--skip-concurrency``
(CON thread-entry/lock rules over the serving source). The recompile
pass needs a recorded run's buckets: it runs only when ``--obs-dir``
is given — padding buckets are a runtime artifact, there is nothing
to analyze statically without one. The trace, sharded, and schedule
tiers share one build/trace/lower/compile per specimen
(:class:`~dgmc_tpu.analysis.registry.SpecimenCache`).

The source and concurrency tiers scan the package PLUS the repo-root
bench drivers (``bench.py``, ``serve_bench.py``) and ``benchmarks/``
when they sit next to the package — they gained jit-wrapping and
threading logic and must be linted like the package. ``--source-root``
overrides the whole root set with one tree.

Output: human text (default), ``--json`` (machine-readable, stable),
or ``--format github`` (GitHub Actions ``::error file=...``
annotations for NEW findings — inline PR surfacing from the CI gate).

Exit status: 0 clean under the ``--fail-on`` policy, 1 otherwise, 2 on
usage errors. ``--fail-on`` policies: ``new`` (default — findings not in
the baseline), ``error`` (new findings at ERROR), ``any`` (any finding,
baselined or not), ``none`` (always exit 0; report only).
"""

import argparse
import json
import os
import sys

from dgmc_tpu.analysis import findings as findings_mod
from dgmc_tpu.analysis.catalog import RULE_CATALOG, explain_rule
from dgmc_tpu.analysis.findings import (Severity, default_baseline_path,
                                        load_baseline, sort_findings,
                                        split_by_baseline, write_baseline)

__all__ = ['RULE_CATALOG', 'build_parser', 'collect_findings', 'main']


def build_parser():
    p = argparse.ArgumentParser(
        prog='dgmc-lint',
        description='Static TPU-hostility analysis: jaxpr/HLO trace '
                    'rules, source ast lints, recompile-hazard checks, '
                    'and sharded-HLO communication rules.')
    p.add_argument('--json', action='store_true',
                   help='emit the machine-readable report on stdout '
                        '(alias for --format json; byte-stable)')
    p.add_argument('--format', choices=('text', 'json', 'github'),
                   default=None,
                   help='report format: text (default), json (same '
                        'bytes as --json), or github (GitHub Actions '
                        '::error/::warning annotations for NEW '
                        'findings + a summary line)')
    p.add_argument('--baseline', default=None,
                   help='baseline-suppression file (default: nearest '
                        f'{findings_mod.DEFAULT_BASELINE_NAME} walking '
                        'up from cwd)')
    p.add_argument('--write-baseline', action='store_true',
                   help='record the current findings as the baseline '
                        'and exit 0')
    p.add_argument('--prune-baseline', action='store_true',
                   help='drop baseline entries whose finding no longer '
                        'reproduces (tiers/specimens/rules not analyzed '
                        'in this run are preserved) and exit 0')
    p.add_argument('--fail-on', choices=('new', 'error', 'any', 'none'),
                   default='new',
                   help='exit-1 policy (default: new — findings not in '
                        'the baseline)')
    p.add_argument('--min-severity', default='info',
                   help='drop findings below this severity from the '
                        'report and the --fail-on policy '
                        '(info|warning|error); baseline rewrites '
                        '(--write-baseline/--prune-baseline) ignore it '
                        'so a filtered run cannot un-suppress reviewed '
                        'lower-severity entries')
    p.add_argument('--select', '--rules', dest='select', default=None,
                   help='comma-separated rule ids to keep (default all; '
                        'tiers none of whose rules survive the filter '
                        'are skipped entirely; --rules is the legacy '
                        'spelling)')
    p.add_argument('--ignore', default=None,
                   help='comma-separated rule ids to drop')
    p.add_argument('--skip-trace', action='store_true',
                   help='skip the jaxpr/HLO trace tier')
    p.add_argument('--skip-source', action='store_true',
                   help='skip the source ast tier')
    p.add_argument('--skip-recompile', action='store_true',
                   help='skip the padding-bucket recompile pass')
    p.add_argument('--skip-sharded', action='store_true',
                   help='skip the sharded-HLO (SHD) tier')
    p.add_argument('--skip-sched', action='store_true',
                   help='skip the schedule & liveness (SCH/MEM) tier')
    p.add_argument('--skip-concurrency', action='store_true',
                   help='skip the concurrency (CON) tier')
    p.add_argument('--source-root', default=None,
                   help='source tree to lint with the SRC and CON '
                        'tiers (default: the installed dgmc_tpu '
                        'package plus the repo-root bench drivers — '
                        'bench.py, serve_bench.py, benchmarks/ — when '
                        'present beside it)')
    p.add_argument('--obs-dir', default=None,
                   help='recorded obs run dir: cross-check its padding '
                        'buckets + compile telemetry (RCP202)')
    p.add_argument('--max-const-bytes', type=int, default=None,
                   help='TRC002 threshold in bytes (default 1 MiB)')
    p.add_argument('--comm-budget-bytes', type=int, default=None,
                   help='SHD304 fallback budget for specimens without '
                        'their own comm_budget_bytes (default: only '
                        'per-specimen budgets fire)')
    p.add_argument('--list-rules', action='store_true',
                   help='print the rule catalog and exit')
    p.add_argument('--explain', default=None, metavar='RULE[,RULE...]',
                   help="print the rule's what/why/fix doc and exit "
                        '(see also docs/source/modules/lint-rules.rst)')
    return p


def collect_findings(args, progress):
    """``(findings, skipped_specimens)`` for the enabled tiers.

    A tier runs only when it can still produce a selected rule: with
    ``--select SRC101`` there is no reason to pay the trace/SHD tiers'
    specimen compiles (the dominant lint cost) for findings the filter
    is guaranteed to drop. ``_rules_analyzed`` — also the baseline
    writers' preservation set — is the single source of that truth."""
    rules = _rules_analyzed(args)

    def tier_on(prefix):
        return any(r.startswith(prefix) for r in rules)

    out = []
    skipped = []
    if tier_on('SRC'):
        from dgmc_tpu.analysis.source_rules import lint_source_paths
        roots = _source_roots(args)
        progress(f'source tier: {", ".join(roots)}')
        out.extend(lint_source_paths(roots))
    if tier_on('CON'):
        from dgmc_tpu.analysis.con_rules import lint_concurrency_paths
        roots = _source_roots(args)
        progress(f'concurrency tier: {", ".join(roots)}')
        out.extend(lint_concurrency_paths(roots))
    if tier_on('RCP'):
        # _rules_analyzed drops RCP without --obs-dir: padding buckets
        # are a runtime artifact, there is nothing to analyze
        # statically. (The trace tier's fixed shapes are already one
        # program each by construction.)
        from dgmc_tpu.analysis.recompile import (analyze_buckets,
                                                 load_obs_buckets)
        buckets, events = load_obs_buckets(args.obs_dir)
        progress(f'recompile pass: {len(buckets)} observed bucket(s) '
                 f'from {args.obs_dir}')
        out.extend(analyze_buckets(buckets, specimen='obs',
                                   compile_events=events))
    cache = None
    if tier_on('TRC') or tier_on('SHD') or tier_on('SCH') \
            or tier_on('MEM'):
        from dgmc_tpu.analysis.registry import SpecimenCache
        cache = SpecimenCache()
    if tier_on('TRC'):
        from dgmc_tpu.analysis.registry import run_trace_tier
        out.extend(run_trace_tier(const_bytes=args.max_const_bytes,
                                  on_progress=progress, skipped=skipped,
                                  cache=cache))
    if tier_on('SHD'):
        from dgmc_tpu.analysis.shd_rules import run_sharded_tier
        out.extend(run_sharded_tier(
            cache=cache, comm_budget_bytes=args.comm_budget_bytes,
            on_progress=progress, skipped=skipped))
    if tier_on('SCH') or tier_on('MEM'):
        from dgmc_tpu.analysis.sched_rules import run_sched_tier
        out.extend(run_sched_tier(cache=cache, on_progress=progress,
                                  skipped=skipped))
    return out, skipped


#: Repo-root bench drivers / dirs linted alongside the package when
#: they exist beside it (PRs 15-18 gave them jit-wrapping and threading
#: logic; a package-only scan leaves them invisible to SRC/CON rules).
_DRIVER_ROOTS = ('bench.py', 'serve_bench.py', 'benchmarks')


def _source_roots(args):
    """The SRC/CON scan roots: ``--source-root`` verbatim when given,
    else the installed package plus whichever repo-root bench drivers
    exist beside it."""
    if args.source_root is not None:
        return [args.source_root]
    import dgmc_tpu
    pkg = os.path.dirname(os.path.abspath(dgmc_tpu.__file__))
    roots = [pkg]
    repo = os.path.dirname(pkg)
    for name in _DRIVER_ROOTS:
        cand = os.path.join(repo, name)
        if os.path.exists(cand):
            roots.append(cand)
    return roots


def _rules_analyzed(args):
    """The rule-id set this run can produce, given tier skips and
    select/ignore filters — everything OUTSIDE it is preserved on
    baseline rewrites."""
    rules = set(RULE_CATALOG)
    if args.skip_trace:
        rules -= {r for r in rules if r.startswith('TRC')}
    if args.skip_source:
        rules -= {r for r in rules if r.startswith('SRC')}
    if args.skip_recompile or not args.obs_dir:
        rules -= {r for r in rules if r.startswith('RCP')}
    if args.skip_sharded:
        rules -= {r for r in rules if r.startswith('SHD')}
    if args.skip_sched:
        rules -= {r for r in rules if r.startswith(('SCH', 'MEM'))}
    if args.skip_concurrency:
        rules -= {r for r in rules if r.startswith('CON')}
    if args.select:
        rules &= _parse_rules(args.select)
    if args.ignore:
        rules -= _parse_rules(args.ignore)
    return rules


def _entries_not_analyzed(prior_baseline, args, skipped_specimens):
    """Prior-baseline entries whose producing tier/specimen/rule this
    run did not analyze — preserved verbatim on ``--write-baseline`` /
    ``--prune-baseline`` so a refresh from a smaller environment (fewer
    devices, a skipped tier, a --select subset) cannot silently
    un-suppress findings CI will still produce."""
    skipped = set(skipped_specimens)
    analyzed_rules = _rules_analyzed(args)
    keep = []
    for e in prior_baseline.values():
        rule = e.get('rule', '')
        specimen = e.get('where', '').split(':', 1)[0]
        if rule not in analyzed_rules or specimen in skipped:
            keep.append(e)
    return keep


def _parse_rules(spec):
    return {r.strip() for r in spec.split(',') if r.strip()}


def render_text(report, stream=None):
    w = (stream or sys.stdout).write
    for f in report['findings']:
        mark = '' if f['fingerprint'] not in report['_suppressed'] else \
            ' [baselined]'
        w(f"{f['severity'].upper():7s} {f['rule']} {f['where']}{mark}\n")
        w(f"        {f['message']}\n")
        if f.get('detail'):
            w(f"        ({f['detail']})\n")
    s = report['summary']
    w(f"dgmc-lint: {s['total']} finding(s) — {s['new']} new, "
      f"{s['suppressed']} baselined "
      f"(errors {s['errors']}, warnings {s['warnings']}, "
      f"infos {s['infos']})\n")


_GH_LEVEL = {'error': 'error', 'warning': 'warning', 'info': 'notice'}


def _gh_escape(s):
    """GitHub workflow-command escaping (%, CR, LF; commas/colons too
    in property values, per the runner's parser)."""
    return (str(s).replace('%', '%25').replace('\r', '%0D')
            .replace('\n', '%0A'))


def _gh_escape_prop(s):
    return _gh_escape(s).replace(':', '%3A').replace(',', '%2C')


def _where_file_line(where):
    """``(file, line)`` parsed out of a finding's where string —
    handles both ``path/file.py:12`` and ``specimen:path/file.py:12``;
    (None, None) for non-file locations (e.g. the recompile pass's
    ``obs``)."""
    parts = where.split(':')
    for i, part in enumerate(parts):
        if part.endswith('.py'):
            line = None
            if i + 1 < len(parts) and parts[i + 1].isdigit():
                line = parts[i + 1]
            return part, line
    return None, None


def render_github(report, stream=None):
    """GitHub Actions annotations for the NEW findings (baselined ones
    are reviewed debt — annotating them on every PR would be noise),
    plus the same summary line the text renderer ends with."""
    new = set(report['new'])
    w = (stream or sys.stdout).write
    for f in report['findings']:
        if f['fingerprint'] not in new:
            continue
        level = _GH_LEVEL.get(f['severity'], 'warning')
        file, line = _where_file_line(f['where'])
        props = [f'title={_gh_escape_prop("dgmc-lint " + f["rule"])}']
        if file:
            props.insert(0, f'file={_gh_escape_prop(file)}')
            if line:
                props.insert(1, f'line={line}')
        w(f'::{level} {",".join(props)}::'
          f'{_gh_escape(f["rule"] + ": " + f["message"])}\n')
    s = report['summary']
    w(f"dgmc-lint: {s['total']} finding(s) — {s['new']} new, "
      f"{s['suppressed']} baselined "
      f"(errors {s['errors']}, warnings {s['warnings']}, "
      f"infos {s['infos']})\n")


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for rule, desc in sorted(RULE_CATALOG.items()):
            print(f'{rule}  {desc}')
        return 0
    if args.explain:
        rules = sorted(_parse_rules(args.explain))
        unknown = [r for r in rules if r not in RULE_CATALOG]
        if unknown:
            print(f'dgmc-lint: unknown rule id(s): {unknown} '
                  f'(--list-rules prints the catalog)', file=sys.stderr)
            return 2
        print('\n\n'.join(explain_rule(r) for r in rules))
        return 0
    if args.write_baseline and args.prune_baseline:
        print('dgmc-lint: --write-baseline and --prune-baseline are '
              'mutually exclusive (regenerate OR prune)',
              file=sys.stderr)
        return 2

    if args.json and args.format not in (None, 'json'):
        print(f'dgmc-lint: --json conflicts with '
              f'--format {args.format}', file=sys.stderr)
        return 2
    fmt = args.format or ('json' if args.json else 'text')
    quiet = fmt == 'json'

    def progress(msg):
        if not quiet:
            print(f'[dgmc-lint] {msg}', file=sys.stderr)

    try:
        min_sev = Severity.parse(args.min_severity)
    except ValueError as e:
        print(f'dgmc-lint: {e}', file=sys.stderr)
        return 2
    keep_rules = _parse_rules(args.select) if args.select else None
    drop_rules = _parse_rules(args.ignore) if args.ignore else set()
    unknown = ((keep_rules or set()) | drop_rules) - set(RULE_CATALOG)
    if unknown:
        print(f'dgmc-lint: unknown rule id(s): {sorted(unknown)}',
              file=sys.stderr)
        return 2

    if args.obs_dir and not os.path.exists(
            os.path.join(args.obs_dir, 'timings.json')):
        # A vanished obs dir must not silently disable the telemetry
        # cross-check the caller asked for (e.g. the CI gate).
        print(f'dgmc-lint: --obs-dir {args.obs_dir} has no timings.json '
              f'(not an obs run directory?)', file=sys.stderr)
        return 2

    found, skipped_specimens = collect_findings(args, progress)
    if keep_rules is not None:
        found = [f for f in found if f.rule in keep_rules]
    if drop_rules:
        found = [f for f in found if f.rule not in drop_rules]
    found = sort_findings(found)
    # --min-severity filters the REPORT only. Baseline rewrites work on
    # the unfiltered set: `--prune-baseline --min-severity error` must
    # not classify still-reproducing warning/info suppressions as stale
    # (_entries_not_analyzed protects skipped tiers/rules/specimens,
    # but severity is a per-finding property it cannot see).
    reported = [f for f in found if f.severity >= min_sev]

    baseline_path = args.baseline or default_baseline_path()
    if args.write_baseline:
        # migrate=True: rewriting is the one-shot migration path off
        # legacy (version-1, line-hashed) baselines — the old entries
        # are only needed to preserve unanalyzed tiers.
        prior_version = findings_mod.baseline_version(baseline_path)
        preserved = _entries_not_analyzed(
            load_baseline(baseline_path, migrate=True), args,
            skipped_specimens)
        if prior_version == 1 and preserved:
            # Preserved v1 entries keep legacy line-hashed fingerprints
            # that can never match a v2 finding: the tiers/specimens
            # this environment skipped will report as NEW wherever they
            # DO run (CI's 8-device mesh). Say so loudly instead of
            # letting the gate break a push later.
            print(f'dgmc-lint: WARNING: migrated a version-1 baseline '
                  f'while {len(preserved)} entr'
                  f'{"y" if len(preserved) == 1 else "ies"} of '
                  f'unanalyzed tiers/specimens had to be preserved '
                  f'with legacy fingerprints that can never match '
                  f'again — re-run `dgmc-lint --write-baseline` in an '
                  f'environment that analyzes everything (e.g. under '
                  f'XLA_FLAGS=--xla_force_host_platform_device_count=8)'
                  f' or CI will report those findings as new',
                  file=sys.stderr)
        write_baseline(baseline_path, found, preserved_entries=preserved)
        if not quiet:
            kept = (f' (+ {len(preserved)} preserved from tiers/'
                    f'specimens not analyzed here)' if preserved else '')
            print(f'dgmc-lint: wrote {len(found)} finding(s) to '
                  f'{baseline_path}{kept}')
    elif args.prune_baseline:
        # NO migrate here: prune never re-records findings, so against a
        # v1 (line-hashed) ledger every analyzed entry would read as
        # stale and the whole reviewed debt ledger would be deleted in
        # one command. Migration is --write-baseline's job.
        try:
            prior = load_baseline(baseline_path)
        except ValueError as e:
            print(f'dgmc-lint: {e}', file=sys.stderr)
            return 2
        produced = {f.fingerprint for f in found}
        protected = {e['fingerprint'] for e in _entries_not_analyzed(
            prior, args, skipped_specimens)}
        stale = [e for fp, e in prior.items()
                 if fp not in produced and fp not in protected]
        kept = [e for fp, e in prior.items()
                if fp in produced or fp in protected]
        # Prune only: kept entries pass through verbatim, nothing is
        # added — accepting NEW findings stays an explicit
        # --write-baseline review.
        write_baseline(baseline_path, (), preserved_entries=kept)
        if not quiet:
            print(f'dgmc-lint: pruned {len(stale)} stale entr'
                  f'{"y" if len(stale) == 1 else "ies"} from '
                  f'{baseline_path} ({len(kept)} kept)')
            for e in stale:
                print(f'  - {e.get("rule")} {e.get("where")}')
        return 0

    try:
        baseline = load_baseline(baseline_path)
    except ValueError as e:
        # Legacy (line-hashed) or unknown baseline version: checking
        # against it would silently report everything as new — surface
        # the migration instruction as a usage error instead.
        print(f'dgmc-lint: {e}', file=sys.stderr)
        return 2
    new, suppressed = split_by_baseline(reported, baseline)

    report = {
        'tool': 'dgmc-lint',
        'baseline': baseline_path if baseline or args.write_baseline
        else None,
        'findings': [f.to_json() for f in reported],
        'new': [f.fingerprint for f in new],
        'summary': {
            'total': len(reported),
            'new': len(new),
            'suppressed': len(suppressed),
            'errors': sum(f.severity == Severity.ERROR
                          for f in reported),
            'warnings': sum(f.severity == Severity.WARNING
                            for f in reported),
            'infos': sum(f.severity == Severity.INFO for f in reported),
        },
    }
    if fmt == 'json':
        print(json.dumps(report, indent=1, sort_keys=True))
    elif fmt == 'github':
        render_github(report)
    else:
        report['_suppressed'] = {f.fingerprint for f in suppressed}
        render_text(report)
        del report['_suppressed']

    if args.write_baseline or args.fail_on == 'none':
        return 0
    if args.fail_on == 'any':
        return 1 if reported else 0
    if args.fail_on == 'error':
        return 1 if any(f.severity == Severity.ERROR for f in new) else 0
    return 1 if new else 0                                   # 'new'


if __name__ == '__main__':
    try:
        sys.exit(main())
    except BrokenPipeError:   # |head closed the pipe mid-report
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(1)
