"""``dgmc-lint`` — the TPU-hostility linter CLI.

Usage::

    python -m dgmc_tpu.analysis.lint [--json] [--fail-on new]
    dgmc-lint --write-baseline          # record current findings
    dgmc-lint --json --fail-on new      # CI gate: fail on un-baselined
    dgmc-lint --obs-dir runs/obs_pf     # + recompile telemetry cross-check

Tiers (each skippable): ``--skip-trace`` (lower + walk the registered
hot functions), ``--skip-source`` (ast lints over the package source),
``--skip-recompile`` (padding-bucket churn). The recompile pass needs a
recorded run's buckets: it runs only when ``--obs-dir`` is given —
padding buckets are a runtime artifact, there is nothing to analyze
statically without one.

Exit status: 0 clean under the ``--fail-on`` policy, 1 otherwise, 2 on
usage errors. ``--fail-on`` policies: ``new`` (default — findings not in
the baseline), ``error`` (new findings at ERROR), ``any`` (any finding,
baselined or not), ``none`` (always exit 0; report only).
"""

import argparse
import json
import os
import sys

from dgmc_tpu.analysis import findings as findings_mod
from dgmc_tpu.analysis.findings import (Severity, default_baseline_path,
                                        load_baseline, sort_findings,
                                        split_by_baseline, write_baseline)

RULE_CATALOG = {
    'TRC001': 'dtype promotion: 64-bit value introduced in a <=32-bit '
              'pipeline',
    'TRC002': 'giant constant folded into the program',
    'TRC003': 'host callback in a program expected callback-free '
              '(probes disabled)',
    'TRC004': 'donated argument lost its input-output aliasing',
    'TRC005': 'scatter without unique_indices (serial/atomic on TPU)',
    'TRC006': 'large sort where a top-k selection was intended',
    'SRC100': 'source file failed to parse',
    'SRC101': 'tracer leak: jitted function stores to self/global',
    'SRC102': 'host sync inside jitted code (float/int/bool/.item/'
              'np.asarray)',
    'SRC103': 'jax.jit constructed inside a loop',
    'SRC104': 'static arg with an unhashable (mutable) default',
    'RCP201': 'padding bucket dominated by another (avoidable compile '
              'churn)',
    'RCP202': 'compile events exceed what padding buckets explain',
}


def build_parser():
    p = argparse.ArgumentParser(
        prog='dgmc-lint',
        description='Static TPU-hostility analysis: jaxpr/HLO trace '
                    'rules, source ast lints, recompile-hazard checks.')
    p.add_argument('--json', action='store_true',
                   help='emit the machine-readable report on stdout')
    p.add_argument('--baseline', default=None,
                   help='baseline-suppression file (default: nearest '
                        f'{findings_mod.DEFAULT_BASELINE_NAME} walking '
                        'up from cwd)')
    p.add_argument('--write-baseline', action='store_true',
                   help='record the current findings as the baseline '
                        'and exit 0')
    p.add_argument('--fail-on', choices=('new', 'error', 'any', 'none'),
                   default='new',
                   help='exit-1 policy (default: new — findings not in '
                        'the baseline)')
    p.add_argument('--min-severity', default='info',
                   help='drop findings below this severity '
                        '(info|warning|error)')
    p.add_argument('--rules', default=None,
                   help='comma-separated rule ids to keep (default all)')
    p.add_argument('--skip-trace', action='store_true',
                   help='skip the jaxpr/HLO trace tier')
    p.add_argument('--skip-source', action='store_true',
                   help='skip the source ast tier')
    p.add_argument('--skip-recompile', action='store_true',
                   help='skip the padding-bucket recompile pass')
    p.add_argument('--source-root', default=None,
                   help='source tree to lint (default: the installed '
                        'dgmc_tpu package)')
    p.add_argument('--obs-dir', default=None,
                   help='recorded obs run dir: cross-check its padding '
                        'buckets + compile telemetry (RCP202)')
    p.add_argument('--max-const-bytes', type=int, default=None,
                   help='TRC002 threshold in bytes (default 1 MiB)')
    p.add_argument('--list-rules', action='store_true',
                   help='print the rule catalog and exit')
    return p


def collect_findings(args, progress):
    """``(findings, skipped_specimens)`` for the enabled tiers."""
    out = []
    skipped = []
    if not args.skip_source:
        from dgmc_tpu.analysis.source_rules import lint_source_tree
        root = args.source_root
        if root is None:
            import dgmc_tpu
            root = os.path.dirname(os.path.abspath(dgmc_tpu.__file__))
        progress(f'source tier: {root}')
        out.extend(lint_source_tree(root))
    if not args.skip_recompile and args.obs_dir:
        from dgmc_tpu.analysis.recompile import (analyze_buckets,
                                                 load_obs_buckets)
        buckets, events = load_obs_buckets(args.obs_dir)
        progress(f'recompile pass: {len(buckets)} observed bucket(s) '
                 f'from {args.obs_dir}')
        out.extend(analyze_buckets(buckets, specimen='obs',
                                   compile_events=events))
        # Without an obs dir there is nothing to analyze statically —
        # buckets are a runtime artifact. (The trace tier's fixed shapes
        # are already one program each by construction.)
    if not args.skip_trace:
        from dgmc_tpu.analysis.registry import run_trace_tier
        out.extend(run_trace_tier(const_bytes=args.max_const_bytes,
                                  on_progress=progress, skipped=skipped))
    return out, skipped


def _entries_not_analyzed(prior_baseline, args, skipped_specimens):
    """Prior-baseline entries whose producing tier/specimen this run did
    not analyze — preserved verbatim on ``--write-baseline`` so a
    refresh from a smaller environment (fewer devices, a skipped tier)
    cannot silently un-suppress findings CI will still produce."""
    skipped = set(skipped_specimens)
    keep = []
    for e in prior_baseline.values():
        rule = e.get('rule', '')
        specimen = e.get('where', '').split(':', 1)[0]
        if rule.startswith('TRC') and (args.skip_trace
                                       or specimen in skipped):
            keep.append(e)
        elif rule.startswith('SRC') and args.skip_source:
            keep.append(e)
        elif rule.startswith('RCP') and (args.skip_recompile
                                         or not args.obs_dir):
            keep.append(e)
    return keep


def render_text(report, stream=sys.stdout):
    w = stream.write
    for f in report['findings']:
        mark = '' if f['fingerprint'] not in report['_suppressed'] else \
            ' [baselined]'
        w(f"{f['severity'].upper():7s} {f['rule']} {f['where']}{mark}\n")
        w(f"        {f['message']}\n")
        if f.get('detail'):
            w(f"        ({f['detail']})\n")
    s = report['summary']
    w(f"dgmc-lint: {s['total']} finding(s) — {s['new']} new, "
      f"{s['suppressed']} baselined "
      f"(errors {s['errors']}, warnings {s['warnings']}, "
      f"infos {s['infos']})\n")


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for rule, desc in sorted(RULE_CATALOG.items()):
            print(f'{rule}  {desc}')
        return 0

    quiet = args.json

    def progress(msg):
        if not quiet:
            print(f'[dgmc-lint] {msg}', file=sys.stderr)

    try:
        min_sev = Severity.parse(args.min_severity)
    except ValueError as e:
        print(f'dgmc-lint: {e}', file=sys.stderr)
        return 2
    keep_rules = (set(r.strip() for r in args.rules.split(',') if r.strip())
                  if args.rules else None)
    if keep_rules is not None:
        unknown = keep_rules - set(RULE_CATALOG)
        if unknown:
            print(f'dgmc-lint: unknown rule id(s): {sorted(unknown)}',
                  file=sys.stderr)
            return 2

    if args.obs_dir and not os.path.exists(
            os.path.join(args.obs_dir, 'timings.json')):
        # A vanished obs dir must not silently disable the telemetry
        # cross-check the caller asked for (e.g. the CI gate).
        print(f'dgmc-lint: --obs-dir {args.obs_dir} has no timings.json '
              f'(not an obs run directory?)', file=sys.stderr)
        return 2

    found, skipped_specimens = collect_findings(args, progress)
    found = [f for f in found if f.severity >= min_sev]
    if keep_rules is not None:
        found = [f for f in found if f.rule in keep_rules]
    found = sort_findings(found)

    baseline_path = args.baseline or default_baseline_path()
    if args.write_baseline:
        preserved = _entries_not_analyzed(load_baseline(baseline_path),
                                          args, skipped_specimens)
        write_baseline(baseline_path, found, preserved_entries=preserved)
        if not quiet:
            kept = (f' (+ {len(preserved)} preserved from tiers/'
                    f'specimens not analyzed here)' if preserved else '')
            print(f'dgmc-lint: wrote {len(found)} finding(s) to '
                  f'{baseline_path}{kept}')

    baseline = load_baseline(baseline_path)
    new, suppressed = split_by_baseline(found, baseline)

    report = {
        'tool': 'dgmc-lint',
        'baseline': baseline_path if baseline or args.write_baseline
        else None,
        'findings': [f.to_json() for f in found],
        'new': [f.fingerprint for f in new],
        'summary': {
            'total': len(found),
            'new': len(new),
            'suppressed': len(suppressed),
            'errors': sum(f.severity == Severity.ERROR for f in found),
            'warnings': sum(f.severity == Severity.WARNING for f in found),
            'infos': sum(f.severity == Severity.INFO for f in found),
        },
    }
    if args.json:
        print(json.dumps(report, indent=1, sort_keys=True))
    else:
        report['_suppressed'] = {f.fingerprint for f in suppressed}
        render_text(report)
        del report['_suppressed']

    if args.write_baseline or args.fail_on == 'none':
        return 0
    if args.fail_on == 'any':
        return 1 if found else 0
    if args.fail_on == 'error':
        return 1 if any(f.severity == Severity.ERROR for f in new) else 0
    return 1 if new else 0                                   # 'new'


if __name__ == '__main__':
    try:
        sys.exit(main())
    except BrokenPipeError:   # |head closed the pipe mid-report
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(1)
