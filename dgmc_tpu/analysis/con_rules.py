"""Concurrency-tier lints (CON5xx): static race detection over the
threaded serve plane.

Five rules, all reading the per-module model built by
:mod:`dgmc_tpu.analysis.concurrency`:

``CON501`` unlocked-shared-rmw
    A class attribute is read-modify-written (``+=`` / ``self.x =
    self.x + ...``) from a method reachable from a thread entry point
    while NO write site of that attribute in the class holds a lock.
    The PR-15 serve-counter bug as a rule: ``+=`` is read-op-write,
    not atomic, so concurrent handler threads lose increments. Plain
    rebinding (``self.x = value``) is exempt — a single STORE_ATTR is
    atomic under the GIL and the watchdog's cache refreshes rely on
    that.
``CON502`` lock-order-inversion
    Two locks of one class are acquired nested in both orders across
    call paths (lexically, or one ``self.<m>()`` call level deep).
    Deadlock by construction the first time two threads interleave.
``CON503`` non-atomic-artifact-write
    ``open(path, 'w')`` on an artifact path in a function that never
    calls ``os.replace``/``os.rename`` and whose path expression does
    not name a temp file. A concurrent reader (supervisor, scraper) or
    a crash mid-write observes a torn file; the repo's discipline is
    tmp+rename (``utils/io.write_json_atomic``).
``CON504`` unsafe-signal-handler
    A registered ``signal.signal`` handler acquires a lock, performs
    buffered IO (``open``/``print``/logging), or builds allocation-
    heavy formatted output (``json.dumps``, ``str.format``,
    ``traceback.format_*``, ``''.join``) directly in its body. The
    handler interrupts the main thread at an arbitrary point: any lock
    may already be held. The watchdog's lock-free signal path
    (``_on_signal`` -> ``dump(use_locks=False)``) is the positive
    model.
``CON505`` unbounded-shared-growth
    A list/dict/set/deque attribute grows (``.append``/``.add``/keyed
    store) from a thread-entry method and the class shows no cap: no
    ``deque(maxlen=...)``, no ``len()`` check, no eviction, no
    rotation. A long-lived serving process accretes per-query state
    until the OOM killer arrives; the bounded-ring discipline
    (FlightRecorder, qtrace capacity) exists for this.

Like the source tier, the scanner refuses bytecode and attaches the
flagged line's stripped text as the finding context (line-independent
v2 fingerprints).
"""

import ast
import os
from typing import List, Optional, Sequence

from dgmc_tpu.analysis.concurrency import (ModuleModel,
                                           build_module_model,
                                           _mentions_tmp, _self_attr)
from dgmc_tpu.analysis.findings import (Finding, Severity,
                                        disambiguate_contexts)
from dgmc_tpu.analysis.source_rules import (_refuse_bytecode,
                                            _with_line_context,
                                            iter_source_files)

__all__ = ['lint_concurrency_file', 'lint_concurrency_tree',
           'lint_concurrency_paths']

#: Attribute names on ``self`` whose mutation is synchronization, not
#: shared state (events/flags set from handlers by design).
_SYNC_FACTORY_NAMES = {'Event', 'Barrier'}

_LOGGING_METHODS = {'debug', 'info', 'warning', 'warn', 'error',
                    'exception', 'critical', 'log'}
_HEAVY_FORMATTERS = {'dumps', 'format', 'join'}


def _finding(rule, severity, rel, node, message, detail=None) -> Finding:
    return Finding(rule=rule, severity=severity,
                   where=f'{rel}:{getattr(node, "lineno", 0)}',
                   message=message, detail=detail)


def _sync_attrs(cls) -> set:
    """Attrs assigned ``threading.Event()``-style sync primitives —
    ``.set()`` from a handler thread is their whole point."""
    out = set()
    for m in cls.methods.values():
        for stmt in ast.walk(m):
            if isinstance(stmt, ast.Assign) \
                    and isinstance(stmt.value, ast.Call):
                f = stmt.value.func
                name = f.attr if isinstance(f, ast.Attribute) else (
                    f.id if isinstance(f, ast.Name) else None)
                if name in _SYNC_FACTORY_NAMES:
                    for t in stmt.targets:
                        attr = _self_attr(t)
                        if attr is not None:
                            out.add(attr)
    return out


# ---------------------------------------------------------------------------
# CON501 — unlocked read-modify-write from a thread-entry path
# ---------------------------------------------------------------------------

def _check_unlocked_rmw(model: ModuleModel, rel) -> List[Finding]:
    out = []
    for cls in model.classes:
        if not cls.entry_closure:
            continue
        sync = _sync_attrs(cls)
        for attr, sites in sorted(cls.writes_by_attr().items()):
            if attr in cls.lock_attrs or attr in sync:
                continue
            live = [w for w in sites if not w.in_init]
            if not live:
                continue
            # Any guarded write means the class HAS a locking story for
            # this attribute; mixed-discipline is a different (noisier)
            # analysis, out of scope for a gate.
            if any(w.locks_held for w in live):
                continue
            for w in live:
                if not w.rmw or w.method not in cls.entry_closure:
                    continue
                kind, origin = cls.entry_closure[w.method]
                via = (f'`{cls.name}.{w.method}`' if w.method == origin
                       else f'`{cls.name}.{w.method}` (reached from '
                            f'{kind} entry `{origin}`)')
                out.append(_finding(
                    'CON501', Severity.ERROR, rel, w.node,
                    f'`self.{attr}` read-modify-written from thread '
                    f'entry path {via} with no lock on any write site '
                    f'— concurrent increments are lost',
                    detail=f'entry kind: {kind}; guard every write of '
                           f'`{attr}` with a class lock (the '
                           f'StreamingHistogram.observe pattern) or '
                           f'make it thread-local'))
    return out


# ---------------------------------------------------------------------------
# CON502 — inconsistent nested lock order
# ---------------------------------------------------------------------------

def _check_lock_order(model: ModuleModel, rel) -> List[Finding]:
    out = []
    for cls in model.classes:
        reported = set()
        for (a, b), site in sorted(
                cls.lock_edges.items(),
                key=lambda kv: getattr(kv[1], 'lineno', 0)):
            if (b, a) not in cls.lock_edges:
                continue
            pair = frozenset((a, b))
            if pair in reported:
                continue
            reported.add(pair)
            other = cls.lock_edges[(b, a)]
            # Anchor on the later-in-file site; name both.
            first, second = sorted(
                (site, other), key=lambda n: getattr(n, 'lineno', 0))
            out.append(_finding(
                'CON502', Severity.ERROR, rel, second,
                f'locks `{a}` and `{b}` of `{cls.name}` are acquired '
                f'nested in both orders — deadlock by construction '
                f'when two threads interleave',
                detail=f'opposite-order site: {rel}:'
                       f'{getattr(first, "lineno", 0)}; pick one '
                       f'canonical order, or release the first lock '
                       f'before taking the second'))
    return out


# ---------------------------------------------------------------------------
# CON503 — artifact written in place (no tmp+rename)
# ---------------------------------------------------------------------------

def _open_write_mode(call: ast.Call) -> Optional[str]:
    """The mode string when ``call`` is ``open(..., 'w'/'wb'/...)``
    (truncating write), else None."""
    if not (isinstance(call.func, ast.Name) and call.func.id == 'open'):
        return None
    mode = None
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
            and isinstance(call.args[1].value, str):
        mode = call.args[1].value
    for kw in call.keywords:
        if kw.arg == 'mode' and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            mode = kw.value.value
    if mode and mode.startswith(('w', 'x')):
        return mode
    return None


def _check_artifact_writes(tree: ast.Module, rel) -> List[Finding]:
    out = []
    # Each def is its own scope; module top level is a pseudo-scope.
    scopes: List[ast.AST] = [tree]
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scopes.append(node)
    for scope in scopes:
        own = list(_iter_scope(scope))
        renames = any(
            isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
            and n.func.attr in ('replace', 'rename', 'renames')
            and isinstance(n.func.value, ast.Name)
            and n.func.value.id == 'os'
            for n in own)
        if renames:
            continue
        name = getattr(scope, 'name', '<module>')
        for n in own:
            if not isinstance(n, ast.Call):
                continue
            mode = _open_write_mode(n)
            if mode is None or not n.args:
                continue
            if _mentions_tmp(n.args[0]):
                continue
            out.append(_finding(
                'CON503', Severity.WARNING, rel, n,
                f'`open(..., {mode!r})` in `{name}` writes the '
                f'artifact in place — a reader or crash mid-write '
                f'sees a torn file',
                detail='write to a tmp path and os.replace() it into '
                       'place (utils/io.write_json_atomic is the '
                       'repo model), or append instead'))
    return out


def _iter_scope(scope: ast.AST):
    """Nodes belonging to ``scope`` directly — not to a nested def."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


# ---------------------------------------------------------------------------
# CON504 — unsafe work in a signal handler
# ---------------------------------------------------------------------------

def _check_signal_handlers(model: ModuleModel, rel) -> List[Finding]:
    out = []
    for handler in model.signal_handlers:
        scope = handler.node
        hazards = []
        for n in _iter_scope(scope):
            if isinstance(n, ast.With):
                for item in n.items:
                    if _is_lockish(item.context_expr, handler.lock_names):
                        hazards.append((item.context_expr,
                                        'acquires a lock (`with ...`)'))
            elif isinstance(n, ast.Call):
                f = n.func
                if isinstance(f, ast.Attribute):
                    if f.attr == 'acquire':
                        hazards.append((n, 'acquires a lock '
                                           '(`.acquire()`)'))
                    elif f.attr in _LOGGING_METHODS \
                            and isinstance(f.value, ast.Name) \
                            and f.value.id in ('logging', 'logger',
                                               'log'):
                        hazards.append((n, 'calls logging (takes the '
                                           'logging module lock)'))
                    elif f.attr in _HEAVY_FORMATTERS:
                        if f.attr == 'dumps':
                            if isinstance(f.value, ast.Name) \
                                    and f.value.id == 'json':
                                hazards.append(
                                    (n, 'builds json.dumps output '
                                        '(allocation-heavy)'))
                        elif f.attr == 'format' and not isinstance(
                                f.value, ast.Name):
                            hazards.append(
                                (n, 'builds str.format output '
                                    '(allocation-heavy)'))
                        elif f.attr == 'join' and isinstance(
                                f.value, ast.Constant):
                            hazards.append(
                                (n, 'builds a joined string '
                                    '(allocation-heavy)'))
                    elif f.attr.startswith('format') \
                            and isinstance(f.value, ast.Name) \
                            and f.value.id == 'traceback':
                        hazards.append(
                            (n, f'calls traceback.{f.attr} '
                                f'(allocation-heavy formatting)'))
                elif isinstance(f, ast.Name):
                    if f.id == 'open':
                        hazards.append((n, 'opens a file (buffered '
                                           'IO)'))
                    elif f.id == 'print':
                        hazards.append((n, 'calls print() (buffered '
                                           'IO, takes stdout '
                                           'internals)'))
        for node, what in hazards:
            out.append(_finding(
                'CON504', Severity.ERROR, rel, node,
                f'signal handler `{handler.name}` {what} — the '
                f'interrupted thread may already hold the resource',
                detail='set a flag/Event and do the work on a thread, '
                       'or restrict the handler to pre-cached state '
                       'and lock-free writes (the watchdog '
                       '`_on_signal` -> `dump(use_locks=False)` '
                       'model)'))
    return out


def _is_lockish(expr: ast.AST, lock_names) -> bool:
    attr = _self_attr(expr)
    if attr is not None:
        return attr in lock_names
    if isinstance(expr, ast.Name):
        return expr.id in lock_names
    return False


# ---------------------------------------------------------------------------
# CON505 — unbounded shared container growth from a serving thread
# ---------------------------------------------------------------------------

def _check_unbounded_growth(model: ModuleModel, rel) -> List[Finding]:
    out = []
    for cls in model.classes:
        if not cls.entry_closure:
            continue
        seen_attr_method = set()
        for g in cls.growth:
            if g.method not in cls.entry_closure:
                continue
            capped = cls.container_attrs.get(g.attr)
            if capped is None:      # not a container built in __init__
                continue
            if capped or g.attr in cls.bounded_attrs:
                continue
            key = (g.attr, g.method)
            if key in seen_attr_method:
                continue
            seen_attr_method.add(key)
            kind, origin = cls.entry_closure[g.method]
            op = ('keyed store' if g.op == 'setitem'
                  else f'`.{g.op}()`')
            via = (f'`{cls.name}.{g.method}`' if g.method == origin
                   else f'`{cls.name}.{g.method}` (reached from '
                        f'{kind} entry `{origin}`)')
            out.append(_finding(
                'CON505', Severity.WARNING, rel, g.node,
                f'`self.{g.attr}` grows without bound ({op}) from '
                f'thread entry path {via} — no maxlen/len-check/'
                f'eviction anywhere in the class',
                detail=f'entry kind: {kind}; use deque(maxlen=...) or '
                       f'an explicit capacity check with drop '
                       f'accounting (the FlightRecorder ring / qtrace '
                       f'capacity discipline)'))
    return out


# ---------------------------------------------------------------------------
# File / tree drivers
# ---------------------------------------------------------------------------

def lint_concurrency_file(path: str,
                          rel: Optional[str] = None) -> List[Finding]:
    """All concurrency rules over one ``.py`` file. ``rel`` overrides
    the location prefix used in findings (defaults to ``path``). A file
    that fails to parse is the source tier's problem (SRC100); this
    tier stays silent on it."""
    _refuse_bytecode(path)
    rel = rel or path
    with open(path, encoding='utf-8') as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError:
        return []
    model = build_module_model(tree)
    out = []
    out += _check_unlocked_rmw(model, rel)
    out += _check_lock_order(model, rel)
    out += _check_artifact_writes(tree, rel)
    out += _check_signal_handlers(model, rel)
    out += _check_unbounded_growth(model, rel)
    return disambiguate_contexts(_with_line_context(f, src) for f in out)


def lint_concurrency_tree(root: str,
                          exclude: Sequence[str] = ()) -> List[Finding]:
    """Concurrency rules over every ``.py`` under ``root``
    (recursively), reporting repo-relative locations."""
    root = os.path.abspath(root)
    base = os.path.dirname(root)
    out = []
    for path in iter_source_files(root):
        rel = os.path.relpath(path, base)
        if any(rel.startswith(e) for e in exclude):
            continue
        out.extend(lint_concurrency_file(path, rel=rel))
    return out


def lint_concurrency_paths(paths: Sequence[str]) -> List[Finding]:
    """Concurrency rules over a mix of files and directories — the
    multi-root scan the CLI drives (package + repo-root bench drivers
    + ``benchmarks/``)."""
    out = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isdir(p):
            out.extend(lint_concurrency_tree(p))
        else:
            out.extend(lint_concurrency_file(p, rel=os.path.basename(p)))
    return out
