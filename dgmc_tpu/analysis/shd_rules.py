"""SHD tier: rules over post-GSPMD partitioned HLO of sharded specimens.

The trace tier sees programs *before* partitioning; every hazard this
tier hunts only exists *after* GSPMD has inserted the communication —
which is exactly why ROADMAP item 1's multichip hangs and item 3's
sharding defeats were runtime-only discoveries until now. Each
registered multi-device specimen is compiled under its mesh, the
partitioned HLO is parsed once
(:func:`~dgmc_tpu.analysis.hlo_comm.parse_hlo_module` — the same walker
``obs/cost.py`` builds its collective account on), and five rules run
over the per-program collective schedule:

``SHD301`` branch-divergent-collectives (error)
    A ``conditional`` whose sibling branches carry different collective
    sequences — a collective reachable on one control path but not the
    other. If the predicate ever disagrees across devices (non-replicated
    input, NaN-path divergence), part of the mesh enters a collective
    its peers never post: the static face of the rc:124 multichip-hang
    class.
``SHD302`` corr-replication (error)
    An ``all-gather``/``collective-broadcast`` materializing a full
    correspondence-shaped tensor (rank >= 3 result at least as big as
    the specimen's declared ``[B, N_s, N_t]`` payload). GSPMD inserts
    these silently at sharding boundaries; one of them un-shards the
    million-entity S matrix the whole layout exists to split.
``SHD303`` reshard-churn (warning)
    Two or more resharding collectives that BOUNCE the layout inside
    one ``while`` body — ``all-to-all``s, and ``collective-permute``s
    composed through the body's dataflow (a permute fed by another
    permute: the data left and came back in one iteration) — instead
    of the layout being settled once outside the loop. Independent
    per-iteration permutes are the pipelined streamed-S ring rotation
    and do not count.
``SHD304`` comm-budget (warning)
    The program's total collective payload exceeds the specimen's
    recorded per-step communication budget (``comm_budget_bytes`` in the
    specimen build, like the recompile pass's compiles-per-bucket
    budget). Reported in power-of-two buckets so the finding's identity
    survives small payload drift but releases on an order-of-magnitude
    regression.
``SHD305`` precision-contract (error)
    A reduction/contraction accumulating in bf16 — worst when an
    explicit f32->bf16 ``convert`` feeds it (precision was available and
    thrown away before the accumulation). ``models/precision.py``'s
    contract is bf16 *compute* with f32 *accumulation*; a bf16 running
    sum stops absorbing addends once it is ~256x any contribution, so
    this is a correctness rule, not a style rule.
"""

import dataclasses
import re
from typing import List, Optional

from dgmc_tpu.analysis.findings import (Finding, Severity,
                                        disambiguate_contexts)
from dgmc_tpu.analysis.hlo_comm import (HloModule, collective_schedule,
                                        parse_hlo_module)

__all__ = ['ShardedContext', 'analyze_sharded_hlo', 'run_sharded_tier',
           'check_branch_divergence', 'check_corr_replication',
           'check_reshard_churn', 'check_comm_budget',
           'check_precision_contract']

#: Collectives that re-replicate a sharded tensor (SHD302).
_REPLICATING = ('all-gather', 'collective-broadcast')
#: Collectives that move a tensor between layouts (SHD303).
_RESHARDING = ('collective-permute', 'all-to-all')

_LHS_CONTRACT = re.compile(r'lhs_contracting_dims=\{([0-9,]*)\}')


@dataclasses.dataclass
class ShardedContext:
    """Provenance prefix + thresholds for one partitioned program."""
    specimen: str = 'program'
    #: Full correspondence-matrix payload bytes (``B*N_s*N_t*itemsize``)
    #: when the specimen declares one; SHD302 runs only with it set.
    corr_bytes: Optional[int] = None
    #: Per-step collective-byte budget; SHD304 runs only with it set.
    comm_budget_bytes: Optional[int] = None
    #: Minimum accumulated elements before a bf16 accumulator is worth
    #: flagging (tiny reductions cannot drift meaningfully).
    accum_elems: int = 64
    #: Resharding collectives inside one loop body before SHD303 fires.
    reshard_churn_min: int = 2


def _prod(dims) -> int:
    out = 1
    for d in dims:
        out *= d
    return out


def _loc(op_or_coll, fallback: str) -> str:
    """Stable location for a finding: source provenance when the HLO
    metadata carries it, else the op-name scope path, else a structural
    fallback (never the compiler's drifting computation names)."""
    loc = getattr(op_or_coll, 'source_loc', None)
    if loc:
        return loc
    name = getattr(op_or_coll, 'op_name', '')
    return name or fallback


def _pow2_bucket(nbytes: int) -> str:
    """``<= 2^k`` byte bucket — the finding's identity-bearing size, so
    the fingerprint survives payload jitter but releases when the
    program's communication grows past the next power of two."""
    k = max(1, nbytes)
    bucket = 1
    while bucket < k:
        bucket <<= 1
    if bucket >= 1 << 20:
        return f'<= {bucket >> 20} MiB'
    if bucket >= 1 << 10:
        return f'<= {bucket >> 10} KiB'
    return f'<= {bucket} B'


# ---------------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------------


def check_branch_divergence(module: HloModule,
                            ctx: ShardedContext) -> List[Finding]:
    """SHD301: sibling conditional branches with different collective
    sequences."""
    out = []
    cond_idx = 0
    for comp, op in module.iter_ops():
        branches = op.branch_computations()
        if len(branches) < 2:
            continue
        cond_idx += 1
        seqs = [tuple(c.kind for c in module.flatten_collectives(b))
                for b in branches]
        if len(set(seqs)) <= 1:
            continue
        rendered = ' vs '.join('[' + ', '.join(s) + ']' for s in seqs)
        out.append(Finding(
            rule='SHD301', severity=Severity.ERROR,
            context=f'conditional {rendered}',
            where=f'{ctx.specimen}:{_loc(op, f"conditional#{cond_idx}")}',
            message=(f'collective sequence diverges across conditional '
                     f'branches ({rendered}) — a collective reachable '
                     f'on one control path but not its sibling'),
            detail=('if the predicate ever disagrees across devices, '
                    'part of the mesh posts a collective its peers '
                    'never enter: distributed deadlock (the rc:124 '
                    'multichip-hang class). Hoist the collective out '
                    'of the conditional or make both branches '
                    'communicate identically; branch computations: '
                    + ', '.join(branches))))
    return out


def check_corr_replication(module: HloModule,
                           ctx: ShardedContext) -> List[Finding]:
    """SHD302: all-gather materializing a full correspondence-shaped
    tensor."""
    if not ctx.corr_bytes:
        return []
    out = []
    for coll in collective_schedule(module):
        if coll.kind not in _REPLICATING:
            continue
        # Identify "S got un-sharded": a rank>=3 result (the [B, N_s,
        # N_t] family) at least as large as the declared full matrix.
        m = re.search(r'([a-z][a-z0-9]*)\[([0-9,]+)\]', coll.line)
        if not m:
            continue
        dims = [int(d) for d in m.group(2).split(',') if d]
        if len(dims) < 3 or coll.nbytes < ctx.corr_bytes:
            continue
        shape = f'{m.group(1)}[{m.group(2)}]'
        out.append(Finding(
            rule='SHD302', severity=Severity.ERROR,
            context=f'{coll.kind} {shape}',
            where=f'{ctx.specimen}:{_loc(coll, coll.kind)}',
            message=(f'`{coll.kind}` materializes a full '
                     f'correspondence-shaped tensor ({shape}) — '
                     f'implicit replication defeats the S-matrix '
                     f'sharding'),
            detail=(f'payload {coll.nbytes} B >= declared full '
                    f'correspondence payload {ctx.corr_bytes} B '
                    f'(replica_groups={coll.replica_groups}, '
                    f'channel_id={coll.channel_id}); add a '
                    f'with_sharding_constraint at the producing op or '
                    f'reformulate the consumer to work on shards')))
    return out


def _region_computations(module: HloModule, root: str):
    """``root`` plus every computation reachable from it through region
    refs (fusion interiors excluded, matching the schedule walk)."""
    seen = []

    def walk(name):
        comp = module.computations.get(name)
        if comp is None or name in seen:
            return
        seen.append(name)
        for op in comp.ops:
            if op.opcode == 'fusion':
                continue
            for sub in op.called_computations():
                walk(sub)

    walk(root)
    return seen


def _churn_resharding(module: HloModule, body: str):
    """Resharding collectives in ``body``'s region that actually BOUNCE
    the layout. The bounce signature is *composition*: a
    collective-permute whose local dataflow is fed by (or feeds)
    another resharding collective in the same computation — the data
    left and came back inside one iteration. Permutes of INDEPENDENT
    tensors are single resharding events, not churn: re-issuing the
    boundary permute every iteration is the pipelined streamed-S ring
    rotation working as designed (at ANY ring size — a 2-device ring's
    mapping is its own inverse, which is why churn cannot be read off
    the source_target_pairs alone). ``all-to-all`` always counts: it
    is a full reshard with no pipeline reading."""
    out = []
    for name in _region_computations(module, body):
        comp = module.computations[name]
        defs = {op.result: op for op in comp.ops}
        resh = [op for op in comp.ops
                if op.collective_kind in _RESHARDING]
        composed = set()
        for op in resh:
            seen, stack = set(), list(op.operand_refs())
            while stack:
                ref = stack.pop()
                if ref in seen:
                    continue
                seen.add(ref)
                producer = defs.get(ref)
                if producer is None:
                    continue
                if (producer is not op
                        and producer.collective_kind in _RESHARDING):
                    composed.add(id(op))
                    composed.add(id(producer))
                    break
                stack.extend(producer.operand_refs())
        out.extend(op for op in resh
                   if op.opcode != 'collective-permute'
                   or id(op) in composed)
    return out


def check_reshard_churn(module: HloModule,
                        ctx: ShardedContext) -> List[Finding]:
    """SHD303: resharding collectives that bounce the layout inside one
    loop body (:func:`_churn_resharding` — composed permutes and
    all-to-alls; independent ring-rotation permutes are the pipelined
    chunk loop working as designed and do not count)."""
    out = []
    for i, (while_op, body) in enumerate(module.while_bodies()):
        resh = _churn_resharding(module, body)
        if len(resh) < ctx.reshard_churn_min:
            continue
        kinds = sorted({op.collective_kind for op in resh})
        out.append(Finding(
            rule='SHD303', severity=Severity.WARNING,
            context=f'while {"/".join(kinds)}',
            where=f'{ctx.specimen}:{_loc(while_op, f"while#{i}")}',
            message=(f'resharding churn inside a loop body '
                     f'({"/".join(kinds)} round-trip) — the layout is '
                     f'bounced every iteration'),
            detail=(f'{len(resh)} resharding collective(s), '
                    f'{sum(op.result_bytes for op in resh)} B payload '
                    f'per iteration; settle the layout once outside the '
                    f'loop (sharding constraints on the carried state) '
                    f'instead of round-tripping it in the consensus '
                    f'iteration body')))
    return out


def check_comm_budget(module: HloModule,
                      ctx: ShardedContext) -> List[Finding]:
    """SHD304: total per-step collective payload over the specimen's
    recorded budget."""
    if not ctx.comm_budget_bytes:
        return []
    sched = collective_schedule(module)
    total = sum(c.nbytes for c in sched)
    if total <= ctx.comm_budget_bytes:
        return []
    per_kind = {}
    for c in sched:
        per_kind[c.kind] = per_kind.get(c.kind, 0) + c.nbytes
    breakdown = ', '.join(f'{k}: {v} B'
                          for k, v in sorted(per_kind.items()))
    return [Finding(
        rule='SHD304', severity=Severity.WARNING,
        where=f'{ctx.specimen}:comm-budget',
        message=(f'collective payload {_pow2_bucket(total)} per step '
                 f'exceeds the recorded '
                 f'{ctx.comm_budget_bytes} B communication budget'),
        detail=(f'exact total {total} B over {len(sched)} '
                f'collective(s) — {breakdown}; either the new '
                f'communication is intended (raise the specimen budget '
                f'in the registry and re-baseline) or a sharding '
                f'boundary moved'))]


def _fed_by_f32_convert(defs, operand_name: str) -> bool:
    producer = defs.get(operand_name)
    if producer is None or producer.opcode != 'convert':
        return False
    ops = producer.operands()
    return bool(ops) and ops[0][0] == 'f32'


def check_precision_contract(module: HloModule,
                             ctx: ShardedContext) -> List[Finding]:
    """SHD305: bf16 accumulation (reduce/dot), worst when fed by an
    explicit f32->bf16 downcast."""
    out = []
    hits = 0
    for comp in module.computations.values():
        defs = {op.result: op for op in comp.ops}
        for op in comp.ops:
            shape = op.result_shape
            if shape is None or shape[0] != 'bf16':
                continue
            operands = op.operands()
            if op.opcode == 'reduce':
                if not operands:
                    continue
                in_elems = _prod(operands[0][1])
                acc = in_elems // max(_prod(shape[1]), 1)
                fed = _fed_by_f32_convert(defs, operands[0][2])
            elif op.opcode == 'dot':
                m = _LHS_CONTRACT.search(op.line)
                if not m or not operands:
                    continue
                lhs_dims = operands[0][1]
                acc = 1
                try:
                    for idx in (int(s) for s in m.group(1).split(',')
                                if s):
                        acc *= lhs_dims[idx]
                except IndexError:
                    continue
                fed = any(_fed_by_f32_convert(defs, o[2])
                          for o in operands[:2])
            else:
                continue
            if acc < ctx.accum_elems:
                continue
            if fed:
                message = (f'f32->bf16 downcast feeds `{op.opcode}` '
                           f'with a bf16 accumulator — '
                           f'f32-accumulation contract violation')
            else:
                message = (f'`{op.opcode}` accumulates in bf16 — '
                           f'f32-accumulation contract violation')
            # Structural fallback (opcode + walk ordinal, like
            # SHD301's conditional#N) — comp.name/op.result are
            # compiler-assigned and renumber on unrelated recompiles,
            # which would churn the fingerprint.
            out.append(Finding(
                rule='SHD305', severity=Severity.ERROR,
                context=f'{op.opcode} {op.result_type}',
                where=f'{ctx.specimen}:'
                      f'{_loc(op, f"{op.opcode}#{hits}")}',
                message=message,
                detail=(f'{acc} element(s) accumulated into a bf16 '
                        f'result ({op.result_type}); a bf16 running '
                        f'sum stops absorbing addends at ~256x scale — '
                        f'set preferred_element_type=f32 on the '
                        f'contraction / keep the reduction in f32 '
                        f'(models/precision.py contract)')))
            hits += 1
    return out


def analyze_sharded_hlo(hlo_text: str,
                        ctx: Optional[ShardedContext] = None,
                        ) -> List[Finding]:
    """All SHD rules over one partitioned program (parsed once)."""
    ctx = ctx or ShardedContext()
    module = parse_hlo_module(hlo_text)
    out = []
    out += check_branch_divergence(module, ctx)
    out += check_corr_replication(module, ctx)
    out += check_reshard_churn(module, ctx)
    out += check_comm_budget(module, ctx)
    out += check_precision_contract(module, ctx)
    return disambiguate_contexts(out)


# ---------------------------------------------------------------------------
# Tier driver
# ---------------------------------------------------------------------------


def run_sharded_tier(specimens=None, *, cache=None,
                     comm_budget_bytes=None, on_progress=None,
                     skipped=None) -> List[Finding]:
    """Compile every SHD-registered specimen under its mesh (reusing the
    lint run's shared :class:`~dgmc_tpu.analysis.registry.SpecimenCache`
    lowerings) and run the SHD rules over the partitioned HLO. Mesh
    specimens below the process's device count are skipped (reported,
    and appended to ``skipped`` so baseline writers preserve their
    prior entries)."""
    from dgmc_tpu.analysis.registry import (SpecimenCache,
                                            iter_runnable_specimens)

    cache = cache if cache is not None else SpecimenCache()
    findings = []
    for spec in iter_runnable_specimens('shd', specimens=specimens,
                                        on_progress=on_progress,
                                        skipped=skipped):
        if on_progress:
            on_progress(f'sharded-hlo {spec.name}')
        art = cache.artifacts(spec)
        built = art.built()
        text = art.compiled().as_text()
        ctx = ShardedContext(
            specimen=spec.name,
            corr_bytes=built.get('corr_bytes'),
            comm_budget_bytes=built.get('comm_budget_bytes',
                                        comm_budget_bytes))
        findings.extend(analyze_sharded_hlo(text, ctx))
    return findings
