from dgmc_tpu.parallel.mesh import (DATA_AXIS, MODEL_AXIS, make_mesh,
                                    batch_spec, corr_spec, corr_sharding)
from dgmc_tpu.parallel.rules import (DEFAULT_TOPK_BLOCK, PartitionRules,
                                     corr_row_rules, match_partition_rules,
                                     replicated_rules, shard_tree,
                                     streamed_rules, tree_shardings)
from dgmc_tpu.parallel.sharding import (replicate, shard_batch,
                                        make_sharded_train_step,
                                        make_sharded_eval_step)
from dgmc_tpu.parallel.topk import sharded_topk_rows, sharded_topk_cols
from dgmc_tpu.parallel.distributed import (global_batch, host_obs_dir,
                                           initialize_distributed,
                                           is_coordinator,
                                           local_batch_slice)

__all__ = [
    'initialize_distributed',
    'is_coordinator',
    'host_obs_dir',
    'global_batch',
    'local_batch_slice',
    'DATA_AXIS',
    'MODEL_AXIS',
    'make_mesh',
    'batch_spec',
    'corr_spec',
    'corr_sharding',
    'DEFAULT_TOPK_BLOCK',
    'PartitionRules',
    'match_partition_rules',
    'tree_shardings',
    'shard_tree',
    'replicated_rules',
    'corr_row_rules',
    'streamed_rules',
    'replicate',
    'shard_batch',
    'make_sharded_train_step',
    'make_sharded_eval_step',
    'sharded_topk_rows',
    'sharded_topk_cols',
]
