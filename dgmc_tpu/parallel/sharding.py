"""Sharded training: batch-parallel + correspondence-parallel train steps.

The reference has no distributed execution at all (SURVEY.md §2.5); here the
train step from ``dgmc_tpu/train/steps.py`` is compiled over a mesh with:

- the pair batch sharded over the ``data`` axis (pure data parallelism —
  gradients are combined by XLA's reduction collectives automatically,
  because the loss is a mean over the sharded batch axis; BatchNorm
  backbones are safe here too: the masked batch statistics are reductions
  over the GLOBAL logical batch, so GSPMD inserts the cross-shard
  collectives for them as well — pinned by
  ``tests/parallel/test_batchnorm_dp.py``),
- parameters and optimizer state replicated,
- optionally, correspondence-shaped intermediates (``S_hat``/``S_idx``,
  shape ``[B, N_s, ...]``) row-sharded over the ``model`` axis via the
  model's ``corr_sharding`` constraint — activation sharding for
  DBP15K-scale graphs where a single pair's ``N_s x N_t`` state dwarfs the
  weights.

GSPMD inserts the collectives (psum for grads, all_gathers at sharding
boundaries); they ride ICI on a real slice. Nothing here speaks a transport
protocol — that is the point of the XLA-collective design.
"""

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from dgmc_tpu.ops.pallas.dispatch import disable_fused_kernels
from dgmc_tpu.parallel.mesh import DATA_AXIS
from dgmc_tpu.train import steps as _steps


def _reject_explicit_fused(model, mesh):
    """Explicitly requested Pallas kernels cannot be silenced by the
    trace-time context — reject them loudly, matching DGMC's own
    ``corr_sharding`` check, instead of tracing a ``pallas_call`` into the
    partitioned program."""
    requested = [role for role, flag in (
        ('psi_1', getattr(model.psi_1, 'fused', None)),
        ('psi_2', getattr(model.psi_2, 'fused', None)),
        ('fused_consensus', getattr(model, 'fused_consensus', None)),
        ('fused_sparse_consensus',
         getattr(model, 'fused_sparse_consensus', None)),
    ) if flag is True]
    if requested:
        raise ValueError(
            f'{requested} request Pallas kernels explicitly, but a '
            f'{mesh.size}-device mesh partitions the program and '
            f'pallas_call has no GSPMD partitioning rule; leave the '
            f'kernel flags at None/False for sharded execution')


def _gspmd_safe(step, mesh, model=None):
    """Trace ``step`` with auto-dispatched Pallas kernels silenced whenever
    the mesh actually partitions (``pallas_call`` has no GSPMD partitioning
    rule — inside a partitioned program it crashes or silently replicates).
    ``jax.typeof(...).vma`` only detects ``shard_map`` manual mode, not
    ``jax.jit(in_shardings=...)`` auto-partitioning, so every auto gate must
    be turned off here at trace time. A single-device mesh never partitions,
    so the kernels stay on there."""
    if mesh.size <= 1:
        return step
    if model is not None:
        _reject_explicit_fused(model, mesh)

    def traced(*args):
        with disable_fused_kernels():
            return step(*args)

    return traced


def replicate(tree, mesh):
    """Place every leaf replicated over the mesh."""
    s = NamedSharding(mesh, P())
    return jax.device_put(tree, s)


def shard_batch(batch, mesh, axis=DATA_AXIS):
    """Place a :class:`PairBatch` (or any leading-``B`` pytree) with its
    batch axis split over ``axis``."""
    s = NamedSharding(mesh, P(axis))
    return jax.device_put(batch, s)


def _resolve_rules(model, mesh, rules, state, batch_axis):
    """Shared rules plumbing for the step builders.

    With ``rules`` (a :class:`~dgmc_tpu.parallel.rules.PartitionRules`),
    the model is cloned with the config's activation constraints /
    streaming knobs and the in/out shardings come from the declarative
    rule match — the replacement for the hand-wired
    ``in_shardings=(repl, batched, repl)`` wiring. ``state`` (an example
    train-state pytree, e.g. the host-side one about to be placed) gives
    the rule matcher the exact pytree to type; without it the state is
    replicated wholesale (identical to the legacy behavior, since the
    default rules replicate everything anyway).
    """
    repl = NamedSharding(mesh, P())
    if rules is None:
        return model, NamedSharding(mesh, P(batch_axis)), repl
    model = rules.apply_to_model(model, mesh)
    state_sh = (rules.state_shardings(state, mesh) if state is not None
                else repl)
    return model, rules.batch_sharding(mesh), state_sh


def make_sharded_train_step(model, mesh, loss_on_s0=False, num_steps=None,
                            detach=None, hits_ks=(), batch_axis=DATA_AXIS,
                            rules=None, state=None, guard=False,
                            fault_nan_step=None):
    """Jit a train step with explicit mesh shardings.

    Same contract as :func:`dgmc_tpu.train.make_train_step` — call it with a
    state placed by :func:`replicate` and a batch placed by
    :func:`shard_batch`; or, with ``rules``, a state/batch placed by
    :meth:`PartitionRules.place <dgmc_tpu.parallel.rules.PartitionRules>`
    (``state`` supplies the example pytree the regex rules are matched
    against — params, optimizer state and guard counters all type from
    one declarative config instead of hand-wired ``in_shardings``).
    """
    model, batched, state_sh = _resolve_rules(model, mesh, rules, state,
                                              batch_axis)
    step = _steps.make_train_step(model, loss_on_s0=loss_on_s0,
                                  num_steps=num_steps, detach=detach,
                                  hits_ks=hits_ks, jit=False, guard=guard,
                                  fault_nan_step=fault_nan_step)
    repl = NamedSharding(mesh, P())
    return jax.jit(_gspmd_safe(step, mesh, model),
                   in_shardings=(state_sh, batched, repl),
                   out_shardings=(state_sh, repl),
                   donate_argnums=(0,))


def make_sharded_eval_step(model, mesh, hits_ks=(1,), num_steps=None,
                           detach=None, batch_axis=DATA_AXIS,
                           rules=None, state=None):
    model, batched, state_sh = _resolve_rules(model, mesh, rules, state,
                                              batch_axis)
    step = _steps.make_eval_step(model, hits_ks=hits_ks, num_steps=num_steps,
                                 detach=detach, jit=False)
    repl = NamedSharding(mesh, P())
    return jax.jit(_gspmd_safe(step, mesh, model),
                   in_shardings=(state_sh, batched, repl),
                   out_shardings=repl)
