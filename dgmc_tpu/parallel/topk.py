"""Mesh-sharded top-k candidate search — the multi-chip KeOps replacement.

Two shardings of the ``N_s x N_t`` similarity sweep (never materialized;
each shard runs the blockwise running-top-k of ``dgmc_tpu/ops/topk.py``):

- **Row sharding** (:func:`sharded_topk_rows`): source rows are split over a
  mesh axis; every device scans the full target set for its rows. No
  collectives at all — rows are independent. This is the default for
  DBP15K-scale graphs (the "sequence parallelism" analog of this workload,
  SURVEY.md §2.5).
- **Column sharding** (:func:`sharded_topk_cols`): the *target* set is split;
  every device computes a local top-k over its column shard, then one
  ``all_gather`` of ``[N_s, k]`` candidates merges them into the global
  top-k. Communication is ``O(N_s * k * n_dev)``, independent of ``N_t`` —
  the right axis when targets dwarf sources or when ``h_t`` is produced
  sharded (e.g. by a column-sharded ψ₁).

Both produce indices bit-identical to ``dense_topk`` (tie-break included).
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from dgmc_tpu.ops.topk import chunked_topk, streamed_topk
# Both sharded searches take the ONE measured block default (256; the
# r03 sweep — see DEFAULT_BLOCK in ops/topk.py and the DISPATCH_DEFAULTS
# table) threaded through the partition-rule config: callers built from a
# PartitionRules pass rules.topk_block, and a bare call inherits the same
# constant instead of the per-callsite 1024/256 literals this module used
# to carry.
from dgmc_tpu.parallel.rules import DEFAULT_TOPK_BLOCK
from dgmc_tpu.parallel.compat import shard_map
from dgmc_tpu.parallel.mesh import MODEL_AXIS


def sharded_topk_rows(mesh, h_s, h_t, k, t_mask=None,
                      block=DEFAULT_TOPK_BLOCK, axis=MODEL_AXIS,
                      chunk=None):
    """Top-k with source rows sharded over ``axis``. ``N_s`` must divide by
    the axis size (pad rows host-side; padded rows are just extra work).
    ``chunk`` additionally streams each shard's rows ``chunk`` at a time
    (``ops/topk.streamed_topk``) so the per-device score tile is
    ``[chunk, block]`` regardless of the shard's row count."""
    if t_mask is None:
        t_mask = jnp.ones((h_t.shape[0], h_t.shape[1]), bool)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(None, axis, None), P(), P()),
        out_specs=P(None, axis, None))
    def inner(h_s_l, h_t_l, t_mask_l):
        if chunk:
            return streamed_topk(h_s_l, h_t_l, k, chunk, t_mask=t_mask_l,
                                 block=block)
        return chunked_topk(h_s_l, h_t_l, k, t_mask=t_mask_l, block=block)

    return inner(h_s, h_t, t_mask)


def _merge_candidates(vals, idx, tile_vals, tile_idx, k):
    """Merge two candidate sets into the running top-k with the DENSE
    tie order: candidates are sorted by global target index before the
    ``top_k``, so equal values always resolve toward the lowest global
    index — whatever order the ring delivered the shards in. (The
    chunk-scan merge can rely on carry-before-tile concatenation
    because its tiles arrive in index order; ring shards do not.)"""
    all_vals = jnp.concatenate([vals, tile_vals], axis=-1)
    all_idx = jnp.concatenate([idx, tile_idx], axis=-1)
    order = jnp.argsort(all_idx, axis=-1)
    all_vals = jnp.take_along_axis(all_vals, order, axis=-1)
    all_idx = jnp.take_along_axis(all_idx, order, axis=-1)
    new_vals, pos = jax.lax.top_k(all_vals, k)
    return new_vals, jnp.take_along_axis(all_idx, pos, axis=-1)


def corr_sharded_topk(sharding, h_s, h_t, k, t_mask,
                      block=DEFAULT_TOPK_BLOCK, chunk=None, ring=False):
    """Top-k under a correspondence sharding, INSIDE a GSPMD program.

    ``sharding`` is the ``corr_sharding`` NamedSharding for
    ``[B, N_s, ...]`` arrays (batch over one mesh axis, source rows over
    another; ``parallel/mesh.corr_spec``). ``pallas_call`` has no GSPMD
    partitioning rule, but ``shard_map`` embeds manual per-shard code in
    an auto-partitioned program — so each (batch, row) shard runs the
    streaming Pallas kernel locally (rows are independent; no
    collectives), instead of the whole program falling back to the ~4×
    slower scan. Ragged row counts are padded up to the mesh tile (padded
    rows are discarded work); only a ragged *batch* axis returns ``None``
    (caller falls back).

    ``chunk`` streams each shard's local rows ``chunk`` at a time
    (``ops/topk.streamed_topk`` inside the shard-local region): the
    distributed shortlisting of the million-entity layout, where even one
    device's ``N_s/n_dev`` row block is too many rows to score against
    every target at once — peak per-device search memory becomes
    ``O(chunk × block)``.

    ``ring`` additionally shards the TARGET set over the same row axis
    and rotates the shards device-to-device: each device starts with its
    own ``N_t/n_dev`` target block and, per rotation, (1) issues the
    shard-boundary ``collective-permute`` handing its CURRENT block to
    the next device — a transfer that depends only on the loop carry,
    never on this rotation's compute — then (2) runs the (double-
    buffered) chunk-streamed search of its rows against the block it
    holds, merging candidates with the dense tie order
    (:func:`_merge_candidates`). The permute therefore overlaps the
    per-tile top-k instead of serializing against it (the SCH402-gated
    overlap win of ROADMAP item 4), and per-device ``h_t`` memory drops
    from ``O(N_t)`` to ``O(N_t/n_dev)``. Results stay bit-identical to
    :func:`~dgmc_tpu.ops.topk.dense_topk` (ties included). Ring needs a
    single concrete mesh axis on the rows, ``N_t`` padded up to the
    ring size (masked columns — discarded work), and ``k <=
    N_t/n_dev`` (a shard must be able to hold a full candidate set);
    otherwise the replicated-target path runs unchanged.
    """
    mesh, spec = sharding.mesh, sharding.spec
    b_ax = spec[0] if len(spec) > 0 else None
    s_ax = spec[1] if len(spec) > 1 else None

    def ax_size(ax):
        if ax is None:
            return 1
        axes = ax if isinstance(ax, tuple) else (ax,)
        out = 1
        for a in axes:
            out *= mesh.shape[a]
        return out

    B, N_s = h_s.shape[0], h_s.shape[1]
    if B % ax_size(b_ax):
        # Padding the batch axis would multiply wasted work by the whole
        # per-pair cost; B is protocol-small (1 for DBP15K), so a ragged
        # batch keeps the scan fallback.
        return None
    # Ragged ROWS pad up to the mesh tile: rows are independent, padded
    # rows are discarded work (identical to the scan path's masking), and
    # staying on the kernel is ~4-5x cheaper than falling back (KeOps
    # never falls back by shape either, reference dgmc.py:85-94).
    pad_s = (-N_s) % ax_size(s_ax)
    if pad_s:
        h_s = jnp.pad(h_s, ((0, 0), (0, pad_s), (0, 0)))
    if t_mask is None:
        t_mask = jnp.ones((h_t.shape[0], h_t.shape[1]), bool)

    # The embedding is usually traced inside disable_fused_kernels()
    # (make_sharded_train_step silences auto-Pallas for the GSPMD parts),
    # but THIS region is manual shard-local code — exactly what the
    # kernel supports — so that contextvar is deliberately ignored. The
    # dedicated disable_embedded_kernels() switch remains as the escape
    # hatch if the shard_map Pallas path misbehaves on some topology.
    from dgmc_tpu.ops.pallas.dispatch import (embedded_kernels_allowed,
                                              record_dispatch)
    use_kernel = (jax.default_backend() == 'tpu'
                  and embedded_kernels_allowed())
    record_dispatch(
        'topk_embedded', 'pallas' if use_kernel else 'fallback',
        'auto-tpu' if use_kernel
        else ('embedded-disabled' if jax.default_backend() == 'tpu'
              else f'backend={jax.default_backend()}'))

    # AD opacity (`_ad_opaque`) sits OUTSIDE the shard_map: the search is
    # non-differentiable by design, and on jax 0.4.37
    # grad-over-shard_map-over-custom_jvp asserts in pjit — so the
    # shard-local body calls the plain jitted cores and the custom_jvp
    # wraps the whole sharded call. Without it, linearizing the embedded
    # scan stacks per-tile select masks as loop residuals
    # (pred[num_blocks, rows, block] per device — see ops/topk._ad_opaque).
    from dgmc_tpu.ops.topk import (_ad_opaque, _chunked_topk,
                                   _streamed_topk, _tile_sort)
    sort_tiles = _tile_sort()

    # Ring eligibility: one concrete mesh axis on the rows (ppermute
    # needs a named axis), more than one shard, and a shard wide enough
    # to hold k candidates. Anything else runs the replicated path —
    # same results, no boundary collectives to overlap.
    n_ring = ax_size(s_ax) if isinstance(s_ax, str) else 1
    if ring and n_ring > 1:
        N_t = h_t.shape[1]
        pad_t = (-N_t) % n_ring
        shard_cols = (N_t + pad_t) // n_ring
        if k <= shard_cols:
            if pad_t:
                h_t = jnp.pad(h_t, ((0, 0), (0, pad_t), (0, 0)))
                t_mask = jnp.pad(t_mask, ((0, 0), (0, pad_t)))
            out = _ring_topk(mesh, b_ax, s_ax, n_ring, shard_cols,
                             h_s, h_t, t_mask, k, block,
                             int(chunk) if chunk else 0, use_kernel,
                             sort_tiles)
            return out[:, :N_s] if pad_s else out

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(b_ax, s_ax, None), P(b_ax, None, None), P(b_ax, None)),
        out_specs=P(b_ax, s_ax, None))
    def local(hs, ht, tm):
        if chunk:
            return _streamed_topk(hs, ht, k, tm, int(chunk), block, False,
                                  use_kernel, sort_tiles)
        return _chunked_topk(hs, ht, k, tm, block, False, use_kernel,
                             sort_tiles)

    out = _ad_opaque(local, h_s, h_t, t_mask)
    return out[:, :N_s] if pad_s else out


def _ring_topk(mesh, b_ax, s_ax, n_ring, shard_cols, h_s, h_t, t_mask,
               k, block, chunk, use_kernel, sort_tiles):
    """The rotating-shard search behind ``corr_sharded_topk(ring=True)``.

    Shard-local loop, one iteration per target shard: the body FIRST
    issues the boundary ``ppermute`` handing the currently-held target
    block (and its mask) to the next device — data-dependent only on
    the loop carry — and THEN scores its rows against that same block
    through the double-buffered chunk scan, so the transfer and the
    per-tile top-k share no dependency edge and the schedule model (and
    a real TPU scheduler) can run them concurrently. After ``n_ring``
    rotations every device has scored every target column exactly once.

    Tie-exactness bookkeeping: after ``j`` rotations device ``d`` holds
    shard ``(d - j) mod n_ring``, so local candidate positions lift to
    global columns at ``shard_id * shard_cols``; positions beyond the
    shard's real width (the chunk scan's own block padding — value
    ``finfo.min``, never a winner against live columns) are remapped
    PAST the padded target range so they can never steal an equal-value
    tie from a real masked column in another shard.
    """
    from dgmc_tpu.ops.topk import _ad_opaque, _chunked_topk, _streamed_topk
    n_pad_total = n_ring * shard_cols
    perm = [(i, (i + 1) % n_ring) for i in range(n_ring)]

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(b_ax, s_ax, None), P(b_ax, s_ax, None),
                  P(b_ax, s_ax)),
        out_specs=P(b_ax, s_ax, None))
    def local(hs, ht, tm):
        my = jax.lax.axis_index(s_ax)

        def body(carry, j):
            vals, idx, buf_t, buf_m = carry
            # Boundary permute FIRST: depends on the carry alone, so
            # the search below can hide it.
            nxt_t = jax.lax.ppermute(buf_t, s_ax, perm)
            nxt_m = jax.lax.ppermute(buf_m, s_ax, perm)
            if chunk:
                tv, tp = _streamed_topk(hs, buf_t, k, buf_m, chunk,
                                        block, True, use_kernel,
                                        sort_tiles)
            else:
                tv, tp = _chunked_topk(hs, buf_t, k, buf_m, block, True,
                                       use_kernel, sort_tiles)
            shard_id = (my - j) % n_ring
            ti = jnp.where(tp < shard_cols,
                           shard_id * shard_cols + tp,
                           n_pad_total + tp)
            vals, idx = _merge_candidates(vals, idx, tv, ti, k)
            return (vals, idx, nxt_t, nxt_m), None

        init_vals = jnp.full(hs.shape[:2] + (k,), -jnp.inf, hs.dtype)
        init_idx = jnp.zeros(hs.shape[:2] + (k,), jnp.int32)
        from dgmc_tpu.ops.pallas.dispatch import vma_of
        vma = tuple(vma_of(hs))
        if vma:
            init_vals = jax.lax.pcast(init_vals, vma, to='varying')
            init_idx = jax.lax.pcast(init_idx, vma, to='varying')
        (vals, idx, _, _), _ = jax.lax.scan(
            body, (init_vals, init_idx, ht, tm),
            jnp.arange(n_ring, dtype=jnp.int32))
        return idx

    return _ad_opaque(local, h_s, h_t, t_mask)


def sharded_topk_cols(mesh, h_s, h_t, k, t_mask=None,
                      block=DEFAULT_TOPK_BLOCK, axis=MODEL_AXIS):
    """Top-k with target columns sharded over ``axis``; one all_gather of
    per-shard candidates merges local winners into the global top-k."""
    B, N_t = h_t.shape[0], h_t.shape[1]
    if t_mask is None:
        t_mask = jnp.ones((B, N_t), bool)
    n_shards = mesh.shape[axis]
    if N_t % n_shards:
        raise ValueError(f'N_t={N_t} not divisible by {n_shards} shards')
    shard_cols = N_t // n_shards
    if k > shard_cols:
        raise ValueError(f'k={k} exceeds columns per shard ({shard_cols})')

    # check_vma off: every shard derives the identical merge from the
    # all_gathered candidates, a replication the type system can't infer.
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P(None, axis, None), P(None, axis)),
        out_specs=P(), check_vma=False)
    def inner(h_s_l, h_t_l, t_mask_l):
        # Local blockwise running top-k over this device's column shard
        # (never materializes the [N_s, shard_cols] score tile), lifted to
        # global column indices.
        my_shard = jax.lax.axis_index(axis)
        vals, idx = chunked_topk(h_s_l, h_t_l, k, t_mask=t_mask_l,
                                 block=block, return_values=True)
        idx = idx + my_shard * shard_cols
        # Merge candidates from all shards: [n_shards, B, N_s, k].
        all_vals = jax.lax.all_gather(vals, axis)
        all_idx = jax.lax.all_gather(idx, axis)
        cat = lambda a: jnp.moveaxis(a, 0, -2).reshape(  # noqa: E731
            a.shape[1], a.shape[2], -1)
        # Order candidates by global column so equal values tie-break toward
        # the lower index, exactly like a dense top_k over the full matrix.
        flat_vals, flat_idx = cat(all_vals), cat(all_idx)
        order = jnp.argsort(flat_idx, axis=-1)
        flat_vals = jnp.take_along_axis(flat_vals, order, axis=-1)
        flat_idx = jnp.take_along_axis(flat_idx, order, axis=-1)
        best, pos = jax.lax.top_k(flat_vals, k)
        return jnp.take_along_axis(flat_idx, pos, axis=-1)

    return inner(h_s, h_t, t_mask)
