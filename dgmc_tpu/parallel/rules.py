"""Partition rules: declarative regex → PartitionSpec sharding config.

Sharding decisions were previously hand-wired per callsite: every
``jax.jit(in_shardings=...)`` spelled out replicated-vs-batched trees,
and the correspondence layout lived in ad-hoc ``corr_sharding`` plumbing
through the CLIs. This module makes sharding a *config object* in the
``match_partition_rules`` style (SNIPPETS.md [3]): an ordered list of
``(regex, PartitionSpec)`` rules is matched against the '/'-joined pytree
path of every leaf of the train state (params AND optimizer state AND
guard counters — the whole :class:`~dgmc_tpu.train.state.TrainState` /
``GuardedTrainState`` pytree), plus *named activation rules* for the
arrays that dominate memory at DBP15K-and-beyond scale:

- ``'corr'``   — the correspondence matrix ``S`` (``S_hat``/``S_0``/
  ``S_L``: dense ``[B, N_s, N_t]`` or sparse ``[B, N_s, K]``),
- ``'topk'``   — the top-k candidate shortlist ``S_idx [B, N_s, K]``
  (defaults to the ``'corr'`` rule when absent),
- ``'psi2'``   — the ψ₂ consensus intermediates living on source rows
  (the indicator noise ``r_s`` and consensus colourings ``o_s``,
  ``[B, N_s, R]`` / ``[num_steps, B, N_s, R]`` when stream-packed).

:func:`~dgmc_tpu.parallel.sharding.make_sharded_train_step` /
``make_sharded_eval_step`` consume a :class:`PartitionRules` in place of
their hand-wired ``in_shardings``; :class:`~dgmc_tpu.models.DGMC` consumes
the activation rules through its ``corr_sharding`` / ``topk_sharding`` /
``psi2_sharding`` constraint fields, all set at once by
:meth:`PartitionRules.apply_to_model`.

Matching semantics (pinned by ``tests/parallel/test_rules.py``):

- rules apply **first-match-wins**, in declaration order;
- scalar leaves (rank 0 or one element) are never partitioned — they get
  ``P()`` without consulting the rules (optimizer ``count``, ``step``,
  guard ledgers);
- a non-scalar leaf no rule matches **raises**, naming the leaf path —
  a silent default would replicate terabyte-scale state without anyone
  deciding that.

The config also owns the knobs the sharded execution threads through the
model instead of per-callsite literals:

- ``topk_block`` — the target-axis tile of the blockwise candidate
  search. One default for every path: **256**, the measured optimum of
  the r03 on-chip sweep at DBP15K scale (bench.py ``topk_ms`` 17.7 /
  21.1 / 24.8 ms at 256 / 1024 / 4096 — the Pallas kernel ignores the
  knob entirely, so the block size only matters on the scan paths,
  where smaller tiles also mean lower peak tile memory).
  ``parallel/topk.py`` previously defaulted 1024 in one function and
  256 in another; both now share :data:`DEFAULT_TOPK_BLOCK`.
- ``stream_chunk`` — source-node chunk streaming for the candidate
  search (``ops/topk.streamed_topk``): the ``[rows, block]`` score
  tile only ever covers ``stream_chunk`` rows, so a 10⁶×10⁶ pair's
  search peaks at ``O(chunk × block)`` per device instead of
  ``O(N_s × block)``.
"""

import dataclasses
import re
from typing import Mapping, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dgmc_tpu.ops.topk import DEFAULT_BLOCK as DEFAULT_TOPK_BLOCK
from dgmc_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

#: Default source-chunk length for streamed candidate search: 8192 rows
#: keeps the per-chunk score tile at 8192 x 256 x 4 B = 8 MiB while the
#: per-tile GEMM stays MXU-sized.
DEFAULT_STREAM_CHUNK = 8192


def leaf_path_str(path) -> str:
    """Render a ``tree_flatten_with_path`` key path as ``a/b/0/c``."""
    parts = []
    for k in path:
        if hasattr(k, 'key'):          # DictKey
            parts.append(str(k.key))
        elif hasattr(k, 'name'):       # GetAttrKey (struct/NamedTuple)
            parts.append(str(k.name))
        elif hasattr(k, 'idx'):        # SequenceKey
            parts.append(str(k.idx))
        else:                          # FlattenedIndexKey and friends
            parts.append(str(getattr(k, 'index', k)).strip('[].'))
    return '/'.join(parts)


def _is_scalar(leaf) -> bool:
    shape = getattr(leaf, 'shape', ())
    size = 1
    for d in shape:
        size *= d
    return len(shape) == 0 or size == 1


def match_partition_rules(rules, tree):
    """Return a pytree of :class:`PartitionSpec` matching ``tree``.

    ``rules`` is an ordered iterable of ``(regex, PartitionSpec)``;
    ``re.search`` runs against each leaf's '/'-joined path and the FIRST
    matching rule wins. Scalar leaves (rank 0, or a single element) get
    ``P()`` without consulting the rules. A non-scalar leaf that no rule
    matches raises :class:`ValueError` naming the leaf path — add a rule
    (a final ``('.*', P())`` replicates the remainder explicitly).
    """
    rules = tuple(rules)

    def spec_for(path, leaf):
        name = leaf_path_str(path)
        if _is_scalar(leaf):
            return P()
        for pattern, spec in rules:
            if re.search(pattern, name) is not None:
                return spec
        raise ValueError(
            f'no partition rule matches leaf {name!r} '
            f'(shape {getattr(leaf, "shape", None)}); rules tried: '
            f'{[r for r, _ in rules]!r} — append (".*", P()) to '
            f'replicate unmatched leaves explicitly')

    return jax.tree_util.tree_map_with_path(spec_for, tree)


def tree_shardings(rules, tree, mesh: Mesh):
    """``match_partition_rules`` result as a :class:`NamedSharding`
    pytree over ``mesh`` (the form ``jax.jit(in_shardings=...)`` and
    ``jax.device_put`` take)."""
    return jax.tree.map(lambda spec: NamedSharding(mesh, spec),
                        match_partition_rules(rules, tree),
                        is_leaf=lambda x: isinstance(x, P))


def shard_tree(tree, rules, mesh: Mesh):
    """Place ``tree`` on ``mesh`` with every leaf laid out per its
    matched rule."""
    return jax.device_put(tree, tree_shardings(rules, tree, mesh))


@dataclasses.dataclass(frozen=True)
class PartitionRules:
    """One declarative sharding config for a training setup.

    Args:
        state: ordered ``(regex, PartitionSpec)`` rules over the train
            state pytree — params, optimizer state, batch stats, guard
            counters. First match wins; see
            :func:`match_partition_rules`.
        batch: PartitionSpec for the pair batch's leading ``B`` axis
            (``P(DATA_AXIS)`` for data parallelism, ``P()``/``None``
            for a replicated single giant pair).
        activations: named activation rules — ``'corr'``, ``'topk'``,
            ``'psi2'`` (module docstring), plus the embedding-table
            rules ``'psi1'`` (source ψ₁ table ``h_s [B, N_s, C]``) and
            ``'corpus'`` (target ψ₁ table ``h_t [B, N_t, C]`` — shard
            it only with ``ring_targets``, which consumes it sharded;
            both are opt-in, see :func:`streamed_rules`). Missing
            names mean "no constraint" (``'topk'`` falls back to
            ``'corr'``).
        topk_block: target-axis tile for the blockwise candidate
            search, threaded to every consumer in place of per-callsite
            literals.
        stream_chunk: when set, the candidate search streams source
            rows in chunks of this many (``ops/topk.streamed_topk`` /
            the shard-local scan inside
            :func:`~dgmc_tpu.parallel.topk.corr_sharded_topk`).
        ring_targets: rotate TARGET shards device-to-device during the
            sharded candidate search
            (:func:`~dgmc_tpu.parallel.topk.corr_sharded_topk`
            ``ring=True``): per-device ``h_t`` memory drops to
            ``O(N_t / devices)`` and the shard-boundary
            ``collective-permute`` is issued a rotation ahead so it
            overlaps the per-tile top-k instead of serializing it —
            the pipelined form SCH402's overlap budget pins. Bit-
            identical results; falls back to the replicated-target
            path when the row axis cannot ring (single shard, tuple
            axis, or ``k`` wider than a target shard).
    """
    state: Tuple[Tuple[str, P], ...] = (('.*', P()),)
    batch: Optional[P] = None
    activations: Mapping[str, P] = dataclasses.field(default_factory=dict)
    topk_block: int = DEFAULT_TOPK_BLOCK
    stream_chunk: Optional[int] = None
    ring_targets: bool = False

    # -- pytree placement ---------------------------------------------------

    def state_shardings(self, state, mesh: Mesh):
        """NamedSharding pytree for the train-state pytree."""
        return tree_shardings(self.state, state, mesh)

    def batch_sharding(self, mesh: Mesh) -> NamedSharding:
        return NamedSharding(mesh, self.batch if self.batch is not None
                             else P())

    def place(self, state, batch, mesh: Mesh):
        """Device-put ``(state, batch)`` per this config."""
        return (shard_tree(state, self.state, mesh),
                jax.device_put(batch, self.batch_sharding(mesh)))

    # -- named activations --------------------------------------------------

    def activation_spec(self, name: str) -> Optional[P]:
        spec = self.activations.get(name)
        if spec is None and name == 'topk':
            spec = self.activations.get('corr')
        return spec

    def activation_sharding(self, name: str,
                            mesh: Mesh) -> Optional[NamedSharding]:
        spec = self.activation_spec(name)
        return None if spec is None else NamedSharding(mesh, spec)

    def apply_to_model(self, model, mesh: Mesh):
        """Clone a :class:`~dgmc_tpu.models.DGMC` with every knob this
        config owns: the three activation constraints, the streaming
        chunk, and the candidate-search block size."""
        return model.clone(
            corr_sharding=self.activation_sharding('corr', mesh),
            topk_sharding=self.activation_sharding('topk', mesh),
            psi2_sharding=self.activation_sharding('psi2', mesh),
            psi1_sharding=self.activation_sharding('psi1', mesh),
            corpus_sharding=self.activation_sharding('corpus', mesh),
            stream_chunk=self.stream_chunk,
            ring_targets=self.ring_targets,
            topk_block=self.topk_block)


def replicated_rules(batch_axis: Optional[str] = DATA_AXIS,
                     **kw) -> PartitionRules:
    """The classic data-parallel config ``make_sharded_train_step``
    hand-wired before this module existed: state replicated, pair batch
    split over ``batch_axis``, no activation constraints."""
    return PartitionRules(
        state=(('.*', P()),),
        batch=None if batch_axis is None else P(batch_axis), **kw)


def corr_row_rules(batch_axis: Optional[str] = DATA_AXIS,
                   row_axis: str = MODEL_AXIS, **kw) -> PartitionRules:
    """The ``--model_shards`` layout: batch over ``data``,
    correspondence rows over ``model`` (``parallel/mesh.corr_spec``)."""
    corr = P(batch_axis, row_axis)
    return PartitionRules(
        state=(('.*', P()),),
        batch=None if batch_axis is None else P(batch_axis),
        activations={'corr': corr, 'psi2': corr}, **kw)


def streamed_rules(row_axis: str = DATA_AXIS,
                   stream_chunk: Optional[int] = DEFAULT_STREAM_CHUNK,
                   **kw) -> PartitionRules:
    """Million-entity single-pair config (ROADMAP item 3): one giant
    ``B=1`` pair replicated, the correspondence matrix row-sharded over
    ``row_axis`` (the ``data`` axis — for this workload the source rows
    ARE the data), the shortlist and ψ₂ source-row intermediates
    following it, and the candidate search streaming ``stream_chunk``
    source rows at a time so peak memory is
    ``O(chunk × block)`` + ``O(N_s/devices × K)`` per device — never
    ``O(N_s × N_t)`` anywhere. Targets RING over the same axis by
    default (``ring_targets=True``): per-device ``h_t`` drops to one
    shard and the boundary permutes pipeline against the per-tile
    top-k (pass ``ring_targets=False`` for the replicated-target
    layout)."""
    row = P(None, row_axis)
    kw.setdefault('ring_targets', True)
    # The 'psi1'/'corpus' embedding-table rules (shard ψ₁'s own compute
    # with the rows/ring) exist but are deliberately NOT defaults: on
    # this container's CPU GSPMD the constrained step measured 8.36 s
    # vs 7.37 s replicated at 2^17 (the edge scatters force comm
    # without dropping the replicated compute) — the on-silicon
    # re-measure is recorded in benchmarks/DISPATCH_DEFAULTS.md. Pass
    # activations={'psi1': ..., 'corpus': ...} explicitly to opt in.
    return PartitionRules(
        state=(('.*', P()),),
        batch=None,
        activations={'corr': row, 'topk': row, 'psi2': row},
        stream_chunk=stream_chunk, **kw)
