"""JAX version-compat shims for the sharding API surface.

The repo targets the current ``jax.shard_map`` API (top-level export,
``check_vma=`` keyword, vma-typed ``ShapeDtypeStruct``), but deployment
containers routinely pin older releases — this container ships
jax 0.4.37, where ``shard_map`` still lives in ``jax.experimental``,
the replication check is spelled ``check_rep``, and the vma type system
does not exist. Every call site routes through this module so the
version split is resolved in exactly one place:

- :func:`shard_map` — top-level ``jax.shard_map`` when present, else
  ``jax.experimental.shard_map.shard_map`` with ``check_vma`` translated
  to ``check_rep`` (same semantics: both gate the out-spec replication /
  varying-axes check).
- :func:`shape_dtype_struct` — ``jax.ShapeDtypeStruct`` that only
  forwards ``vma=`` where the constructor accepts it (pre-vma JAX has no
  manual-axes type to declare; dropping it is exact there).

The Pallas-side vma helpers (``vma_of`` / ``promote_vma``) live in
:mod:`dgmc_tpu.ops.pallas.dispatch`; they degrade to no-ops through the
same feature probes.
"""

import jax

__all__ = ['HAS_NATIVE_SHARD_MAP', 'shard_map', 'shape_dtype_struct']

#: True when this JAX exports top-level ``jax.shard_map`` (>= 0.6 API).
HAS_NATIVE_SHARD_MAP = hasattr(jax, 'shard_map')


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kwargs):
    """Version-portable ``shard_map`` (keyword-only, partial-friendly).

    Accepts the modern keyword surface; on pre-export JAX the call is
    forwarded to ``jax.experimental.shard_map.shard_map`` with
    ``check_vma`` renamed to its predecessor ``check_rep``.
    """
    if HAS_NATIVE_SHARD_MAP:
        if check_vma is not None:
            kwargs['check_vma'] = check_vma
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kwargs)
    from jax.experimental.shard_map import shard_map as _shard_map
    if check_vma is not None:
        kwargs.setdefault('check_rep', check_vma)
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)


def shape_dtype_struct(shape, dtype, *, vma=None, **kwargs):
    """``jax.ShapeDtypeStruct`` forwarding ``vma`` only where supported.

    Pallas ``out_shape`` declarations stamp the varying-manual-axes set on
    their outputs under the vma type system; earlier JAX has no such type,
    so the annotation is meaningless there and is dropped.
    """
    if vma:
        try:
            return jax.ShapeDtypeStruct(shape, dtype, vma=vma, **kwargs)
        except TypeError:
            pass
    return jax.ShapeDtypeStruct(shape, dtype, **kwargs)
