"""Device-mesh construction helpers.

The reference is strictly single-process / single-device (SURVEY.md §2.5),
so every parallelism feature here is net-new design: a
``jax.sharding.Mesh`` with a ``data`` axis (the pair-batch dimension ``B``
— the workload's natural data-parallel axis) and a ``model`` axis over which
the correspondence matrix rows (``N_s``) are sharded for DBP15K-scale
graphs. Collectives are XLA's (``psum``/``all_gather`` over ICI/DCN),
inserted by GSPMD from sharding annotations — the TPU-native replacement
for a NCCL/MPI backend.
"""

from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = 'data'
MODEL_AXIS = 'model'


def make_mesh(data: Optional[int] = None, model: int = 1,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a ``(data, model)`` mesh over the available devices.

    ``data=None`` takes every device not claimed by ``model``. On real TPU
    slices ``mesh_utils`` lays the axes out so the (inner) model axis rides
    the fastest ICI links.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if data is None:
        if n % model:
            raise ValueError(f'{n} devices not divisible by model={model}')
        data = n // model
    if data * model != n:
        raise ValueError(f'mesh {data}x{model} != {n} devices')
    mesh_devices = mesh_utils.create_device_mesh(
        (data, model), devices=np.asarray(devices))
    return Mesh(mesh_devices, (DATA_AXIS, MODEL_AXIS))


def batch_spec() -> P:
    """PartitionSpec sharding a leading pair-batch axis over ``data``."""
    return P(DATA_AXIS)


def corr_spec() -> P:
    """PartitionSpec for correspondence-shaped arrays ``[B, N_s, ...]``:
    batch over ``data``, source-node rows over ``model``."""
    return P(DATA_AXIS, MODEL_AXIS)


def corr_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, corr_spec())
