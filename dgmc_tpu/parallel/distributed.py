"""Multi-host (multi-process) initialization.

The reference has no distributed backend at all (SURVEY.md §2.5); here the
communication layer is XLA collectives over ICI/DCN, so scaling beyond one
host only needs the JAX distributed runtime brought up before any backend
touch — after that, ``jax.devices()`` spans the slice/pod and the same
``make_mesh`` + sharding annotations drive cross-host collectives with no
NCCL/MPI analog to manage.

Typical usage (same script on every host)::

    from dgmc_tpu.parallel import initialize_distributed, make_mesh
    initialize_distributed()   # pods/SLURM/MPI auto-detected; no-op solo
    mesh = make_mesh(model=8)  # now spans all hosts' devices

On clusters JAX cannot auto-detect, pass ``coordinator_address``,
``num_processes`` and ``process_id`` explicitly.
"""

from typing import Optional

import jax

_initialized = False


def _already_initialized() -> bool:
    """True when some other component already brought the runtime up.

    ``jax.distributed.is_initialized`` is the public query (jax >= 0.4.34);
    fall back to the private state probe only on older versions, and treat
    a failed probe as "not initialized" — the RuntimeError fallback in
    :func:`initialize_distributed` then handles the race.
    """
    probe = getattr(jax.distributed, 'is_initialized', None)
    if probe is not None:
        try:
            return bool(probe())
        except Exception:
            pass
    try:
        from jax._src import distributed as _dist
        return _dist.global_state.client is not None
    except Exception:
        return False


# RuntimeError message meaning the runtime is already up — benign on any
# path (a launcher beat us to it). The "called after backend init" error
# is benign ONLY for the auto-detect path (a single-process script calling
# late); an explicit multi-process request that cannot be honored must
# fail loudly, not degrade into isolated single-process jobs.
_BENIGN_ALWAYS = ('only be called once', 'called more than once')
_BENIGN_AUTO = ('only be called once', 'called more than once',
                'before any JAX calls', 'before any JAX computations')


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None,
                           deadline_s: Optional[float] = None,
                           hang_report_path: Optional[str] = None) -> int:
    """Bring up the JAX distributed runtime (idempotent).

    Best called before any JAX backend initialization. With no arguments,
    cluster detection is delegated to ``jax.distributed.initialize`` (TPU
    pods, SLURM, Open MPI, ...); in a plain single-process launch that
    detection fails and this becomes a no-op returning 1, so scripts can
    call it unconditionally. Safe to call when a launcher already
    initialized the runtime. Returns the process count.

    ``deadline_s`` + ``hang_report_path`` put the bring-up under a
    :class:`~dgmc_tpu.resilience.distributed_guard.FenceGuard`:
    ``jax.distributed.initialize`` blocks in C until every process of
    the declared mesh joins, so one absent host hangs ALL hosts with no
    Python-level recourse — the guard converts that into a
    ``hang_report.json`` (phase ``distributed-init``) and a
    ``FENCE_TIMEOUT_RC`` exit the supervisor can classify and restart
    elastically on a smaller mesh.
    """
    global _initialized
    if _initialized or _already_initialized():
        _initialized = True
        return jax.process_count()
    guard = None
    if deadline_s and hang_report_path:
        from dgmc_tpu.resilience.distributed_guard import FenceGuard
        guard = FenceGuard(hang_report_path, deadline_s,
                           phase='distributed-init')
    import contextlib
    explicit = (coordinator_address is not None
                or num_processes not in (None, 1)
                or process_id is not None)
    with guard or contextlib.nullcontext():
        if explicit:
            try:
                jax.distributed.initialize(
                    coordinator_address=coordinator_address,
                    num_processes=num_processes,
                    process_id=process_id)
            except RuntimeError as e:
                if not any(m in str(e) for m in _BENIGN_ALWAYS):
                    raise
        else:
            try:
                jax.distributed.initialize()
            except ValueError:
                # No cluster environment detected: single-process launch.
                pass
            except RuntimeError as e:
                if not any(m in str(e) for m in _BENIGN_AUTO):
                    raise
    _initialized = True
    return jax.process_count()


def is_coordinator() -> bool:
    """True on the process that should write checkpoints / logs."""
    return jax.process_index() == 0


def host_obs_dir(obs_dir):
    """Per-host obs directory for this process.

    Single-process runs keep ``obs_dir`` unchanged (artifacts land at
    the root, as ever). Multi-process runs get
    ``obs_dir/host_<process_index>/`` so EVERY host records telemetry —
    a straggling or hanging non-coordinator host is precisely the one
    whose evidence matters — and
    ``python -m dgmc_tpu.obs.aggregate <obs_dir>`` merges the
    subdirectories into the straggler/skew summary. Falsy ``obs_dir``
    passes through (the observer stays disabled).
    """
    if not obs_dir or jax.process_count() == 1:
        return obs_dir
    import os
    return os.path.join(obs_dir, f'host_{jax.process_index()}')


def local_batch_slice(batch):
    """This process's rows of a host-side batch whose leading axis will be
    sharded over the data axis: with a contiguous ``P('data')`` layout,
    process ``p`` owns rows ``[p*B/nproc, (p+1)*B/nproc)``. The batch's
    leading dimension must divide evenly across processes."""
    import numpy as np
    nproc, pid = jax.process_count(), jax.process_index()

    def cut(x):
        x = np.asarray(x)
        assert x.shape[0] % nproc == 0, (
            f'batch axis {x.shape[0]} not divisible by {nproc} processes')
        per = x.shape[0] // nproc
        return x[pid * per:(pid + 1) * per]

    return jax.tree.map(cut, batch)


def global_batch(batch, mesh, axis=None, replicate=False):
    """Assemble a globally-addressable array pytree from per-process data.

    ``replicate=True``: every process passes identical full arrays (e.g.
    the DBP15K whole-graph pair) and gets a mesh-replicated global array.
    Otherwise each process passes ITS slice (see :func:`local_batch_slice`)
    and the leading axis is sharded over ``axis``. This is the
    multi-process feeding path ``jax.jit`` needs: plain ``device_put``
    cannot build arrays spanning non-addressable devices.
    """
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    if axis is None:
        from dgmc_tpu.parallel.mesh import DATA_AXIS
        axis = DATA_AXIS
    sharding = NamedSharding(mesh, P() if replicate else P(axis))

    def put(x):
        return jax.make_array_from_process_local_data(sharding,
                                                      np.asarray(x))

    return jax.tree.map(put, batch)
