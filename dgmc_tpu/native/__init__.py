"""Native (C++) host runtime: batch collation via ctypes.

The compute path is JAX/XLA/Pallas; the host runtime around it — here, the
padded-batch collation that feeds the device — is native C++, mirroring the
reference's reliance on native collation inside its data loader (SURVEY.md
§2.3/§2.4). The shared library is compiled on first use with the system
``g++`` (no pip installs) and cached next to this package; everything
degrades to the pure-NumPy implementation when no compiler is available.
"""

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, 'collate.cpp')
_LIB_PATH = os.path.join(_HERE, 'libdgmc_collate.so')

_lock = threading.Lock()
_lib = None
_tried = False


def _build():
    cmd = ['g++', '-O3', '-shared', '-fPIC', '-o', _LIB_PATH, _SRC]
    subprocess.run(cmd, check=True, capture_output=True)


def load_library():
    """The collation library, building it on first use; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if not os.path.exists(_LIB_PATH) or (
                    os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)):
                _build()
            lib = ctypes.CDLL(_LIB_PATH)
        except (OSError, subprocess.CalledProcessError):
            return None

        lib.pad_graph_batch.restype = ctypes.c_int
        lib.pad_graph_batch.argtypes = [
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_void_p),                 # xs
            ctypes.POINTER(ctypes.c_int64),                  # ns
            ctypes.POINTER(ctypes.c_void_p),                 # senders
            ctypes.POINTER(ctypes.c_void_p),                 # receivers
            ctypes.POINTER(ctypes.c_int64),                  # es
            ctypes.POINTER(ctypes.c_void_p),                 # eattrs
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ]
        lib.pad_ground_truth.restype = None
        lib.pad_ground_truth.argtypes = [
            ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_void_p, ctypes.c_void_p,
        ]
        _lib = lib
        return _lib


def available():
    return load_library() is not None


def _ptr_array(arrays):
    """A C array of pointers into the given NumPy arrays (or None)."""
    ptrs = (ctypes.c_void_p * len(arrays))()
    for i, a in enumerate(arrays):
        ptrs[i] = None if a is None else a.ctypes.data_as(ctypes.c_void_p)
    return ptrs


def pad_graphs_native(graphs, num_nodes, num_edges, feat_dim, edge_dim):
    """C++-backed equivalent of the NumPy loop in
    :func:`dgmc_tpu.utils.data.pad_graphs`. Returns the padded arrays dict
    or None when the native library is unavailable."""
    lib = load_library()
    if lib is None:
        return None

    B = len(graphs)
    xs, ns, senders, receivers, es, eattrs = [], [], [], [], [], []
    for i, g in enumerate(graphs):
        # The C++ path memcpys feat_dim/edge_dim-wide rows straight from
        # these buffers, so a width mismatch that the NumPy path would catch
        # as a broadcast error must be rejected here, not read out of bounds.
        if g.x is not None and (g.x.ndim != 2 or g.x.shape[1] != feat_dim):
            raise ValueError(
                f'graph {i}: x has shape {g.x.shape}, expected '
                f'[*, {feat_dim}]')
        if g.edge_attr is not None and (
                g.edge_attr.ndim != 2 or edge_dim is None or
                g.edge_attr.shape[1] != edge_dim):
            raise ValueError(
                f'graph {i}: edge_attr has shape {g.edge_attr.shape}, '
                f'expected [*, {edge_dim}]')
        x = None if g.x is None else np.ascontiguousarray(g.x, np.float32)
        e = np.ascontiguousarray(g.edge_index, np.int64)
        xs.append(x)
        ns.append(g.num_nodes)
        senders.append(np.ascontiguousarray(e[0]))
        receivers.append(np.ascontiguousarray(e[1]))
        es.append(g.num_edges)
        eattrs.append(None if g.edge_attr is None else
                      np.ascontiguousarray(g.edge_attr, np.float32))

    x_out = np.zeros((B, num_nodes, feat_dim), np.float32)
    senders_out = np.zeros((B, num_edges), np.int32)
    receivers_out = np.zeros((B, num_edges), np.int32)
    node_mask = np.zeros((B, num_nodes), np.uint8)
    edge_mask = np.zeros((B, num_edges), np.uint8)
    eattr_out = (np.zeros((B, num_edges, edge_dim), np.float32)
                 if edge_dim else None)

    rc = lib.pad_graph_batch(
        B, num_nodes, num_edges, feat_dim, edge_dim or 0,
        _ptr_array(xs), (ctypes.c_int64 * B)(*ns),
        _ptr_array(senders), _ptr_array(receivers),
        (ctypes.c_int64 * B)(*es), _ptr_array(eattrs),
        x_out.ctypes.data_as(ctypes.c_void_p),
        senders_out.ctypes.data_as(ctypes.c_void_p),
        receivers_out.ctypes.data_as(ctypes.c_void_p),
        node_mask.ctypes.data_as(ctypes.c_void_p),
        edge_mask.ctypes.data_as(ctypes.c_void_p),
        None if eattr_out is None else
        eattr_out.ctypes.data_as(ctypes.c_void_p))
    if rc != 0:
        g = graphs[rc - 1]
        raise ValueError(f'graph {rc - 1} ({g.num_nodes} nodes / '
                         f'{g.num_edges} edges) exceeds padding '
                         f'({num_nodes} / {num_edges})')
    return dict(x=x_out, senders=senders_out, receivers=receivers_out,
                node_mask=node_mask.astype(bool),
                edge_mask=edge_mask.astype(bool),
                edge_attr=eattr_out)


def pad_ground_truth_native(y_cols, num_nodes):
    """C++-backed GT padding: list of per-pair y_col arrays (or None) ->
    (y[B, N] int32, y_mask[B, N] bool); None if unavailable."""
    lib = load_library()
    if lib is None:
        return None
    B = len(y_cols)
    cols = [None if y is None else np.ascontiguousarray(y, np.int64)
            for y in y_cols]
    lens = [0 if y is None else len(y) for y in cols]
    y_out = np.empty((B, num_nodes), np.int32)
    mask_out = np.empty((B, num_nodes), np.uint8)
    lib.pad_ground_truth(
        B, num_nodes, _ptr_array(cols), (ctypes.c_int64 * B)(*lens),
        y_out.ctypes.data_as(ctypes.c_void_p),
        mask_out.ctypes.data_as(ctypes.c_void_p))
    return y_out, mask_out.astype(bool)
