// Native host-side collation: ragged graphs -> padded GraphBatch arrays.
//
// The TPU-native counterpart of the reference's native data plumbing: the
// reference leans on PyG's C++ collation inside torch DataLoader workers
// (reference dgmc/utils/data.py:9-16 customizes `__inc__` for it); here the
// padded, fixed-shape batch IS the device format, and this translation unit
// fills a whole batch's arrays in one pass — one memcpy-bound sweep instead
// of a Python loop of NumPy slice assignments. Loaded via ctypes
// (dgmc_tpu/native/__init__.py), with a NumPy fallback when no compiler is
// available.
//
// Build: g++ -O3 -shared -fPIC -o libdgmc_collate.so collate.cpp

#include <cstdint>
#include <cstring>

extern "C" {

// All output buffers are caller-allocated and zero-initialised by the
// caller contract EXCEPT masks, which this function fully writes.
//   B: batch size; N/E: padded node/edge counts; C: feature dim;
//   D: edge-attr dim (0 = none).
//   xs[b]:     [ns[b], C] float32 node features (may be null -> zeros)
//   senders[b]/receivers[b]: [es[b]] int64 edge endpoints
//   eattrs[b]: [es[b], D] float32 edge attributes (may be null)
// Returns 0 on success, b+1 if graph b exceeds the padding.
int pad_graph_batch(
    int64_t B, int64_t N, int64_t E, int64_t C, int64_t D,
    const float** xs, const int64_t* ns,
    const int64_t** senders, const int64_t** receivers, const int64_t* es,
    const float** eattrs,
    float* x_out,            // [B, N, C]
    int32_t* senders_out,    // [B, E]
    int32_t* receivers_out,  // [B, E]
    uint8_t* node_mask_out,  // [B, N]
    uint8_t* edge_mask_out,  // [B, E]
    float* eattr_out) {      // [B, E, D] or null
  for (int64_t b = 0; b < B; ++b) {
    const int64_t n = ns[b];
    const int64_t e = es[b];
    if (n > N || e > E) return static_cast<int>(b + 1);

    if (xs[b] != nullptr) {
      std::memcpy(x_out + b * N * C, xs[b], sizeof(float) * n * C);
    }
    int32_t* s_row = senders_out + b * E;
    int32_t* r_row = receivers_out + b * E;
    for (int64_t i = 0; i < e; ++i) {
      s_row[i] = static_cast<int32_t>(senders[b][i]);
      r_row[i] = static_cast<int32_t>(receivers[b][i]);
    }
    uint8_t* nm = node_mask_out + b * N;
    std::memset(nm, 1, n);
    std::memset(nm + n, 0, N - n);
    uint8_t* em = edge_mask_out + b * E;
    std::memset(em, 1, e);
    std::memset(em + e, 0, E - e);
    if (eattr_out != nullptr && eattrs[b] != nullptr) {
      std::memcpy(eattr_out + b * E * D, eattrs[b], sizeof(float) * e * D);
    }
  }
  return 0;
}

// Dense ground-truth padding: y_cols[b] is [lens[b]] int64 (target column
// per source node, -1 invalid); writes y_out [B, N] int32 (-1 padded) and
// y_mask_out [B, N] uint8.
void pad_ground_truth(
    int64_t B, int64_t N,
    const int64_t** y_cols, const int64_t* lens,
    int32_t* y_out, uint8_t* y_mask_out) {
  for (int64_t b = 0; b < B; ++b) {
    int32_t* y_row = y_out + b * N;
    uint8_t* m_row = y_mask_out + b * N;
    const int64_t len = y_cols[b] == nullptr ? 0 : lens[b];
    for (int64_t i = 0; i < len; ++i) {
      const int64_t v = y_cols[b][i];
      y_row[i] = static_cast<int32_t>(v);
      m_row[i] = v >= 0 ? 1 : 0;
    }
    for (int64_t i = len; i < N; ++i) {
      y_row[i] = -1;
      m_row[i] = 0;
    }
  }
}

}  // extern "C"
