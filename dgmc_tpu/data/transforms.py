"""Host-side keypoint-graph transforms.

Capability parity with the PyG transforms the reference consumes
(``T.Delaunay``, ``T.FaceToEdge``, ``T.Cartesian``, ``T.Distance``,
``T.Constant``, ``T.KNNGraph`` at reference ``examples/pascal.py:25-29`` and
``examples/pascal_pf.py:68-72``). These are data-prep, not device compute —
they run once at dataset build time in NumPy/SciPy (the reference likewise
runs them on host inside its ``DataLoader`` workers), so the jit path only
ever sees padded arrays.
"""

import numpy as np

from dgmc_tpu.utils.data import Graph


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, g: Graph) -> Graph:
        # Shallow-copy so repeated application to a cached Graph can't
        # accumulate state (transforms rebind fields, never mutate arrays).
        import dataclasses
        g = dataclasses.replace(g)
        for t in self.transforms:
            g = t(g)
        return g


class Constant:
    """Set (or append to) node features a constant value column."""

    def __init__(self, value=1.0, cat=True):
        self.value = value
        self.cat = cat

    def __call__(self, g: Graph) -> Graph:
        n = g.num_nodes
        col = np.full((n, 1), self.value, np.float32)
        if g.x is not None and self.cat:
            g.x = np.concatenate([g.x, col], axis=1)
        else:
            g.x = col
        return g


class KNNGraph:
    """Connect every node to its k nearest neighbors (edges j -> i)."""

    def __init__(self, k=6, loop=False):
        self.k = k
        self.loop = loop

    def __call__(self, g: Graph) -> Graph:
        pos = g.pos
        n = pos.shape[0]
        d2 = ((pos[:, None, :] - pos[None, :, :]) ** 2).sum(-1)
        if not self.loop:
            np.fill_diagonal(d2, np.inf)
        k = min(self.k, n - (0 if self.loop else 1))
        if k <= 0:
            g.edge_index = np.zeros((2, 0), np.int64)
            return g
        nbrs = np.argpartition(d2, k - 1, axis=1)[:, :k]   # [n, k] sources
        targets = np.repeat(np.arange(n), k)
        sources = nbrs.reshape(-1)
        g.edge_index = np.stack([sources, targets]).astype(np.int64)
        return g


class Delaunay:
    """Delaunay triangulation of ``pos`` into faces (SciPy/Qhull on host).

    Degenerate sizes follow the reference's PyG behavior: <3 nodes becomes a
    complete graph's edges, exactly 3 nodes one triangle.
    """

    def __call__(self, g: Graph) -> Graph:
        n = g.pos.shape[0]
        if n < 2:
            g.face = np.zeros((3, 0), np.int64)
            g.edge_index = np.zeros((2, 0), np.int64)
            return g
        if n == 2:
            g.face = None
            g.edge_index = np.array([[0, 1], [1, 0]], np.int64)
            return g
        if n == 3:
            g.face = np.array([[0], [1], [2]], np.int64)
            return g
        from scipy.spatial import Delaunay as SciPyDelaunay
        from scipy.spatial import QhullError
        try:
            tri = SciPyDelaunay(g.pos, qhull_options='QJ')
            g.face = tri.simplices.T.astype(np.int64)
        except QhullError:
            # Collinear and other degenerate layouts: chain the points.
            order = np.argsort(g.pos[:, 0] + 1e-9 * g.pos[:, 1])
            src = order[:-1]
            dst = order[1:]
            g.edge_index = np.stack([
                np.concatenate([src, dst]),
                np.concatenate([dst, src])]).astype(np.int64)
            g.face = None
        return g


class FaceToEdge:
    """Triangle faces -> undirected (symmetric, deduplicated) edges."""

    def __init__(self, remove_faces=True):
        self.remove_faces = remove_faces

    def __call__(self, g: Graph) -> Graph:
        face = getattr(g, 'face', None)
        if face is not None and face.size:
            pairs = np.concatenate(
                [face[[0, 1]], face[[1, 2]], face[[2, 0]]], axis=1)
            und = np.concatenate([pairs, pairs[::-1]], axis=1)
            und = np.unique(und, axis=1)
            g.edge_index = und.astype(np.int64)
        if self.remove_faces and hasattr(g, 'face'):
            g.face = None
        return g


class Cartesian:
    """Edge pseudo-coordinates: relative node positions, normalized to
    ``[0, 1]`` (the anisotropic option of reference ``pascal.py:28``)."""

    def __init__(self, norm=True, max_value=None):
        self.norm = norm
        self.max_value = max_value

    def __call__(self, g: Graph) -> Graph:
        src, dst = g.edge_index
        cart = g.pos[src] - g.pos[dst]
        if self.norm and cart.size:
            scale = self.max_value or np.abs(cart).max()
            cart = cart / (2 * max(scale, 1e-12)) + 0.5
        attr = cart.astype(np.float32)
        if g.edge_attr is not None:
            g.edge_attr = np.concatenate([g.edge_attr, attr], axis=1)
        else:
            g.edge_attr = attr
        return g


class Distance:
    """Edge pseudo-coordinates: euclidean node distance, normalized (the
    isotropic option of reference ``pascal.py:28``)."""

    def __init__(self, norm=True, max_value=None):
        self.norm = norm
        self.max_value = max_value

    def __call__(self, g: Graph) -> Graph:
        src, dst = g.edge_index
        d = np.linalg.norm(g.pos[src] - g.pos[dst], axis=1, keepdims=True)
        if self.norm and d.size:
            scale = self.max_value or d.max()
            d = d / max(scale, 1e-12)
        attr = d.astype(np.float32)
        if g.edge_attr is not None:
            g.edge_attr = np.concatenate([g.edge_attr, attr], axis=1)
        else:
            g.edge_attr = attr
        return g
