from dgmc_tpu.data.transforms import (Compose, Constant, KNNGraph, Delaunay,
                                      FaceToEdge, Cartesian, Distance)
from dgmc_tpu.data.synthetic import RandomGraphPairs

__all__ = [
    'Compose',
    'Constant',
    'KNNGraph',
    'Delaunay',
    'FaceToEdge',
    'Cartesian',
    'Distance',
    'RandomGraphPairs',
]
