from dgmc_tpu.data.transforms import (Compose, Constant, KNNGraph, Delaunay,
                                      FaceToEdge, Cartesian, Distance)
from dgmc_tpu.data.synthetic import (RandomGraphPairs, SyntheticKG,
                                     synthetic_kg_alignment)

__all__ = [
    'Compose',
    'Constant',
    'KNNGraph',
    'Delaunay',
    'FaceToEdge',
    'Cartesian',
    'Distance',
    'RandomGraphPairs',
    'SyntheticKG',
    'synthetic_kg_alignment',
]
