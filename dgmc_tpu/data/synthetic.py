"""Synthetic geometric-matching pairs — the no-download training workload.

Capability parity with the reference's ``RandomGraphDataset`` (reference
``examples/pascal_pf.py:23-65``): each item is a source point cloud of
30-60 inliers uniform in ``[-1, 1]^2``, a target copy jittered with Gaussian
noise (sigma 0.05), and 0-20 per-side outliers placed in ``[2, 3]^2``;
ground truth matches inlier i to inlier i. Pairs are built fresh per access
from a per-index PRNG seed, so the dataset is deterministic given its seed
while still giving a different draw per epoch when ``reseed`` is used.
"""

import numpy as np

from dgmc_tpu.utils.data import Graph, GraphPair


class RandomGraphPairs:
    """Virtual dataset of random matchable point-cloud pairs."""

    def __init__(self, min_inliers=30, max_inliers=60, min_outliers=0,
                 max_outliers=20, noise=0.05, transform=None, length=1024,
                 seed=0):
        self.min_inliers = min_inliers
        self.max_inliers = max_inliers
        self.min_outliers = min_outliers
        self.max_outliers = max_outliers
        self.noise = noise
        self.transform = transform
        self.length = length
        self.seed = seed
        self.epoch = 0

    def set_epoch(self, epoch):
        """Advance the virtual dataset so each epoch draws fresh pairs."""
        self.epoch = epoch

    def __len__(self):
        return self.length

    def __getitem__(self, idx):
        rng = np.random.RandomState(
            (self.seed * 1_000_003 + self.epoch * 7919 + idx) % (2 ** 31))
        n_in = rng.randint(self.min_inliers, self.max_inliers + 1)
        n_out_s = rng.randint(self.min_outliers, self.max_outliers + 1)
        n_out_t = rng.randint(self.min_outliers, self.max_outliers + 1)

        pos_in = rng.uniform(-1.0, 1.0, (n_in, 2))
        pos_s = np.concatenate(
            [pos_in, rng.uniform(2.0, 3.0, (n_out_s, 2))]).astype(np.float32)
        pos_t_in = pos_in + self.noise * rng.randn(n_in, 2)
        pos_t = np.concatenate(
            [pos_t_in, rng.uniform(2.0, 3.0, (n_out_t, 2))]).astype(
                np.float32)

        g_s = Graph(edge_index=np.zeros((2, 0), np.int64), pos=pos_s)
        g_t = Graph(edge_index=np.zeros((2, 0), np.int64), pos=pos_t)
        if self.transform is not None:
            g_s = self.transform(g_s)
            g_t = self.transform(g_t)

        # Inlier i in the source matches inlier i in the target; source
        # outliers have no ground truth.
        y_col = np.concatenate([np.arange(n_in),
                                np.full(n_out_s, -1)]).astype(np.int64)
        return GraphPair(s=g_s, t=g_t, y_col=y_col)
