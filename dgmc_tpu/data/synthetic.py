"""Synthetic matching workloads — the no-download training data.

Two generators:

- :class:`RandomGraphPairs` — capability parity with the reference's
  ``RandomGraphDataset`` (reference ``examples/pascal_pf.py:23-65``):
  each item is a source point cloud of 30-60 inliers uniform in
  ``[-1, 1]^2``, a target copy jittered with Gaussian noise (sigma 0.05),
  and 0-20 per-side outliers placed in ``[2, 3]^2``; ground truth matches
  inlier i to inlier i. Pairs are built fresh per access from a per-index
  PRNG seed, so the dataset is deterministic given its seed while still
  giving a different draw per epoch when ``reseed`` is used.
- :func:`synthetic_kg_alignment` — protocol-faithful synthetic
  knowledge-graph alignment at ARBITRARY scale (the DBP15K stand-in the
  ``--synthetic`` CLI path and the streamed-S million-entity benchmark
  both build on): a random source KG whose entities are injectively
  mapped into a larger target KG as variance-preserving noisy copies,
  with a fraction of the mapped edges rewired and distractor
  entities/edges added. Construction is O(nodes + edges) host work —
  nothing quadratic — so 10⁶×10⁶ pairs build in seconds.
"""

from typing import NamedTuple

import numpy as np

from dgmc_tpu.utils.data import Graph, GraphPair


class RandomGraphPairs:
    """Virtual dataset of random matchable point-cloud pairs."""

    def __init__(self, min_inliers=30, max_inliers=60, min_outliers=0,
                 max_outliers=20, noise=0.05, transform=None, length=1024,
                 seed=0):
        self.min_inliers = min_inliers
        self.max_inliers = max_inliers
        self.min_outliers = min_outliers
        self.max_outliers = max_outliers
        self.noise = noise
        self.transform = transform
        self.length = length
        self.seed = seed
        self.epoch = 0

    def set_epoch(self, epoch):
        """Advance the virtual dataset so each epoch draws fresh pairs."""
        self.epoch = epoch

    def __len__(self):
        return self.length

    def __getitem__(self, idx):
        rng = np.random.RandomState(
            (self.seed * 1_000_003 + self.epoch * 7919 + idx) % (2 ** 31))
        n_in = rng.randint(self.min_inliers, self.max_inliers + 1)
        n_out_s = rng.randint(self.min_outliers, self.max_outliers + 1)
        n_out_t = rng.randint(self.min_outliers, self.max_outliers + 1)

        pos_in = rng.uniform(-1.0, 1.0, (n_in, 2))
        pos_s = np.concatenate(
            [pos_in, rng.uniform(2.0, 3.0, (n_out_s, 2))]).astype(np.float32)
        pos_t_in = pos_in + self.noise * rng.randn(n_in, 2)
        pos_t = np.concatenate(
            [pos_t_in, rng.uniform(2.0, 3.0, (n_out_t, 2))]).astype(
                np.float32)

        g_s = Graph(edge_index=np.zeros((2, 0), np.int64), pos=pos_s)
        g_t = Graph(edge_index=np.zeros((2, 0), np.int64), pos=pos_t)
        if self.transform is not None:
            g_s = self.transform(g_s)
            g_t = self.transform(g_t)

        # Inlier i in the source matches inlier i in the target; source
        # outliers have no ground truth.
        y_col = np.concatenate([np.arange(n_in),
                                np.full(n_out_s, -1)]).astype(np.int64)
        return GraphPair(s=g_s, t=g_t, y_col=y_col)


class SyntheticKG(NamedTuple):
    """Raw arrays of one synthetic KG-alignment pair (host numpy; the
    caller owns batching/blocking/precision policy)."""
    x_s: np.ndarray          # [n_s, dim] source entity features
    senders_s: np.ndarray    # [e_s] int32
    receivers_s: np.ndarray  # [e_s] int32
    x_t: np.ndarray          # [n_t, dim] target entity features
    senders_t: np.ndarray    # [e_t] int32
    receivers_t: np.ndarray  # [e_t] int32
    perm: np.ndarray         # [n_s] int32: source i aligns to target perm[i]
    train_mask: np.ndarray   # [n_s] bool: the seed-alignment split


def synthetic_kg_alignment(n_s, n_t, e_s, e_t, dim, noise_min=0.5,
                           noise_max=2.5, rewire=0.15, seed_frac=0.3,
                           rng=None):
    """DBP15K-protocol synthetic KG alignment at arbitrary scale.

    A random source KG; the target KG holds an injectively mapped noisy
    copy of every source entity plus unaligned distractor entities, with
    ``rewire`` of the mapped edges rewired and extra distractor edges —
    the miniature quality gate's construction
    (tests/models/test_two_phase_quality.py) parameterized to any shape.
    Seeds follow the reference's 30% split (``seed_frac``).

    Design notes carried over from the full-scale tuning runs:

    - Unit-NORM feature scale (``1/sqrt(dim)`` per component), like the
      real pipeline's summed word vectors (O(1) norms): N(0,1)^dim
      features would give the initial similarity logits a std of
      ~sqrt(dim), a saturated softmax whose escape is seed luck
      (measured: seed 0 trains, seed 1 flatlines).
    - Per-entity noise sigma drawn uniformly in ``[noise_min,
      noise_max]``: homogeneous noise has a sharp all-or-nothing
      learnability transition (measured at dim=300: sigma 1.5
      saturates, 1.8 never lifts off), while heterogeneity yields the
      mid-range phase-1 accuracy of the real embeddings.
    - Variance-preserving blend ``(x + sigma*noise)/sqrt(1+sigma^2)``:
      corr(x_s, x_t[perm]) = 1/sqrt(1+sigma²) per entity while every
      target row keeps unit feature variance — un-normalized additive
      noise gives aligned entities systematically larger norms, and
      those rows then dominate every similarity row's softmax
      (measured: training never lifts off at full scale).
    """
    if rng is None:
        rng = np.random.RandomState(0)
    assert n_t >= n_s and e_t >= e_s

    x_s = (rng.randn(n_s, dim) / np.sqrt(dim)).astype(np.float32)
    snd = rng.randint(0, n_s, e_s).astype(np.int32)
    rcv = rng.randint(0, n_s, e_s).astype(np.int32)

    perm = rng.permutation(n_t)[:n_s].astype(np.int32)
    x_t = (rng.randn(n_t, dim) / np.sqrt(dim)).astype(np.float32)
    sigma = rng.uniform(noise_min, noise_max, (n_s, 1)).astype(np.float32)
    noise = (rng.randn(n_s, dim) / np.sqrt(dim)).astype(np.float32)
    x_t[perm] = (x_s + sigma * noise) / np.sqrt(1.0 + sigma ** 2)
    keep = rng.rand(e_s) >= rewire
    snd_t = np.where(keep, perm[snd], rng.randint(0, n_t, e_s))
    rcv_t = np.where(keep, perm[rcv], rng.randint(0, n_t, e_s))
    extra = e_t - e_s
    snd_t = np.concatenate([snd_t, rng.randint(0, n_t, extra)])
    rcv_t = np.concatenate([rcv_t, rng.randint(0, n_t, extra)])

    train_mask = np.zeros(n_s, bool)
    train_mask[:int(seed_frac * n_s)] = True
    return SyntheticKG(x_s=x_s, senders_s=snd, receivers_s=rcv, x_t=x_t,
                       senders_t=snd_t.astype(np.int32),
                       receivers_t=rcv_t.astype(np.int32),
                       perm=perm, train_mask=train_mask)
