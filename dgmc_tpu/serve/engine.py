"""Per-bucket AOT match executables: shortlist → consensus rerank.

One executable per declared padding bucket, compiled at startup
(``warm()``) and only ever *executed* on the query path — the zero-
per-query-compile contract the bench cross-checks against the obs
compile counter. Each executable is the model's own forward
(:meth:`dgmc_tpu.models.DGMC.__call__`) with the corpus ψ₁ table passed
in precomputed (``h_t=...``), so serving answers are bit-identical to a
full in-graph forward under the same checkpoint — pinned by
``tests/serve/test_engine.py``.

Corpus placement tiers:

- **device** (default): ``h_t`` device-resident; the in-graph blockwise
  scan (``ops/topk.chunked_topk``) shortlists per query.
- **streamed**: same, with the model's ``stream_chunk`` bounding the
  score tile (configure on the model; the executable shape is the
  same).
- **offload**: ``h_t`` stays in HOST RAM; the shortlist runs host-driven
  through :func:`~dgmc_tpu.ops.offload.offloaded_corpus_topk`
  (PrefetchRing-fed target chunks, bit-identical to the device scan),
  and the rerank executable receives the shortlist + host-gathered
  candidate rows (``S_idx`` / ``h_t_cand``) — the corpus-bigger-than-a-
  chip tier: device residents are O(E_t + query), never O(N_t · C).

Execution is serialized under one lock: answers must be bit-identical
whether N clients arrive concurrently or sequentially (ties included),
and the per-query latency histogram must measure execution, not lock
convoys racing the accelerator.
"""

import contextlib
import threading
import time

import numpy as np

from dgmc_tpu.obs import goodput as goodput_mod
from dgmc_tpu.obs.live import StreamingHistogram
from dgmc_tpu.obs.qtrace import QTRACE_LATENCY_BOUNDS

__all__ = ['MatchEngine']


@contextlib.contextmanager
def _null_span(name):
    """Span sink for untraced calls (warmup, tests, tracing opt-out):
    the query path reads identically with tracing on or off."""
    yield


class MatchEngine:
    """Warm per-bucket executables over one checkpoint + corpus index.

    Args:
        model: the configured :class:`~dgmc_tpu.models.DGMC` (sparse:
            ``k >= 1``).
        variables: restored checkpoint variables
            (``{'params': ..., ['batch_stats': ...]}``).
        index: the :class:`~dgmc_tpu.serve.corpus.CorpusIndex`.
        router: a :class:`~dgmc_tpu.serve.router.QueryRouter` whose
            corpus shape matches ``index``.
        max_results: ranked candidates returned per query node
            (clamped to the model's ``k``).
        noise_seed: the consensus indicator-noise stream is drawn from
            this FIXED key on every query — serving is deterministic by
            construction; two identical queries get identical answers.
        offload: host-RAM corpus tier (see module docstring).
        offload_chunk / prefetch_depth: target-chunk size and ring
            depth for the offloaded shortlist.
        obs: optional :class:`~dgmc_tpu.obs.run.RunObserver` — warmup
            compiles are labelled per bucket and each executable's
            static ``memory_analysis`` is logged.
    """

    def __init__(self, model, variables, index, router, max_results=5,
                 noise_seed=0, offload=False, offload_chunk=4096,
                 prefetch_depth=None, obs=None, audit=False):
        import jax

        if model.k < 1:
            raise ValueError('the serving engine requires the sparse '
                             'variant (k >= 1): the dense correspondence '
                             'matrix is O(N_s x N_t) per query')
        self.model = model
        self.index = index
        self.router = router
        self.max_results = int(min(max_results, model.k))
        self.offload = bool(offload)
        self.audit = bool(audit)
        self.offload_chunk = int(offload_chunk)
        if prefetch_depth is None:
            from dgmc_tpu.ops.offload import DEFAULT_PREFETCH_DEPTH
            prefetch_depth = DEFAULT_PREFETCH_DEPTH
        self.prefetch_depth = int(prefetch_depth)
        self._obs = obs
        self._lock = threading.Lock()
        self._device = jax.local_devices()[0]
        self._variables = jax.device_put(variables, self._device)
        self._t_graph = jax.device_put(index.corpus.graph_batch(),
                                       self._device)
        # Device tier keeps the table resident; offload keeps it host-
        # side (the whole point) and ships only candidate rows.
        self._h_t_dev = (None if self.offload
                         else jax.device_put(index.h_t, self._device))
        self._h_t_host = index.h_t
        self._noise_key = jax.device_put(jax.random.key(int(noise_seed)),
                                         self._device)
        self._exec = {}          # signature -> per-bucket record
        self.query_count = 0
        self.last_latency_s = None
        # -- saturation telemetry (obs.capacity's inputs) ------------------
        # In-flight gauge + the engine lock split into measured wait vs
        # hold. The wait histogram measures the SAME region qtrace's
        # `admission_queue_wait` span wraps (the lock acquire below) —
        # one vocabulary, reconcilable distributions, no third dialect;
        # unlike the span it covers EVERY query, traced or not. Bounds
        # are qtrace's ×1.25 rungs so the two accounts quantize alike.
        self._stats_lock = threading.Lock()
        self.inflight = 0
        self.lock_wait_hist = StreamingHistogram(QTRACE_LATENCY_BOUNDS)
        self.lock_hold_hist = StreamingHistogram(QTRACE_LATENCY_BOUNDS)
        self._t_first_query = None
        self._t_last_query = None

    # -- executables -------------------------------------------------------

    def _match_fn(self):
        import jax
        import jax.numpy as jnp

        from dgmc_tpu.obs import probes as _probes
        model, r = self.model, self.max_results

        def ranked(S_0, S_L, node_mask):
            top_v, pos = jax.lax.top_k(S_L.val, r)
            top_i = jnp.take_along_axis(S_L.idx, pos, axis=-1)
            v0, p0 = jax.lax.top_k(S_0.val, 1)
            i0 = jnp.take_along_axis(S_0.idx, p0, axis=-1)
            # -- per-query confidence proxies, computed in-graph on the
            # already-resident correspondence (cost: O(N·k) elementwise,
            # invisible next to the consensus rerank). Masked means over
            # the REAL query nodes only; padded rows contribute zero.
            mask = node_mask.astype(jnp.float32)
            denom = jnp.maximum(jnp.sum(mask), 1.0)

            def row_mean(x):
                return jnp.sum(x.astype(jnp.float32) * mask) / denom

            k = S_L.val.shape[-1]
            if k >= 2:
                top2, _ = jax.lax.top_k(S_L.val, 2)
                margin = row_mean(top2[..., 0] - top2[..., 1])
            else:
                # Degenerate shortlist of one: the margin is the full
                # top-1 mass (no runner-up to subtract).
                margin = row_mean(top_v[..., 0])
            # Shortlist slots are ordered by the initial score (top_k is
            # sorted), so the winning slot's index IS the selected
            # match's rank inside the shortlist; rank k-1 means the
            # answer sat on the shortlist boundary and a wider search
            # could have changed it.
            sel_rank = pos[..., 0].astype(jnp.float32)
            saturated = ((pos[..., 0] == k - 1).astype(jnp.float32)
                         if k > 1 else jnp.zeros_like(sel_rank))
            return {'cand_idx': top_i, 'cand_prob': top_v,
                    'initial_idx': i0[..., 0], 'initial_prob': v0[..., 0],
                    'shortlist_idx': S_L.idx,
                    'q_entropy': _probes.entropy(S_L.val, node_mask),
                    'q_margin': margin,
                    'q_correction': _probes.delta_norm(
                        S_L.val, S_0.val, node_mask),
                    'q_saturation': row_mean(sel_rank / max(k - 1, 1)),
                    'q_saturated_frac': row_mean(saturated)}

        if self.offload:
            def match(variables, q_graph, t_graph, S_idx, h_t_cand, key):
                S_0, S_L = model.apply(
                    variables, q_graph, t_graph, train=False,
                    rngs={'noise': key}, S_idx=S_idx, h_t_cand=h_t_cand)
                return ranked(S_0, S_L, q_graph.node_mask)
        else:
            def match(variables, q_graph, t_graph, h_t, key):
                S_0, S_L = model.apply(
                    variables, q_graph, t_graph, train=False,
                    rngs={'noise': key}, h_t=h_t)
                return ranked(S_0, S_L, q_graph.node_mask)
        return match

    def _embed_fn(self):
        """Query-side ψ₁ for the host-driven offloaded shortlist."""
        model = self.model

        def embed(psi1_vars, q_graph):
            return model.psi_1.apply(psi1_vars, q_graph.x, q_graph,
                                     train=False)
        return embed

    def _psi1_vars(self):
        out = {'params': self._variables['params']['psi_1']}
        bs = self._variables.get('batch_stats') or {}
        if bs and bs.get('psi_1'):
            out['batch_stats'] = bs['psi_1']
        return out

    def _template(self, bucket):
        """Zero-filled query batch of the bucket's padded shape — the
        abstract signature every AOT lowering compiles against."""
        from dgmc_tpu.ops.graph import GraphBatch
        n, e = bucket.nodes, bucket.edges
        c = self.index.corpus.feat_dim
        return GraphBatch(
            x=np.zeros((1, n, c), np.float32),
            senders=np.zeros((1, e), np.int32),
            receivers=np.zeros((1, e), np.int32),
            node_mask=np.zeros((1, n), bool),
            edge_mask=np.zeros((1, e), bool))

    def warm(self):
        """AOT-compile every declared bucket's executable(s) now.

        Returns ``{signature: info}`` with per-bucket compile seconds
        and the executable's static per-device memory bound — the
        warmup account the service logs and the bench diffs restart
        runs against. After this returns, the query path executes only.
        """
        import jax

        from dgmc_tpu.obs.memory import compiled_memory
        # One jitted wrapper each, hoisted out of the bucket loop (the
        # repo's own SRC103 lint); each bucket still gets its own
        # .lower().compile() — the per-shape AOT executable.
        jit_match = jax.jit(self._match_fn())
        jit_embed = jax.jit(self._embed_fn())
        report = {}
        for bucket in self.router.buckets:
            sig = self.router.signature(bucket)
            label = f'serve_bucket_{bucket.nodes}x{bucket.edges}'
            t0 = time.perf_counter()
            tpl = self._template(bucket)
            ctx = (self._obs.compile_label(label) if self._obs
                   else _null())
            with ctx:
                if self.offload:
                    k = self.model.k
                    s_tpl = np.zeros((1, bucket.nodes, k), np.int32)
                    c_tpl = np.zeros(
                        (1, bucket.nodes, k, self.index.embed_dim),
                        np.float32)
                    lowered = jit_match.lower(
                        self._variables, tpl, self._t_graph, s_tpl,
                        c_tpl, self._noise_key)
                    compiled = lowered.compile()
                    embed_c = jit_embed.lower(
                        self._psi1_vars(), tpl).compile()
                else:
                    lowered = jit_match.lower(
                        self._variables, tpl, self._t_graph,
                        self._h_t_dev, self._noise_key)
                    compiled = lowered.compile()
                    # The query path does not need ψ₁ standalone on the
                    # device tier, but the shadow audit's exhaustive
                    # re-scan does — compile it here in BOTH tiers so
                    # the audit never compiles on a live process.
                    embed_c = jit_embed.lower(
                        self._psi1_vars(), tpl).compile()
            info = {'bucket': bucket,
                    'exec': compiled,
                    'embed': embed_c,
                    'compile_s': round(time.perf_counter() - t0, 3),
                    'queries': 0,
                    'pad_sum': 0.0,
                    'goodput_sum': 0.0,
                    'stages': self._stage_flops(lowered)}
            if self.offload:
                # Drive the full offloaded pipeline once at the padded
                # template shape: the host-driven merge step
                # (ops/offload._corpus_merge) is jitted per shape
                # config, and its compiles must land HERE, in the
                # warmup account — never on the first live query after
                # a (re)start. The template sweep walks the same chunk
                # sequence (ragged tail included) every real query
                # walks, so the query path stays execute-only.
                with (self._obs.compile_label(label) if self._obs
                      else _null()):
                    self._execute(info, tpl)
                info['compile_s'] = round(time.perf_counter() - t0, 3)
            if self.audit:
                # Same discipline for the shadow audit's exhaustive
                # scan: its host-driven merge steps are jitted per
                # shape config, and those compiles belong in the
                # warmup account — the audit thread must stay
                # execute-only on a live process. The sweep scans a
                # TRUNCATED table slice (one full chunk + the ragged
                # tail): jit shape configs depend on the chunk shapes,
                # not the chunk count, so this compiles everything the
                # full-corpus audit scan executes at a fraction of the
                # warm-window cost (warm-beats-cold margins are thin).
                with (self._obs.compile_label(label) if self._obs
                      else _null()):
                    self.exhaustive_topk(tpl, info,
                                         table=self._audit_warm_slice())
                info['compile_s'] = round(time.perf_counter() - t0, 3)
            mem = compiled_memory(compiled)
            if mem:
                info['memory'] = mem
            self._exec[sig] = info
            report[sig] = {
                'bucket': f'{bucket.nodes}x{bucket.edges}',
                'compile_s': info['compile_s'],
                'static_bytes': (mem or {}).get('total_bytes'),
            }
            if self._obs:
                self._obs.log(0, event=f'serve_warm_{label}',
                              compile_s=info['compile_s'],
                              **({'static_bytes': mem['total_bytes']}
                                 if mem else {}))
        return report

    @staticmethod
    def _stage_flops(lowered):
        """Per-stage FLOP attribution of one bucket's lowering
        (``obs/cost.stage_table`` over the debug-info MLIR) — what the
        per-query goodput ratio composes with. ``None`` when the
        compiler IR is unavailable; the ratio then falls back to the
        mask-only account, never guesses."""
        try:
            from dgmc_tpu.obs.cost import stage_table
            asm = lowered.compiler_ir().operation.get_asm(
                enable_debug_info=True)
            return stage_table(asm) or None
        except Exception:
            return None

    @property
    def buckets_warm(self):
        return len(self._exec)

    def bucket_stats(self):
        return {info['bucket']: info['queries']
                for info in self._exec.values()}

    def capacity_stats(self):
        """The saturation/goodput account (``obs.capacity``'s live
        input): in-flight count, lock wait/hold histogram snapshots,
        the measured arrival window, and per-bucket pad-fraction /
        goodput-ratio running means."""
        with self._stats_lock:
            wait = self.lock_wait_hist.snapshot()
            hold = self.lock_hold_hist.snapshot()
            inflight = self.inflight
            t0, t1 = self._t_first_query, self._t_last_query
            buckets = {}
            pad_sum = good_sum = queries = 0
            for info in self._exec.values():
                b = info['bucket']
                q = info['queries']
                buckets[f'{b.nodes}x{b.edges}'] = {
                    'queries': q,
                    'pad_fraction': (round(info['pad_sum'] / q, 6)
                                     if q else None),
                    'goodput_ratio': (round(info['goodput_sum'] / q, 6)
                                      if q else None),
                }
                pad_sum += info['pad_sum']
                good_sum += info['goodput_sum']
                queries += q
        window_s = (t1 - t0) if (t0 is not None and t1 is not None
                                 and t1 > t0) else None
        return {
            'inflight': inflight,
            'queries': queries,
            'window_s': round(window_s, 6) if window_s else None,
            'lock_wait': wait,
            'lock_hold': hold,
            'pad_fraction': (round(pad_sum / queries, 6)
                             if queries else None),
            'goodput_ratio': (round(good_sum / queries, 6)
                              if queries else None),
            'buckets': buckets,
        }

    # -- the query path ----------------------------------------------------

    def match(self, graph, trace=None):
        """Answer one query :class:`~dgmc_tpu.utils.data.Graph`.

        Routes, pads, executes the bucket's warm executable, and
        returns the structured answer (host python). Raises
        :class:`~dgmc_tpu.serve.router.UnknownBucketError` for a query
        outside the declared bucket space and :class:`ValueError` for a
        malformed one — both map to structured 4xx at the HTTP layer.
        Thread-safe; execution is serialized (see module docstring).

        ``trace`` is an optional :class:`~dgmc_tpu.obs.qtrace.
        QueryTrace`: each phase of the query path runs under its span
        from the shared serve vocabulary, including the lock acquire
        (``admission_queue_wait``) — the convoy the latency histogram
        deliberately excludes is exactly what the trace must expose.
        """
        span = trace.span if trace is not None else _null_span
        with span('bucket_resolve'):
            if graph.x is None:
                raise ValueError('query graphs need node features x')
            if graph.x.shape[1] != self.index.corpus.feat_dim:
                raise ValueError(
                    f'query feature width {graph.x.shape[1]} != corpus '
                    f'feature width {self.index.corpus.feat_dim}')
            n_real = graph.num_nodes
            bucket = self.router.route(n_real, graph.num_edges)
            sig = self.router.signature(bucket)
            info = self._exec.get(sig)
            if info is None:
                raise UnknownExecutableError(bucket, sig)
        with span('pad_and_stage'):
            q = self.router.pad_query(graph, bucket)
        # Per-query goodput: the routed bucket vs the query's real
        # shape (the corpus side is fully real by construction),
        # composed with the bucket lowering's per-stage FLOPs.
        fills = goodput_mod.pair_fills(
            {'nodes_real': n_real, 'nodes_padded': bucket.nodes,
             'edges_real': graph.num_edges, 'edges_padded': bucket.edges},
            {'nodes_real': self.router.corpus_nodes,
             'nodes_padded': self.router.corpus_nodes,
             'edges_real': self.router.corpus_edges,
             'edges_padded': self.router.corpus_edges})
        good = goodput_mod.goodput_ratio(fills, info.get('stages'))
        with self._stats_lock:
            self.inflight += 1
        t_wait = time.perf_counter()
        with span('admission_queue_wait'):
            self._lock.acquire()
        t_hold = time.perf_counter()
        done = False
        try:
            obs = self._obs
            step = obs.step() if obs is not None else _null()
            t0 = time.perf_counter()
            with step:
                out = self._execute(info, q, span)
            self.last_latency_s = time.perf_counter() - t0
            done = True
        finally:
            self._lock.release()
            t_done = time.perf_counter()
            with self._stats_lock:
                self.inflight -= 1
                self.lock_wait_hist.observe(t_hold - t_wait)
                self.lock_hold_hist.observe(t_done - t_hold)
                if self._t_first_query is None:
                    self._t_first_query = t_wait
                self._t_last_query = t_done
                if done:
                    # Per-bucket running means count ANSWERED queries
                    # only, so the pad/goodput account divides by the
                    # same population `queries` does.
                    info['queries'] += 1
                    self.query_count += 1
                    info['pad_sum'] += 1.0 - (n_real / bucket.nodes)
                    if good is not None:
                        info['goodput_sum'] += good
        with span('serialize'):
            return self._answer(bucket, n_real, out)

    def _execute(self, info, q, span=_null_span):
        import jax
        with span('pad_and_stage'):
            q = jax.device_put(q, self._device)
        if not self.offload:
            with span('device_execute'):
                out = info['exec'](self._variables, q, self._t_graph,
                                   self._h_t_dev, self._noise_key)
                return {k: np.asarray(v) for k, v in out.items()}
        from dgmc_tpu.ops.offload import offloaded_corpus_topk
        with span('device_execute'):
            h_s = info['embed'](self._psi1_vars(), q)
            _vals, idx, _stats = offloaded_corpus_topk(
                h_s, self._h_t_host, self.model.k, self.offload_chunk,
                depth=self.prefetch_depth, device=self._device)
        with span('shortlist_merge'):
            idx_host = np.asarray(idx)
            h_t_cand = self._h_t_host[0][idx_host[0]][None]
        with span('consensus_rerank'):
            out = info['exec'](self._variables, q, self._t_graph, idx,
                               h_t_cand, self._noise_key)
            return {k: np.asarray(v) for k, v in out.items()}

    def _audit_warm_slice(self):
        """The smallest table slice whose streamed scan walks every jit
        shape config the full-corpus audit scan walks: one full chunk
        plus the ragged tail (or the whole table when it fits in one
        chunk). Used only by the warm() template sweep."""
        n = self._h_t_host.shape[1]
        chunk = self.offload_chunk
        if n <= chunk:
            return self._h_t_host
        return self._h_t_host[:, :chunk + (n % chunk)]

    def exhaustive_topk(self, q_padded, info, table=None):
        """Exhaustive corpus top-k for one padded query batch — the
        shadow audit's reference scan: query-side ψ₁ through the warm
        embed executable, then the host-driven streamed scan over the
        FULL host-resident corpus table (bit-identical tie-breaking to
        the in-graph shortlist). Deliberately lock-free: the audit runs
        off the hot path and must not convoy live queries.

        ``table`` overrides the scanned table (the warm() sweep passes
        the truncated compile-coverage slice); the live audit always
        scans the full corpus.

        Returns the ``[1, N, k]`` candidate index array (host numpy).
        """
        import jax

        from dgmc_tpu.ops.offload import offloaded_corpus_topk
        q = jax.device_put(q_padded, self._device)
        h_s = info['embed'](self._psi1_vars(), q)
        _vals, idx, _stats = offloaded_corpus_topk(
            h_s, self._h_t_host if table is None else table,
            self.model.k, self.offload_chunk,
            depth=self.prefetch_depth, device=self._device)
        return np.asarray(idx)

    def _answer(self, bucket, n_real, out):
        matches = []
        for i in range(n_real):
            cands = [[int(t), float(p)] for t, p in
                     zip(out['cand_idx'][0, i], out['cand_prob'][0, i])]
            matches.append({
                'node': i,
                'target': cands[0][0],
                'score': cands[0][1],
                'candidates': cands,
                'initial': [int(out['initial_idx'][0, i]),
                            float(out['initial_prob'][0, i])],
            })
        return {
            'bucket': f'{bucket.nodes}x{bucket.edges}',
            'signature': self.router.signature(bucket),
            'nodes': n_real,
            'matches': matches,
            # Per-query confidence proxies (deterministic: the fixed
            # noise key makes them a pure function of the query).
            'quality': {
                'entropy': round(float(out['q_entropy']), 6),
                'margin': round(float(out['q_margin']), 6),
                'correction': round(float(out['q_correction']), 6),
                'saturation': round(float(out['q_saturation']), 6),
                'saturated_frac': round(float(out['q_saturated_frac']),
                                        6),
            },
            # Internal (popped by the HTTP layer before serialization):
            # the served shortlist rows the shadow audit compares
            # against the exhaustive scan. Plain int lists, not the
            # device array — answers stay ==-comparable (the repeat-
            # determinism pin) and drop no device buffer reference.
            '_audit': {'shortlist_idx': [
                [int(t) for t in row]
                for row in out['shortlist_idx'][0, :n_real]]},
        }


class UnknownExecutableError(RuntimeError):
    """A routed bucket with no warm executable — warm() was skipped or
    raced; the service maps it to a 503, never an inline compile."""

    def __init__(self, bucket, sig):
        self.payload = {
            'error': 'bucket-not-warm',
            'detail': f'bucket {bucket.nodes}x{bucket.edges} (signature '
                      f'{sig}) has no warm executable',
        }
        super().__init__(self.payload['detail'])


class _null:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
