"""Sampled shadow audit: re-score live queries exhaustively, off-lock.

The serving shortlist is a top-k search the engine trusts; this module
is the instrument that keeps checking it. A deterministic seeded-hash
sample of live queries (:func:`dgmc_tpu.obs.quality.audit_keep` — the
qtrace retention discipline: the audited set is a pure function of
``(seed, trace ids)``, byte-identical across runs and replicas) is
queued to a single background thread, re-embedded through the bucket's
warm ψ₁ executable and scanned against the FULL host-resident corpus
table (:func:`~dgmc_tpu.ops.offload.offloaded_corpus_topk`, bit-
identical tie-breaking to the in-graph scan). The measurement is
shortlist recall@k of the *served* candidate set against the exhaustive
reference, per real query node.

On today's exact tiers the scan and the serving shortlist are the same
algorithm, so recall must be **1.0** — the audit is a continuous
bit-exactness check, and any drop is a bug, not noise. When a lossy
(quantized / ANN) index lands, the same sensor becomes the
recall@k ≥ 0.99 gate with zero extra wiring.

Deliberately off the engine's execution lock: device dispatch is
thread-safe and the audit must never convoy live queries. All audit
compiles happen at warmup (``MatchEngine.warm`` runs the template scan
under the bucket's compile label when auditing is on), so the thread is
execute-only on a live process — the zero-per-query-compile contract
covers the audit too.
"""

import collections
import sys
import threading

from dgmc_tpu.obs.quality import audit_keep

__all__ = ['ShadowAuditor']


class ShadowAuditor:
    """One background audit thread over a bounded query queue.

    Args:
        engine: the warm :class:`~dgmc_tpu.serve.engine.MatchEngine`.
        tracker: the observer's
            :class:`~dgmc_tpu.obs.quality.QualityTracker` (receives
            ``observe_audit`` per audited query).
        sample_rate: keep fraction in [0, 1].
        seed: hash seed (the service's ``--seed``).
        capacity: queue bound — under backpressure new candidates are
            DROPPED and counted, never blocking the serving path.
    """

    def __init__(self, engine, tracker, sample_rate, seed=0,
                 capacity=128):
        self.engine = engine
        self.tracker = tracker
        self.sample_rate = float(sample_rate)
        self.seed = int(seed)
        self.capacity = int(capacity)
        self.dropped = 0
        self.audited = 0
        self.errors = 0
        self._queue = collections.deque()
        self._cond = threading.Condition()
        self._closed = False
        self._busy = False
        self._thread = threading.Thread(target=self._run,
                                        name='shadow-audit', daemon=True)
        self._thread.start()

    def keep(self, trace_id):
        return audit_keep(self.seed, trace_id, self.sample_rate)

    def maybe_submit(self, trace_id, graph, audit_info):
        """Enqueue one served query if the deterministic sample keeps
        it. Returns True when enqueued."""
        if not self.keep(trace_id):
            return False
        with self._cond:
            if self._closed:
                return False
            if len(self._queue) >= self.capacity:
                self.dropped += 1
                return False
            self._queue.append((trace_id, graph, audit_info))
            self._cond.notify()
        return True

    # -- the audit thread --------------------------------------------------

    def _run(self):
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait()
                if not self._queue and self._closed:
                    return
                item = self._queue.popleft()
                self._busy = True
            try:
                self._audit_one(*item)
            except Exception as e:    # noqa: BLE001 — audit never kills serving
                with self._cond:
                    self.errors += 1
                print(f'shadow-audit: {type(e).__name__}: {e}',
                      file=sys.stderr, flush=True)
            finally:
                with self._cond:
                    self._busy = False
                    self._cond.notify_all()   # wake drain() waiters

    def _audit_one(self, trace_id, graph, audit_info):
        engine = self.engine
        bucket = engine.router.route(graph.num_nodes, graph.num_edges)
        info = engine._exec[engine.router.signature(bucket)]
        q = engine.router.pad_query(graph, bucket)
        exact = engine.exhaustive_topk(q, info)
        served = audit_info['shortlist_idx']    # [n_real][k] int lists
        n_real = len(served)
        k = len(served[0]) if served else 1
        reference = exact[0, :n_real]
        recalls = [
            len(set(served[i])
                & set(int(t) for t in reference[i])) / k
            for i in range(n_real)]
        recall = sum(recalls) / max(len(recalls), 1)
        # Under _cond like dropped/errors: the counters are read from
        # serving/main threads (gauges, close-time accounting) while
        # this thread increments — an unlocked += loses counts (CON501).
        with self._cond:
            self.audited += 1
        self.tracker.observe_audit(trace_id, recall,
                                   exact=recall >= 1.0)

    # -- lifecycle ---------------------------------------------------------

    def drain(self, timeout_s=60.0):
        """Block until the queue is empty and the in-flight item (if
        any) finished — bench/test determinism. Returns True when
        drained within the deadline."""
        import time
        deadline = time.time() + timeout_s
        with self._cond:
            while self._queue or self._busy:
                remaining = deadline - time.time()
                if remaining <= 0 or not self._cond.wait(
                        timeout=remaining):
                    return False
            return True

    def close(self, timeout_s=10.0):
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=timeout_s)
