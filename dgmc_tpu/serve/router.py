"""Padding-bucket query routing on the lint's own signature hash.

Every distinct padding shape a serving process accepts is a distinct
XLA program (``analysis/recompile.py``); a router that padded queries
ad hoc would compile on the query path — the RCP201/202 churn findings
as live latency spikes. This router inverts that: the bucket space is
DECLARED at startup (``--buckets 32x64,64x128``), every declared bucket
gets its executable AOT-compiled before the first query, and a query
that fits no declared bucket is a structured 4xx
(:class:`UnknownBucketError`), never an inline compile.

Bucket identity is :func:`dgmc_tpu.analysis.recompile.bucket_signature`
— the SAME public helper the recompile lint hashes telemetry rows with,
over the same ``{batch, nodes, edges}`` row format the collation layer
records (``utils/data.pad_pair_batch`` →
``registry.padding_bucket_table``). One definition, two consumers;
``tests/serve/test_router.py`` pins the agreement on every registry
specimen's recorded buckets, so the lint's churn math and the router's
executable table can never drift apart.
"""

import re
from typing import List, NamedTuple

from dgmc_tpu.analysis.recompile import bucket_signature

__all__ = ['Bucket', 'QueryRouter', 'UnknownBucketError', 'parse_buckets',
           'DEFAULT_BUCKETS']

#: Default declared bucket ladder (query nodes x edges): power-of-two
#: rungs covering small-to-medium query graphs. Serving deployments
#: declare their own via ``--buckets``.
DEFAULT_BUCKETS = ((16, 48), (32, 96), (64, 192))


class Bucket(NamedTuple):
    """One declared query padding bucket (source-side shape)."""
    nodes: int
    edges: int


class UnknownBucketError(Exception):
    """A query that fits no declared bucket. Carries the structured
    4xx payload the service returns verbatim — the client learns the
    declared bucket space instead of paying for an inline compile."""

    def __init__(self, nodes, edges, buckets):
        self.payload = {
            'error': 'unknown-bucket',
            'detail': f'query ({nodes} nodes, {edges} edges) fits no '
                      f'declared padding bucket; the service only runs '
                      f'warm AOT-compiled executables (no inline '
                      f'compiles on the query path)',
            'query': {'nodes': int(nodes), 'edges': int(edges)},
            'buckets': [f'{b.nodes}x{b.edges}' for b in buckets],
        }
        super().__init__(self.payload['detail'])


def parse_buckets(spec) -> List[Bucket]:
    """``'32x96,64x192'`` → sorted, deduplicated bucket list."""
    out = set()
    for part in str(spec).split(','):
        part = part.strip()
        if not part:
            continue
        m = re.match(r'^(\d+)x(\d+)$', part)
        if not m:
            raise ValueError(f'bad bucket spec {part!r} (want NxE, e.g. '
                             f'32x96)')
        b = Bucket(int(m.group(1)), int(m.group(2)))
        if b.nodes < 1 or b.edges < 1:
            raise ValueError(f'bucket {part!r} must be positive')
        out.add(b)
    if not out:
        raise ValueError(f'no buckets in spec {spec!r}')
    return sorted(out)


class QueryRouter:
    """Route queries into declared padding buckets.

    Args:
        buckets: declared :class:`Bucket` list (or a ``'NxE,...'``
            spec string).
        corpus_nodes / corpus_edges: the fixed target-side padding every
            bucket pairs with — the signature hashes the PAIR shape,
            exactly like the telemetry rows the lint consumes.
    """

    def __init__(self, buckets, corpus_nodes, corpus_edges):
        if isinstance(buckets, str):
            buckets = parse_buckets(buckets)
        self.buckets = sorted(Bucket(int(n), int(e)) for n, e in buckets)
        self.corpus_nodes = int(corpus_nodes)
        self.corpus_edges = int(corpus_edges)

    def route(self, nodes, edges) -> Bucket:
        """Smallest declared bucket that fits (nodes, edges) — smallest
        by node padding then edge padding, so a query pays the least
        masked-row waste the declaration allows. No fit raises
        :class:`UnknownBucketError`."""
        for b in self.buckets:
            if nodes <= b.nodes and edges <= b.edges:
                return b
        raise UnknownBucketError(nodes, edges, self.buckets)

    def bucket_row(self, bucket) -> dict:
        """The obs-telemetry padding-bucket row this bucket collates as
        (``registry.padding_bucket_table`` format) — the row format
        :func:`~dgmc_tpu.analysis.recompile.bucket_signature` is
        defined over."""
        return {'batch': 1,
                'nodes': f'{bucket.nodes}x{self.corpus_nodes}',
                'edges': f'{bucket.edges}x{self.corpus_edges}'}

    def signature(self, bucket) -> str:
        """The bucket's executable-table key: the recompile lint's own
        signature hash over this bucket's telemetry row."""
        return bucket_signature(self.bucket_row(bucket))

    def record(self, bucket, real_nodes=None, real_edges=None):
        """Count one collation into ``bucket`` in the process-wide obs
        registry — the serve-side twin of ``pad_pair_batch``'s
        telemetry, so a recorded serve run's padding buckets feed the
        same RCP202 compile-churn cross-check as a training run's.

        ``real_nodes``/``real_edges`` are the query's PRE-padding sizes;
        when given, the real-size totals land beside the bucket counter
        (``registry.record_padding``) so per-bucket pad waste is
        recomputable from the recorded obs dir (``obs.goodput``). The
        target side is the corpus — fully real by construction.
        """
        from dgmc_tpu.obs.registry import record_padding
        row = self.bucket_row(bucket)
        real = None
        if real_nodes is not None and real_edges is not None:
            real = {'nodes_s': int(real_nodes),
                    'edges_s': int(real_edges),
                    'nodes_t': self.corpus_nodes,
                    'edges_t': self.corpus_edges}
        record_padding(real=real, **row)

    def pad_query(self, graph, bucket):
        """Collate one host :class:`~dgmc_tpu.utils.data.Graph` into
        ``bucket``'s padded ``GraphBatch`` (B=1), recording the
        collation (real pre-padding sizes included) in the registry."""
        from dgmc_tpu.utils.data import pad_graphs
        self.record(bucket, real_nodes=graph.num_nodes,
                    real_edges=graph.num_edges)
        return pad_graphs([graph], bucket.nodes, bucket.edges)
