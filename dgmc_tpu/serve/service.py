"""The serving worker: checkpoint → cache → warm buckets → answer.

``ServeService`` owns the worker lifecycle:

1. build/load the corpus (synthetic by spec, or an ``.npz``),
2. restore the checkpoint (``train/checkpoint.py`` hardening included;
   ``--init-missing`` seeds + saves step 0 into an empty directory so
   smoke/bench runs are self-contained AND deterministic across
   supervised restarts),
3. load-or-build the ψ₁ corpus cache (sha256-manifested; a verified
   cache hit is the WARM restart path — the recompute is skipped and
   the hit is logged + exported as the ``corpus_cache_hit`` gauge),
4. AOT-warm every declared bucket executable,
5. serve ``/match`` beside ``/healthz``/``/metrics``/``/status`` on the
   observer's telemetry plane, with per-query latency streamed into the
   Prometheus histogram (``dgmc_step_latency_seconds`` — a "step" IS a
   query here) and startup-phase timings logged for the cold-vs-warm
   restart account.

Run supervised via ``python -m dgmc_tpu.serve --supervise``: the
monitor kills a wedged worker on the same /healthz verdict the plane
itself serves, and the restarted worker comes back warm from the cache.
The idle loop beats the watchdog — an idle server is healthy; only a
WEDGED one (a query stuck in XLA, a deadlocked handler) goes stale and
gets restarted.
"""

import argparse
import collections
import json
import os
import signal
import sys
import threading
import time

import numpy as np

from dgmc_tpu.obs.qtrace import QueryTracer
from dgmc_tpu.serve.router import (DEFAULT_BUCKETS, QueryRouter,
                                   UnknownBucketError, parse_buckets)

__all__ = ['ServeService', 'add_serve_args', 'main', 'ERROR_CLASSES']

#: Per-class query-error labels in the Prometheus exposition
#: (``dgmc_query_errors_total{class=...}``): HTTP code + cause, every
#: class pre-seeded at 0 so scrapers always see the full label set.
ERROR_CLASSES = ('bad-query-400', 'bucket-miss-400', 'method-405',
                 'engine-500', 'warming-503', 'bucket-not-warm-503')


def add_serve_args(parser):
    """The serving CLI surface (``python -m dgmc_tpu.serve``)."""
    parser.add_argument('--ckpt_dir', '--ckpt-dir', dest='ckpt_dir',
                        type=str, required=True,
                        help='checkpoint directory (train/checkpoint.py '
                             'layout); the serving weights')
    parser.add_argument('--init-missing', '--init_missing',
                        dest='init_missing', action='store_true',
                        help='if the checkpoint directory is empty, '
                             'initialize seeded parameters and SAVE them '
                             'as step 0 before serving — self-contained '
                             'smoke/bench runs whose supervised restarts '
                             'restore identical weights')
    parser.add_argument('--corpus-npz', '--corpus_npz', dest='corpus_npz',
                        type=str, default=None,
                        help='corpus arrays: .npz with x [N,C] float32, '
                             'senders [E] int32, receivers [E] int32 '
                             '(default: synthetic by the --corpus-* '
                             'flags)')
    parser.add_argument('--corpus-nodes', '--corpus_nodes',
                        dest='corpus_nodes', type=int, default=4096)
    parser.add_argument('--corpus-edges', '--corpus_edges',
                        dest='corpus_edges', type=int, default=16384)
    parser.add_argument('--corpus-dim', '--corpus_dim', dest='corpus_dim',
                        type=int, default=64,
                        help='synthetic corpus feature width (and the '
                             'width every query must ship)')
    parser.add_argument('--corpus-seed', '--corpus_seed',
                        dest='corpus_seed', type=int, default=0)
    parser.add_argument('--cache-dir', '--cache_dir', dest='cache_dir',
                        type=str, default=None,
                        help='ψ₁ corpus-cache directory (default '
                             '<ckpt_dir>/corpus_cache; "" disables '
                             'caching — every restart is cold)')
    parser.add_argument('--buckets', type=str,
                        default=','.join(f'{n}x{e}'
                                         for n, e in DEFAULT_BUCKETS),
                        help='declared query padding buckets '
                             '"NxE,NxE,..." — each gets a warm AOT '
                             'executable at startup; queries outside '
                             'the declared space get a structured 4xx '
                             '(default %(default)s)')
    parser.add_argument('--dim', type=int, default=64,
                        help='ψ₁ hidden width')
    parser.add_argument('--rnd_dim', type=int, default=16)
    parser.add_argument('--num_layers', type=int, default=2)
    parser.add_argument('--num_steps', type=int, default=4,
                        help='consensus rerank iterations per query')
    parser.add_argument('--k', type=int, default=10,
                        help='shortlist size (candidates reranked per '
                             'query node)')
    parser.add_argument('--max-results', '--max_results',
                        dest='max_results', type=int, default=5,
                        help='ranked candidates returned per node')
    parser.add_argument('--stream-chunk', '--stream_chunk',
                        dest='stream_chunk', type=int, default=0,
                        help='stream the shortlist search over source '
                             'chunks of this many rows (0 = off)')
    parser.add_argument('--offload-corpus', '--offload_corpus',
                        dest='offload_corpus', action='store_true',
                        help='host-RAM corpus tier: the ψ₁ table stays '
                             'in host memory; the shortlist streams '
                             'target chunks through the prefetch ring '
                             '(ops/offload.offloaded_corpus_topk) and '
                             'the rerank executable receives the '
                             'shortlist + candidate rows — device '
                             'residents stay O(corpus edges + query), '
                             'whatever the corpus row count')
    parser.add_argument('--offload-chunk', '--offload_chunk',
                        dest='offload_chunk', type=int, default=4096)
    parser.add_argument('--prefetch-depth', '--prefetch_depth',
                        dest='prefetch_depth', type=int, default=0,
                        help='prefetch ring depth for --offload-corpus '
                             '(0 = library default)')
    parser.add_argument('--noise-seed', '--noise_seed', dest='noise_seed',
                        type=int, default=0,
                        help='fixed consensus indicator-noise key: '
                             'serving is deterministic — identical '
                             'queries get bit-identical answers')
    parser.add_argument('--seed', type=int, default=0)
    parser.add_argument('--qtrace-sample', '--qtrace_sample',
                        dest='qtrace_sample', type=float, default=0.05,
                        help='deterministic keep fraction for per-query '
                             'span trees beyond the slowest-K reservoir '
                             'and errors (hash of seed+trace id, not '
                             'random; default %(default)s)')
    parser.add_argument('--qtrace-slowest', '--qtrace_slowest',
                        dest='qtrace_slowest', type=int, default=8,
                        help='always-keep reservoir: the K slowest '
                             'queries (default %(default)s)')
    parser.add_argument('--qtrace-capacity', '--qtrace_capacity',
                        dest='qtrace_capacity', type=int, default=256,
                        help='sampled-ring bound; qtrace.jsonl holds at '
                             'most capacity + error ring + K records '
                             '(default %(default)s)')
    parser.add_argument('--slo-ms', '--slo_ms', dest='slo_ms',
                        type=float, default=0.0,
                        help='end-to-end query SLO in ms; a breaching '
                             'query dumps the flight recorder with its '
                             'span tree attached (0 = off)')
    parser.add_argument('--min-margin', '--min_margin',
                        dest='min_margin', type=float, default=0.0,
                        help='low-confidence floor on the per-query '
                             'top-1/top-2 margin: a served answer whose '
                             'margin falls below it dumps the flight '
                             'recorder with the offending query attached '
                             '— the SLO pattern applied to accuracy '
                             '(0 = off)')
    parser.add_argument('--audit-sample', '--audit_sample',
                        dest='audit_sample', type=float, default=0.0,
                        help='shadow-audit keep fraction: that share of '
                             'live queries (deterministic hash of '
                             'seed+trace id) is re-scored through the '
                             'exhaustive corpus scan off the hot lock, '
                             'and shortlist recall@k against the served '
                             'answer lands in quality.json — on the '
                             'exact tiers recall must be 1.0 (0 = off)')
    from dgmc_tpu.obs import add_obs_flag
    from dgmc_tpu.resilience import add_supervisor_args
    add_obs_flag(parser)
    add_supervisor_args(parser)
    return parser


def _load_corpus(args):
    from dgmc_tpu.serve.corpus import Corpus, synthetic_corpus
    if args.corpus_npz:
        d = np.load(args.corpus_npz)
        return Corpus(x=np.asarray(d['x'], np.float32),
                      senders=np.asarray(d['senders'], np.int32),
                      receivers=np.asarray(d['receivers'], np.int32))
    return synthetic_corpus(args.corpus_nodes, args.corpus_edges,
                            args.corpus_dim, seed=args.corpus_seed)


class ServeService:
    """One serving worker (construct, :meth:`start`, :meth:`serve_forever`
    or drive in-process from tests via :attr:`port`/:meth:`stop`)."""

    def __init__(self, args):
        self.args = args
        self.engine = None
        self.obs = None
        self.port = None
        self.ready = False
        self.phases = {}
        self.queries_served = 0
        self.query_errors = collections.Counter(
            {cls: 0 for cls in ERROR_CLASSES})
        # Handler threads (ThreadingHTTPServer: one per request) bump
        # these outside the engine's execution lock — the non-atomic
        # += needs its own lock or concurrent clients lose increments.
        self._counts = threading.Lock()
        self._stop = threading.Event()
        self.low_confidence = 0
        # Flush-loop-private QPS bookmark (only serve_forever touches
        # it; queries_served itself stays under _counts).
        self._last_flush_queries = 0
        self.auditor = None
        self.qtracer = None
        if getattr(args, 'obs_dir', None):
            slo_ms = getattr(args, 'slo_ms', 0.0) or 0.0
            self.qtracer = QueryTracer(
                path=os.path.join(args.obs_dir, 'qtrace.jsonl'),
                sample_rate=getattr(args, 'qtrace_sample', 0.05),
                slowest_k=getattr(args, 'qtrace_slowest', 8),
                capacity=getattr(args, 'qtrace_capacity', 256),
                seed=getattr(args, 'seed', 0),
                slo_s=(slo_ms / 1e3) if slo_ms > 0 else None,
                on_breach=self._on_slo_breach)

    # -- startup -----------------------------------------------------------

    def start(self):
        args = self.args
        t_start = time.perf_counter()

        from dgmc_tpu.obs import RunObserver
        # The observer comes up FIRST: warmup compiles must be counted
        # (the zero-per-query-compile check is a delta against them),
        # the watchdog must cover the startup phases, and /healthz must
        # answer while the cache builds. /match answers 503 until ready.
        self.obs = RunObserver(args.obs_dir,
                               watchdog_deadline_s=args.watchdog_deadline,
                               obs_port=args.obs_port,
                               routes={'/match': self.handle_match})
        self.obs.add_metrics_provider(self._serve_metric_families)
        # SLO/anomaly planes: --slo judges every query against the
        # declared objectives (error budget + burn rates in /metrics,
        # /status and slo.json); the anomaly watch is always on —
        # query latency, QPS, compile events and quality margins feed
        # streaming detectors that arm the flight recorder. A
        # malformed --slo file fails startup here, loudly.
        self.obs.attach_anomaly()
        self.obs.attach_slo(getattr(args, 'slo', None))
        self.port = self.obs.live_port
        obs = self.obs

        def phase(name, fn):
            t0 = time.perf_counter()
            if obs.watchdog is not None:
                obs.watchdog.beat('serve-startup', name)
            out = fn()
            self.phases[f'{name}_s'] = round(time.perf_counter() - t0, 3)
            if obs.watchdog is not None:
                obs.watchdog.done()
            return out

        corpus = phase('corpus', lambda: _load_corpus(args))
        model, variables, step = phase(
            'checkpoint', lambda: self._restore(corpus))
        index, cache_info = phase(
            'cache', lambda: self._index(corpus, model, variables, step))
        self.cache_info = cache_info

        router = QueryRouter(parse_buckets(args.buckets),
                             corpus.num_nodes, corpus.num_edges)
        from dgmc_tpu.serve.engine import MatchEngine
        audit_rate = getattr(args, 'audit_sample', 0.0) or 0.0
        self.engine = MatchEngine(
            model, variables, index, router,
            max_results=args.max_results, noise_seed=args.noise_seed,
            offload=args.offload_corpus,
            offload_chunk=args.offload_chunk,
            prefetch_depth=args.prefetch_depth or None, obs=obs,
            audit=audit_rate > 0)
        warm_report = phase('warm', self.engine.warm)

        if obs.quality is not None and audit_rate > 0:
            obs.quality.set_audit_params(audit_rate,
                                         getattr(args, 'seed', 0))
        if audit_rate > 0:
            from dgmc_tpu.serve.audit import ShadowAuditor
            self.auditor = ShadowAuditor(
                self.engine, obs.quality, sample_rate=audit_rate,
                seed=getattr(args, 'seed', 0))
        # One scrape answers "how fast AND how good": the qtrace
        # summary joins /status beside the observer's own quality block.
        if self.qtracer is not None:
            obs.add_status_section('qtrace', self.qtracer.summary)
        # And "how much headroom": the live queueing model over the
        # engine's saturation account (obs.capacity.live_summary).
        obs.add_status_section('capacity', self._capacity_status)
        if obs.quality is not None:
            obs.add_metrics_provider(obs.quality.metric_families)

        self.phases['ready_s'] = round(time.perf_counter() - t_start, 3)
        cache_hit = cache_info['cache'] == 'hit'
        obs.set_gauge('serve_ready', 1)
        obs.set_gauge('corpus_cache_hit', 1 if cache_hit else 0)
        obs.set_gauge('serve_buckets_warm', self.engine.buckets_warm)
        obs.set_gauge('queries_served', 0)
        obs.set_gauge('low_confidence_breaches', 0)
        if self.auditor is not None:
            obs.set_gauge('audited_queries', 0)
        warm_compiles = self._compile_events()
        obs.set_gauge('serve_warmup_compiles', warm_compiles)
        obs.log(0, event='serve_ready', cache=cache_info['cache'],
                cache_seconds=cache_info['seconds'],
                warmup_compiles=warm_compiles,
                buckets=len(warm_report), **self.phases)
        self.ready = True
        print(f'serve: ready in {self.phases["ready_s"]:.2f}s '
              f'(cache {cache_info["cache"]}, '
              f'{self.engine.buckets_warm} buckets warm, '
              f'{warm_compiles} warmup compiles) on port {self.port}',
              file=sys.stderr, flush=True)
        return self

    def _restore(self, corpus):
        import jax

        from dgmc_tpu.models import DGMC, RelCNN
        from dgmc_tpu.train import create_train_state
        from dgmc_tpu.train.checkpoint import Checkpointer
        args = self.args
        psi_1 = RelCNN(corpus.feat_dim, args.dim, args.num_layers,
                       batch_norm=False, cat=True, lin=True, dropout=0.0)
        psi_2 = RelCNN(args.rnd_dim, args.rnd_dim, args.num_layers,
                       batch_norm=False, cat=True, lin=True, dropout=0.0)
        model = DGMC(psi_1, psi_2, num_steps=args.num_steps, k=args.k,
                     stream_chunk=args.stream_chunk or None)
        state = create_train_state(
            model, jax.random.key(args.seed), self._init_batch(corpus))
        ckpt = Checkpointer(args.ckpt_dir)
        steps = ckpt.all_steps()
        if not steps:
            if not args.init_missing:
                raise SystemExit(
                    f'serve: no checkpoint under {args.ckpt_dir} (pass '
                    f'--init-missing to seed-initialize and save step 0)')
            ckpt.save(0, state, wait=True)
            steps = [0]
        restored = ckpt.restore(state)
        step = ckpt.restored_step
        ckpt.close()
        variables = {'params': restored.params}
        if restored.batch_stats:
            variables['batch_stats'] = restored.batch_stats
        return model, variables, step

    def _init_batch(self, corpus):
        """Tiny init stand-in pair: parameter shapes depend only on
        feature widths (train/state.create_train_state docs)."""
        from dgmc_tpu.serve.corpus import synthetic_corpus
        from dgmc_tpu.utils.data import PairBatch
        c = corpus.feat_dim
        g_s = synthetic_corpus(16, 48, c, seed=1).graph_batch(
            dummy_x=False)
        g_t = synthetic_corpus(24, 64, c, seed=2).graph_batch(
            dummy_x=False)
        y = np.full((1, 16), -1, np.int32)
        y[0, :8] = np.arange(8)
        return PairBatch(s=g_s, t=g_t, y=y, y_mask=y >= 0)

    def _index(self, corpus, model, variables, step):
        from dgmc_tpu.serve.corpus import load_or_build
        args = self.args
        cache_dir = args.cache_dir
        if cache_dir is None:
            cache_dir = os.path.join(args.ckpt_dir, 'corpus_cache')
        bs = (variables.get('batch_stats') or {}).get('psi_1')
        return load_or_build(
            cache_dir or None, model.psi_1, variables['params']['psi_1'],
            corpus, batch_stats=bs, checkpoint_step=step,
            log=lambda m: print(f'serve: {m}', file=sys.stderr,
                                flush=True))

    def _compile_events(self):
        w = self.obs._watcher
        return (w.summary() or {}).get('events', 0) if w else 0

    def _count_error(self, cls):
        with self._counts:
            self.query_errors[cls] += 1

    def _on_slo_breach(self, record):
        """SLO-breach hook: dump the flight recorder NOW with the
        offending span tree attached — the trailing run context and
        the slow query's own decomposition in one artifact."""
        obs = self.obs
        if obs is not None:
            obs.flight_dump('slo-breach', extra={'qtrace': record})

    def _serve_metric_families(self):
        """Serve-plane metric families for the observer's ``/metrics``
        exposition: per-class error counters, the qtrace per-stage
        histograms and retention counters, and the capacity/goodput
        plane (in-flight gauge, lock wait/hold histograms, per-bucket
        pad fraction, goodput ratio)."""
        with self._counts:
            errors = dict(self.query_errors)
        families = [(
            'dgmc_query_errors_total', 'counter',
            'Query errors by class (HTTP code + cause).',
            [('', {'class': cls}, errors.get(cls, 0))
             for cls in ERROR_CLASSES])]
        if self.qtracer is not None:
            families.extend(self.qtracer.metric_families())
        if self.engine is not None:
            families.extend(self._capacity_metric_families())
        return families

    def _capacity_metric_families(self):
        """The saturation/goodput families. Families are always
        present once the engine is up (a scraper sees the full set
        from the first scrape); per-bucket pad-fraction samples appear
        as buckets answer queries, and the goodput gauge appears with
        the first measured ratio — absent measurements are absent, not
        zero."""
        from dgmc_tpu.obs.live import histogram_family
        cap = self.engine.capacity_stats()
        pad_samples = [
            ('', {'bucket': name}, row['pad_fraction'])
            for name, row in sorted((cap.get('buckets') or {}).items())
            if row.get('pad_fraction') is not None]
        good_samples = ([('', {}, cap['goodput_ratio'])]
                        if cap.get('goodput_ratio') is not None else [])
        return [
            ('dgmc_inflight', 'gauge',
             'Queries currently inside the engine (admitted, waiting '
             'for or holding the execution lock).',
             [('', {}, cap.get('inflight', 0))]),
            ('dgmc_pad_fraction', 'gauge',
             'Mean padded-away node fraction per routed bucket '
             '(router bucket vs real query shape).', pad_samples),
            ('dgmc_goodput_ratio', 'gauge',
             'Useful FLOPs / executed FLOPs across answered queries '
             '(obs.goodput, composed with per-bucket stage FLOPs).',
             good_samples),
            histogram_family(
                'dgmc_lock_wait_seconds',
                'Engine lock wait (the admission_queue_wait region, '
                'every query — traced or not).', cap['lock_wait']),
            histogram_family(
                'dgmc_lock_hold_seconds',
                'Engine lock hold (service time of the serialized '
                'executor).', cap['lock_hold']),
        ]

    def _capacity_status(self):
        """The `/status` ``capacity`` section: the live queueing model
        (obs.capacity) over the engine's saturation account, with the
        lock-wait distribution reconciled against qtrace's
        ``admission_queue_wait`` stage."""
        from dgmc_tpu.obs.capacity import live_summary
        return live_summary(
            self.engine.capacity_stats(),
            qtrace_summary=(self.qtracer.summary()
                            if self.qtracer is not None else None))

    # -- the /match route --------------------------------------------------

    def handle_match(self, method, body, headers=None):
        """``(method, body bytes, headers) -> (code, payload[,
        headers])`` for the plane's route table. Every failure is
        structured AND counted per class: 405 wrong method, 503 warming
        up / bucket not warm, 400 malformed / unknown bucket, 500
        engine fault.

        Every request gets a trace: the W3C ``traceparent`` header is
        adopted when present (and echoed back in the response headers),
        otherwise a deterministic id is minted. Successful answers
        carry ``trace_id`` + per-stage ``stages_ms`` + the end-to-end
        ``trace_ms``; the ``x-qtrace: off`` header opts one request out
        entirely (the bench's overhead-measurement path)."""
        headers = headers or {}
        tracer = self.qtracer
        if tracer is not None and str(
                headers.get('x-qtrace', '')).lower() in ('off', '0',
                                                         'false'):
            tracer = None
        trace = tracer.start(headers.get('traceparent')) \
            if tracer is not None else None
        t0 = time.perf_counter()
        code, payload = self._match_inner(method, body, trace)
        self._record_slo(code, time.perf_counter() - t0,
                         trace.stage_ms()
                         if trace is not None and code == 200 else None)
        if trace is None:
            return code, payload
        record = tracer.finish(
            trace, status=code,
            bucket=payload.get('bucket') if code == 200 else None,
            error=None if code == 200 else payload.get('error'))
        payload['trace_id'] = trace.trace_id
        if code == 200:
            payload['stages_ms'] = trace.stage_ms()
            payload['trace_ms'] = record['total_ms']
        tracer.maybe_flush()
        return code, payload, {
            'traceparent': trace.response_traceparent()}

    def _record_slo(self, code, latency_s, stages_ms):
        """Feed one query outcome to the SLO/anomaly planes. Client
        faults (400/405) are not service unavailability — the service
        answered correctly; 5xx and the warming/not-warm 503s are."""
        obs = self.obs
        if obs is None:
            return
        if obs.slo is not None:
            obs.slo.record(code < 500 and code != 503,
                           latency_s=latency_s, stages_ms=stages_ms)
        if obs.anomaly is not None:
            obs.anomaly.observe('query_latency_s', latency_s)

    def _match_inner(self, method, body, trace):
        if method != 'POST':
            self._count_error('method-405')
            return 405, {'error': 'POST a JSON query to /match',
                         'schema': {'nodes': '[[feat,...],...]',
                                    'edges': '[[src,dst],...]'}}
        if not self.ready:
            self._count_error('warming-503')
            return 503, {'error': 'warming-up',
                         'phases': dict(self.phases)}
        try:
            payload = json.loads(body.decode('utf-8'))
            from dgmc_tpu.utils.data import Graph
            x = np.asarray(payload['nodes'], np.float32)
            edges = np.asarray(payload.get('edges') or [], np.int64)
            edges = (edges.T if edges.size
                     else np.zeros((2, 0), np.int64))
            if x.ndim != 2:
                raise ValueError(f'nodes must be [N, C], got shape '
                                 f'{x.shape}')
            graph = Graph(edge_index=edges, x=x)
        except (ValueError, KeyError, TypeError,
                UnicodeDecodeError) as e:
            self._count_error('bad-query-400')
            return 400, {'error': 'bad-query',
                         'detail': f'{type(e).__name__}: {e}'}
        t0 = time.perf_counter()
        from dgmc_tpu.serve.engine import UnknownExecutableError
        try:
            answer = self.engine.match(graph, trace=trace)
        except UnknownBucketError as e:
            self._count_error('bucket-miss-400')
            return 400, e.payload
        except UnknownExecutableError as e:
            self._count_error('bucket-not-warm-503')
            return 503, e.payload
        except ValueError as e:
            self._count_error('bad-query-400')
            return 400, {'error': 'bad-query',
                         'detail': f'{type(e).__name__}: {e}'}
        except Exception as e:       # noqa: BLE001 — counted 500
            self._count_error('engine-500')
            return 500, {'error': 'engine-fault',
                         'detail': f'{type(e).__name__}: {e}'}
        with self._counts:
            self.queries_served += 1
            served = self.queries_served
        self.obs.set_gauge('queries_served', served)
        audit_info = answer.pop('_audit', None)
        self._observe_quality(answer, graph, trace, audit_info)
        answer['latency_ms'] = round(
            (time.perf_counter() - t0) * 1e3, 3)
        return 200, answer

    def _observe_quality(self, answer, graph, trace, audit_info):
        """Quality-plane bookkeeping for one served answer: histogram
        the confidence proxies, fire the --min-margin breach hook, and
        hand the sampled query to the shadow auditor."""
        quality = answer.get('quality') or {}
        tracker = self.obs.quality
        if tracker is not None and quality:
            tracker.observe_query(quality)
        min_margin = getattr(self.args, 'min_margin', 0.0) or 0.0
        margin = quality.get('margin')
        if margin is not None and self.obs.anomaly is not None:
            # Accuracy drift watch: a sustained confidence-margin slide
            # (CUSUM) arms the flight recorder even when no single
            # answer crosses the --min-margin floor.
            self.obs.anomaly.observe('quality_margin', margin)
        if min_margin > 0 and margin is not None \
                and margin < min_margin:
            with self._counts:
                self.low_confidence += 1
                breaches = self.low_confidence
            if tracker is not None:
                tracker.record_low_confidence()
            self.obs.set_gauge('low_confidence_breaches', breaches)
            # The qtrace SLO pattern applied to accuracy: dump the
            # flight recorder NOW, with the under-confident query
            # attached — trailing run context + the offending answer's
            # own confidence decomposition in one artifact.
            self.obs.flight_dump('low-confidence', extra={
                'quality': dict(quality),
                'min_margin': min_margin,
                'query': {'bucket': answer.get('bucket'),
                          'nodes': answer.get('nodes'),
                          'trace_id': (trace.trace_id
                                       if trace is not None else None)},
            })
        if self.auditor is not None and trace is not None \
                and audit_info is not None:
            self.auditor.maybe_submit(trace.trace_id, graph, audit_info)

    # -- lifecycle ---------------------------------------------------------

    def serve_forever(self, poll_s=0.5, flush_every_s=5.0):
        """Idle loop until SIGTERM/SIGINT/:meth:`stop`: beats the
        watchdog (an idle server is healthy) and periodically flushes
        the obs artifacts so the latest query telemetry is on disk for
        scrapers of the FILE artifacts too."""
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                signal.signal(sig, lambda *_: self._stop.set())
            except ValueError:
                break
        last_flush = time.time()
        while not self._stop.is_set():
            self._stop.wait(poll_s)
            if self.obs.watchdog is not None:
                self.obs.watchdog.beat('idle')
            if time.time() - last_flush >= flush_every_s:
                if self.auditor is not None:
                    self.obs.set_gauge('audited_queries',
                                       self.auditor.audited)
                if self.obs.anomaly is not None:
                    # Demand-shape watch: served-QPS per flush window.
                    # A traffic cliff (deploy gone wrong upstream) or
                    # surge shifts this series and arms the recorder.
                    with self._counts:
                        served = self.queries_served
                    elapsed = max(time.time() - last_flush, 1e-9)
                    self.obs.anomaly.observe(
                        'qps',
                        (served - self._last_flush_queries) / elapsed)
                    self._last_flush_queries = served
                self.obs.flush()
                self._flush_capacity()
                if self.qtracer is not None:
                    self.qtracer.flush()
                last_flush = time.time()
        self.close()
        return 0

    def stop(self):
        self._stop.set()

    def close(self):
        if self.auditor is not None:
            # Finish the queued audits so the final quality.json and
            # gauges carry the complete account, then stop the thread.
            self.auditor.drain(timeout_s=30.0)
            self.auditor.close()
            if self.obs is not None:
                self.obs.set_gauge('audited_queries',
                                   self.auditor.audited)
        if self.qtracer is not None:
            self.qtracer.flush()
        if self.obs is not None:
            self.obs.flush()
            self._flush_capacity()
            self.obs.close()

    def _flush_capacity(self):
        """Persist the live capacity model as ``capacity.json`` so the
        recorded obs dir carries the utilization/saturation account
        (what ``obs.report`` summarizes and ``obs.diff``'s
        ``--max-utilization`` gate reads) — not just the live
        ``/status`` scrape."""
        if self.engine is not None and self.obs is not None:
            self.obs.write_artifact('capacity.json',
                                    self._capacity_status())


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog='python -m dgmc_tpu.serve',
        description='Online matching service: persistent query-serving '
                    'worker (ψ₁ corpus cache, warm AOT bucket '
                    'executables, shortlist→consensus rerank) with '
                    '/match mounted beside the live telemetry plane. '
                    'Run under --supervise for warm self-healing '
                    'restarts.')
    add_serve_args(parser)
    args = parser.parse_args(argv)
    if args.supervise:
        from dgmc_tpu.resilience.supervisor import supervise_cli
        return supervise_cli('dgmc_tpu.serve', args, argv,
                             ladder=('disable-fused',))
    if not args.obs_dir:
        raise SystemExit('serve: --obs-dir is required (the /match '
                         'plane and the latency account live there)')
    if args.obs_port is None:
        args.obs_port = 0
    service = ServeService(args).start()
    return service.serve_forever()
