"""Online matching service: the retrieval-then-rerank serving split.

The paper's two-stage matcher is exactly a serving architecture: ψ₁
node embeddings are a pure function of the graph and the checkpoint —
precomputable and cacheable for the whole target corpus — while only
the neighborhood-consensus refinement must run per query
(``efficiency.json``: consensus iterations dominate the step). This
package assembles the pieces PRs 6–14 built into a persistent process
that answers "match this query graph against the corpus":

- :mod:`~dgmc_tpu.serve.corpus` — the corpus index: ψ₁ embeddings for
  the target corpus computed ONCE from a checkpoint and persisted to
  disk under a sha256-checksummed manifest (the checkpoint layer's
  hardening applied to the serving cache), so a restarted worker skips
  the recompute entirely — the warm-restart story.
- :mod:`~dgmc_tpu.serve.router` — padding-bucket query routing on the
  SAME :func:`~dgmc_tpu.analysis.recompile.bucket_signature` hash the
  recompile lint keys on: declared buckets get warm AOT-compiled
  executables at startup; an unfittable query is a structured 4xx,
  never an inline compile (RCP201/202 as latency-SLO guards).
- :mod:`~dgmc_tpu.serve.engine` — per-bucket AOT executables: ψ₁ on
  the query, top-k shortlist against the cached corpus table (device-
  resident, streamed, or host-RAM offloaded through
  :func:`~dgmc_tpu.ops.offload.offloaded_corpus_topk`), consensus
  rerank on the shortlist; bit-identical answers across repeats and
  across the corpus-placement tiers.
- :mod:`~dgmc_tpu.serve.service` — the worker process: ``/match``
  mounted beside the live plane's ``/healthz``/``/metrics``/``/status``
  (:mod:`dgmc_tpu.obs.live`), per-query latency streamed into the
  Prometheus histogram, supervised restarts via
  ``python -m dgmc_tpu.serve --supervise``
  (:mod:`dgmc_tpu.resilience.supervisor`) restarting **warm** from the
  on-disk embedding cache.
- :mod:`~dgmc_tpu.serve.client` — query sampling + HTTP/endpoint-
  discovery helpers shared by ``serve_bench.py``, the CI serve-smoke
  job and the tests.

Evidence rounds land as ``benchmarks/SERVE_r*.json`` (rendered by
``python -m dgmc_tpu.obs.timeline``) the way training rounds record
``BENCH_*``/``SCALE_*``.
"""

from dgmc_tpu.serve.corpus import Corpus, CorpusIndex, synthetic_corpus
from dgmc_tpu.serve.engine import MatchEngine
from dgmc_tpu.serve.router import (QueryRouter, UnknownBucketError,
                                   parse_buckets)
from dgmc_tpu.serve.service import ServeService, add_serve_args

__all__ = ['Corpus', 'CorpusIndex', 'synthetic_corpus', 'MatchEngine',
           'QueryRouter', 'UnknownBucketError', 'parse_buckets',
           'ServeService', 'add_serve_args']
