"""Corpus index: ψ₁ embeddings computed once, cached to disk, verified.

The serving split's precompute half. A :class:`Corpus` is the host-side
target graph (entity features + edges); a :class:`CorpusIndex` is that
graph plus its ψ₁ embedding table ``h_t [1, N_t, C]`` under a specific
checkpoint. The table is a pure function of ``(corpus, ψ₁ params)``, so
it is computed ONCE and persisted under a sha256-checksummed manifest
(the same tmp+rename / hash-every-file discipline
``train/checkpoint.py`` applies to checkpoints): a restarted worker
re-hashes the cache against the manifest AND matches the recorded
corpus/parameter fingerprints before trusting it, so a cache from a
different checkpoint, a different corpus, or a torn write is rebuilt —
never silently served.

The embedding forward runs through the model's own ψ₁ module
(``model.psi_1.apply`` on the ``psi_1`` parameter subtree), so the
cached table is bit-identical to what an end-to-end
:meth:`~dgmc_tpu.models.DGMC.__call__` would compute in-graph
(tests/serve/test_engine.py pins this transitively: cached-h_t answers
equal full-forward answers).
"""

import dataclasses
import hashlib
import json
import os
import time
from typing import Optional

import numpy as np

from dgmc_tpu.utils.io import sha256_file, write_json_atomic

__all__ = ['Corpus', 'CorpusIndex', 'synthetic_corpus', 'CACHE_MANIFEST',
           'CACHE_TABLE']

#: Cache directory contents: the embedding table and its manifest.
CACHE_TABLE = 'h_t.npy'
CACHE_MANIFEST = 'manifest.json'


def _sha256_bytes(*chunks):
    h = hashlib.sha256()
    for c in chunks:
        h.update(c)
    return h.hexdigest()


@dataclasses.dataclass
class Corpus:
    """Host-side target corpus: the graph queries are matched INTO."""
    x: np.ndarray          # [N_t, C] float32 entity features
    senders: np.ndarray    # [E_t] int32
    receivers: np.ndarray  # [E_t] int32

    @property
    def num_nodes(self):
        return self.x.shape[0]

    @property
    def num_edges(self):
        return self.senders.shape[0]

    @property
    def feat_dim(self):
        return self.x.shape[1]

    def fingerprint(self):
        """Content hash of the corpus arrays (shape-delimited so two
        different-shape corpora can never collide by concatenation)."""
        return _sha256_bytes(
            repr((self.x.shape, self.senders.shape)).encode(),
            np.ascontiguousarray(self.x).tobytes(),
            np.ascontiguousarray(self.senders.astype(np.int32)).tobytes(),
            np.ascontiguousarray(
                self.receivers.astype(np.int32)).tobytes())

    def graph_batch(self, dummy_x=True):
        """The ``GraphBatch`` target side of every serve executable.

        ``dummy_x=True`` (the serving default) ships a width-1 zero
        feature array: with a precomputed ``h_t`` the model never reads
        ``graph_t.x``, so the raw corpus features stay off the device —
        the matching stage's device residents are the edge structure
        and the embedding table only.
        """
        from dgmc_tpu.ops.graph import GraphBatch
        n, e = self.num_nodes, self.num_edges
        x = (np.zeros((1, n, 1), np.float32) if dummy_x
             else self.x[None].astype(np.float32))
        return GraphBatch(
            x=x,
            senders=self.senders[None].astype(np.int32),
            receivers=self.receivers[None].astype(np.int32),
            node_mask=np.ones((1, n), bool),
            edge_mask=np.ones((1, e), bool))


def synthetic_corpus(num_nodes, num_edges, dim, seed=0):
    """Unit-norm-feature synthetic corpus (the
    :func:`~dgmc_tpu.data.synthetic.synthetic_kg_alignment` feature
    scale, so ψ₁ similarity logits stay in the trainable regime)."""
    rng = np.random.RandomState(seed)
    x = (rng.randn(num_nodes, dim) / np.sqrt(dim)).astype(np.float32)
    snd = rng.randint(0, num_nodes, num_edges).astype(np.int32)
    rcv = rng.randint(0, num_nodes, num_edges).astype(np.int32)
    return Corpus(x=x, senders=snd, receivers=rcv)


def params_fingerprint(params):
    """Content hash of a parameter subtree (leaf paths + bytes): the
    cache-invalidation key tying a corpus cache to the exact checkpoint
    weights that produced it."""
    import jax
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    h = hashlib.sha256()
    for path, leaf in leaves:
        h.update(jax.tree_util.keystr(path).encode())
        arr = np.asarray(leaf)
        h.update(repr((arr.shape, str(arr.dtype))).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


@dataclasses.dataclass
class CorpusIndex:
    """A corpus plus its ψ₁ embedding table under one checkpoint."""
    corpus: Corpus
    h_t: np.ndarray                 # [1, N_t, C_out] float32
    meta: dict

    @property
    def embed_dim(self):
        return self.h_t.shape[-1]


def compute_embeddings(psi_1, psi_1_params, corpus, batch_stats=None):
    """``h_t = ψ₁(corpus)`` through the model's own backbone module on
    its parameter subtree — the table an in-graph forward would build."""
    variables = {'params': psi_1_params}
    if batch_stats:
        variables['batch_stats'] = batch_stats
    g = corpus.graph_batch(dummy_x=False)
    h = psi_1.apply(variables, g.x, g, train=False)
    return np.asarray(h, dtype=np.float32)


def write_cache(cache_dir, index):
    """Persist ``h_t`` + manifest atomically (tmp+rename both)."""
    os.makedirs(cache_dir, exist_ok=True)
    table_path = os.path.join(cache_dir, CACHE_TABLE)
    tmp = table_path + '.tmp'
    with open(tmp, 'wb') as f:
        np.save(f, index.h_t)
    os.replace(tmp, table_path)
    manifest = dict(index.meta)
    manifest['files'] = {CACHE_TABLE: {
        'sha256': sha256_file(table_path),
        'bytes': os.path.getsize(table_path)}}
    write_json_atomic(os.path.join(cache_dir, CACHE_MANIFEST), manifest,
                      indent=1, sort_keys=True)
    return table_path


def load_cache(cache_dir, corpus_fp, params_fp):
    """``(h_t, meta)`` when the cache verifies, else ``(None, reason)``.

    Verification is three-layered: the manifest must parse, every
    manifested file must re-hash to its recorded sha256/size (a torn or
    bit-flipped table is a rebuild, not a crash — and never a silently
    wrong answer), and the recorded corpus/params fingerprints must
    match the CURRENT corpus and checkpoint (a cache from yesterday's
    weights is stale, not corrupt — same outcome)."""
    mpath = os.path.join(cache_dir, CACHE_MANIFEST)
    try:
        with open(mpath) as f:
            meta = json.load(f)
    except FileNotFoundError:
        return None, 'no-manifest'
    except (OSError, ValueError) as e:
        return None, f'manifest-unreadable:{type(e).__name__}'
    if meta.get('corpus_fingerprint') != corpus_fp:
        return None, 'corpus-mismatch'
    if meta.get('params_fingerprint') != params_fp:
        return None, 'params-mismatch'
    for rel, want in (meta.get('files') or {}).items():
        p = os.path.join(cache_dir, rel)
        if not os.path.isfile(p):
            return None, f'missing:{rel}'
        if os.path.getsize(p) != want.get('bytes'):
            return None, f'size-mismatch:{rel}'
        if sha256_file(p) != want.get('sha256'):
            return None, f'sha256-mismatch:{rel}'
    try:
        h_t = np.load(os.path.join(cache_dir, CACHE_TABLE))
    except (OSError, ValueError) as e:
        return None, f'table-unreadable:{type(e).__name__}'
    return h_t, meta


def load_or_build(cache_dir, psi_1, psi_1_params, corpus,
                  batch_stats=None, checkpoint_step: Optional[int] = None,
                  log=None):
    """The worker's startup path: verified cache hit, or build + persist.

    Returns ``(CorpusIndex, info)`` where ``info`` carries the
    warm/cold evidence the restart measurements key on:
    ``{'cache': 'hit' | 'miss:<reason>', 'seconds': <load or build>}``.
    """
    corpus_fp = corpus.fingerprint()
    params_fp = params_fingerprint(psi_1_params)
    t0 = time.perf_counter()
    if cache_dir:
        h_t, meta_or_reason = load_cache(cache_dir, corpus_fp, params_fp)
        if h_t is not None:
            info = {'cache': 'hit',
                    'seconds': round(time.perf_counter() - t0, 3)}
            if log:
                log(f'corpus cache HIT: {cache_dir} '
                    f'({h_t.nbytes >> 20} MiB ψ₁ table verified in '
                    f'{info["seconds"]:.3f}s; recompute skipped)')
            return CorpusIndex(corpus, h_t, meta_or_reason), info
        reason = meta_or_reason
    else:
        reason = 'disabled'
    h_t = compute_embeddings(psi_1, psi_1_params, corpus,
                             batch_stats=batch_stats)
    build_s = round(time.perf_counter() - t0, 3)
    meta = {
        'version': 1,
        'corpus_fingerprint': corpus_fp,
        'params_fingerprint': params_fp,
        'checkpoint_step': checkpoint_step,
        'shape': list(h_t.shape),
        'dtype': str(h_t.dtype),
        'built_unix': round(time.time(), 3),
        'build_s': build_s,
    }
    index = CorpusIndex(corpus, h_t, meta)
    if cache_dir:
        write_cache(cache_dir, index)
    info = {'cache': f'miss:{reason}', 'seconds': build_s}
    if log:
        log(f'corpus cache MISS ({reason}): built {h_t.nbytes >> 20} '
            f'MiB ψ₁ table in {build_s:.3f}s'
            + (f', persisted to {cache_dir}' if cache_dir else ''))
    return index, info
