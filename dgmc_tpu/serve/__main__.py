"""``python -m dgmc_tpu.serve`` — the online matching service CLI."""

import sys

from dgmc_tpu.serve.service import main

if __name__ == '__main__':
    sys.exit(main())
