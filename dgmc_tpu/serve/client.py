"""Client-side helpers: query sampling, HTTP, endpoint discovery.

Shared by ``serve_bench.py``, the CI serve-smoke job and the tests so
the load driver, the smoke assertions and the determinism pins all
speak the exact same wire format. jax-free: a load client must not pay
a backend bring-up to POST JSON.
"""

import json
import os
import time
import urllib.error
import urllib.request

import numpy as np

__all__ = ['sample_query', 'query_payload', 'post_match', 'get_json',
           'discover_endpoint', 'confidence_of']


def sample_query(corpus_x, num_nodes, num_edges, seed=0, noise=0.6):
    """One synthetic query against a corpus feature table.

    Picks ``num_nodes`` random corpus entities, emits variance-
    preserving noisy copies of their features (the
    ``synthetic_kg_alignment`` blend, so a trained ψ₁ can actually
    find them) plus random edges among the picked nodes. Returns
    ``(Graph, gt)`` where ``gt[i]`` is the corpus index query node
    ``i`` was sampled from — the label the bench scores hits against.
    """
    from dgmc_tpu.utils.data import Graph
    rng = np.random.RandomState(seed)
    n_t, dim = corpus_x.shape
    picks = rng.choice(n_t, size=num_nodes, replace=False)
    sigma = rng.uniform(0.2, noise, (num_nodes, 1)).astype(np.float32)
    eps = (rng.randn(num_nodes, dim) / np.sqrt(dim)).astype(np.float32)
    x = ((corpus_x[picks] + sigma * eps)
         / np.sqrt(1.0 + sigma ** 2)).astype(np.float32)
    snd = rng.randint(0, num_nodes, num_edges)
    rcv = rng.randint(0, num_nodes, num_edges)
    g = Graph(edge_index=np.stack([snd, rcv]).astype(np.int64), x=x)
    return g, picks.astype(np.int64)


def query_payload(graph):
    """The ``/match`` POST body for a host ``Graph``."""
    return {'nodes': np.asarray(graph.x).tolist(),
            'edges': np.asarray(graph.edge_index).T.tolist()}


def post_match(port, payload, host='127.0.0.1', timeout_s=60.0,
               traceparent=None, qtrace=None):
    """POST one query; returns ``(status_code, response_dict)`` or
    ``None`` when the endpoint is unreachable.

    ``traceparent`` propagates a W3C trace context to the worker (the
    server echoes the id back — in the payload's ``trace_id`` and the
    response ``traceparent`` header, surfaced as
    ``response['server_traceparent']``). ``qtrace=False`` sends
    ``x-qtrace: off``, opting this one request out of tracing (the
    bench's overhead-measurement path). The client-observed wall time
    is attached as ``response['client_ms']`` so callers can account
    client-vs-server latency skew per query: ``client_ms`` minus the
    server's ``trace_ms`` is the wire + HTTP + JSON overhead the
    server-side span tree cannot see."""
    body = json.dumps(payload).encode('utf-8')
    headers = {'Content-Type': 'application/json'}
    if traceparent:
        headers['traceparent'] = traceparent
    if qtrace is False:
        headers['x-qtrace'] = 'off'
    req = urllib.request.Request(
        f'http://{host}:{int(port)}/match', data=body,
        headers=headers, method='POST')
    t0 = time.perf_counter()
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            out = json.loads(resp.read().decode('utf-8'))
            code = resp.status
            echoed = resp.headers.get('traceparent')
    except urllib.error.HTTPError as e:
        try:
            out = json.loads(e.read().decode('utf-8'))
        except Exception:
            out = {}
        code = e.code
        echoed = e.headers.get('traceparent') if e.headers else None
    except Exception:
        return None
    if isinstance(out, dict):
        out['client_ms'] = round((time.perf_counter() - t0) * 1e3, 3)
        if echoed:
            out['server_traceparent'] = echoed
    return code, out


def confidence_of(response):
    """The per-query confidence block of a ``/match`` answer.

    Successful answers carry a ``quality`` dict beside ``stages_ms`` —
    the engine's in-graph proxies (``entropy``, ``margin``,
    ``correction``, ``saturation``, ``saturated_frac``; see the serve
    docs for semantics). Returns ``{}`` for errors and for answers from
    servers predating the quality plane, so callers can always iterate
    it."""
    if not isinstance(response, dict):
        return {}
    quality = response.get('quality')
    return dict(quality) if isinstance(quality, dict) else {}


def get_json(port, path, host='127.0.0.1', timeout_s=10.0):
    """GET a JSON (or text) endpoint; ``(code, payload)`` or ``None``."""
    url = f'http://{host}:{int(port)}{path}'
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            body = resp.read().decode('utf-8')
            code = resp.status
    except urllib.error.HTTPError as e:
        code = e.code
        try:
            body = e.read().decode('utf-8')
        except Exception:
            return None
    except Exception:
        return None
    try:
        return code, json.loads(body)
    except ValueError:
        return code, body


def discover_endpoint(obs_root, timeout_s=0.0, poll_s=0.25):
    """Find the serving worker's live endpoint from heartbeat files.

    Scans ``obs_root`` and its ``attempt_*/`` children (the supervisor's
    per-attempt layout) for the freshest ``heartbeat.json`` advertising
    a ``port`` — the SAME discovery the supervisor's /healthz watch
    uses, so a worker whose plane moved to an ephemeral port (the
    port-in-use retry) is found at its real address. Returns
    ``(host, port, pid)`` or ``None`` after ``timeout_s``.
    """
    deadline = time.time() + timeout_s

    def scan():
        best = None
        dirs = [obs_root]
        try:
            dirs += [os.path.join(obs_root, d)
                     for d in os.listdir(obs_root)
                     if d.startswith('attempt_')]
        except OSError:
            pass
        for d in dirs:
            path = os.path.join(d, 'heartbeat.json')
            try:
                with open(path) as f:
                    hb = json.load(f)
            except (OSError, ValueError):
                continue
            if not hb.get('port'):
                continue
            if best is None or hb.get('time', 0) > best[0]:
                best = (hb.get('time', 0), hb)
        if best is None:
            return None
        hb = best[1]
        return (hb.get('host') or '127.0.0.1', int(hb['port']),
                hb.get('pid'))

    while True:
        found = scan()
        if found is not None or time.time() >= deadline:
            return found
        time.sleep(poll_s)
