"""Top-k correspondence candidates without materializing the score matrix.

The reference relies on KeOps ``LazyTensor.argKmin`` to stream the
``N_s x N_t`` similarity scan (reference ``dgmc/models/dgmc.py:85-94``), with
a dense ``topk`` fallback. The TPU-native equivalent is a blockwise scan:
tile the target axis, compute one ``[B, N_s, block]`` score tile at a time on
the MXU, and carry a running top-k per source row — the same
row-statistics-carry trick flash-attention uses. HBM footprint is
``O(N_s * (k + block))`` instead of ``O(N_s * N_t)``.

Tie-breaking matches the dense path exactly: ``jax.lax.top_k`` prefers lower
positions on equal values, and the running carry is concatenated *before*
each new tile, so earlier target indices always win ties — identical to
``dense_topk`` on the full matrix.
"""

import functools

import jax
import jax.numpy as jnp


def dense_topk(h_s, h_t, k, t_mask=None):
    """Reference-semantics top-k over the fully materialized score matrix.

    h_s: ``[B, N_s, C]``, h_t: ``[B, N_t, C]`` → indices ``[B, N_s, k]`` of
    the k largest inner products per source row. Invalid target columns
    (``t_mask`` False) are pushed to the bottom of the ranking.
    """
    scores = jnp.einsum('bsc,btc->bst', h_s, h_t)
    if t_mask is not None:
        neg = jnp.finfo(scores.dtype).min
        scores = jnp.where(t_mask[:, None, :], scores, neg)
    return jax.lax.top_k(scores, k)[1]


@functools.partial(jax.jit, static_argnames=('k', 'block', 'return_values'))
def chunked_topk(h_s, h_t, k, t_mask=None, block=1024, return_values=False):
    """Blockwise running top-k of ``h_s @ h_t^T`` along the target axis.

    Produces indices identical to :func:`dense_topk` (including tie order)
    while only ever holding one ``[B, N_s, block]`` score tile. With
    ``return_values`` the running scores come back too (``(vals, idx)``) —
    used by the distributed column-sharded merge.
    """
    B, N_s, C = h_s.shape
    N_t = h_t.shape[1]
    if t_mask is None:
        t_mask = jnp.ones((B, N_t), dtype=bool)

    pad = (-N_t) % block
    if pad:
        h_t = jnp.pad(h_t, ((0, 0), (0, pad), (0, 0)))
        t_mask = jnp.pad(t_mask, ((0, 0), (0, pad)))
    num_blocks = h_t.shape[1] // block

    h_t_blocks = h_t.reshape(B, num_blocks, block, C).transpose(1, 0, 2, 3)
    m_blocks = t_mask.reshape(B, num_blocks, block).transpose(1, 0, 2)

    neg = jnp.finfo(h_s.dtype).min
    # Carry starts at true -inf, strictly below the finfo.min used for masked
    # candidates, so even fully-masked columns rank by index order exactly as
    # in dense_topk (matters only when k exceeds the valid target count).
    init_vals = jnp.full((B, N_s, k), -jnp.inf, dtype=h_s.dtype)
    init_idx = jnp.zeros((B, N_s, k), dtype=jnp.int32)
    # Under shard_map the scan body output varies over the manual mesh axes
    # of h_s; the constant init carry must carry the same varying type.
    vma = tuple(jax.typeof(h_s).vma)
    if vma:
        init_vals = jax.lax.pcast(init_vals, vma, to='varying')
        init_idx = jax.lax.pcast(init_idx, vma, to='varying')

    def step(carry, inp):
        vals, idx = carry
        ht_b, m_b, start = inp
        scores = jnp.einsum('bsc,btc->bst', h_s, ht_b)
        scores = jnp.where(m_b[:, None, :], scores, neg)
        cand_idx = (start + jnp.arange(block, dtype=jnp.int32))
        cand_idx = jnp.broadcast_to(cand_idx, (B, N_s, block))
        # Carry first: on ties, earlier (lower-index) entries win, matching
        # lax.top_k over the full matrix.
        all_vals = jnp.concatenate([vals, scores], axis=-1)
        all_idx = jnp.concatenate([idx, cand_idx], axis=-1)
        new_vals, pos = jax.lax.top_k(all_vals, k)
        new_idx = jnp.take_along_axis(all_idx, pos, axis=-1)
        return (new_vals, new_idx), None

    starts = jnp.arange(num_blocks, dtype=jnp.int32) * block
    (vals, idx), _ = jax.lax.scan(step, (init_vals, init_idx),
                                  (h_t_blocks, m_blocks, starts))
    if return_values:
        return vals, idx
    return idx
