"""Top-k correspondence candidates without materializing the score matrix.

The reference relies on KeOps ``LazyTensor.argKmin`` to stream the
``N_s x N_t`` similarity scan (reference ``dgmc/models/dgmc.py:85-94``), with
a dense ``topk`` fallback. The TPU-native equivalent is a blockwise scan:
tile the target axis, compute one ``[B, N_s, block]`` score tile at a time on
the MXU, and carry a running top-k per source row — the same
row-statistics-carry trick flash-attention uses. HBM footprint is
``O(N_s * (k + block))`` instead of ``O(N_s * N_t)``.

Per tile, the k best entries are extracted by **k rounds of (argmax,
mask-out)** — O(k·block) cheap VPU work — rather than a ``lax.top_k`` sort
of the whole tile; the tile's k survivors then merge with the running carry
through one tiny ``top_k`` over ``2k``. Raced on-chip at DBP15K scale
(15000x20000, C=256, k=10) this is 2.5x the sort formulation: 86 ms vs
211 ms per call at block=1024 (``benchmarks/topk_tpu.json``,
``benchmarks/topk_bench.py``).

Tie-breaking matches the dense path exactly: ``argmax`` takes the *first*
maximum (lowest target index, the ``lax.top_k`` rule), and the merge
concatenates the running carry *before* the tile survivors, so earlier
target indices always win ties — bit-identical to ``dense_topk`` on the
full matrix, which the dense≡sparse(k=N) contract tests rely on.
"""

import functools

import jax
import jax.numpy as jnp


def dense_topk(h_s, h_t, k, t_mask=None):
    """Reference-semantics top-k over the fully materialized score matrix.

    h_s: ``[B, N_s, C]``, h_t: ``[B, N_t, C]`` → indices ``[B, N_s, k]`` of
    the k largest inner products per source row. Invalid target columns
    (``t_mask`` False) are pushed to the bottom of the ranking.
    """
    scores = jnp.einsum('bsc,btc->bst', h_s, h_t)
    if t_mask is not None:
        neg = jnp.finfo(scores.dtype).min
        scores = jnp.where(t_mask[:, None, :], scores, neg)
    return jax.lax.top_k(scores, k)[1]


def chunked_topk(h_s, h_t, k, t_mask=None, block=256, return_values=False,
                 pallas=None, dispatch_reason='explicit'):
    """Blockwise running top-k of ``h_s @ h_t^T`` along the target axis.

    Produces indices identical to :func:`dense_topk` (including tie order)
    while only ever holding one ``[B, N_s, block]`` score tile. With
    ``return_values`` the running scores come back too (``(vals, idx)``) —
    used by the distributed column-sharded merge. The default ``block``
    follows the on-chip sweep at DBP15K scale (bench.py ``topk_ms``:
    17.7 / 21.1 / 24.8 ms at 256 / 1024 / 4096), which only matters where
    the Pallas kernel doesn't apply (off-TPU / GSPMD; the kernel ignores
    ``block``).

    The candidate search is pure *selection* and is non-differentiable by
    design on every path (the reference uses KeOps ``argKmin`` outside
    autograd the same way, reference ``dgmc/models/dgmc.py:85-94``);
    gradients flow through the differentiable re-gather of the selected
    rows, never through the search.

    ``pallas=None`` auto-dispatches to the VMEM-resident Pallas kernel
    (:mod:`dgmc_tpu.ops.pallas.topk`) on TPU — 21 ms vs 82 ms for this
    scan at 15000x20000 — results are bit-identical either way. The
    kernel is shard-local, so the auto path stays ON inside
    ``shard_map`` manual mode (the kernel declares its varying-manual-axes
    type; ``parallel/topk.py`` row/col sharding runs it per shard). Pass
    ``pallas=False`` inside GSPMD auto-partitioned programs only
    (``pallas_call`` has no GSPMD partitioning rule;
    :class:`~dgmc_tpu.models.DGMC` does this when ``corr_sharding`` is
    set).

    The auto decision is resolved *here*, in an un-jitted wrapper, and
    passed down as a static arg: it reads a trace-time contextvar
    (:func:`~dgmc_tpu.ops.pallas.dispatch.fused_kernels_allowed`) that a
    nested ``jax.jit`` cache would otherwise bake into a cached jaxpr and
    never consult again.
    """
    from dgmc_tpu.ops.pallas import dispatch
    from dgmc_tpu.ops.pallas.topk import BLOCK_T
    if pallas is None:
        pallas = dispatch.auto_fused('topk', size_ok=k <= BLOCK_T,
                                     size_reason=f'k>{BLOCK_T}')
    else:
        # The kernel itself still requires k <= BLOCK_T (the jitted body
        # silently falls back otherwise) — record what actually runs.
        # ``dispatch_reason`` lets an orchestrator that forces the path
        # label WHY (DGMC passes 'gspmd-silenced' under corr_sharding);
        # a plain user-passed flag stays 'explicit'.
        taken = bool(pallas) and k <= BLOCK_T
        dispatch.record_dispatch(
            'topk', 'pallas' if taken else 'fallback',
            dispatch_reason if taken == bool(pallas) else f'k>{BLOCK_T}')
    return _chunked_topk(h_s, h_t, k, t_mask, block, return_values,
                         bool(pallas))


@functools.partial(jax.jit,
                   static_argnames=('k', 'block', 'return_values', 'pallas'))
def _chunked_topk(h_s, h_t, k, t_mask, block, return_values, pallas):
    h_s = jax.lax.stop_gradient(h_s)
    h_t = jax.lax.stop_gradient(h_t)
    B, N_s, C = h_s.shape
    if pallas:
        from dgmc_tpu.ops.pallas.topk import BLOCK_T, pallas_topk
        if k <= BLOCK_T:
            return pallas_topk(h_s, h_t, k, t_mask=t_mask,
                               return_values=return_values)
    N_t = h_t.shape[1]
    if t_mask is None:
        t_mask = jnp.ones((B, N_t), dtype=bool)

    pad = (-N_t) % block
    if pad:
        h_t = jnp.pad(h_t, ((0, 0), (0, pad), (0, 0)))
        t_mask = jnp.pad(t_mask, ((0, 0), (0, pad)))
    num_blocks = h_t.shape[1] // block

    h_t_blocks = h_t.reshape(B, num_blocks, block, C).transpose(1, 0, 2, 3)
    m_blocks = t_mask.reshape(B, num_blocks, block).transpose(1, 0, 2)

    neg = jnp.finfo(h_s.dtype).min
    # Carry starts at true -inf, strictly below the finfo.min used for masked
    # candidates, so even fully-masked columns rank by index order exactly as
    # in dense_topk (matters only when k exceeds the valid target count).
    init_vals = jnp.full((B, N_s, k), -jnp.inf, dtype=h_s.dtype)
    init_idx = jnp.zeros((B, N_s, k), dtype=jnp.int32)
    # Under shard_map the scan body output varies over the manual mesh axes
    # of h_s; the constant init carry must carry the same varying type.
    from dgmc_tpu.ops.pallas.dispatch import vma_of
    vma = tuple(vma_of(h_s))
    if vma:
        init_vals = jax.lax.pcast(init_vals, vma, to='varying')
        init_idx = jax.lax.pcast(init_idx, vma, to='varying')

    kk = min(k, block)
    cols = jnp.arange(block, dtype=jnp.int32)

    def tile_topk(scores):
        """k rounds of (argmax, mask-out): the tile's k best, sorted desc
        with lowest-index tie preference (exactly lax.top_k's order) at
        O(k*block) VPU cost instead of a sort."""
        def one(s, _):
            p = jnp.argmax(s, axis=-1)
            v = jnp.take_along_axis(s, p[..., None], axis=-1)[..., 0]
            s = jnp.where(cols == p[..., None], -jnp.inf, s)
            return s, (v, p)

        _, (tv, tp) = jax.lax.scan(one, scores, None, length=kk)
        return jnp.moveaxis(tv, 0, -1), jnp.moveaxis(tp, 0, -1)

    def step(carry, inp):
        vals, idx = carry
        ht_b, m_b, start = inp
        scores = jnp.einsum('bsc,btc->bst', h_s, ht_b)
        scores = jnp.where(m_b[:, None, :], scores, neg)
        tile_vals, tile_pos = tile_topk(scores)
        tile_idx = start + tile_pos.astype(jnp.int32)
        # Carry first: on ties, earlier (lower-index) entries win, matching
        # lax.top_k over the full matrix.
        all_vals = jnp.concatenate([vals, tile_vals], axis=-1)
        all_idx = jnp.concatenate([idx, tile_idx], axis=-1)
        new_vals, pos = jax.lax.top_k(all_vals, k)
        new_idx = jnp.take_along_axis(all_idx, pos, axis=-1)
        return (new_vals, new_idx), None

    starts = jnp.arange(num_blocks, dtype=jnp.int32) * block
    (vals, idx), _ = jax.lax.scan(step, (init_vals, init_idx),
                                  (h_t_blocks, m_blocks, starts))
    if return_values:
        return vals, idx
    return idx
