"""Top-k correspondence candidates without materializing the score matrix.

The reference relies on KeOps ``LazyTensor.argKmin`` to stream the
``N_s x N_t`` similarity scan (reference ``dgmc/models/dgmc.py:85-94``), with
a dense ``topk`` fallback. The TPU-native equivalent is a blockwise scan:
tile the target axis, compute one ``[B, N_s, block]`` score tile at a time on
the MXU, and carry a running top-k per source row — the same
row-statistics-carry trick flash-attention uses. HBM footprint is
``O(N_s * (k + block))`` instead of ``O(N_s * N_t)``.

Per tile, the k best entries are extracted by **k rounds of (argmax,
mask-out)** on TPU — O(k·block) cheap VPU work — rather than a
``lax.top_k`` sort of the whole tile; the tile's k survivors then merge
with the running carry through one tiny ``top_k`` over ``2k``. Raced
on-chip at DBP15K scale (15000x20000, C=256, k=10) this is 2.5x the sort
formulation: 86 ms vs 211 ms per call at block=1024
(``benchmarks/topk_tpu.json``, ``benchmarks/topk_bench.py``). On CPU the
cost model inverts — the rounds run near-scalar — so the extractor is
backend-conditional (bit-identical either way; see ``tile_topk``).

Tie-breaking matches the dense path exactly: ``argmax`` takes the *first*
maximum (lowest target index, the ``lax.top_k`` rule), and the merge
concatenates the running carry *before* the tile survivors, so earlier
target indices always win ties — bit-identical to ``dense_topk`` on the
full matrix, which the dense≡sparse(k=N) contract tests rely on.
"""

import functools

import jax
import jax.numpy as jnp

#: One measured default for every blockwise-scan path: the r03 on-chip
#: sweep at DBP15K scale timed 17.7 / 21.1 / 24.8 ms at block 256 / 1024 /
#: 4096 (bench.py ``topk_ms``; benchmarks/DISPATCH_DEFAULTS.md), and the
#: smaller tile also has the lower peak tile memory. The Pallas kernel
#: ignores the knob entirely.  ``dgmc_tpu/parallel/rules.py`` re-exports
#: this as ``DEFAULT_TOPK_BLOCK`` so sharded callsites thread it from the
#: partition-rule config instead of per-callsite literals.
DEFAULT_BLOCK = 256

#: Per-tile extractor override: ``None`` = auto by backend (sort form on
#: CPU, argmax rounds on TPU — see ``tile_topk`` in ``_chunked_topk``);
#: ``True``/``False`` force one form (tests pin the two forms equal on
#: the same backend).
TILE_SORT = None


def dense_topk(h_s, h_t, k, t_mask=None):
    """Reference-semantics top-k over the fully materialized score matrix.

    h_s: ``[B, N_s, C]``, h_t: ``[B, N_t, C]`` → indices ``[B, N_s, k]`` of
    the k largest inner products per source row. Invalid target columns
    (``t_mask`` False) are pushed to the bottom of the ranking.
    """
    scores = jnp.einsum('bsc,btc->bst', h_s, h_t)
    if t_mask is not None:
        neg = jnp.finfo(scores.dtype).min
        scores = jnp.where(t_mask[:, None, :], scores, neg)
    return jax.lax.top_k(scores, k)[1]


def chunked_topk(h_s, h_t, k, t_mask=None, block=DEFAULT_BLOCK,
                 return_values=False, pallas=None,
                 dispatch_reason='explicit'):
    """Blockwise running top-k of ``h_s @ h_t^T`` along the target axis.

    Produces indices identical to :func:`dense_topk` (including tie order)
    while only ever holding one ``[B, N_s, block]`` score tile. With
    ``return_values`` the running scores come back too (``(vals, idx)``) —
    used by the distributed column-sharded merge. The default ``block``
    follows the on-chip sweep at DBP15K scale (bench.py ``topk_ms``:
    17.7 / 21.1 / 24.8 ms at 256 / 1024 / 4096), which only matters where
    the Pallas kernel doesn't apply (off-TPU / GSPMD; the kernel ignores
    ``block``).

    The candidate search is pure *selection* and is non-differentiable by
    design on every path (the reference uses KeOps ``argKmin`` outside
    autograd the same way, reference ``dgmc/models/dgmc.py:85-94``);
    gradients flow through the differentiable re-gather of the selected
    rows, never through the search.

    ``pallas=None`` auto-dispatches to the VMEM-resident Pallas kernel
    (:mod:`dgmc_tpu.ops.pallas.topk`) on TPU — 21 ms vs 82 ms for this
    scan at 15000x20000 — results are bit-identical either way. The
    kernel is shard-local, so the auto path stays ON inside
    ``shard_map`` manual mode (the kernel declares its varying-manual-axes
    type; ``parallel/topk.py`` row/col sharding runs it per shard). Pass
    ``pallas=False`` inside GSPMD auto-partitioned programs only
    (``pallas_call`` has no GSPMD partitioning rule;
    :class:`~dgmc_tpu.models.DGMC` does this when ``corr_sharding`` is
    set).

    The auto decision is resolved *here*, in an un-jitted wrapper, and
    passed down as a static arg: it reads a trace-time contextvar
    (:func:`~dgmc_tpu.ops.pallas.dispatch.fused_kernels_allowed`) that a
    nested ``jax.jit`` cache would otherwise bake into a cached jaxpr and
    never consult again.
    """
    pallas = _resolve_dispatch(pallas, k, dispatch_reason)
    sort_tiles = _tile_sort()

    def core(hs, ht, tm):
        return _chunked_topk(hs, ht, k, tm, block, return_values, pallas,
                             sort_tiles)

    return _ad_opaque(core, h_s, h_t, t_mask)


def _resolve_dispatch(pallas, k, dispatch_reason):
    """Shared Pallas dispatch resolution for the search wrappers: the
    auto decision (trace-time contextvar) or the caller's explicit flag,
    recorded in the dispatch ledger with the reason that actually
    applies. Resolved OUTSIDE the jit (see chunked_topk docstring)."""
    from dgmc_tpu.ops.pallas import dispatch
    from dgmc_tpu.ops.pallas.topk import BLOCK_T
    if pallas is None:
        pallas = dispatch.auto_fused('topk', size_ok=k <= BLOCK_T,
                                     size_reason=f'k>{BLOCK_T}')
    else:
        # The kernel itself still requires k <= BLOCK_T (the jitted body
        # silently falls back otherwise) — record what actually runs.
        # ``dispatch_reason`` lets an orchestrator that forces the path
        # label WHY (DGMC passes 'gspmd-silenced' under corr_sharding);
        # a plain user-passed flag stays 'explicit'.
        taken = bool(pallas) and k <= BLOCK_T
        dispatch.record_dispatch(
            'topk', 'pallas' if taken else 'fallback',
            dispatch_reason if taken == bool(pallas) else f'k>{BLOCK_T}')
    return bool(pallas)


def _tile_sort():
    """Resolve the per-tile extractor OUTSIDE the jit (the override /
    backend check must not be baked into a cached jaxpr — same rule as
    the Pallas dispatch contextvar above)."""
    import jax as _jax
    return (_jax.default_backend() != 'tpu' if TILE_SORT is None
            else bool(TILE_SORT))


def _ad_opaque(core, *args):
    """Run the search as an AD-opaque primitive: the JVP returns the
    primal with (symbolic-float0 / zero) tangents WITHOUT tracing into
    the scan.

    The search is pure selection and non-differentiable by design (its
    inputs are stop_gradient'ed internally), but under ``value_and_grad``
    jax still *linearizes* the blockwise scan — and through the nested
    ``jit`` boundary the partial-eval conservatively stacks the tile
    select masks as loop residuals: a ``pred[num_blocks, B, rows,
    block]`` tensor, 2 GiB PER DEVICE at the streamed 10⁶-target shape
    (r7 buffer-assignment dump) backing a search whose real state is the
    ``[B, rows, k]`` carry. ``custom_jvp`` makes the non-differentiability
    structural, so no linearization of the scan exists to save."""
    import numpy as _np
    f = jax.custom_jvp(core)

    @f.defjvp
    def _jvp(primals, tangents):
        out = core(*primals)
        zeros = jax.tree.map(
            lambda o: (jnp.zeros_like(o)
                       if jnp.issubdtype(o.dtype, jnp.floating)
                       else _np.zeros(o.shape, jax.dtypes.float0)), out)
        return out, zeros

    # Belt and braces: sever the tangents BEFORE the call too. With live
    # tangents entering, jax 0.4.37 still routes the call through the
    # jvp machinery and the nested-jit partial-eval stages the scan
    # conservatively (the residual-stacking this wrapper exists to
    # prevent); with stop_gradient'ed operands the custom call is pure
    # primal and the scan is never linearized. Gradients were never
    # meant to flow here — the search stop_gradients internally anyway.
    return f(*jax.tree.map(jax.lax.stop_gradient, args))


@functools.partial(jax.jit,
                   static_argnames=('k', 'block', 'return_values', 'pallas',
                                    'sort_tiles'))
def _chunked_topk(h_s, h_t, k, t_mask, block, return_values, pallas,
                  sort_tiles):
    h_s = jax.lax.stop_gradient(h_s)
    h_t = jax.lax.stop_gradient(h_t)
    B, N_s, C = h_s.shape
    if pallas:
        from dgmc_tpu.ops.pallas.topk import BLOCK_T, pallas_topk
        if k <= BLOCK_T:
            return pallas_topk(h_s, h_t, k, t_mask=t_mask,
                               return_values=return_values)
    N_t = h_t.shape[1]
    if t_mask is None:
        t_mask = jnp.ones((B, N_t), dtype=bool)

    pad = (-N_t) % block
    if pad:
        h_t = jnp.pad(h_t, ((0, 0), (0, pad), (0, 0)))
        t_mask = jnp.pad(t_mask, ((0, 0), (0, pad)))
    num_blocks = h_t.shape[1] // block

    h_t_blocks = h_t.reshape(B, num_blocks, block, C).transpose(1, 0, 2, 3)
    m_blocks = t_mask.reshape(B, num_blocks, block).transpose(1, 0, 2)

    neg = jnp.finfo(h_s.dtype).min
    # Carry starts at true -inf, strictly below the finfo.min used for masked
    # candidates, so even fully-masked columns rank by index order exactly as
    # in dense_topk (matters only when k exceeds the valid target count).
    init_vals = jnp.full((B, N_s, k), -jnp.inf, dtype=h_s.dtype)
    init_idx = jnp.zeros((B, N_s, k), dtype=jnp.int32)
    # Under shard_map the scan body output varies over the manual mesh axes
    # of h_s; the constant init carry must carry the same varying type.
    from dgmc_tpu.ops.pallas.dispatch import vma_of
    vma = tuple(vma_of(h_s))
    if vma:
        init_vals = jax.lax.pcast(init_vals, vma, to='varying')
        init_idx = jax.lax.pcast(init_idx, vma, to='varying')

    kk = min(k, block)
    cols = jnp.arange(block, dtype=jnp.int32)
    # Per-tile extractor, backend-conditional at trace time. The two forms
    # are BIT-IDENTICAL (the rounds form reproduces lax.top_k's
    # sorted-desc, lowest-index-tie order by construction) — only the
    # cost model differs, and it differs in opposite directions:
    # - TPU: k rounds of (argmax, mask-out) measured 2.5x the sort form
    #   (86 vs 211 ms at 15000x20000 k=10, benchmarks/topk_tpu.json) —
    #   O(k*block) cheap VPU work beats a tile sort.
    # - CPU (the fallback/virtual-device mesh path, where the streamed
    #   million-row sweep actually runs in CI and the scale bench): the
    #   argmax rounds run near-scalar and lose ~8x to one lax.top_k pass
    #   (40.1 vs 4.7 s for a 2048-row chunk against 2^20 targets at k=4,
    #   r7) — at 10^6x10^6 that is the difference between a 5-hour and a
    #   40-minute single-device sweep.

    def tile_topk(scores):
        if sort_tiles:
            return jax.lax.top_k(scores, kk)

        def one(s, _):
            p = jnp.argmax(s, axis=-1)
            v = jnp.take_along_axis(s, p[..., None], axis=-1)[..., 0]
            s = jnp.where(cols == p[..., None], -jnp.inf, s)
            return s, (v, p)

        _, (tv, tp) = jax.lax.scan(one, scores, None, length=kk)
        return jnp.moveaxis(tv, 0, -1), jnp.moveaxis(tp, 0, -1)

    def step(carry, inp):
        vals, idx = carry
        ht_b, m_b, start = inp
        scores = jnp.einsum('bsc,btc->bst', h_s, ht_b)
        scores = jnp.where(m_b[:, None, :], scores, neg)
        tile_vals, tile_pos = tile_topk(scores)
        tile_idx = start + tile_pos.astype(jnp.int32)
        # Carry first: on ties, earlier (lower-index) entries win, matching
        # lax.top_k over the full matrix.
        all_vals = jnp.concatenate([vals, tile_vals], axis=-1)
        all_idx = jnp.concatenate([idx, tile_idx], axis=-1)
        new_vals, pos = jax.lax.top_k(all_vals, k)
        new_idx = jnp.take_along_axis(all_idx, pos, axis=-1)
        return (new_vals, new_idx), None

    starts = jnp.arange(num_blocks, dtype=jnp.int32) * block
    (vals, idx), _ = jax.lax.scan(step, (init_vals, init_idx),
                                  (h_t_blocks, m_blocks, starts))
    if return_values:
        return vals, idx
    return idx


def streamed_topk(h_s, h_t, k, chunk, t_mask=None, block=DEFAULT_BLOCK,
                  return_values=False, pallas=None,
                  dispatch_reason='explicit'):
    """Source-node chunk-streamed top-k: :func:`chunked_topk` run as a
    ``lax.scan`` over chunks of source rows (``ops/blocked.py``-style).

    :func:`chunked_topk` streams the *target* axis but still computes all
    ``N_s`` rows per tile, so its peak score tile is ``[B, N_s, block]``
    — 4 GiB at ``N_s = 10⁶`` with the default block. Streaming the
    source axis too bounds it at ``[B, chunk, block]``, the
    million-entity prerequisite (ROADMAP item 3): the ``N_s × N_t``
    sweep only ever exists as one ``chunk × block`` tile, whatever the
    pair size. Rows are independent, so each chunk's running top-k
    (with the same per-tile merge) is already its rows' global answer
    and the results are **bit-identical** to :func:`chunked_topk` —
    tie-breaking included (``tests/ops/test_topk.py``).

    Same dispatch contract as :func:`chunked_topk`: the auto Pallas
    decision resolves here (un-jitted) and streams chunk-by-chunk
    through the kernel when taken.

    The chunk loop is **double-buffered**: the scan carry holds the
    chunk being scored while the body issues the NEXT chunk's
    source-row fetch, so iteration ``k+1``'s gather depends only on the
    loop counter — never on iteration ``k``'s compute — and the fetch
    hides behind the per-tile top-k instead of serializing ahead of it
    (ROADMAP item 4; SCH403's single-buffered shape). Two chunk slots
    live instead of one — ``O(2 x chunk x C)`` — and results stay
    bit-identical: the same chunks are scored in the same order.
    """
    pallas = _resolve_dispatch(pallas, k, dispatch_reason)
    sort_tiles = _tile_sort()
    chunk = int(chunk)

    def core(hs, ht, tm):
        return _streamed_topk(hs, ht, k, tm, chunk, block, return_values,
                              pallas, sort_tiles)

    return _ad_opaque(core, h_s, h_t, t_mask)


@functools.partial(jax.jit, static_argnames=('k', 'chunk', 'block',
                                             'return_values', 'pallas',
                                             'sort_tiles'))
def _streamed_topk(h_s, h_t, k, t_mask, chunk, block, return_values,
                   pallas, sort_tiles):
    B, N_s, C = h_s.shape
    pad = (-N_s) % chunk
    if pad:
        # Padded rows are discarded work, exactly like the padded target
        # columns of the inner scan.
        h_s = jnp.pad(h_s, ((0, 0), (0, pad), (0, 0)))
    n_chunks = h_s.shape[1] // chunk
    chunks = h_s.reshape(B, n_chunks, chunk, C).transpose(1, 0, 2, 3)

    # Double-buffered chunk pipeline: the carry holds the PREFETCHED
    # chunk k, and the body (1) issues chunk k+1's fetch — a
    # dynamic-slice off the loop counter alone, independent of this
    # iteration's compute — then (2) scores the carried chunk. The
    # fetch is therefore never on the body's critical path (the serial
    # form chained slice -> einsum -> merge, which is exactly the
    # SCH403 single-buffered shape), so a scheduler can run it under
    # the per-tile top-k. The final iteration's fetch is clamped to the
    # last chunk — one discarded re-fetch instead of a ragged epilogue.
    def body(cur, i):
        nxt = jax.lax.dynamic_index_in_dim(
            chunks, jnp.minimum(i + 1, n_chunks - 1), axis=0,
            keepdims=False)
        out = _chunked_topk(cur, h_t, k, t_mask, block, True, pallas,
                            sort_tiles)
        return nxt, out

    _, (vals, idx) = jax.lax.scan(body, chunks[0],
                                  jnp.arange(n_chunks, dtype=jnp.int32))
    # [n_chunks, B, chunk, k] -> [B, N_s, k]
    merge = lambda a: a.transpose(1, 0, 2, 3).reshape(  # noqa: E731
        B, n_chunks * chunk, k)[:, :N_s]
    if return_values:
        return merge(vals), merge(idx)
    return merge(idx)
