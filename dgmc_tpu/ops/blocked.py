"""Scatter-free edge aggregation: node-range-blocked one-hot matmuls.

The profile of the DBP15K-scale sparse step (465 ms on-chip) shows it is
dominated by ~130 scatter-add ops of ~1.2 ms each — the forward
``segment_sum`` reductions of message passing plus the scatter-add VJPs of
the node gathers (see ``benchmarks/sparse_diag.py`` and the round-3 notes
in ``benchmarks/README.md``). TPU has no fast scatter; it DOES have a fast
MXU. Graph structure is static across an entire training run, so the
edge→node reduction can be restructured host-side, once, into a form that
is pure (batched) matmul on device:

1. Host (``build_edge_blocks``): sort edges by destination node; partition
   into blocks of ≤ ``block_edges`` edges such that every block's
   destinations fall inside one aligned node range of ``rows`` rows (heavy
   "hub" ranges simply get several blocks). Pad blocks with masked edges.
2. Device (``adj_matmul``): gather the operand rows at the blocked source
   endpoints, build each block's ``[block_edges, rows]`` one-hot routing
   matrix (edge-structure-only ⇒ XLA CSEs one copy across all layers AND
   all consensus iterations of a step), and contract on the MXU:
   ``[NB, E_b, R] x [NB, E_b, C] -> [NB, R, C]``. Blocks sharing a node
   range are combined by a second tiny one-hot matmul ``[NR, NB]`` —
   no scatter anywhere.
3. Backward: ``d/dh`` of ``out[n] = Σ_{e: dst=n} h[src_e]`` is the SAME
   computation over the transposed adjacency, so a ``custom_vjp`` runs it
   with the source-blocked structure — the gradient is also matmuls, never
   a scatter-add.

This replaces the capability the reference buys from ``torch_scatter``
CUDA kernels (reference ``dgmc/models/rel.py:25-31`` via PyG
``MessagePassing``) with an MXU-native formulation.
"""

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from dgmc_tpu.ops.graph import GraphBatch


@struct.dataclass
class EdgeBlocks:
    """One direction of blocked adjacency: dst-sorted, range-aligned.

    Shapes (per batch element): ``src [B, NB, E_b]`` int32 source-endpoint
    node ids; ``dst_local [B, NB, E_b]`` int32 destination offset within
    the block's node range; ``mask [B, NB, E_b]`` bool edge validity;
    ``range_id [B, NB]`` int32 aligned node-range index of each block;
    ``inv_degree [B, N, 1]`` float reciprocal destination in-degree
    (1 where empty) — mean aggregation is a static elementwise scale.
    ``rows`` / ``num_ranges`` are static ints.
    """
    src: jnp.ndarray
    dst_local: jnp.ndarray
    mask: jnp.ndarray
    range_id: jnp.ndarray
    inv_degree: jnp.ndarray
    rows: int = struct.field(pytree_node=False)
    num_ranges: int = struct.field(pytree_node=False)
    # Optional dtype (e.g. jnp.bfloat16) for the gathered operand rows: the
    # blocked gathers are random-access-bandwidth bound, so halving row
    # bytes nearly halves their cost; accumulation stays f32.
    gather_dtype: Optional[str] = struct.field(pytree_node=False,
                                               default=None)


def _build_one(src, dst, mask, num_nodes, rows, block_edges):
    """Block one graph's edge list (numpy, host-side)."""
    src = np.asarray(src)[mask]
    dst = np.asarray(dst)[mask]
    order = np.argsort(dst, kind='stable')
    src, dst = src[order], dst[order]
    num_ranges = -(-num_nodes // rows)

    blocks = []  # (range_id, src_chunk, dst_local_chunk)
    rid_of = dst // rows
    start = 0
    e = len(dst)
    while start < e:
        rid = rid_of[start]
        # end of this range's edge run
        run_end = start + np.searchsorted(rid_of[start:], rid + 1)
        end = min(start + block_edges, run_end)
        # Within a block, order edges by SOURCE row: summation order is
        # irrelevant to the one-hot contraction, and a monotone index
        # stream is the friendliest access pattern the row gather can get.
        o = np.argsort(src[start:end], kind='stable')
        blocks.append((rid, src[start:end][o],
                       (dst[start:end] - rid * rows)[o]))
        start = end
    if not blocks:
        blocks.append((0, np.zeros(0, np.int32), np.zeros(0, np.int32)))

    nb = len(blocks)
    b_src = np.zeros((nb, block_edges), np.int32)
    b_loc = np.zeros((nb, block_edges), np.int32)
    b_msk = np.zeros((nb, block_edges), bool)
    b_rid = np.zeros((nb,), np.int32)
    for i, (rid, s, l) in enumerate(blocks):
        n = len(s)
        b_src[i, :n] = s
        b_loc[i, :n] = l
        b_msk[i, :n] = True
        b_rid[i] = rid

    deg = np.bincount(dst, minlength=num_nodes).astype(np.float32)
    inv_deg = (1.0 / np.maximum(deg, 1.0))[:, None]
    return b_src, b_loc, b_msk, b_rid, inv_deg, num_ranges


def build_edge_blocks(senders, receivers, edge_mask, num_nodes, rows=128,
                      block_edges=512):
    """Host-side blocking of a batched edge list, both directions.

    Args mirror :class:`GraphBatch` fields (``[B, E]`` numpy arrays).
    Returns ``(incoming, outgoing)`` :class:`EdgeBlocks` — ``incoming``
    aggregates messages TO each edge's receiver (dst=receiver,
    src=sender), ``outgoing`` the reverse. The two are mutual transposes:
    each serves as the other's backward structure in :func:`adj_matmul`.

    Batch elements are padded to one common block count.
    """
    senders = np.asarray(senders)
    receivers = np.asarray(receivers)
    edge_mask = np.asarray(edge_mask)
    out = []
    for dst, src in ((receivers, senders), (senders, receivers)):
        per = [_build_one(src[b], dst[b], edge_mask[b], num_nodes, rows,
                          block_edges) for b in range(dst.shape[0])]
        nb = max(p[0].shape[0] for p in per)

        def pad(a, n=nb):
            return np.pad(a, ((0, n - a.shape[0]),) + ((0, 0),) *
                          (a.ndim - 1))

        out.append(EdgeBlocks(
            src=jnp.asarray(np.stack([pad(p[0]) for p in per])),
            dst_local=jnp.asarray(np.stack([pad(p[1]) for p in per])),
            mask=jnp.asarray(np.stack([pad(p[2]) for p in per])),
            range_id=jnp.asarray(np.stack([pad(p[3]) for p in per])),
            inv_degree=jnp.asarray(np.stack([p[4] for p in per])),
            rows=rows, num_ranges=per[0][5]))
    return out[0], out[1]


def _routed(h, src, loc, msk, rid, rows, num_ranges, out_rows, gather_dtype,
            scale=None):
    """Core blocked contraction: ``out[b, n] = Σ_{e: dst=n} h[b, src_e]``
    (times an optional per-entry ``scale [B, NB, E_b]``).

    ``h [B, M, C]`` is the gathered-from table (``src`` indexes its rows),
    ``out_rows`` the un-padded output row count.
    """
    C = h.shape[-1]
    acc = jnp.promote_types(h.dtype, jnp.float32)
    # Narrow-row guard: bf16 only pays when it still leaves >= 512-byte
    # gather rows; measured at C=32 the 64-byte bf16 rows made the random
    # gathers ~1.6x SLOWER (sub-line transfers), while at C=256 bf16 wins.
    if gather_dtype is not None and C * 2 >= 512:
        h = h.astype(gather_dtype)
    else:
        gather_dtype = None
    # The same guard in reverse for narrow low-precision tables (bf16
    # compute policy): upcasting to float32 rows is exact and moves the
    # gather back to >= 128-byte lines, which measured ~1.6x faster than
    # 64-byte sub-line rows. The optimization barrier is load-bearing:
    # convert and gather commute, and without it XLA fuses the convert
    # INTO the gather kernel (its cost model prefers the smaller table
    # read), silently reinstating the 64-byte-row gather this guard
    # exists to avoid — profiled at 0.78 vs 0.44 ms per ψ₂ target gather
    # on the bf16 DBP15K leg. The barrier materializes the f32 table
    # once (a [N, C] elementwise pass, trivial next to the gather).
    if h.dtype.itemsize * C < 128 and jnp.issubdtype(h.dtype,
                                                     jnp.floating):
        h = jax.lax.optimization_barrier(h.astype(jnp.float32))

    # Batch-FLATTENED row gather: one [B*M, C] table with globally
    # offset indices instead of a per-element vmapped gather. A batched
    # leading dim is the TPU gather/scatter slow path (see the batch_pair
    # notes in models/dgmc.py), and under --pairs-per-step batching the
    # per-element form would pay that tax B times per aggregation.
    # mode='clip': block indices are host-built and always in-bounds
    # (padding points at row 0 under mask=False, zeroed by the one-hot
    # contraction), so jnp.take's default out-of-bounds 'fill' would
    # only add a full-width select_n pass over every gathered row —
    # profiled at ~0.56 ms per gather at DBP15K scale, ~40 ms/step
    # across ψ₁/ψ₂ before this was pinned.
    B, M = h.shape[0], h.shape[1]
    gidx = src + (jnp.arange(B, dtype=src.dtype) * M)[:, None, None]
    g = jnp.take(h.reshape(B * M, C), gidx.reshape(-1), axis=0,
                 mode='clip')
    g = g.reshape(src.shape + (C,))                        # [B, NB, E_b, C]
    if scale is not None:
        g = g * scale[..., None].astype(g.dtype)
    # Edge-structure-only routing tensor: CSE'd across every layer and
    # consensus iteration that aggregates over this graph.
    onehot = (loc[..., None] == jnp.arange(rows)) & msk[..., None]
    # HIGHEST precision for f32 operands: these contractions are tiny
    # (a few GFLOP) but route f32 values, and the default single-pass
    # bf16 MXU mode would silently round every message. bf16 operands
    # (gather_dtype) are exact in one pass. (A single-pass bf16
    # contraction of the exactly-bf16-representable upcast tables was
    # tried in r5 and LOST ~30 ms/step — narrow bf16 operands pay
    # (2,1)-packing relayouts that dwarf the saved MXU passes.)
    prec = (None if gather_dtype is not None
            else jax.lax.Precision.HIGHEST)
    per_block = jnp.einsum('aber,abec->abrc', onehot.astype(g.dtype), g,
                           precision=prec,
                           preferred_element_type=acc)  # [B, NB, R, C]
    combine = (rid[:, None, :] == jnp.arange(num_ranges)[None, :, None])
    # Combine is tiny; keep it HIGHEST so f32 partial sums are never
    # re-rounded regardless of gather dtype.
    out = jnp.einsum('anb,abrc->anrc', combine.astype(acc), per_block,
                     precision=jax.lax.Precision.HIGHEST,
                     preferred_element_type=acc)
    return out.reshape(B, num_ranges * rows, C)[:, :out_rows].astype(acc)


def _routed_sum(h, blocks):
    return _routed(h, blocks.src, blocks.dst_local, blocks.mask,
                   blocks.range_id, blocks.rows, blocks.num_ranges,
                   h.shape[1], blocks.gather_dtype)


@jax.custom_vjp
def adj_matmul(h, fwd_blocks, bwd_blocks):
    """``out[b, n, :] = Σ_{edges e with dst=n} h[b, src_e, :]`` — the
    gather+segment-sum of message passing as pure MXU matmuls, with a
    matmul (never scatter-add) backward via the transposed blocking.
    """
    return _routed_sum(h, fwd_blocks)


def _fwd(h, fwd_blocks, bwd_blocks):
    return _routed_sum(h, fwd_blocks), (bwd_blocks,)


def _bwd(res, d_out):
    (bwd_blocks,) = res
    return _routed_sum(d_out, bwd_blocks), None, None


adj_matmul.defvjp(_fwd, _bwd)


# Design notes from on-chip measurement (benchmarks/sparse_diag.py):
# - A "dual" variant running BOTH directions as one concatenated gather +
#   contraction (with an order-preserving backward so the routing tensor
#   CSEs across passes) measured no better than two adj_matmul calls —
#   the >2^19-row combined gather runs ~3x less efficiently (10 vs
#   31 GB/s), eating the op-count saving; chunking it back under the
#   cliff recovered nothing.
# - Sorting edges by source within a block (monotone gather stream) made
#   no measurable difference; the row gather is latency- not
#   pattern-bound at these sizes. The sort is kept anyway: it is free at
#   build time and can only help.


class UnionPair:
    """A (source, target) graph pair disjoint-unioned along the NODE axis.

    Per batch element the two graphs become one graph of ``N_s' + N_t``
    nodes (``N_s'`` = source side padded up to a block-row boundary),
    target-side edge endpoints offset by ``N_s'`` — the reference's
    ``__inc__`` collation trick (reference ``dgmc/utils/data.py:9-16``)
    applied on-device. One backbone application then covers both sides,
    halving the op count of the per-consensus-step ψ₂ applications — and
    on the tunneled TPU, where EVERY kernel pays a ~0.3-0.5 ms dispatch
    floor, op count is the entire game at DBP15K scale.

    Only profitable combined with blocked adjacency: with plain
    gather/scatter aggregation, scatter cost grows with the union's node
    count and a union measured 58 vs 36 ms per consensus iteration; the
    blocked contraction's cost is bytes-bound and indifferent to table
    size. Built at trace time from already-blocked sides (cheap index
    concats, CSE'd by XLA).
    """

    def __init__(self, g_s, g_t):
        bs, bt = g_s.blocks_in, g_t.blocks_in
        assert bs is not None and bt is not None, (
            'UnionPair requires blocked graphs (ops/blocked.py)')
        assert bs.rows == bt.rows
        self.n_s, self.n_t = g_s.num_nodes, g_t.num_nodes
        # Align the source side to a whole number of block rows so target
        # node ids / range ids offset cleanly.
        self.pad = bs.num_ranges * bs.rows - self.n_s
        off, nr_s = self.n_s + self.pad, bs.num_ranges

        def merge(a, b):
            ones = jnp.ones((a.inv_degree.shape[0], self.pad, 1),
                            a.inv_degree.dtype)
            return EdgeBlocks(
                src=jnp.concatenate([a.src, b.src + off], axis=1),
                dst_local=jnp.concatenate([a.dst_local, b.dst_local],
                                          axis=1),
                mask=jnp.concatenate([a.mask, b.mask], axis=1),
                range_id=jnp.concatenate(
                    [a.range_id, b.range_id + nr_s], axis=1),
                inv_degree=jnp.concatenate(
                    [a.inv_degree, ones, b.inv_degree], axis=1),
                rows=a.rows, num_ranges=nr_s + b.num_ranges,
                gather_dtype=a.gather_dtype)

        ea_s, ea_t = g_s.edge_attr, g_t.edge_attr
        self.graph = GraphBatch(
            x=self._cat(g_s.x, g_t.x),
            senders=jnp.concatenate([g_s.senders, g_t.senders + off],
                                    axis=1),
            receivers=jnp.concatenate([g_s.receivers, g_t.receivers + off],
                                      axis=1),
            node_mask=self._cat(g_s.node_mask, g_t.node_mask),
            edge_mask=jnp.concatenate([g_s.edge_mask, g_t.edge_mask],
                                      axis=1),
            edge_attr=(None if ea_s is None else
                       jnp.concatenate([ea_s, ea_t], axis=1)),
            blocks_in=merge(g_s.blocks_in, g_t.blocks_in),
            blocks_out=merge(g_s.blocks_out, g_t.blocks_out))

    def _cat(self, a_s, a_t):
        if self.pad:
            widths = ((0, 0), (0, self.pad)) + ((0, 0),) * (a_s.ndim - 2)
            a_s = jnp.pad(a_s, widths)
        return jnp.concatenate([a_s, a_t], axis=1)

    def apply(self, fn, x_s, x_t):
        """Run ``fn(x, graph) -> [B, N, C]`` once over the union; split
        the result back into per-side arrays."""
        out = fn(self._cat(x_s, x_t), self.graph)
        return out[:, :self.n_s], out[:, self.n_s + self.pad:]


def repeat_graph(graph, reps):
    """Tile a :class:`GraphBatch` — including any attached
    :class:`EdgeBlocks` — ``reps``× along the batch axis.

    The ``--pairs-per-step`` replication path: replicas are
    byte-identical, so the host-side blocking runs ONCE on the B=1 graph
    and the resulting index tensors are repeated, instead of
    ``build_edge_blocks`` re-sorting the same 100k+-edge lists per
    replica (x2 directions x2 sides) at startup.
    """
    if reps <= 1:
        return graph

    def t(a):
        return None if a is None else jnp.repeat(jnp.asarray(a), reps,
                                                 axis=0)

    def tb(b):
        if b is None:
            return None
        return b.replace(src=t(b.src), dst_local=t(b.dst_local),
                         mask=t(b.mask), range_id=t(b.range_id),
                         inv_degree=t(b.inv_degree))

    return graph.replace(
        x=t(graph.x), senders=t(graph.senders),
        receivers=t(graph.receivers), node_mask=t(graph.node_mask),
        edge_mask=t(graph.edge_mask), edge_attr=t(graph.edge_attr),
        blocks_in=tb(graph.blocks_in), blocks_out=tb(graph.blocks_out))


def attach_blocks(graph, rows=128, block_edges=512, min_nodes=1024,
                  gather_dtype=None) -> 'object':
    """Return ``graph`` with blocked-adjacency structure attached.

    Host-side, one-off; a no-op for small graphs (``num_nodes <
    min_nodes``), where plain gather/scatter is already cheap and the
    padding overhead isn't worth it.

    ``gather_dtype='bfloat16'`` moves message rows AND routing tensors as
    bf16 with f32 accumulation — both the blocked gathers and the routing
    matmuls are bytes-bound, so this nearly halves their cost; routing
    weights are exact 0/1 either way. Narrow-row exception: rows below
    512 bytes in bf16 (``C < 256``) silently stay/upcast to float32 inside
    ``_routed`` — sub-cache-line gather rows measured ~1.6× SLOWER, and the
    upcast is numerically exact, so a ``gather_dtype='bfloat16'`` request
    on narrow channels keeps f32 traffic by design. The default is ``None`` (full-f32
    message traffic, bit-faithful to the gather/scatter path up to
    summation order): reduced-precision messages belong to the explicit
    bf16 compute policy (``dtype=jnp.bfloat16`` on the backbones), which
    the quality gates exercise end to end — not to a silent data-layout
    default.
    """
    if graph.num_nodes < min_nodes or graph.blocks_in is not None:
        return graph
    if gather_dtype is not None and not isinstance(gather_dtype, str):
        # Accept a models/precision.Precision policy (or raw dtype) in
        # place of the dtype string — the CLIs pass their policy through.
        from dgmc_tpu.models.precision import gather_dtype_of
        gather_dtype = gather_dtype_of(gather_dtype)
    inc, outg = build_edge_blocks(graph.senders, graph.receivers,
                                  graph.edge_mask, graph.num_nodes,
                                  rows=rows, block_edges=block_edges)
    if gather_dtype is not None:
        inc = inc.replace(gather_dtype=gather_dtype)
        outg = outg.replace(gather_dtype=gather_dtype)
    return graph.replace(blocks_in=inc, blocks_out=outg)
