"""Trace-time switch for auto-dispatched Pallas kernels.

Pallas ``custom_call``s have no GSPMD partitioning rule, so every kernel
that auto-enables on TPU must stay off inside partitioned programs.
``shard_map``'s manual mode is detectable from ``jax.typeof(x).vma``, but
GSPMD auto-partitioning (``corr_sharding``) is not visible from inside a
module — so the orchestrator (:class:`~dgmc_tpu.models.DGMC`) wraps its
partitioned region in :func:`disable_fused_kernels`, and each auto gate
consults :func:`fused_kernels_allowed`. Explicitly requested kernels
(``fused=True``) are not silenced — DGMC rejects those loudly instead.
"""

import contextlib
import contextvars

import jax

_fused_ok = contextvars.ContextVar('dgmc_tpu_fused_kernels_ok',
                                   default=True)
# Separate switch for kernels EMBEDDED via shard_map inside GSPMD programs
# (parallel/topk.corr_sharded_topk): those are deliberately immune to
# disable_fused_kernels() — the orchestrator sets that while tracing the
# partitioned region, yet the embedded manual region is exactly where the
# kernel is valid. This dedicated opt-out restores an escape hatch should
# the shard_map Pallas path misbehave on some topology.
_embedded_ok = contextvars.ContextVar('dgmc_tpu_embedded_kernels_ok',
                                      default=True)


def vma_union(*arrays):
    """Union of the varying-manual-axes sets of ``arrays`` — empty outside
    ``shard_map`` manual mode. Pallas kernels are shard-local, so they run
    under a mesh as long as (a) every operand carries the same vma and
    (b) the ``out_shape`` declares it; see :func:`promote_vma`."""
    out = frozenset()
    for a in arrays:
        out |= frozenset(jax.typeof(a).vma)
    return out


def promote_vma(vma, *arrays):
    """Promote every array to carry ``vma`` (replicated → varying is
    free); no-op when ``vma`` is empty."""
    def one(a):
        missing = tuple(sorted(vma - set(jax.typeof(a).vma)))
        return jax.lax.pcast(a, missing, to='varying') if missing else a

    return tuple(one(a) for a in arrays)


@contextlib.contextmanager
def disable_fused_kernels():
    """Trace-time context: auto-dispatched Pallas kernels pick their
    fallback path inside this block."""
    token = _fused_ok.set(False)
    try:
        yield
    finally:
        _fused_ok.reset(token)


def fused_kernels_allowed():
    return _fused_ok.get()


@contextlib.contextmanager
def disable_embedded_kernels():
    """Trace-time context: shard_map-embedded Pallas kernels (the GSPMD
    top-k embedding) fall back to their scan paths inside this block."""
    token = _embedded_ok.set(False)
    try:
        yield
    finally:
        _embedded_ok.reset(token)


def embedded_kernels_allowed():
    return _embedded_ok.get()
