"""Trace-time switch for auto-dispatched Pallas kernels.

Pallas ``custom_call``s have no GSPMD partitioning rule, so every kernel
that auto-enables on TPU must stay off inside partitioned programs.
``shard_map``'s manual mode is detectable from ``jax.typeof(x).vma``, but
GSPMD auto-partitioning (``corr_sharding``) is not visible from inside a
module — so the orchestrator (:class:`~dgmc_tpu.models.DGMC`) wraps its
partitioned region in :func:`disable_fused_kernels`, and each auto gate
consults :func:`fused_kernels_allowed`. Explicitly requested kernels
(``fused=True``) are not silenced — DGMC rejects those loudly instead.

Every decision site reports its outcome through :func:`record_dispatch`
(pallas-taken vs XLA-fallback, with reason — including
``gspmd-silenced``), so a run's ``dispatch.json`` shows which kernels a
program actually used instead of leaving it to inference from timings.
"""

import contextlib
import contextvars
import os

import jax

from dgmc_tpu.obs.registry import record_dispatch  # noqa: F401  (re-export)

#: Process-wide opt-out, read once at import: the run supervisor's first
#: degradation-ladder rung (dgmc_tpu/resilience/supervisor.py) restarts a
#: repeatedly-failing run with ``DGMC_TPU_DISABLE_FUSED=1`` so every auto
#: gate below (and the shard_map-embedded one) picks its XLA fallback —
#: the same switch a human would flip to rule the Pallas paths out of a
#: hang. Values '', '0', 'false' (any case) leave kernels on.
_ENV_DISABLED = os.environ.get(
    'DGMC_TPU_DISABLE_FUSED', '').strip().lower() not in ('', '0', 'false')

_fused_ok = contextvars.ContextVar('dgmc_tpu_fused_kernels_ok',
                                   default=not _ENV_DISABLED)
# Separate switch for kernels EMBEDDED via shard_map inside GSPMD programs
# (parallel/topk.corr_sharded_topk): those are deliberately immune to
# disable_fused_kernels() — the orchestrator sets that while tracing the
# partitioned region, yet the embedded manual region is exactly where the
# kernel is valid. This dedicated opt-out restores an escape hatch should
# the shard_map Pallas path misbehave on some topology.
_embedded_ok = contextvars.ContextVar('dgmc_tpu_embedded_kernels_ok',
                                      default=not _ENV_DISABLED)


def vma_of(x):
    """Varying-manual-axes set of ``x`` — empty outside ``shard_map``
    manual mode, and always empty on JAX versions predating the vma type
    system (where manual-mode Pallas embedding is unavailable anyway)."""
    try:
        t = jax.typeof(x)
    except AttributeError:
        return frozenset()
    return frozenset(getattr(t, 'vma', ()))


def vma_union(*arrays):
    """Union of the varying-manual-axes sets of ``arrays`` — empty outside
    ``shard_map`` manual mode. Pallas kernels are shard-local, so they run
    under a mesh as long as (a) every operand carries the same vma and
    (b) the ``out_shape`` declares it; see :func:`promote_vma`."""
    out = frozenset()
    for a in arrays:
        out |= vma_of(a)
    return out


def promote_vma(vma, *arrays):
    """Promote every array to carry ``vma`` (replicated → varying is
    free); no-op when ``vma`` is empty — including on pre-vma JAX, where
    :func:`vma_of` always reports empty and this path is never taken."""
    if not vma:
        return tuple(arrays)

    def one(a):
        missing = tuple(sorted(vma - vma_of(a)))
        return jax.lax.pcast(a, missing, to='varying') if missing else a

    return tuple(one(a) for a in arrays)


@contextlib.contextmanager
def disable_fused_kernels():
    """Trace-time context: auto-dispatched Pallas kernels pick their
    fallback path inside this block."""
    token = _fused_ok.set(False)
    try:
        yield
    finally:
        _fused_ok.reset(token)


def fused_kernels_allowed():
    return _fused_ok.get()


def auto_fused(kernel, size_ok=True, size_reason='size'):
    """Resolve one auto kernel gate (TPU backend, not GSPMD-silenced,
    size/shape constraints satisfied) and record the outcome + reason in
    the telemetry registry. Call sites that honor an *explicit* user
    setting record it themselves with reason ``'explicit'``.
    """
    if not fused_kernels_allowed():
        take, reason = False, ('env-disabled' if _ENV_DISABLED
                               else 'gspmd-silenced')
    elif jax.default_backend() != 'tpu':
        take, reason = False, f'backend={jax.default_backend()}'
    elif not size_ok:
        take, reason = False, size_reason
    else:
        take, reason = True, 'auto-tpu'
    record_dispatch(kernel, 'pallas' if take else 'fallback', reason)
    return take


@contextlib.contextmanager
def disable_embedded_kernels():
    """Trace-time context: shard_map-embedded Pallas kernels (the GSPMD
    top-k embedding) fall back to their scan paths inside this block."""
    token = _embedded_ok.set(False)
    try:
        yield
    finally:
        _embedded_ok.reset(token)


def embedded_kernels_allowed():
    return _embedded_ok.get()
