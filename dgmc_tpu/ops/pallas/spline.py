"""Pallas TPU kernel: fused SplineConv routing + aggregation.

The MXU formulation of SplineConv (``dgmc_tpu/models/spline.py``) computes
``t = x @ W`` for all ``K^D`` kernels in one GEMM, then routes per-edge
slices of ``t`` to receivers: a gather of ``E * 2^D`` short rows followed
by a masked-mean scatter. Both are latency-bound on TPU (measured ~14 ms
fwd+bwd for a 2-layer psi_2 at the flagship keypoint shape).

At keypoint scale the whole per-graph working set fits in VMEM
(``t_b [N*K^D, O]`` is ~400 KB for N=64, K=5, D=2, O=64), so this kernel
replaces gather+scatter with dense MXU matmuls per graph, built
in-register from iota comparisons — no HBM gather traffic at all:

- ``RouteT[m_tile, E]``: one-hot of the ``2^D`` active (sender, knot)
  slots per edge, pre-scaled by the closed-form basis weights and the edge
  mask. Built transposed, per M-tile: the M axis is tiled to respect the
  16 MB scoped-VMEM limit, and routing inputs ride in ``[A, E]`` layout so
  the E axis lands on the 128-lane dimension (an ``[E, A]`` layout wastes
  32x VMEM to lane padding).
- ``msgs[E, O] = sum_tiles RouteT_tile^T @ t_tile`` accumulated in VMEM
  scratch (expressed as ``dot_general`` contractions — nothing is ever
  materialized transposed);
- ``RcvHot[N, E]``: receiver one-hot; ``agg = (RcvHot @ msgs) / deg``
  (masked mean, PyG semantics: empty neighborhoods give zeros).

The whole operation is linear in ``t``, so the backward pass is the same
structure transposed (a second kernel produces ``d_t`` tile by tile),
wired via ``custom_vjp``. Routing tensors (basis, indices, mask) derive
from edge data and carry no gradients.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dgmc_tpu.parallel.compat import shape_dtype_struct

M_TILE = 256

# Dispatch gate: per-cell VMEM is dominated by the [M_TILE, E] route chunk
# and the [N, E] / [E, O] panels.
MAX_E = 2048
MAX_M = 16384
MAX_N = 1024


def _route_t_tile(flat_ref, basis_ref, emask_ref, start, width):
    """RouteT chunk [width, E] for global t-rows [start, start+width)."""
    flat = flat_ref[0]            # [A, E] int32
    basis = basis_ref[0]          # [A, E] f32
    emask = emask_ref[0]          # [1, E] f32
    A, E = flat.shape
    iota = start + jax.lax.broadcasted_iota(jnp.int32, (width, E), 0)
    route_t = jnp.zeros((width, E), jnp.float32)
    for a in range(A):  # static unroll; A = 2^D is tiny
        route_t = route_t + jnp.where(iota == flat[a][None, :],
                                      basis[a][None, :], 0.0)
    return route_t * emask


def _rcv_hot(rcv_ref, emask_ref, N):
    rcv = rcv_ref[0]              # [1, E] int32
    emask = emask_ref[0]          # [1, E] f32
    E = rcv.shape[1]
    iota_n = jax.lax.broadcasted_iota(jnp.int32, (N, E), 0)
    return (rcv == iota_n).astype(jnp.float32) * emask


def _fwd_kernel(N, n_mt, t_ref, flat_ref, basis_ref, rcv_ref, emask_ref,
                out_ref, acc):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc[...])

    route_t = _route_t_tile(flat_ref, basis_ref, emask_ref, j * M_TILE,
                            M_TILE)                  # [W, E]
    acc[...] += jax.lax.dot_general(
        route_t, t_ref[0], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)          # [E, O]

    @pl.when(j == n_mt - 1)
    def _out():
        hot = _rcv_hot(rcv_ref, emask_ref, N)        # [N, E]
        agg = jax.lax.dot_general(
            hot, acc[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)      # [N, O]
        deg = jnp.sum(hot, axis=1, keepdims=True)
        out_ref[0] = (agg / jnp.maximum(deg, 1.0)).astype(out_ref.dtype)


def _bwd_kernel(N, n_mt, g_ref, flat_ref, basis_ref, rcv_ref, emask_ref,
                dt_ref, dmsgs):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        hot = _rcv_hot(rcv_ref, emask_ref, N)        # [N, E]
        g = g_ref[0].astype(jnp.float32)             # [N, O]
        deg = jnp.sum(hot, axis=1, keepdims=True)
        g = g / jnp.maximum(deg, 1.0)
        dmsgs[...] = jax.lax.dot_general(
            hot, g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)      # [E, O]

    route_t = _route_t_tile(flat_ref, basis_ref, emask_ref, j * M_TILE,
                            M_TILE)                  # [W, E]
    dt_ref[0] = jax.lax.dot_general(
        route_t, dmsgs[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dt_ref.dtype)


def _common_specs(flat_t, basis_t, rcv, emask_f):
    return [
        pl.BlockSpec((1,) + flat_t.shape[1:], lambda b, j: (b, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1,) + basis_t.shape[1:], lambda b, j: (b, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1,) + rcv.shape[1:], lambda b, j: (b, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1,) + emask_f.shape[1:], lambda b, j: (b, 0, 0),
                     memory_space=pltpu.VMEM),
    ]


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def route_aggregate(t, flat, basis, receivers, edge_mask, num_nodes,
                    interpret=False):
    """Masked-mean aggregation of basis-blended (sender, knot) slices.

    t: ``[B, M, O]`` node-through-all-kernels features (``M = N * K^D``);
    flat: ``[B, E, A]`` flattened (sender, knot) indices; basis:
    ``[B, E, A]`` weights; receivers ``[B, E]``; edge_mask ``[B, E]``.
    Returns ``[B, N, O]``. Bilinear in ``(t, basis)``: ``t`` cotangents come
    from the tiled backward kernel; ``basis`` cotangents (gradients w.r.t.
    edge attributes, which the unfused gather+einsum path propagates too)
    are computed analytically — but only when ``basis`` is actually being
    differentiated (``symbolic_zeros`` perturbation flag), so the common
    training path, where edge attributes are data, pays nothing for them.
    """
    out, _ = _fwd_impl(t, flat, basis, receivers, edge_mask, num_nodes,
                       interpret)
    return out


def _prep(flat, basis, receivers, edge_mask):
    """Lane-friendly [*, E]-minor layouts for the routing tensors."""
    flat_t = jnp.swapaxes(flat, 1, 2).astype(jnp.int32)       # [B, A, E]
    basis_t = jnp.swapaxes(basis.astype(jnp.float32), 1, 2)   # [B, A, E]
    rcv = receivers[:, None, :].astype(jnp.int32)             # [B, 1, E]
    emask_f = edge_mask[:, None, :].astype(jnp.float32)       # [B, 1, E]
    return (jax.lax.stop_gradient(flat_t),
            jax.lax.stop_gradient(basis_t), rcv, emask_f)


def _fwd_impl(t, flat, basis, receivers, edge_mask, num_nodes, interpret):
    from dgmc_tpu.ops.pallas.dispatch import promote_vma, vma_union
    B, M, O = t.shape
    pad = (-M) % M_TILE
    t_p = jnp.pad(t, ((0, 0), (0, pad), (0, 0))) if pad else t
    n_mt = (M + pad) // M_TILE
    flat_t, basis_t, rcv, emask_f = _prep(flat, basis, receivers,
                                          edge_mask)
    vma = vma_union(t_p, flat_t, basis_t, rcv, emask_f)
    t_p, flat_t, basis_t, rcv, emask_f = promote_vma(
        vma, t_p, flat_t, basis_t, rcv, emask_f)
    E = flat_t.shape[2]
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, num_nodes, n_mt),
        grid=(B, n_mt),
        in_specs=[pl.BlockSpec((1, M_TILE, O), lambda b, j: (b, j, 0),
                               memory_space=pltpu.VMEM)]
        + _common_specs(flat_t, basis_t, rcv, emask_f),
        out_specs=pl.BlockSpec((1, num_nodes, O), lambda b, j: (b, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=shape_dtype_struct((B, num_nodes, O), t.dtype,
                                     vma=vma),
        scratch_shapes=[pltpu.VMEM((E, O), jnp.float32)],
        interpret=interpret,
    )(t_p, flat_t, basis_t, rcv, emask_f)
    return out, (M, flat_t, basis_t, rcv, emask_f)


def _fwd(t, flat, basis, receivers, edge_mask, num_nodes, interpret):
    # symbolic_zeros=True: every differentiable-position arg arrives as a
    # CustomVJPPrimal carrying a .perturbed flag. ``t`` is saved for the
    # analytic basis cotangent only when basis is actually differentiated.
    vals = (t.value, flat.value, basis.value, receivers.value,
            edge_mask.value)
    out, res = _fwd_impl(*vals, num_nodes, interpret)
    extra = vals if basis.perturbed else None
    return out, (res, extra)


def _symzero(shape, dtype):
    from jax.custom_derivatives import SymbolicZero
    try:
        aval = jax.typeof(shape_dtype_struct(shape, dtype))
    except AttributeError:  # pre-vma JAX: no jax.typeof
        aval = jax.core.ShapedArray(shape, dtype)
    return SymbolicZero(aval.to_tangent_aval())


def _bwd(num_nodes, interpret, res, g):
    from dgmc_tpu.ops.pallas.dispatch import promote_vma, vma_union
    (M, flat_t, basis_t, rcv, emask_f), extra = res
    B, _, O = g.shape
    vma = vma_union(g, flat_t, basis_t, rcv, emask_f)
    g, flat_t, basis_t, rcv, emask_f = promote_vma(
        vma, g, flat_t, basis_t, rcv, emask_f)
    E = flat_t.shape[2]
    pad = (-M) % M_TILE
    n_mt = (M + pad) // M_TILE
    d_t = pl.pallas_call(
        functools.partial(_bwd_kernel, num_nodes, n_mt),
        grid=(B, n_mt),
        in_specs=[pl.BlockSpec((1, num_nodes, O), lambda b, j: (b, 0, 0),
                               memory_space=pltpu.VMEM)]
        + _common_specs(flat_t, basis_t, rcv, emask_f),
        out_specs=pl.BlockSpec((1, M_TILE, O), lambda b, j: (b, j, 0),
                               memory_space=pltpu.VMEM),
        out_shape=shape_dtype_struct((B, M + pad, O), g.dtype,
                                     vma=vma),
        scratch_shapes=[pltpu.VMEM((E, O), jnp.float32)],
        interpret=interpret,
    )(g, flat_t, basis_t, rcv, emask_f)[:, :M]

    A = flat_t.shape[1]
    if extra is None:
        d_basis = _symzero((B, E, A), jnp.float32)
    else:
        # d_basis[b,e,a] = mask_e * sum_o (g/deg)[b, rcv_e, o]
        #                           * t[b, flat[b,e,a], o]
        # — the same cotangent the unfused gather+einsum path produces.
        t_v, flat_v, basis_v, receivers_v, edge_mask_v = extra
        emask = edge_mask_v.astype(g.dtype)
        deg = jax.vmap(lambda r, m: jax.ops.segment_sum(
            m, r, num_segments=num_nodes))(receivers_v, emask)
        g_norm = g / jnp.maximum(deg, 1.0)[..., None]
        dmsgs = jnp.take_along_axis(g_norm, receivers_v[..., None], axis=1)
        picked = jnp.take_along_axis(
            t_v, flat_v.reshape(B, E * A, 1), axis=1).reshape(B, E, A, O)
        d_basis = (jnp.einsum('beo,beao->bea', dmsgs, picked)
                   * emask[..., None]).astype(basis_v.dtype)

    return (d_t, _symzero((B, E, A), jnp.int32), d_basis,
            _symzero((B, E), jnp.int32), _symzero((B, E), jnp.bool_))


route_aggregate.defvjp(_fwd, _bwd, symbolic_zeros=True)


def route_aggregate_fits(num_nodes, num_edges, kd, out_features):
    """True when the per-graph working set fits the kernel's VMEM gate.

    Per-cell VMEM scales with the [M_TILE, E] route chunk, the [N, E]
    receiver one-hot, and the O-wide panels ([E, O] scratch, [M_TILE, O]
    t tile, [N, O] out) — so E*O and N*E are bounded jointly alongside
    the per-axis caps."""
    return (num_edges <= MAX_E and num_nodes * kd <= MAX_M
            and num_nodes <= MAX_N
            and num_edges * out_features <= 512 * 1024
            and num_nodes * num_edges <= 512 * 1024
            and M_TILE * out_features <= 512 * 1024)
