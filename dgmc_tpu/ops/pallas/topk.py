"""Pallas TPU kernel: streaming exact top-k of ``h_s @ h_t^T``.

The KeOps-``argKmin`` replacement at full speed (SURVEY.md §2.3). The
jnp scan in :mod:`dgmc_tpu.ops.topk` already avoids materializing the
``N_s x N_t`` score matrix, but every extraction round re-reads its
``[B, N_s, block]`` score tile from HBM. Here the tile never leaves VMEM:

- grid ``(B, S_tiles, T_blocks)`` with the target-block axis innermost, so
  each ``[TILE_S, C]`` row stripe sees its target blocks consecutively;
- per cell, one MXU ``dot`` builds ``[TILE_S, BLOCK]`` scores in VMEM;
- a running top-k carry ``[TILE_S, k]`` lives in VMEM scratch across the
  T-block sweep;
- selection is **gather-free**: per round, take the row max, then pick the
  *smallest global candidate index* attaining it. Because the carry always
  holds indices from earlier target blocks (strictly smaller than the
  current block's), and both carry and block candidates are index-ascending
  within equal values, smallest-global-index == first-position — exactly
  ``lax.top_k``'s lower-index-wins tie rule, so results are bit-identical
  to ``dense_topk`` (the dense≡sparse(k=N) contract relies on this).

HBM traffic is just ``h_s + h_t + out`` (~40 MB at DBP15K scale vs ~25 GB
of score-tile re-reads for the scan): measured on-chip at 15000x20000,
C=256, k=10 — 20.7 ms for this kernel vs 82 ms for the itermax scan vs
211 ms for the original sort scan (benchmarks/topk_tpu.json).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE_S = 256
BLOCK_T = 512

_INT_MAX = jnp.iinfo(jnp.int32).max


def _kernel(k, n_t_pad, h_s_ref, h_t_ref, m_ref, vals_ref, idx_ref,
            c_vals, c_idx):
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        c_vals[...] = jnp.full_like(c_vals[...], -jnp.inf)
        c_idx[...] = jnp.zeros_like(c_idx[...])

    h_s = h_s_ref[0]                       # [TILE_S, C]
    h_t = h_t_ref[0]                       # [BLOCK_T, C]
    mask = m_ref[0, 0]                     # [BLOCK_T] bool
    scores = jax.lax.dot_general(
        h_s, h_t, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)  # [TILE_S, BLOCK_T]
    if h_s.dtype != jnp.float32:
        # Round through the input dtype so selection sees exactly the
        # values the jnp scan's einsum would produce (bf16 inputs), then
        # carry them in the float32 scratch (exact superset).
        scores = scores.astype(h_s.dtype).astype(jnp.float32)
        neg = jnp.float32(jnp.finfo(h_s.dtype).min)
    else:
        neg = jnp.finfo(jnp.float32).min
    scores = jnp.where(mask[None, :], scores, neg)

    start = j * BLOCK_T
    block_idx = jnp.broadcast_to(
        start + jax.lax.broadcasted_iota(jnp.int32, (1, BLOCK_T), 1),
        scores.shape)

    # Candidate pool: carry first (indices from earlier blocks, always
    # smaller), then this block. [TILE_S, k + BLOCK_T].
    cand_v = jnp.concatenate([c_vals[...], scores], axis=-1)
    cand_i = jnp.concatenate([c_idx[...], block_idx], axis=-1)

    new_v = []
    new_i = []
    for _ in range(k):
        v = jnp.max(cand_v, axis=-1)                        # [TILE_S]
        sel = cand_v == v[:, None]
        gi = jnp.min(jnp.where(sel, cand_i, _INT_MAX), axis=-1)
        new_v.append(v)
        new_i.append(gi)
        hit = sel & (cand_i == gi[:, None])
        cand_v = jnp.where(hit, -jnp.inf, cand_v)
    c_vals[...] = jnp.stack(new_v, axis=-1)
    c_idx[...] = jnp.stack(new_i, axis=-1)

    @pl.when(j == n_t_pad // BLOCK_T - 1)
    def _out():
        vals_ref[0] = c_vals[...]
        idx_ref[0] = c_idx[...]


@functools.partial(jax.jit,
                   static_argnames=('k', 'return_values', 'interpret'))
def pallas_topk(h_s, h_t, k, t_mask=None, return_values=False,
                interpret=False):
    """Exact ``dense_topk``-equivalent indices via the streaming kernel.

    h_s: ``[B, N_s, C]``; h_t: ``[B, N_t, C]`` -> idx ``[B, N_s, k]``
    (plus values when ``return_values``).

    The candidate *search* is pure selection and carries no gradients (the
    reference's KeOps ``argKmin`` is likewise used outside autograd,
    reference ``dgmc/models/dgmc.py:85-94``; DGMC recomputes ``S_hat`` from
    a differentiable gather of the selected rows). Inputs are
    stop-gradiented so AD never traces into the kernel.
    """
    h_s = jax.lax.stop_gradient(h_s)
    h_t = jax.lax.stop_gradient(h_t)
    B, N_s, C = h_s.shape
    N_t = h_t.shape[1]
    if t_mask is None:
        t_mask = jnp.ones((B, N_t), dtype=bool)

    # shard_map manual mode: the kernel is shard-local, so it runs under a
    # mesh as long as the varying-manual-axes type is declared — promote
    # every input to the union vma and stamp it on the outputs. Outside
    # shard_map all vma sets are empty and this is a no-op.
    from dgmc_tpu.ops.pallas.dispatch import promote_vma, vma_union
    from dgmc_tpu.parallel.compat import shape_dtype_struct
    vma = vma_union(h_s, h_t, t_mask)
    h_s, h_t, t_mask = promote_vma(vma, h_s, h_t, t_mask)

    pad_s = (-N_s) % TILE_S
    pad_t = (-N_t) % BLOCK_T
    h_s_p = jnp.pad(h_s, ((0, 0), (0, pad_s), (0, 0)))
    h_t_p = jnp.pad(h_t, ((0, 0), (0, pad_t), (0, 0)))
    m_p = jnp.pad(t_mask, ((0, 0), (0, pad_t)))
    n_s_pad, n_t_pad = N_s + pad_s, N_t + pad_t

    grid = (B, n_s_pad // TILE_S, n_t_pad // BLOCK_T)
    vals, idx = pl.pallas_call(
        functools.partial(_kernel, k, n_t_pad),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, TILE_S, C), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, BLOCK_T, C), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            # Mask rides as [B, 1, N_t] so the block's trailing dims meet
            # the (8, 128) tiling rule.
            pl.BlockSpec((1, 1, BLOCK_T), lambda b, i, j: (b, 0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, TILE_S, k), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, TILE_S, k), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            # Values ride in the carry's float32; cast back on return.
            shape_dtype_struct((B, n_s_pad, k), jnp.float32, vma=vma),
            shape_dtype_struct((B, n_s_pad, k), jnp.int32, vma=vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((TILE_S, k), jnp.float32),
            pltpu.VMEM((TILE_S, k), jnp.int32),
        ],
        interpret=interpret,
    )(h_s_p, h_t_p, m_p[:, None, :])
    vals, idx = vals[:, :N_s].astype(h_s.dtype), idx[:, :N_s]
    if return_values:
        return vals, idx
    return idx
